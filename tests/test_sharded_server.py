"""End-to-end: the SERVER running the sharded multi-chip backend
(spatial_backend='sharded' in Config, mesh built by build_backend) on
the 8-device virtual CPU mesh, driven by real WebSocket clients through
the tick batcher — BASELINE config-4's shape through the product, not
the bench harness.
"""

import asyncio

import pytest

pytest.importorskip("websockets")  # driven by real WS clients

from tests.client_util import WsClient, free_port
from worldql_server_tpu.engine.config import Config
from worldql_server_tpu.engine.server import WorldQLServer, build_backend
from worldql_server_tpu.parallel import ShardedTpuSpatialBackend
from worldql_server_tpu.protocol import Instruction, Message, Replication, Vector3


def run(coro):
    return asyncio.run(coro)


def _require_devices(n: int):
    import jax

    if jax.device_count() < n:
        pytest.skip(f"needs {n} devices")


def make_sharded_server(**overrides) -> WorldQLServer:
    config = Config()
    config.store_url = "memory://"
    config.http_port = free_port()
    config.ws_port = free_port()
    config.zmq_enabled = False
    config.spatial_backend = "sharded"
    config.mesh_batch = 2
    config.mesh_space = 4
    config.tick_interval = 0.02
    for key, value in overrides.items():
        setattr(config, key, value)
    return WorldQLServer(config)


def test_build_backend_sharded_from_config():
    _require_devices(8)
    config = Config()
    config.spatial_backend = "sharded"
    config.mesh_batch = 2
    config.mesh_space = 0  # auto: all remaining devices
    config.validate()
    backend = build_backend(config)
    assert isinstance(backend, ShardedTpuSpatialBackend)
    assert backend.n_batch == 2 and backend.n_space == 4


def test_config_rejects_bad_mesh():
    config = Config()
    config.spatial_backend = "sharded"
    config.mesh_batch = 0
    with pytest.raises(ValueError):
        config.validate()
    config.mesh_batch = 1
    config.mesh_space = -1
    with pytest.raises(ValueError):
        config.validate()


def test_sharded_server_ws_fanout_through_ticker():
    """Multi-world fan-out through the full product stack: WS transport
    → router → tick batcher → sharded mesh backend → broadcast."""
    _require_devices(8)

    async def scenario():
        server = make_sharded_server()
        assert isinstance(server.backend, ShardedTpuSpatialBackend)
        assert server.ticker is not None
        await server.start()
        try:
            sender = await WsClient.connect(server.config.ws_port)
            subs = [await WsClient.connect(server.config.ws_port)
                    for _ in range(4)]
            worlds = ["alpha", "alpha", "beta", "beta"]
            pos = Vector3(8.0, 8.0, 8.0)
            for client, world in zip(subs, worlds):
                await client.send(Message(
                    instruction=Instruction.AREA_SUBSCRIBE,
                    world_name=world, position=pos,
                ))
            await asyncio.sleep(0.2)

            for i, world in enumerate(("alpha", "beta")):
                await sender.send(Message(
                    instruction=Instruction.LOCAL_MESSAGE,
                    world_name=world, position=pos,
                    parameter=f"msg-{world}",
                    replication=Replication.EXCEPT_SELF,
                ))
            for client, world in zip(subs, worlds):
                got = await client.recv_until(
                    Instruction.LOCAL_MESSAGE, timeout=30
                )
                assert got.parameter == f"msg-{world}"
                assert got.world_name == world

            # disconnect cleanup flows into the mesh index
            await subs[0].close()
            await asyncio.sleep(0.3)
            await sender.send(Message(
                instruction=Instruction.LOCAL_MESSAGE,
                world_name="alpha", position=pos, parameter="after-drop",
            ))
            got = await subs[1].recv_until(Instruction.LOCAL_MESSAGE, timeout=30)
            assert got.parameter == "after-drop"
        finally:
            await server.stop()
        return True

    assert run(scenario())


def test_sharded_server_survives_churn_with_compaction():
    """Server-mode churn: enough subscribe traffic to force background
    compactions of the mesh index while the server keeps serving."""
    _require_devices(8)

    async def scenario():
        server = make_sharded_server()
        server.backend._compact_threshold_override = 64
        await server.start()
        try:
            client = await WsClient.connect(server.config.ws_port)
            listener = await WsClient.connect(server.config.ws_port)
            probe = Vector3(4.0, 4.0, 4.0)
            await listener.send(Message(
                instruction=Instruction.AREA_SUBSCRIBE,
                world_name="hot", position=probe,
            ))
            # Interleave probes with the churn: each probe rides a
            # ticker flush, which is what arms/swaps compactions.
            for i in range(300):
                await client.send(Message(
                    instruction=Instruction.AREA_SUBSCRIBE,
                    world_name=f"w{i % 5}",
                    position=Vector3(
                        float((i * 37) % 500), 0.0, float((i * 91) % 500)
                    ),
                ))
                if i % 50 == 49:
                    await client.send(Message(
                        instruction=Instruction.LOCAL_MESSAGE,
                        world_name="hot", position=probe,
                        parameter=f"probe-{i}",
                    ))
                    got = await listener.recv_until(
                        Instruction.LOCAL_MESSAGE, timeout=30
                    )
                    assert got.parameter == f"probe-{i}"
            server.backend.wait_compaction()
            assert server.backend.compactions >= 1
            assert server.backend.compaction_failures == 0

            await client.send(Message(
                instruction=Instruction.LOCAL_MESSAGE,
                world_name="hot", position=probe, parameter="still-alive",
            ))
            got = await listener.recv_until(Instruction.LOCAL_MESSAGE, timeout=30)
            assert got.parameter == "still-alive"
        finally:
            await server.stop()
        return True

    assert run(scenario())
