"""Delta ticks (ISSUE 13): temporal coherence, parity-pinned.

The contract under test: with ``delta_ticks`` armed, every observable
result — query fan-out lists lane for lane, entity positions/cubes/
targets, frames on the wire — is IDENTICAL to the full-recompute
path across arbitrary churn schedules, while the engine provably
reuses the clean majority (and the device does sublinear work). The
off mode stays byte-for-byte the pre-delta pipeline.
"""

import asyncio
import time
import urllib.request
import uuid

import numpy as np
import pytest

from worldql_server_tpu.engine.config import Config
from worldql_server_tpu.engine.metrics import Metrics
from worldql_server_tpu.engine.peers import Peer, PeerMap
from worldql_server_tpu.engine.router import Router
from worldql_server_tpu.engine.server import WorldQLServer
from worldql_server_tpu.engine.ticker import TickBatcher
from worldql_server_tpu.entities.plane import EntityPlane
from worldql_server_tpu.protocol import deserialize_message
from worldql_server_tpu.protocol.types import (
    Entity, Instruction, Message, Vector3,
)
from worldql_server_tpu.robustness import failpoints
from worldql_server_tpu.robustness.overload import OverloadGovernor
from worldql_server_tpu.storage.memory_store import MemoryRecordStore
from worldql_server_tpu.robustness.resilient import ResilientBackend
from worldql_server_tpu.spatial.delta_ticks import (
    TemporalCoherence, row_signatures,
)
from worldql_server_tpu.spatial.quantize import cube_coords_batch
from worldql_server_tpu.spatial.tpu_backend import TpuSpatialBackend

from tests.client_util import ZmqClient, free_port
from tests.prom_parser import validate_exposition


def run(coro):
    return asyncio.run(coro)


# region: TemporalCoherence units


def test_coherence_dirty_sequence_is_exact():
    co = TemporalCoherence()
    co.note_key(100)
    seq_then = co.seq
    co.store(h1=7, h2=77, key=100, seq=seq_then, targets=("a",))
    # clean cube at the entry's sequence: replays
    reused, dirty = co.partition([7], [77])
    assert reused == [["a"]] and dirty == []
    # a LATER mutation of the cube invalidates exactly that entry
    co.note_key(100)
    reused, dirty = co.partition([7], [77])
    assert reused == [None] and dirty == [0]
    # a mutation of a DIFFERENT cube does not
    co.store(h1=7, h2=77, key=100, seq=co.seq, targets=("a",))
    co.note_key(999)
    reused, dirty = co.partition([7], [77])
    assert reused == [["a"]] and dirty == []


def test_coherence_h2_mismatch_and_floor_reject():
    co = TemporalCoherence()
    co.store(h1=5, h2=50, key=1, seq=co.seq, targets=())
    # 128-bit verify: an h1 collision with a different h2 recomputes
    assert co.partition([5], [51]) == ([None], [0])
    # wholesale invalidation rejects racing inserts stamped before it
    stale_seq = co.seq
    co.invalidate_all()
    co.store(h1=5, h2=50, key=1, seq=stale_seq, targets=())
    assert co.partition([5], [50]) == ([None], [0])


def test_coherence_cache_bound_resets_not_grows():
    co = TemporalCoherence(max_entries=4)
    for i in range(10):
        co.store(h1=i, h2=i, key=i, seq=co.seq, targets=())
    assert len(co.cache) <= 4
    assert co.cache_resets >= 1


def test_row_signatures_fold_every_column():
    wid = np.array([3], np.int32)
    pos = np.array([[1.0, 2.0, 3.0]])
    sid = np.array([9], np.int32)
    repl = np.array([0], np.int8)
    base = row_signatures(wid, pos, sid, repl)
    for cols in (
        (wid + 1, pos, sid, repl),
        (wid, pos + 1e-12, sid, repl),
        (wid, pos, sid + 1, repl),
        (wid, pos, sid, repl + 1),
    ):
        other = row_signatures(*cols)
        assert (base[0] != other[0]).all() or (base[1] != other[1]).all()
    again = row_signatures(wid, pos.copy(), sid, repl)
    assert base[0][0] == again[0][0] and base[1][0] == again[1][0]


# endregion

# region: query-path parity property


def _staged(q_pos, sid, m):
    return (
        np.zeros(m, np.int32),
        np.ascontiguousarray(q_pos[:m]),
        sid[:m],
        np.zeros(m, np.int8),
    )


def test_delta_query_parity_under_randomized_churn():
    """>= 200 ticks of randomized churn — moves, joins, leaves, peer
    removals, query churn, forced query-tier changes — keep the delta
    path lane-for-lane identical to full recompute, with reuse and
    the O(K) tombstone scatter provably firing."""
    rng = np.random.default_rng(1234)
    n, m = 256, 64
    bes = [
        TpuSpatialBackend(16, compact_threshold=64),
        TpuSpatialBackend(16, compact_threshold=64),
    ]
    assert bes[0].configure_delta_ticks("auto")
    peers = [uuid.UUID(int=i + 1) for i in range(n)]
    pos = rng.uniform(-250, 250, (n, 3))
    cubes = cube_coords_batch(pos, 16)
    live = np.ones(n, bool)
    for be in bes:
        be.bulk_add_subscriptions("w", peers, cubes)
        be.flush()
    q_pos = pos[rng.integers(0, n, m)].copy()
    sid = np.full(m, -1, np.int32)

    for tick in range(210):
        op = rng.random()
        if op < 0.22:  # moves through the base+delta path
            mv = np.unique(rng.integers(0, n, int(rng.integers(1, 5))))
            mv = mv[live[mv]]
            if mv.size:
                new_cubes = cube_coords_batch(
                    rng.uniform(-250, 250, (mv.size, 3)), 16
                )
                for be in bes:
                    be.bulk_move_subscriptions(
                        "w", [peers[i] for i in mv], cubes[mv],
                        [peers[i] for i in mv], new_cubes,
                    )
                cubes[mv] = new_cubes
        elif op < 0.36:  # leaves (tombstones)
            i = int(rng.integers(0, n))
            if live[i]:
                for be in bes:
                    be.remove_subscription(
                        "w", peers[i], tuple(int(c) for c in cubes[i])
                    )
                live[i] = False
        elif op < 0.48:  # joins (delta appends)
            dead = np.flatnonzero(~live)
            if dead.size:
                i = int(dead[0])
                new_cube = cube_coords_batch(
                    rng.uniform(-250, 250, (1, 3)), 16
                )
                for be in bes:
                    be.bulk_add_subscriptions("w", [peers[i]], new_cube)
                cubes[i] = new_cube[0]
                live[i] = True
        elif op < 0.56:  # wholesale peer removal
            i = int(rng.integers(0, n))
            if live[i]:
                for be in bes:
                    be.remove_peer(peers[i])
                live[i] = False
        elif op < 0.72:  # query churn (fresh positions)
            rows = rng.integers(0, m, 3)
            q_pos[rows] = rng.uniform(-250, 250, (3, 3))
        # forced tier changes: three fixed batch sizes (pow2 tiers)
        mm = (m, 32, 16)[int(rng.integers(0, 12)) % 3 if tick % 7 == 0
                         else 0]
        cols = _staged(q_pos, sid, mm)
        outs = [
            be.collect_local_batch(be.dispatch_staged_batch(*cols))
            for be in bes
        ]
        assert outs[0] == outs[1], f"tick {tick} diverged"
    on = bes[0]
    assert on.delta_reused > 0, "reuse never fired"
    assert on.delta_sync_scatters > 0, "tombstone scatter never fired"
    assert on.delta_recomputed > 0
    # the off backend never touched the coherence machinery
    assert bes[1].delta_reused == 0 and bes[1].delta_recomputed == 0


def test_delta_off_is_pinned_to_the_pre_delta_pipeline():
    """--delta-ticks off: the handle shapes, counters and coherence
    state are untouched — byte-for-byte the old dispatch pipeline."""
    be = TpuSpatialBackend(16)
    peers = [uuid.UUID(int=i + 1) for i in range(8)]
    pos = np.random.default_rng(0).uniform(-50, 50, (8, 3))
    be.bulk_add_subscriptions("w", peers, cube_coords_batch(pos, 16))
    be.flush()
    cols = _staged(pos, np.full(8, -1, np.int32), 8)
    handle = be.dispatch_staged_batch(*cols)
    assert handle[1][0] in ("csr", "dense")  # never a "tc" handle
    be.collect_local_batch(handle)
    assert be.delta_reused == be.delta_recomputed == 0
    assert not be._coherence.cache and not be._coherence.dirty
    assert be.delta_sync_scatters == 0


def test_sharded_backend_supports_delta_via_flat_region_replay():
    """ISSUE 14 satellite (the PR 13 leftover): result reuse runs on
    the mesh — clean queries replay from the shard-local (host) cache,
    dirty partitions dispatch through the mesh kernels' per-shard flat
    regions — pinned lane-for-lane against a full-recompute mesh twin
    under randomized churn. The delta-SYNC tombstone scatter stays
    conservatively off (the mesh replicates the delta segment)."""
    from worldql_server_tpu.parallel import (
        ShardedTpuSpatialBackend, make_fanout_mesh,
    )

    rng = np.random.default_rng(77)
    n, m = 128, 32
    mesh = make_fanout_mesh(2, 4)
    bes = [
        ShardedTpuSpatialBackend(16, mesh, compact_threshold=64),
        ShardedTpuSpatialBackend(16, mesh, compact_threshold=64),
    ]
    assert bes[0].configure_delta_ticks("auto"), \
        "mesh must accept delta ticks"
    assert not bes[0]._delta_scatter_supported()
    peers = [uuid.UUID(int=i + 1) for i in range(n)]
    pos = rng.uniform(-250, 250, (n, 3))
    cubes = cube_coords_batch(pos, 16)
    live = np.ones(n, bool)
    for be in bes:
        be.bulk_add_subscriptions("w", peers, cubes)
        be.flush()
    q_pos = pos[rng.integers(0, n, m)].copy()
    sid = np.full(m, -1, np.int32)

    for tick in range(80):
        op = rng.random()
        if op < 0.2:  # moves
            mv = np.unique(rng.integers(0, n, int(rng.integers(1, 4))))
            mv = mv[live[mv]]
            if mv.size:
                new_cubes = cube_coords_batch(
                    rng.uniform(-250, 250, (mv.size, 3)), 16
                )
                for be in bes:
                    be.bulk_move_subscriptions(
                        "w", [peers[i] for i in mv], cubes[mv],
                        [peers[i] for i in mv], new_cubes,
                    )
                cubes[mv] = new_cubes
        elif op < 0.32:  # leaves
            i = int(rng.integers(0, n))
            if live[i]:
                for be in bes:
                    be.remove_subscription(
                        "w", peers[i], tuple(int(c) for c in cubes[i])
                    )
                live[i] = False
        elif op < 0.44:  # joins
            dead = np.flatnonzero(~live)
            if dead.size:
                i = int(dead[0])
                new_cube = cube_coords_batch(
                    rng.uniform(-250, 250, (1, 3)), 16
                )
                for be in bes:
                    be.bulk_add_subscriptions("w", [peers[i]], new_cube)
                cubes[i] = new_cube[0]
                live[i] = True
        elif op < 0.6:  # query churn
            rows = rng.integers(0, m, 2)
            q_pos[rows] = rng.uniform(-250, 250, (2, 3))
        mm = (m, 16)[1 if tick % 11 == 0 else 0]  # forced tier change
        cols = _staged(q_pos, sid, mm)
        outs = [
            be.collect_local_batch(be.dispatch_staged_batch(*cols))
            for be in bes
        ]
        assert outs[0] == outs[1], f"sharded tick {tick} diverged"
    assert bes[0].delta_reused > 0, "mesh reuse never fired"
    assert bes[0].delta_recomputed > 0
    assert bes[1].delta_reused == 0 and bes[1].delta_recomputed == 0


# endregion

# region: resilience (rebuild/failover mid-run)


def test_delta_parity_through_resilience_rebuild_and_failover():
    """A mid-run ResilientBackend rebuild — and later a full failover
    to the CPU mirror — keeps the delta wrapper's results identical
    to a full-recompute wrapper fed the same mutations and the same
    fault schedule (the symmetric x2 failpoint hits both)."""
    rng = np.random.default_rng(77)
    n, m = 128, 32

    def make(mode):
        def factory():
            inner = TpuSpatialBackend(16)
            inner.configure_delta_ticks(mode)
            return inner

        return ResilientBackend(
            factory(), factory=factory, failover_after=3,
        )

    bes = [make("on"), make("off")]
    peers = [uuid.UUID(int=i + 1) for i in range(n)]
    pos = rng.uniform(-150, 150, (n, 3))
    cubes = cube_coords_batch(pos, 16)
    for be in bes:
        be.bulk_add_subscriptions("w", peers, cubes)
        be.flush()
    q_pos = pos[rng.integers(0, n, m)].copy()
    sid = np.full(m, -1, np.int32)
    failpoints.registry.reset()
    try:
        for tick in range(30):
            if tick == 10:
                # one dispatch failure EACH → both wrappers rebuild
                failpoints.registry.set("backend.dispatch", "error:1:x2")
            if tick == 20:
                # sustained failures → both fail over to the mirror
                failpoints.registry.set("backend.dispatch", "error:1")
            if tick in (12, 22):  # churn lands on the fresh inner/mirror
                mv = np.arange(5)
                new_cubes = cube_coords_batch(
                    rng.uniform(-150, 150, (5, 3)), 16
                )
                for be in bes:
                    be.bulk_move_subscriptions(
                        "w", [peers[i] for i in mv], cubes[mv],
                        [peers[i] for i in mv], new_cubes,
                    )
                cubes[mv] = new_cubes
            cols = _staged(q_pos, sid, m)
            outs = [
                be.collect_local_batch(
                    be.dispatch_staged_batch(*cols, fallback=None)
                )
                for be in bes
            ]
            assert outs[0] == outs[1], f"tick {tick} diverged"
    finally:
        failpoints.registry.reset()
    assert bes[0].rebuilds >= 1 and bes[0].failed_over
    assert bes[1].rebuilds >= 1 and bes[1].failed_over


# endregion

# region: overload forced-state tick (ticker-level parity)


class _TickerHarness:
    def __init__(self, delta: str):
        config = Config()
        self.backend = TpuSpatialBackend(config.sub_region_size)
        self.backend.configure_delta_ticks(delta)
        self.peer_map = PeerMap(on_remove=self.backend.remove_peer)
        self.gov = OverloadGovernor(max_batch=64, metrics=Metrics())
        from worldql_server_tpu.engine.staging import QueryStaging

        self.ticker = TickBatcher(
            self.backend, self.peer_map, 10.0, max_batch=64,
            governor=self.gov, staging=QueryStaging(self.backend),
        )
        self.router = Router(
            self.peer_map, self.backend, MemoryRecordStore(config),
            ticker=self.ticker,
        )
        self.inboxes = {}

    async def add_peer(self):
        peer_uuid = uuid.uuid4()
        inbox = self.inboxes.setdefault(peer_uuid, [])

        async def send_raw(data):
            inbox.append(deserialize_message(data))

        await self.peer_map.insert(
            Peer(peer_uuid, "loopback", send_raw, "test")
        )
        return peer_uuid


def test_delta_parity_through_forced_overload_tick():
    """An `overload` forced-state tick (governor driven to SHED_HIGH
    via the deterministic failpoint) admits/sheds identically on the
    delta and full paths — delivered frames match peer for peer."""

    async def scenario():
        hs = [_TickerHarness("on"), _TickerHarness("off")]
        pos = Vector3(1.0, 1.0, 1.0)
        peer_ids = []
        for h in hs:
            a = await h.add_peer()
            b = await h.add_peer()
            peer_ids.append((a, b))
            for p in (a, b):
                await h.router.handle_message(Message(
                    instruction=Instruction.AREA_SUBSCRIBE,
                    sender_uuid=p, world_name="world", position=pos,
                ))
        failpoints.registry.reset()
        try:
            for phase in ("ok", "shed_high", "ok"):
                failpoints.registry.set(
                    "overload.force_state", f"state:{phase}"
                )
                for h in hs:
                    for _ in range(4):
                        await h.router.handle_message(Message(
                            instruction=Instruction.LOCAL_MESSAGE,
                            sender_uuid=peer_ids[hs.index(h)][0],
                            world_name="world", position=pos,
                            parameter=phase,
                        ))
                    await h.ticker.flush()
            counts = []
            for h, (a, b) in zip(hs, peer_ids):
                got = [
                    (m.parameter, m.instruction)
                    for m in h.inboxes[b]
                ]
                counts.append(got)
            assert counts[0] == counts[1]
            assert hs[0].backend.delta_reused > 0
        finally:
            failpoints.registry.reset()

    run(scenario())


# endregion

# region: entity-plane parity property


def _ent_msg(sender, entities, parameter=None):
    return Message(
        instruction=Instruction.LOCAL_MESSAGE, sender_uuid=sender,
        world_name="w", entities=entities, parameter=parameter,
    )


def _vel_flex(v):
    return np.asarray(v, np.float32).astype("<f4").tobytes()


def test_delta_sim_parity_under_randomized_churn():
    """>= 200 sim ticks of randomized churn — client updates, joins,
    leaves, movers, a forced capacity-tier change, and a mid-run
    abort — keep the delta plane's live targets, positions, cubes and
    frame count identical to the full-recompute plane."""
    rng = np.random.default_rng(31)
    owner = uuid.UUID(int=4242)

    def make(mode):
        be = TpuSpatialBackend(16)
        return EntityPlane(
            be, None, cube_size=16, k=4, dt=0.05, bounds=400.0,
            delta_ticks=mode,
        )

    planes = [make("on"), make("off")]
    ids = [uuid.uuid4() for _ in range(220)]
    pos = rng.uniform(-350, 350, (220, 3))
    vel = np.zeros((220, 3), np.float32)
    vel[:12] = rng.uniform(-25, 25, (12, 3))  # a few movers, rest idle
    alive = set(range(200))
    for pl in planes:
        pl.ingest(_ent_msg(owner, [
            Entity(uuid=ids[i], world_name="w",
                   position=Vector3(*pos[i]),
                   flex=_vel_flex(vel[i]) if vel[i].any() else None)
            for i in sorted(alive)
        ]))

    def tick(pl):
        handle = pl.dispatch_tick()
        assert handle is not None
        return pl.apply(pl.collect_tick(handle))

    next_id = 200
    for t in range(205):
        op = rng.random()
        if op < 0.15 and alive:  # client position update
            i = sorted(alive)[int(rng.integers(0, len(alive)))]
            p = rng.uniform(-350, 350, 3)
            for pl in planes:
                pl.ingest(_ent_msg(owner, [Entity(
                    uuid=ids[i], world_name="w", position=Vector3(*p),
                )]))
        elif op < 0.25 and alive:  # leave
            i = sorted(alive)[int(rng.integers(0, len(alive)))]
            alive.discard(i)
            for pl in planes:
                pl.ingest(_ent_msg(owner, [Entity(uuid=ids[i])],
                                   parameter="entity.remove"))
        elif op < 0.35 and next_id < 220:  # join
            i = next_id
            next_id += 1
            alive.add(i)
            for pl in planes:
                pl.ingest(_ent_msg(owner, [Entity(
                    uuid=ids[i], world_name="w",
                    position=Vector3(*pos[i]),
                )]))
        if t == 100:
            # mid-run abort: the in-flight tick drops on BOTH planes
            for pl in planes:
                h = pl.dispatch_tick()
                assert h is not None
                pl.abort_tick()
        frames = [tick(pl) for pl in planes]
        cap = planes[0]._cap
        assert planes[0]._cap == planes[1]._cap
        live = planes[0]._live[:cap]
        assert (live == planes[1]._live[:cap]).all()
        assert np.array_equal(
            planes[0]._pos[:cap][live], planes[1]._pos[:cap][live]
        ), f"tick {t}: positions diverged"
        assert np.array_equal(
            planes[0]._cube[:cap][live], planes[1]._cube[:cap][live]
        ), f"tick {t}: cubes diverged"
        assert len(frames[0]) == len(frames[1]), f"tick {t}"
        wires = [sorted(
            getattr(f, "wire", None) or b"" for f, _ in fr
        ) for fr in frames]
        assert wires[0] == wires[1], f"tick {t}: frame bytes diverged"
    on = planes[0]
    assert on.delta_sim_ticks > 100
    assert on.delta_reused > 0
    assert on.delta_mispredicts == 0
    assert planes[1].delta_sim_ticks == 0


def test_delta_sim_tier_change_falls_back_and_recovers():
    owner = uuid.UUID(int=9)
    be = TpuSpatialBackend(16)
    pl = EntityPlane(be, None, cube_size=16, k=4, delta_ticks="on")
    rng = np.random.default_rng(2)
    pl.ingest(_ent_msg(owner, [
        Entity(uuid=uuid.uuid4(), world_name="w",
               position=Vector3(*rng.uniform(-100, 100, 3)))
        for _ in range(40)
    ]))

    def tick():
        return pl.apply(pl.collect_tick(pl.dispatch_tick()))

    tick()  # cold → full
    tick()  # replay
    assert pl.delta_sim_ticks >= 1
    before_full = pl.full_sim_ticks
    # registration burst past the 256 tier → grow → full fallback
    pl.ingest(_ent_msg(owner, [
        Entity(uuid=uuid.uuid4(), world_name="w",
               position=Vector3(*rng.uniform(-100, 100, 3)))
        for _ in range(300)
    ]))
    tick()
    assert pl._cap > 256
    assert pl.full_sim_ticks == before_full + 1
    tick()  # and delta resumes at the new tier
    assert pl.last_delta_stats.get("fallback") == ""


def test_non_pow2_cube_size_disables_entity_delta():
    be = TpuSpatialBackend(12)
    pl = EntityPlane(be, None, cube_size=12, delta_ticks="on")
    assert not pl._delta_ticks


# endregion

# region: e2e — mostly-idle world over real ZMQ shows reuse in /metrics


def test_e2e_mostly_idle_world_reuse_fraction_in_metrics():
    """Boot the real server (tpu backend + entity sim + delta auto),
    park a mostly-idle world on it over real ZMQ, and read
    ``wql_delta_reuse_fraction > 0.8`` from a strict-parsed /metrics
    scrape — the ISSUE 13 observability acceptance."""

    async def scenario():
        http_port = free_port()
        config = Config(
            store_url="memory://",
            http_port=http_port,
            ws_enabled=False,
            zmq_server_port=free_port(),
            zmq_server_host="127.0.0.1",
        )
        config.spatial_backend = "tpu"
        config.tick_interval = 0.02
        config.entity_sim = True
        config.entity_k = 4
        config.delta_ticks = "auto"
        config.precompile_tiers = False
        server = WorldQLServer(config)
        await server.start()
        try:
            a = await ZmqClient.connect(config.zmq_server_port)
            b = await ZmqClient.connect(config.zmq_server_port)
            # two IDLE co-cube entities (frames still flow, nothing
            # moves) plus a subscription that never changes cubes
            ea, eb = uuid.uuid4(), uuid.uuid4()
            await a.send(Message(
                instruction=Instruction.LOCAL_MESSAGE,
                world_name="arena",
                entities=[Entity(uuid=ea, world_name="arena",
                                 position=Vector3(1, 2, 3))],
            ))
            await b.send(Message(
                instruction=Instruction.LOCAL_MESSAGE,
                world_name="arena",
                entities=[Entity(uuid=eb, world_name="arena",
                                 position=Vector3(2, 2, 3))],
            ))
            await a.send(Message(
                instruction=Instruction.AREA_SUBSCRIBE,
                world_name="arena", position=Vector3(1, 2, 3),
            ))
            plane = server.entity_plane
            deadline = time.perf_counter() + 10
            while plane.entity_count < 2:
                assert time.perf_counter() < deadline
                await asyncio.sleep(0.02)
            # let the idle world tick: replay ticks accumulate reuse
            deadline = time.perf_counter() + 20
            while plane.delta_reused < 20:
                assert time.perf_counter() < deadline, plane.stats()
                await asyncio.sleep(0.05)

            def get(path):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{http_port}{path}"
                ) as resp:
                    return resp.read().decode()

            text = await asyncio.to_thread(get, "/metrics")
            types, samples = validate_exposition(text)
            values = {name: value for name, _, value in samples}
            assert types["wql_delta_reuse_fraction"] == "gauge"
            fraction = values["wql_delta_reuse_fraction"]
            assert fraction > 0.8, f"reuse_fraction {fraction}"
            assert values.get("wql_delta_sim_reused", 0) > 0
        finally:
            await server.stop()

    run(scenario())


# endregion
