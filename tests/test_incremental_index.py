"""Incremental device-index maintenance (spatial/tpu_backend.py).

The round-1 design rebuilt the whole device mirror on any mutation —
O(S) Python per flush. The incremental design must keep per-flush cost
O(churn): base segment immutable + tombstones, delta log for adds, and
background compaction that folds them while serving continues. These
tests pin that machinery against the dict-based CPU oracle.
"""

import random
import uuid

import numpy as np
import pytest

from worldql_server_tpu.protocol.types import Replication, Vector3
from worldql_server_tpu.spatial.backend import LocalQuery
from worldql_server_tpu.spatial.cpu_backend import CpuSpatialBackend
from worldql_server_tpu.spatial.quantize import cube_coords_batch
from worldql_server_tpu.spatial.tpu_backend import TpuSpatialBackend

W = "world"


def _peers(n):
    return [uuid.UUID(int=i + 1) for i in range(n)]


def _query(world, pos, sender):
    return LocalQuery(world, pos, sender, Replication.EXCEPT_SELF)


def test_small_mutation_keeps_base_segment():
    """One add after a compacted base must not rebuild the base — it
    lands in the delta segment."""
    b = TpuSpatialBackend(16, compact_threshold=4)
    peers = _peers(40)
    cubes = np.array([[16 * (i % 5 + 1), 16, 16] for i in range(40)])
    b.bulk_add_subscriptions(W, peers, cubes)
    b.flush()
    b.wait_compaction()
    base_dev_before = b._base_bundle["dev"][0]

    extra = uuid.uuid4()
    b.add_subscription(W, extra, Vector3(5, 5, 5))
    b.flush()
    assert b._base_bundle["dev"][0] is base_dev_before  # base untouched
    assert b._delta_live == 1
    assert extra in b.query_cube(W, Vector3(5, 5, 5))


def test_tombstone_is_visible_after_flush():
    b = TpuSpatialBackend(16)
    a, c = uuid.uuid4(), uuid.uuid4()
    pos = Vector3(5, 5, 5)
    b.add_subscription(W, a, pos)
    b.add_subscription(W, c, pos)
    assert b.match_local_batch([_query(W, pos, uuid.uuid4())]) == [[a, c]]

    # force rows into the base so the removal is a base tombstone
    b._compact_sync()
    assert b._base_live == 2 and b._delta_live == 0
    assert b.remove_subscription(W, a, pos)
    assert b._base_dead == 1
    assert b.match_local_batch([_query(W, pos, uuid.uuid4())]) == [[c]]
    assert b.query_cube(W, pos) == {c}


def test_sync_compaction_folds_delta():
    b = TpuSpatialBackend(16, compact_threshold=8)
    peers = _peers(200)
    for i, p in enumerate(peers):
        b.add_subscription(W, p, Vector3(16 * (i % 10), 5, 5))
    b.flush()
    b.wait_compaction()
    assert b.compactions >= 1
    assert b.subscription_count() == 200
    got = b.match_local_batch([_query(W, Vector3(3, 5, 5), uuid.uuid4())])
    want = b.query_cube(W, Vector3(3, 5, 5))
    assert set(got[0]) == want


def test_async_compaction_with_concurrent_mutations():
    """Mutations landing while a compaction is in flight must survive
    the swap: removals of snapshot rows replay onto the new base, adds
    stay in the delta tail."""
    b = TpuSpatialBackend(16, compact_threshold=4)
    cpu = CpuSpatialBackend(16)
    peers = _peers(64)
    for i, p in enumerate(peers):
        pos = Vector3(16 * (i % 8), 5, 5)
        b.add_subscription(W, p, pos)
        cpu.add_subscription(W, p, pos)
    b.flush()  # may start or complete compactions along the way

    # force an in-flight window deterministically
    b._start_compaction() if b._compaction is None else None
    assert b._compaction is not None

    # concurrent mutations: remove some snapshot rows, add new ones
    for i in (0, 8, 16):
        pos = Vector3(16 * (i % 8), 5, 5)
        assert b.remove_subscription(W, peers[i], pos)
        assert cpu.remove_subscription(W, peers[i], pos)
    fresh = [uuid.uuid4() for _ in range(5)]
    for i, p in enumerate(fresh):
        pos = Vector3(16 * i, 200, 5)
        b.add_subscription(W, p, pos)
        cpu.add_subscription(W, p, pos)

    b.wait_compaction()
    assert b._compaction is None

    queries = [
        _query(W, Vector3(16 * i, 5, 5), uuid.uuid4()) for i in range(8)
    ] + [
        _query(W, Vector3(16 * i, 200, 5), uuid.uuid4()) for i in range(5)
    ]
    for got, want in zip(b.match_local_batch(queries),
                         cpu.match_local_batch(queries)):
        assert set(got) == set(want)
    assert b.subscription_count() == cpu.subscription_count()


def test_remove_peer_during_in_flight_compaction():
    b = TpuSpatialBackend(16, compact_threshold=4)
    peers = _peers(20)
    for i, p in enumerate(peers):
        b.add_subscription(W, p, Vector3(16 * (i % 4), 5, 5))
    b.flush()
    if b._compaction is None:
        b._start_compaction()
    assert b.remove_peer(peers[0])
    b.wait_compaction()
    got = b.match_local_batch([_query(W, Vector3(3, 5, 5), uuid.uuid4())])
    assert peers[0] not in got[0]
    assert not b.is_subscribed_any(W, peers[0])


def test_bulk_add_dedupes_and_counts():
    b = TpuSpatialBackend(16)
    peers = _peers(10)
    cubes = np.array([[16, 16, 16]] * 10)
    assert b.bulk_add_subscriptions(W, peers, cubes) == 10
    # duplicates (same peer+cube) are rejected, new cubes accepted
    cubes2 = np.array([[16, 16, 16]] * 5 + [[32, 16, 16]] * 5)
    assert b.bulk_add_subscriptions(W, peers, cubes2) == 5
    assert b.subscription_count() == 15
    # intra-batch duplicates collapse
    p = [uuid.uuid4()] * 3
    assert b.bulk_add_subscriptions(W, p, np.array([[48, 16, 16]] * 3)) == 1


def test_bulk_remove_matches_single_removals():
    b = TpuSpatialBackend(16)
    peers = _peers(12)
    cubes = np.array([[16 * (i % 3 + 1), 16, 16] for i in range(12)])
    b.bulk_add_subscriptions(W, peers, cubes)
    b.flush()
    removed = b.bulk_remove_subscriptions(W, peers[:6], cubes[:6])
    assert removed == 6
    # double-remove and unknown rows are no-ops
    assert b.bulk_remove_subscriptions(W, peers[:6], cubes[:6]) == 0
    assert b.subscription_count() == 6
    got = b.match_local_batch([_query(W, Vector3(20, 10, 10), uuid.uuid4())])
    want = b.query_cube(W, (32, 16, 16))
    assert set(got[0]) == want


def test_bulk_load_goes_straight_to_base():
    """A load far above the compaction threshold must fold directly
    into the base (no delta dict churn)."""
    b = TpuSpatialBackend(16, compact_threshold=8)
    n = 500
    rng = np.random.default_rng(1)
    pos = rng.uniform(-400, 400, (n, 3))
    cubes = cube_coords_batch(pos, 16)
    assert b.bulk_add_subscriptions(W, _peers(n), cubes) == n
    assert b._delta_live == 0
    assert b._base_live == n
    b.flush()
    assert b.device_stats()["capacity"] >= n


def test_reseed_rebuild_preserves_semantics():
    b = TpuSpatialBackend(16)
    peers = _peers(30)
    for i, p in enumerate(peers):
        b.add_subscription(W, p, Vector3(16 * i, 5, 5))
    before = {i: b.query_cube(W, (16 * i if i else 16, 16, 16))
              for i in range(5)}
    seed0 = b._seed
    b._reseed_rebuild()
    assert b._seed == seed0 + 1
    for i in range(5):
        assert b.query_cube(W, (16 * i if i else 16, 16, 16)) == before[i]
    got = b.match_local_batch([_query(W, Vector3(16, 5, 5), uuid.uuid4())])
    assert set(got[0]) == b.query_cube(W, Vector3(16, 5, 5))


def test_churn_property_vs_cpu_with_tiny_threshold():
    """Randomized churn with compaction forced constantly (threshold 8)
    — every flush exercises tombstone scatter, delta rebuild, swap and
    replay. Must stay equivalent to the CPU oracle throughout."""
    rng = random.Random(0xD00D)
    cpu = CpuSpatialBackend(16)
    tpu = TpuSpatialBackend(16, compact_threshold=8)
    peers = _peers(30)
    worlds = ["alpha", "beta"]

    def rand_pos():
        return Vector3(
            rng.uniform(-100, 100), rng.uniform(-100, 100),
            rng.uniform(-100, 100),
        )

    for _round in range(6):
        for _ in range(120):
            op = rng.random()
            w = rng.choice(worlds)
            p = rng.choice(peers)
            if op < 0.55:
                pos = rand_pos()
                assert cpu.add_subscription(w, p, pos) == \
                    tpu.add_subscription(w, p, pos)
            elif op < 0.85:
                pos = rand_pos()
                assert cpu.remove_subscription(w, p, pos) == \
                    tpu.remove_subscription(w, p, pos)
            else:
                assert cpu.remove_peer(p) == tpu.remove_peer(p)
        queries = [
            LocalQuery(
                rng.choice(worlds + ["never"]), rand_pos(),
                rng.choice(peers), rng.choice(list(Replication)),
            )
            for _ in range(80)
        ]
        for i, (c, t) in enumerate(zip(cpu.match_local_batch(queries),
                                       tpu.match_local_batch(queries))):
            assert set(c) == set(t), f"round {_round} query {i}"
        assert tpu.subscription_count() == cpu.subscription_count()
        if _round % 2:
            tpu.wait_compaction()
    assert tpu.compactions > 0


def test_delta_overrun_stays_off_owning_thread():
    """A delta overrun (churn outpacing compaction) must NOT fold
    synchronously on the owning thread — the flush hands the work to
    the background worker and keeps serving from the oversized delta.
    The worker is gated on an event we control, so this is structural,
    not a timing race."""
    import threading

    b = TpuSpatialBackend(16, compact_threshold=4)
    gate = threading.Event()
    real_work = b._compact_work

    def gated_work(snap):
        gate.wait(timeout=30)
        return real_work(snap)

    b._compact_work = gated_work
    peers = _peers(100)
    for i, p in enumerate(peers):
        b.add_subscription(W, p, Vector3(16 * (i % 10), 5, 5))
    assert b._delta_live > b.SYNC_COMPACT_FACTOR * b._compact_threshold()
    b.flush()  # must return with the fold still pending on the worker
    assert b._compaction is not None
    assert not b._compaction["done"].is_set()
    assert b._delta_live == 100  # still serving from the delta
    got = b.match_local_batch([_query(W, Vector3(3, 5, 5), uuid.uuid4())])
    assert set(got[0]) == b.query_cube(W, Vector3(3, 5, 5))
    gate.set()
    b.wait_compaction()
    assert b.compactions >= 1
    assert b.subscription_count() == 100


def test_persistent_compaction_failure_falls_back_to_sync():
    """If the background worker keeps failing AND the delta overran,
    the flush folds synchronously as a last resort (correctness over
    latency) instead of growing the delta forever."""
    b = TpuSpatialBackend(16, compact_threshold=4)

    def broken_work(snap):
        raise RuntimeError("injected device fault")

    b._compact_work = broken_work
    peers = _peers(80)
    for i, p in enumerate(peers):
        b.add_subscription(W, p, Vector3(16 * (i % 8), 5, 5))

    # Each flush either starts a background attempt, swaps in a failure
    # (re-arming the policy), or — once the streak hits the fallback
    # bound — folds synchronously. Drive until the fold happens.
    for _ in range(4 * b.SYNC_FALLBACK_FAILURES):
        if b._compaction is not None:
            b._compaction["done"].wait(timeout=30)
        b.flush()
        if b.compactions:
            break
    assert b._compaction is None
    assert b.compactions == 1
    assert b.compaction_failures == b.SYNC_FALLBACK_FAILURES
    assert b._delta_live == 0 and b._base_live == 80
    assert b._failed_streak == 0
    got = b.match_local_batch([_query(W, Vector3(3, 5, 5), uuid.uuid4())])
    assert set(got[0]) == b.query_cube(W, Vector3(3, 5, 5))


def test_dead_dominated_churn_also_falls_back_to_sync():
    """Resubscribe churn (remove+add pairs) keeps _delta_live flat
    while tombstoned log rows pile up; with a persistently failing
    worker the fallback must gate on the dead overrun too, or the log
    grows without bound."""
    b = TpuSpatialBackend(16, compact_threshold=4)
    b._compact_work = lambda snap: (_ for _ in ()).throw(
        RuntimeError("injected device fault")
    )
    p = _peers(4)
    for i, q in enumerate(p):
        b.add_subscription(W, q, Vector3(16 * i, 5, 5))

    # churn: each round moves every peer to a fresh cube (remove+add),
    # so live count stays 4 while dead log rows accumulate past
    # SYNC_COMPACT_FACTOR * dead_threshold (dead_threshold = 4096 floor
    # is too big for a unit test — shrink it via the class knobs)
    b._compact_threshold_override = 4
    dead_bound = b.SYNC_COMPACT_FACTOR * 4096
    rounds = 0
    while b.compactions == 0 and rounds < 20000:
        y = 16 * (rounds + 1)
        for i, q in enumerate(p):
            assert b.remove_subscription(
                W, q, Vector3(16 * i, 16 * rounds, 5)
            )
            assert b.add_subscription(W, q, Vector3(16 * i, y, 5))
        rounds += 1
        if rounds % 64 == 0:
            if b._compaction is not None:
                b._compaction["done"].wait(timeout=30)
            b.flush()
    assert b.compactions == 1, f"no sync fold after {rounds} rounds"
    assert b._dn - b._delta_live <= dead_bound + 8 * len(p)
    assert b.subscription_count() == 4


def test_wedged_worker_is_abandoned_and_overrun_folds_sync():
    """A worker that HANGS (never sets done) must not block the policy
    forever: an overrun flush abandons it after the stall timeout,
    counts a failure, and — once the streak hits the bound — folds
    synchronously so the log stays bounded."""
    import threading
    import time as time_mod

    b = TpuSpatialBackend(16, compact_threshold=4)
    b.COMPACT_STALL_SECS = 0.01  # instance attr shadows the class knob
    gate = threading.Event()
    b._compact_work = lambda snap: gate.wait(timeout=60)
    peers = _peers(100)
    for i, p in enumerate(peers):
        b.add_subscription(W, p, Vector3(16 * (i % 10), 5, 5))

    folds = 0
    for _ in range(2 * b.SYNC_FALLBACK_FAILURES + 2):
        b.flush()
        if b.compactions:
            folds = b.compactions
            break
        b._dirty = True  # keep the policy step running
        time_mod.sleep(0.03)  # outlive the stall timeout
    gate.set()
    assert folds == 1
    assert b.compaction_failures == b.SYNC_FALLBACK_FAILURES
    assert b._compaction is None
    assert b._delta_live == 0 and b._base_live == 100
    got = b.match_local_batch([_query(W, Vector3(3, 5, 5), uuid.uuid4())])
    assert set(got[0]) == b.query_cube(W, Vector3(3, 5, 5))


def test_wait_compaction_raises_on_wedged_worker():
    """wait_compaction (shutdown path) must never hang: a worker that
    makes no progress within the stall timeout is abandoned and
    surfaced as an error."""
    import threading

    b = TpuSpatialBackend(16, compact_threshold=4)
    b.COMPACT_STALL_SECS = 0.01
    gate = threading.Event()
    b._compact_work = lambda snap: gate.wait(timeout=60)
    for i, p in enumerate(_peers(20)):
        b.add_subscription(W, p, Vector3(16 * (i % 4), 5, 5))
    b.flush()
    assert b._compaction is not None
    with pytest.raises(RuntimeError, match="wedged"):
        b.wait_compaction()
    gate.set()
    assert b._compaction is None
    assert b.compaction_failures == 1


def test_successful_rebuild_resets_failure_streak():
    """A successful base install (e.g. a huge bulk load folding straight
    into the base) proves the path healthy — a stale streak must not
    force the NEXT overrun onto the owning thread."""
    b = TpuSpatialBackend(16, compact_threshold=4)
    b._failed_streak = b.SYNC_FALLBACK_FAILURES
    n = 200  # > SYNC_COMPACT_FACTOR * threshold → direct base fold
    rng = np.random.default_rng(3)
    cubes = cube_coords_batch(rng.uniform(-300, 300, (n, 3)), 16)
    b.bulk_add_subscriptions(W, _peers(n), cubes)
    assert b._base_live == n
    assert b._failed_streak == 0


def test_eviction_storm_reuses_pid_index():
    """remove_peer must not scan the whole base per eviction: the
    pid-sorted view is built once per base epoch and shared by every
    eviction in a storm."""
    b = TpuSpatialBackend(16, compact_threshold=8)
    cpu = CpuSpatialBackend(16)
    n = 600
    peers = _peers(n)
    rng = np.random.default_rng(7)
    pos = rng.uniform(-300, 300, (n, 3))
    cubes = cube_coords_batch(pos, 16)
    b.bulk_add_subscriptions(W, peers, cubes)
    for p, c in zip(peers, cubes):
        cpu.add_subscription(W, p, tuple(int(v) for v in c))
    b.flush()
    b.wait_compaction()
    assert b._base_live == n

    assert b.remove_peer(peers[0]) and cpu.remove_peer(peers[0])
    cache = b._base_pid_order
    assert cache is not None
    for p in peers[1:200]:
        assert b.remove_peer(p) == cpu.remove_peer(p)
    assert b._base_pid_order is cache  # one build served the storm
    # double-eviction is a no-op through the index too
    assert not b.remove_peer(peers[0])
    assert b.query_world(W) == cpu.query_world(W)
    assert b.subscription_count() == cpu.subscription_count()
    queries = [
        _query(W, Vector3(*pos[i]), uuid.uuid4()) for i in range(0, n, 17)
    ]
    for got, want in zip(b.match_local_batch(queries),
                         cpu.match_local_batch(queries)):
        assert set(got) == set(want)


def test_world_level_views_survive_churn():
    b = TpuSpatialBackend(16, compact_threshold=4)
    cpu = CpuSpatialBackend(16)
    peers = _peers(10)
    for i, p in enumerate(peers):
        for j in range(3):
            pos = Vector3(16 * j, 16 * i, 5)
            b.add_subscription(W, p, pos)
            cpu.add_subscription(W, p, pos)
    b.flush()
    for p in peers[:5]:
        b.remove_peer(p)
        cpu.remove_peer(p)
    assert b.query_world(W) == cpu.query_world(W)
    assert b.cube_count(W) == cpu.cube_count(W)
    for p in peers:
        assert b.is_subscribed_any(W, p) == cpu.is_subscribed_any(W, p)


def test_per_world_bulk_loads_fold_to_base():
    """Consecutive per-world bulk calls (each under the single-call
    fold limit) must still route to the base once the delta would
    overrun — the 1M-sub bench pattern — and defer the device upload
    to one flush."""
    import numpy as np

    b = TpuSpatialBackend(cube_size=16)
    rng = np.random.default_rng(5)
    n, n_worlds = 40_000, 8
    cubes = rng.integers(-50, 50, (n, 3)).astype(np.int64) * 16
    peers = [uuid.UUID(int=i + 1) for i in range(n)]
    wids = np.arange(n) * n_worlds // n
    for w in range(n_worlds):
        sel = np.flatnonzero(wids == w)
        b.bulk_add_subscriptions(
            f"w{w}", [peers[i] for i in sel], cubes[sel]
        )
    stats = b.device_stats()
    assert stats["delta_rows"] < n // 4, (
        f"bulk loads left {stats['delta_rows']} rows in the delta log"
    )
    # upload was deferred: nothing on device until the flush
    assert b._base_bundle is None and b._base_stale
    b.flush()
    assert b._base_bundle is not None and not b._base_stale
    assert b.subscription_count() == n
    # and the device answers: pick a subscriber's cube, expect company
    got = b.query_cube("w0", tuple(cubes[0]))
    assert peers[0] in got
