"""CLI entry-point behavior: dotenv loading, git-hash version, and the
port pre-check (main.rs:51, build.rs:4-11, main.rs:73-98 parity)."""

import socket

import pytest

from worldql_server_tpu.__main__ import check_ports, main
from worldql_server_tpu.engine.config import Config
from worldql_server_tpu.utils.dotenv import load_dotenv, parse_dotenv
from worldql_server_tpu.utils.version import full_version, git_short_hash


# region: dotenv


def test_parse_dotenv_dialect():
    text = """
# comment
WQL_WS_PORT=9001
export WQL_HTTP_PORT=9002
QUOTED="hello world"
SINGLE='x=y'
QUOTED_COMMENT="127.0.0.1" # loopback
UNCLOSED="oops
TRAILING=value # comment
EMPTY=
BAD LINE IGNORED
=alsobad
"""
    env = parse_dotenv(text)
    assert env == {
        "WQL_WS_PORT": "9001",
        "WQL_HTTP_PORT": "9002",
        "QUOTED": "hello world",
        "SINGLE": "x=y",
        "QUOTED_COMMENT": "127.0.0.1",
        "TRAILING": "value",
        "EMPTY": "",
    }


def test_load_dotenv_never_overrides(tmp_path, monkeypatch):
    envfile = tmp_path / ".env"
    envfile.write_text("WQL_TEST_A=file\nWQL_TEST_B=file\n")
    monkeypatch.setenv("WQL_TEST_A", "live")
    monkeypatch.delenv("WQL_TEST_B", raising=False)
    assert load_dotenv(str(envfile)) == 1
    import os
    assert os.environ["WQL_TEST_A"] == "live"  # live environment wins
    assert os.environ["WQL_TEST_B"] == "file"
    monkeypatch.delenv("WQL_TEST_B")


def test_load_dotenv_missing_file_is_fine(tmp_path):
    assert load_dotenv(str(tmp_path / "nope.env")) == 0


def test_dotenv_feeds_config(tmp_path, monkeypatch):
    """A .env in the working directory supplies WQL_* fallbacks, the
    same as the reference's dotenv() before Args::parse."""
    import os

    monkeypatch.chdir(tmp_path)
    (tmp_path / ".env").write_text("WQL_SUBSCRIPTION_REGION_CUBE_SIZE=48\n")
    monkeypatch.delenv("WQL_SUBSCRIPTION_REGION_CUBE_SIZE", raising=False)
    load_dotenv()
    try:
        assert Config().sub_region_size == 48
    finally:
        # plain pop, NOT monkeypatch.delenv: delenv would record the
        # leaked value and monkeypatch teardown would RESTORE it,
        # poisoning every later Config() in the session
        os.environ.pop("WQL_SUBSCRIPTION_REGION_CUBE_SIZE", None)


# endregion

# region: version


def test_git_hash_from_env(monkeypatch):
    monkeypatch.setenv("WQL_GIT_HASH", "abcdef1234")
    assert git_short_hash() == "abcdef1"
    assert full_version("0.1.0") == "0.1.0 (abcdef1)"


def test_git_hash_from_checkout(monkeypatch):
    """The package lives inside a git checkout here, so the live
    rev-parse path must produce a short hash."""
    monkeypatch.delenv("WQL_GIT_HASH", raising=False)
    h = git_short_hash()
    assert h is not None and len(h) == 7
    assert int(h, 16) is not None  # hex


# endregion

# region: port pre-check


def make_quiet_config(**kw) -> Config:
    config = Config(store_url="memory://")
    config.http_enabled = False
    config.ws_enabled = False
    config.zmq_enabled = False
    for key, value in kw.items():
        setattr(config, key, value)
    return config


def test_check_ports_free():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        free = s.getsockname()[1]
    config = make_quiet_config(
        ws_enabled=True, ws_host="127.0.0.1", ws_port=free
    )
    assert check_ports(config) is None


@pytest.mark.parametrize("which,flag", [
    ("ws", "--ws-port"),
    ("http", "--http-port"),
    ("zmq_server", "--zmq-server-port"),
])
def test_check_ports_busy_names_the_flag(which, flag):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        s.listen(1)
        busy = s.getsockname()[1]
        enabled = "zmq" if which == "zmq_server" else which
        config = make_quiet_config(**{
            f"{enabled}_enabled": True,
            f"{which}_host": "127.0.0.1",
            f"{which}_port": busy,
        })
        error = check_ports(config)
    assert error is not None and flag in error and str(busy) in error


def test_main_exits_1_on_busy_port(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)  # no stray .env, no sqlite litter
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        s.listen(1)
        busy = s.getsockname()[1]
        rc = main([
            "--store-url", "memory://",
            "--no-http", "--no-zmq",
            "--ws-host", "127.0.0.1", "--ws-port", str(busy),
        ])
    assert rc == 1
    err = capsys.readouterr().err
    assert "--ws-port" in err and "already in use" in err


def test_main_exits_1_on_config_error(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    rc = main(["--store-url", "memory://", "--sub-region-size", "0"])
    assert rc == 1
    assert "config error" in capsys.readouterr().err


def test_version_flag(capsys, monkeypatch):
    monkeypatch.setenv("WQL_GIT_HASH", "feedc0d")
    with pytest.raises(SystemExit) as exc:
        main(["--version"])
    assert exc.value.code == 0
    assert "(feedc0d)" in capsys.readouterr().out


# endregion
