"""Overload control plane (robustness/overload.py, ISSUE 10).

Covers the governor state machine (hysteresis — no flapping at the
threshold boundary), priority-classed admission, per-peer token-bucket
fairness, tick-deadline degradation, entity-update coalescing, the
non-blocking enqueue regression (a slow device collect must not
head-of-line-block ingest), and the --overload off pin (governor off
⇒ today's behavior: no governor object anywhere, no shed counters, no
healthz/metrics surface).
"""

import asyncio
import json
import threading
import time
import urllib.request
import uuid

import pytest

from worldql_server_tpu.engine.config import Config
from worldql_server_tpu.engine.metrics import Metrics
from worldql_server_tpu.engine.peers import Peer, PeerMap
from worldql_server_tpu.engine.server import WorldQLServer
from worldql_server_tpu.engine.ticker import TickBatcher
from worldql_server_tpu.entities import EntityPlane
from worldql_server_tpu.protocol import deserialize_message
from worldql_server_tpu.protocol.types import (
    Entity,
    Instruction,
    Message,
    Vector3,
)
from worldql_server_tpu.robustness import failpoints
from worldql_server_tpu.robustness.overload import (
    OK,
    REJECT,
    SHED_HIGH,
    SHED_LOW,
    OverloadGovernor,
)
from worldql_server_tpu.spatial.cpu_backend import CpuSpatialBackend

from tests.client_util import free_port


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def clean_failpoints():
    failpoints.registry.reset()
    yield
    failpoints.registry.reset()


# region: state machine + hysteresis


def test_deadline_k_busts_escalate_and_degrade_tier():
    gov = OverloadGovernor(
        max_batch=100, tick_budget_ms=10.0, deadline_k=3,
        recover_ticks=5, min_batch=8, metrics=Metrics(),
    )
    gov.note_tick(15.0, 0)
    gov.note_tick(15.0, 0)
    assert gov.state == OK  # 2 busts < K: a slow pair is noise
    gov.note_tick(15.0, 0)
    assert gov.state == SHED_LOW  # K consecutive busts = load
    assert gov.admitted_batch == 50  # tier halved at the K-th bust
    for _ in range(20):
        gov.note_tick(15.0, 0)
    assert gov.admitted_batch == 8  # floor holds
    assert gov.degraded()


def test_single_bust_resets_consecutive_counter():
    gov = OverloadGovernor(
        max_batch=100, tick_budget_ms=10.0, deadline_k=3,
        metrics=Metrics(),
    )
    for _ in range(50):  # alternating never accumulates K
        gov.note_tick(15.0, 0)
        gov.note_tick(15.0, 0)
        gov.note_tick(5.0, 0)
    assert gov.state == OK
    assert gov.transitions == 0


def test_no_flapping_at_threshold_boundary():
    """A signal parked on the enter threshold escalates ONCE and then
    holds: the exit threshold sits at hysteresis (0.8x) below, so
    hovering between them cannot flap the state."""
    gov = OverloadGovernor(max_batch=100, recover_ticks=5, metrics=Metrics())
    gov.note_queue_depth(50)  # 0.5 x max_batch — the SHED_LOW boundary
    assert gov.state == SHED_LOW
    transitions = gov.transitions
    for i in range(100):  # hover between exit (40) and enter (50)
        gov.note_tick(0.0, 45 if i % 2 else 50)
    assert gov.state == SHED_LOW
    assert gov.transitions == transitions  # held, not flapped
    # genuine recovery: below the exit threshold for recover_ticks
    for _ in range(5):
        gov.note_tick(0.0, 10)
    assert gov.state == OK
    assert gov.transitions == transitions + 1


def test_recovery_steps_down_one_state_at_a_time():
    gov = OverloadGovernor(max_batch=100, recover_ticks=3, metrics=Metrics())
    gov.note_queue_depth(250)  # 2.5x -> REJECT
    assert gov.state == REJECT
    seen = []
    for _ in range(12):
        gov.note_tick(0.0, 0)
        seen.append(gov.state)
    # REJECT -> SHED_HIGH -> SHED_LOW -> OK, 3 healthy samples each:
    # full recovery bounded by 3 x recover_ticks
    assert seen[2] == SHED_HIGH and seen[5] == SHED_LOW and seen[8] == OK
    assert gov.state == OK


def test_tier_restores_and_frame_skip_toggles():
    gov = OverloadGovernor(
        max_batch=64, tick_budget_ms=10.0, deadline_k=2,
        recover_ticks=3, min_batch=4, metrics=Metrics(),
    )
    for _ in range(2):
        gov.note_tick(20.0, 0)
    assert gov.admitted_batch == 32 and gov.degraded()
    # while degraded: the entity frame leg sheds every OTHER tick
    assert gov.take_frame_skip() != gov.take_frame_skip()
    for _ in range(6):  # 3 healthy ticks per doubling
        gov.note_tick(1.0, 0)
    assert gov.admitted_batch == 64 and not gov.degraded()
    assert gov.take_frame_skip() is False  # full service: never skip


def test_force_state_failpoint_drives_transitions_and_is_audited():
    gov = OverloadGovernor(max_batch=100, metrics=Metrics())
    for state in (SHED_LOW, SHED_HIGH, REJECT, OK):
        failpoints.registry.set(
            "overload.force_state", f"state:{state}"
        )
        gov.note_idle(0)
        assert gov.state == state
    assert gov.transitions == 4
    # forced transitions are visible in the failpoints audit gauge
    assert failpoints.registry.fired("overload.force_state") >= 4
    failpoints.registry.clear()
    gov.note_idle(0)
    assert gov.state == OK


# endregion

# region: admission classes + token buckets


def test_admission_classes_in_reject():
    gov = OverloadGovernor(max_batch=100, metrics=Metrics())
    failpoints.registry.set("overload.force_state", "state:reject")
    gov.note_idle(0)
    sender = uuid.uuid4()
    # record ops are durable+acked: NEVER shed
    for instr in (
        Instruction.RECORD_CREATE, Instruction.RECORD_READ,
        Instruction.RECORD_UPDATE, Instruction.RECORD_DELETE,
    ):
        assert gov.admit(instr, sender)
    # liveness survives overload
    assert gov.admit(Instruction.HEARTBEAT, sender)
    # entity updates are never rejected — they coalesce in the plane
    assert gov.admit(Instruction.LOCAL_MESSAGE, sender, is_entity=True)
    # locals and globals are refused at the door, counted by class
    assert not gov.admit(Instruction.LOCAL_MESSAGE, sender)
    assert not gov.admit(Instruction.GLOBAL_MESSAGE, sender)
    assert gov.shed == {
        "local": 1, "global": 1,
        "handshake_new": 0, "handshake_resume": 0,
    }
    assert gov.metrics.counters["overload.shed_local"] == 1
    assert gov.metrics.counters["overload.shed_global"] == 1


def test_globals_shed_last():
    gov = OverloadGovernor(max_batch=100, metrics=Metrics())
    sender = uuid.uuid4()
    for state in (SHED_LOW, SHED_HIGH):
        failpoints.registry.set("overload.force_state", f"state:{state}")
        gov.note_idle(0)
        # below REJECT both pub/sub classes still admit at ingest
        # (locals shed drop-oldest at the ticker queue instead)
        assert gov.admit(Instruction.GLOBAL_MESSAGE, sender)
        assert gov.admit(Instruction.LOCAL_MESSAGE, sender)


def test_per_peer_fairness_hostile_peer_cannot_starve_victims():
    """The ISSUE 10 fairness property: a hostile peer offering 100x
    the fair share is clamped to its bucket rate while every victim's
    admitted rate stays within epsilon of its (fair-share) offer; the
    hostile drops are all counted."""
    clock = [0.0]
    gov = OverloadGovernor(
        max_batch=1000, peer_rate=100.0, peer_burst=100,
        metrics=Metrics(), clock=lambda: clock[0],
    )
    hostile = uuid.uuid4()
    victims = [uuid.uuid4() for _ in range(5)]
    offered = {p: 0 for p in [hostile, *victims]}
    admitted = {p: 0 for p in offered}
    for step in range(2000):  # 2 simulated seconds, 1 ms steps
        clock[0] = step * 1e-3
        for _ in range(10):  # hostile: 10_000 msg/s = 100x fair share
            offered[hostile] += 1
            admitted[hostile] += gov.admit(
                Instruction.LOCAL_MESSAGE, hostile
            )
        if step % 10 == 0:  # victims: 100 msg/s = the bucket rate
            for v in victims:
                offered[v] += 1
                admitted[v] += gov.admit(Instruction.LOCAL_MESSAGE, v)
    for v in victims:  # epsilon = 0: a paced victim loses nothing
        assert admitted[v] == offered[v]
    # hostile clamped to rate x duration + burst (small slack)
    assert admitted[hostile] <= 100 * 2 + 100 + 5
    assert gov.rate_limited == offered[hostile] - admitted[hostile]
    assert (
        gov.metrics.counters["peers.rate_limited"] == gov.rate_limited
    )


def test_rate_limited_records_still_admitted():
    gov = OverloadGovernor(
        max_batch=100, peer_rate=1.0, peer_burst=1,
        metrics=Metrics(), clock=lambda: 0.0,
    )
    sender = uuid.uuid4()
    assert gov.admit(Instruction.LOCAL_MESSAGE, sender)  # burns the burst
    assert not gov.admit(Instruction.LOCAL_MESSAGE, sender)
    # the bucket is empty, but record ops are never dropped by it
    assert gov.admit(Instruction.RECORD_CREATE, sender)


def test_sustained_abuse_evicts_exactly_once():
    evicted = []
    gov = OverloadGovernor(
        max_batch=100, peer_rate=10.0, peer_burst=1, evict_after=5,
        on_evict=evicted.append, metrics=Metrics(), clock=lambda: 0.0,
    )
    bad = uuid.uuid4()
    for _ in range(30):
        gov.admit(Instruction.LOCAL_MESSAGE, bad)
    assert evicted == [bad]
    gov.forget_peer(bad)  # the disconnect path resets the bookkeeping
    assert bad not in gov._buckets


# endregion

# region: ticker integration (the head-of-line fix)


class GatedBackend:
    """Dispatch is instant; collect blocks until released — the shape
    of a slow device tick."""

    def __init__(self):
        self.gate = threading.Event()
        self.collects = 0

    def dispatch_local_batch(self, queries):
        return list(queries)

    def collect_local_batch(self, handle):
        self.collects += 1
        assert self.gate.wait(timeout=15), "gate never released"
        return [[] for _ in handle]

    def supports_staged_dispatch(self):
        return False


def _local(i: int) -> tuple:
    from worldql_server_tpu.spatial.backend import LocalQuery

    message = Message(
        instruction=Instruction.LOCAL_MESSAGE, sender_uuid=uuid.uuid4(),
        world_name="world", position=Vector3(1, 1, 1), parameter=f"m{i}",
    )
    query = LocalQuery(
        world="world", position=message.position,
        sender=message.sender_uuid, replication=message.replication,
    )
    return message, query


def test_slow_collect_does_not_block_enqueue():
    """The ISSUE 10 satellite regression: hitting max_batch mid-message
    used to await a full flush from inside the recv path. With a
    governor the enqueue signals the pump and returns — the only thing
    standing between a message and the queue is the admission decision
    (drop-oldest past the cap)."""

    async def scenario():
        backend = GatedBackend()
        gov = OverloadGovernor(max_batch=4, metrics=Metrics())
        ticker = TickBatcher(
            backend, PeerMap(), interval=0.01, max_batch=4, governor=gov,
        )
        ticker.start()
        for i in range(4):  # fills the batch: pump flushes, collect blocks
            await ticker.enqueue(*_local(i))
        for _ in range(200):
            await asyncio.sleep(0.005)
            if backend.collects:
                break
        assert backend.collects == 1  # a flush is wedged in collect
        t0 = time.perf_counter()
        for i in range(4, 24):  # 20 enqueues against the wedged flush
            await ticker.enqueue(*_local(i))
        elapsed = time.perf_counter() - t0
        assert not backend.gate.is_set()  # collect is STILL blocked
        assert elapsed < 0.5, f"enqueue blocked {elapsed:.3f}s"
        # admission capped the queue: 2 x max_batch, oldest dropped
        assert len(ticker._queue) == 8
        assert gov.drop_oldest == 12
        backend.gate.set()
        await ticker.stop()
        # every admitted message flushed exactly once: 24 - 12 dropped
        assert ticker.messages == 12

    run(scenario())


def test_degraded_tier_caps_flush_batch():
    async def scenario():
        backend = GatedBackend()
        backend.gate.set()  # collect never blocks here
        gov = OverloadGovernor(
            max_batch=16, tick_budget_ms=5.0, deadline_k=1, min_batch=4,
            metrics=Metrics(),
        )
        ticker = TickBatcher(
            backend, PeerMap(), interval=60.0, max_batch=16, governor=gov,
        )
        for _ in range(2):  # two busts halve twice: 16 -> 8 -> 4
            gov.note_tick(50.0, 0)
        assert gov.admitted_batch == 4
        for i in range(10):
            await ticker.enqueue(*_local(i))
        await ticker.flush()
        assert ticker.last_batch == 4  # the admitted tier, not the queue
        assert len(ticker._queue) == 6

    run(scenario())


def test_ungoverned_enqueue_keeps_inline_flush():
    """--overload off pin: without a governor, hitting max_batch still
    flushes inline (today's backpressure, byte for byte)."""

    async def scenario():
        backend = CpuSpatialBackend(16)
        peer_map = PeerMap(on_remove=backend.remove_peer)
        inbox = []
        target = uuid.uuid4()

        async def send_raw(data):
            inbox.append(deserialize_message(data))

        await peer_map.insert(Peer(target, "loop", send_raw, "test"))
        backend.add_subscription("world", target, Vector3(1, 1, 1))
        ticker = TickBatcher(backend, peer_map, interval=60.0, max_batch=3)
        # pump NOT started: only the size-triggered inline flush runs
        for i in range(3):
            await ticker.enqueue(*_local(i))
        assert [m.parameter for m in inbox] == ["m0", "m1", "m2"]

    run(scenario())


# endregion

# region: entity-update coalescing


def _entity_msg(sender, eid, pos, world="world"):
    return Message(
        instruction=Instruction.LOCAL_MESSAGE, sender_uuid=sender,
        world_name=world,
        entities=[Entity(uuid=eid, position=pos, world_name=world)],
    )


def test_entity_updates_coalesce_last_write_wins():
    gov = OverloadGovernor(max_batch=100, metrics=Metrics())
    plane = EntityPlane(
        CpuSpatialBackend(16), PeerMap(), cube_size=16, k=2, dt=0.05,
        metrics=gov.metrics, governor=gov,
    )
    owner = uuid.uuid4()
    eid = uuid.uuid4()
    plane.ingest(_entity_msg(owner, eid, Vector3(1, 1, 1)))
    assert plane.entity_count == 1

    failpoints.registry.set("overload.force_state", "state:shed_low")
    gov.note_idle(0)
    assert gov.coalesce_entities()

    # 5 updates for one live entity: 1 stages, 4 coalesce away as
    # column overwrites of the same staged slot
    for i in range(5):
        plane.ingest(_entity_msg(owner, eid, Vector3(10.0 + i, 2, 3)))
    assert plane.staged_count() == 1
    assert plane.coalesced == 4
    assert gov.metrics.counters["overload.coalesced"] == 4
    # audit invariant: offered == applied/staged + coalesced
    assert plane.updates + plane.coalesced == 6

    # the flip folds ONLY the newest value (lossless for the stream)
    plane._drain_pending()
    slot = plane._slot_of[eid]
    assert plane._pos[slot, 0] == pytest.approx(14.0)
    assert plane._touched[slot]
    assert plane.staged_count() == 0

    # a NEW entity registers immediately even while shedding
    eid2 = uuid.uuid4()
    plane.ingest(_entity_msg(owner, eid2, Vector3(5, 5, 5)))
    assert eid2 in plane._slot_of and not plane.is_staged(eid2)


def test_coalesced_update_enforces_ownership_and_removal():
    gov = OverloadGovernor(max_batch=100, metrics=Metrics())
    plane = EntityPlane(
        CpuSpatialBackend(16), PeerMap(), cube_size=16, k=2, dt=0.05,
        metrics=gov.metrics, governor=gov,
    )
    owner, thief = uuid.uuid4(), uuid.uuid4()
    eid = uuid.uuid4()
    plane.ingest(_entity_msg(owner, eid, Vector3(1, 1, 1)))
    failpoints.registry.set("overload.force_state", "state:shed_high")
    gov.note_idle(0)

    # hijacking update never enters the staging columns
    plane.ingest(_entity_msg(thief, eid, Vector3(9, 9, 9)))
    assert plane.staged_count() == 0

    # staged update of a since-removed entity must not resurrect it
    plane.ingest(_entity_msg(owner, eid, Vector3(2, 2, 2)))
    assert plane.is_staged(eid)
    remove = _entity_msg(owner, eid, Vector3(2, 2, 2))
    remove.parameter = "entity.remove"
    plane.ingest(remove)
    assert plane.staged_count() == 0
    plane._drain_pending()
    assert eid not in plane._slot_of


def test_apply_skip_frames_sheds_fanout_but_advances_state():
    plane = EntityPlane(
        CpuSpatialBackend(16), PeerMap(), cube_size=16, k=2, dt=0.05,
        metrics=Metrics(),
    )
    a, b = uuid.uuid4(), uuid.uuid4()
    plane.ingest(_entity_msg(a, uuid.uuid4(), Vector3(1, 1, 1)))
    plane.ingest(_entity_msg(b, uuid.uuid4(), Vector3(2, 1, 1)))
    handle = plane.dispatch_tick()
    result = plane.collect_tick(handle)
    pairs = plane.apply(result, skip_frames=True)
    assert pairs == []  # the delivery leg shed...
    assert plane.frames_skipped == 1  # ...and accounted
    assert plane.applied_ticks == 1  # the tick itself applied
    # next tick with frames on delivers again
    handle = plane.dispatch_tick()
    pairs = plane.apply(plane.collect_tick(handle))
    assert pairs  # co-cube entities of different peers -> frames

    def count_stats():
        return plane.stats()["frames_skipped"]

    assert count_stats() == 1


# endregion

# region: server wiring + observability surface


def overload_config(**overrides) -> Config:
    config = Config(
        store_url="memory://",
        http_enabled=True, http_host="127.0.0.1", http_port=free_port(),
        ws_enabled=False, zmq_enabled=False,
        spatial_backend="cpu", tick_interval=0.02,
        max_batch=64, overload="on",
        supervisor_backoff=0.005,
    )
    for k, v in overrides.items():
        setattr(config, k, v)
    return config


async def _fetch(port, path, accept=None):
    def get():
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            headers={"Accept": accept} if accept else {},
        )
        with urllib.request.urlopen(req) as resp:
            return resp.read().decode()

    return await asyncio.to_thread(get)


def test_server_overload_surface_healthz_and_metrics():
    async def scenario():
        server = WorldQLServer(overload_config())
        await server.start()
        try:
            assert server.governor is not None
            assert server.router.governor is server.governor
            assert server.ticker._governor is server.governor
            # budget derives from the tick interval
            assert server.governor.tick_budget_ms == pytest.approx(20.0)

            health = json.loads(
                await _fetch(server.config.http_port, "/healthz")
            )
            assert health["overload"]["state"] == "ok"
            assert health["status"] == "ok"

            # forced REJECT degrades health and tags the shed counters
            failpoints.registry.set(
                "overload.force_state", "state:reject"
            )
            server.governor.note_idle(0)
            await server.router.handle_message(Message(
                instruction=Instruction.LOCAL_MESSAGE,
                sender_uuid=uuid.uuid4(), world_name="world",
                position=Vector3(1, 1, 1),
            ))
            health = json.loads(
                await _fetch(server.config.http_port, "/healthz")
            )
            assert health["overload"]["state"] == "reject"
            assert health["overload"]["shed_local"] == 1
            assert health["status"] == "degraded"

            prom = await _fetch(server.config.http_port, "/metrics")
            assert "wql_overload_state_level 3" in prom
            assert "wql_overload_shed_local_total 1" in prom
        finally:
            await server.stop()

    run(scenario())


def test_overload_off_is_todays_behavior():
    """The acceptance pin: --overload off (the default) builds NO
    governor — router, ticker and plane take their unchanged paths,
    and the observability surface carries no overload block."""

    async def scenario():
        config = overload_config(overload="off")
        assert Config().overload == "off"  # the default IS off
        server = WorldQLServer(config)
        await server.start()
        try:
            assert server.governor is None
            assert server.router.governor is None
            assert server.ticker._governor is None
            assert server.overload_status() is None
            assert "overload" not in server.metrics.snapshot()["gauges"]
            health = json.loads(
                await _fetch(server.config.http_port, "/healthz")
            )
            assert "overload" not in health
            prom = await _fetch(server.config.http_port, "/metrics")
            assert "wql_overload" not in prom
        finally:
            await server.stop()

    run(scenario())


def test_config_validation():
    with pytest.raises(ValueError, match="overload must be"):
        Config(overload="maybe").validate()
    with pytest.raises(ValueError, match="overload_evict_after requires"):
        Config(overload_evict_after=5).validate()
    with pytest.raises(ValueError, match="max_batch"):
        Config(max_batch=0).validate()
    Config(
        overload="on", overload_peer_rate=100.0, overload_evict_after=5,
    ).validate()


def test_rate_limit_eviction_goes_through_peer_map():
    async def scenario():
        config = overload_config(
            overload_peer_rate=5.0, overload_peer_burst=2,
            overload_evict_after=3,
        )
        server = WorldQLServer(config)
        await server.start()
        try:
            chatty = uuid.uuid4()

            async def send_raw(data):
                pass

            await server.peer_map.insert(
                Peer(chatty, "loop", send_raw, "test")
            )
            for i in range(10):
                await server.router.handle_message(Message(
                    instruction=Instruction.LOCAL_MESSAGE,
                    sender_uuid=chatty, world_name="world",
                    position=Vector3(1, 1, 1), parameter=f"m{i}",
                ))
            for _ in range(100):
                if server.peer_map.get(chatty) is None:
                    break
                await asyncio.sleep(0.01)
            assert server.peer_map.get(chatty) is None
            assert server.metrics.counters[
                "peers.evicted_rate_limited"
            ] == 1
            # forget_peer ran via the removal hook
            assert chatty not in server.governor._buckets
        finally:
            await server.stop()

    run(scenario())


# endregion
