"""Interest-managed fan-out (ISSUE 18): per-recipient delta frames,
LOD cadence tiers, and per-peer bandwidth budgets.

The contract under test, from ``interest/manager.py``'s docstring:
every frame is stamped ``<kind>:<epoch>:<seq>`` with seq contiguous
per peer within an epoch; every loss path lands in ``mark_resync`` and
forces the next frame full under a bumped epoch; LOD deferral and
bandwidth deferral are LOSSLESS (the diff accumulates, nothing is
truncated); and the :class:`ReplayClient` oracle proves it — its
``deltas_refused`` counter staying at zero IS the "no recipient ever
applies a delta against a frame it never got" guarantee.

The churn property at the bottom drives a REAL ``EntityPlane`` (full
wire ingest + device ticks, ``--delta-ticks on`` variant included) and
checks replayed state against the ground-truth visible set — the exact
state the ``--interest off`` stream conveys — every tick.
"""

import itertools
import random
import uuid

import numpy as np
import pytest

from worldql_server_tpu.engine.config import Config
from worldql_server_tpu.interest import (
    InterestManager,
    ReplayClient,
    parse_stamp,
    stamp,
)
from worldql_server_tpu.interest.manager import (
    DEMOTE_KEYFRAME,
    FRAME_CHUNK,
    PARAM_DELTA,
    PARAM_FULL,
    PARAM_FULL_CONT,
)
from worldql_server_tpu.interest.replay import LegacyClient
from worldql_server_tpu.protocol import deserialize_message


# region: stamp grammar


def test_stamp_roundtrip_and_fixed_width():
    s = stamp(PARAM_DELTA, 7, 300)
    assert s == "entity.frame.delta:00000007:0000012c"
    assert parse_stamp(s) == (PARAM_DELTA, 7, 300)
    # fixed width holds across the whole u32 range — that is what lets
    # a cohort template be byte-patched per peer
    assert len(stamp(PARAM_FULL, 0, 0)) == len(stamp(PARAM_FULL, 2**32 - 1, 1))
    assert parse_stamp(stamp(PARAM_FULL_CONT, 1, 2)) == (PARAM_FULL_CONT, 1, 2)


def test_parse_stamp_rejects_unstamped_parameters():
    assert parse_stamp("entity.frame") is None          # legacy frame
    assert parse_stamp("entity.frame.delta") is None    # bare kind
    assert parse_stamp("entity.frame.delta:zz:00") is None
    assert parse_stamp("entity.remove") is None
    assert parse_stamp(None) is None


# endregion

# region: fake plane (the five columns build_pairs reads)


class FakePlane:
    """Just the plane surface the manager touches: live/pos/uuid/world
    columns plus the peer registry. No device, no index."""

    def __init__(self, cap=2048, worlds=("arena",)):
        self._cap = cap
        self._live = np.zeros(cap, bool)
        self._pos = np.zeros((cap, 3), np.float32)
        self._uuid_bytes = np.zeros((cap, 16), np.uint8)
        self._wid = np.full(cap, -1, np.int32)
        self._world_names = list(worlds)
        self._peer_ids: dict[uuid.UUID, int] = {}
        self._peer_uuids: list[uuid.UUID] = []
        self._peer_slots: dict[int, set[int]] = {}
        self._wire = None  # object encode path

    def pid(self, peer: uuid.UUID) -> int:
        p = self._peer_ids.get(peer)
        if p is None:
            p = self._peer_ids[peer] = len(self._peer_uuids)
            self._peer_uuids.append(peer)
        return p

    def put(self, slot, ent, pos, wid=0, owner=None):
        self._live[slot] = True
        self._uuid_bytes[slot] = np.frombuffer(ent.bytes, np.uint8)
        self._pos[slot] = pos
        self._wid[slot] = wid
        if owner is not None:
            self._peer_slots.setdefault(self.pid(owner), set()).add(slot)

    def drop(self, slot):
        self._live[slot] = False


def run_tick(mgr, plane, vis):
    """One manager tick: ``vis`` maps entity slot -> recipient pids."""
    cap = plane._cap
    k = max((len(v) for v in vis.values()), default=1)
    targets = np.full((cap, k), -1, np.int64)
    for slot, pids in vis.items():
        targets[slot, : len(pids)] = pids
    return mgr.build_pairs(plane, plane._pos, targets, cap)


def frames_for(pairs, peer):
    return [m for m, targets in pairs if peer in targets]


def params(pairs):
    return [m.parameter for m, _ in pairs]


# endregion

# region: delta lifecycle on the fake plane


def test_first_contact_quiet_delta_tombstone_resync_flow():
    plane = FakePlane()
    mgr = InterestManager()
    viewer = uuid.uuid4()
    vp = plane.pid(viewer)
    e1, e2 = uuid.uuid4(), uuid.uuid4()
    plane.put(0, e1, (1.0, 0.0, 0.0))
    plane.put(1, e2, (2.0, 0.0, 0.0))
    rc = ReplayClient()

    # tick 1: first contact is a keyframe opening epoch 1 at seq 0
    pairs = run_tick(mgr, plane, {0: [vp], 1: [vp]})
    assert params(pairs) == [stamp(PARAM_FULL, 1, 0)]
    for m in frames_for(pairs, viewer):
        assert rc.apply(m)
    assert rc.snapshot() == {"arena": {
        e1: (1.0, 0.0, 0.0), e2: (2.0, 0.0, 0.0),
    }}

    # tick 2: nothing moved — no frame, no seq consumed
    assert run_tick(mgr, plane, {0: [vp], 1: [vp]}) == []

    # tick 3: one entity moves — a delta carrying only that entity
    plane._pos[0] = (5.0, 0.0, 0.0)
    pairs = run_tick(mgr, plane, {0: [vp], 1: [vp]})
    assert params(pairs) == [stamp(PARAM_DELTA, 1, 1)]
    assert len(pairs[0][0].entities) == 1
    rc.apply(pairs[0][0])
    assert rc.worlds["arena"][e1] == (5.0, 0.0, 0.0)
    assert rc.worlds["arena"][e2] == (2.0, 0.0, 0.0)

    # tick 4: e2 leaves — a delta tombstone deletes it client-side
    pairs = run_tick(mgr, plane, {0: [vp]})
    assert params(pairs) == [stamp(PARAM_DELTA, 1, 2)]
    rc.apply(pairs[0][0])
    assert set(rc.worlds["arena"]) == {e1}

    # loss: the next frame opens epoch 2 with a complete keyframe
    mgr.mark_resync(viewer)
    pairs = run_tick(mgr, plane, {0: [vp]})
    assert params(pairs) == [stamp(PARAM_FULL, 2, 0)]
    rc.apply(pairs[0][0])
    assert rc.snapshot() == {"arena": {e1: (5.0, 0.0, 0.0)}}
    assert rc.stats()["deltas_refused"] == 0
    assert rc.stats()["gaps_seen"] == 0
    assert mgr.stats()["resyncs"] == 1


def test_mark_resync_is_idempotent_and_unknown_peer_safe():
    mgr = InterestManager()
    mgr.mark_resync(uuid.uuid4())          # never seen: no-op
    assert mgr.resyncs == 0
    plane = FakePlane()
    viewer = uuid.uuid4()
    vp = plane.pid(viewer)
    plane.put(0, uuid.uuid4(), (1, 1, 1))
    run_tick(mgr, plane, {0: [vp]})
    mgr.mark_resync(viewer)
    mgr.mark_resync(viewer)                # second is a no-op
    assert mgr.resyncs == 1


def test_world_hop_tombstones_old_world_and_enters_new():
    plane = FakePlane(worlds=("arena", "lobby"))
    mgr = InterestManager()
    viewer = uuid.uuid4()
    vp = plane.pid(viewer)
    ent = uuid.uuid4()
    plane.put(0, ent, (1, 0, 0), wid=0)
    rc = ReplayClient()
    for m, _ in run_tick(mgr, plane, {0: [vp]}):
        rc.apply(m)
    plane._wid[0] = 1
    pairs = run_tick(mgr, plane, {0: [vp]})
    # leave(arena) + enter(lobby), contiguous seqs, both applied
    kinds = [parse_stamp(m.parameter)[0] for m, _ in pairs]
    assert kinds == [PARAM_DELTA, PARAM_DELTA]
    for m, _ in pairs:
        assert rc.apply(m)
    assert rc.snapshot() == {"lobby": {ent: (1.0, 0.0, 0.0)}}


def test_vacated_world_ships_empty_full_clear_marker():
    plane = FakePlane()
    mgr = InterestManager()
    viewer = uuid.uuid4()
    vp = plane.pid(viewer)
    plane.put(0, uuid.uuid4(), (1, 1, 1))
    rc = ReplayClient()
    for m, _ in run_tick(mgr, plane, {0: [vp]}):
        rc.apply(m)
    assert rc.snapshot() != {}
    # the peer's ledger survives a resync even when nothing is visible
    # anymore: the new epoch must CLEAR the stale world
    mgr.mark_resync(viewer)
    plane.drop(0)
    pairs = run_tick(mgr, plane, {})
    assert params(pairs) == [stamp(PARAM_FULL, 2, 0)]
    assert pairs[0][0].entities in (None, [])
    rc.apply(pairs[0][0])
    assert rc.snapshot() == {}


def test_cohort_dedup_shares_template_across_recipients():
    plane = FakePlane()
    mgr = InterestManager()
    a, b = uuid.uuid4(), uuid.uuid4()
    pa, pb = plane.pid(a), plane.pid(b)
    plane.put(0, uuid.uuid4(), (3, 3, 3))
    pairs = run_tick(mgr, plane, {0: [pa, pb]})
    # identical content -> ONE encode, two stamped copies
    assert len(pairs) == 2
    assert mgr.templates_reused == 1
    wires = {m.wire for m, _ in pairs}
    assert len(wires) == 1       # same epoch:seq cursor position too
    ra, rb = ReplayClient(), ReplayClient()
    for m in frames_for(pairs, a):
        ra.apply(m)
    for m in frames_for(pairs, b):
        rb.apply(m)
    assert ra.snapshot() == rb.snapshot() != {}

    # next tick: one mover, still one template for both recipients —
    # and the per-peer stamp patch touches ONLY the stamp bytes
    plane._pos[0] = (4, 4, 4)
    pairs = run_tick(mgr, plane, {0: [pa, pb]})
    assert len(pairs) == 2 and mgr.templates_reused == 2
    for m, targets in pairs:
        (ra if a in targets else rb).apply(m)
    assert ra.snapshot() == rb.snapshot()
    assert ra.stats()["deltas_refused"] == rb.stats()["deltas_refused"] == 0


def test_desynced_cursor_stamps_diverge_but_both_converge():
    plane = FakePlane()
    mgr = InterestManager()
    a, b = uuid.uuid4(), uuid.uuid4()
    pa, pb = plane.pid(a), plane.pid(b)
    plane.put(0, uuid.uuid4(), (3, 3, 3))
    ra, rb = ReplayClient(), ReplayClient()
    # a joins one tick before b: cursors diverge, content still shared
    for m in frames_for(run_tick(mgr, plane, {0: [pa]}), a):
        ra.apply(m)
    plane._pos[0] = (4, 4, 4)
    pairs = run_tick(mgr, plane, {0: [pa, pb]})
    by_peer = {tuple(t): m.parameter for m, t in pairs}
    assert by_peer[(a,)] == stamp(PARAM_DELTA, 1, 1)
    assert by_peer[(b,)] == stamp(PARAM_FULL, 1, 0)
    for m, targets in pairs:
        (ra if a in targets else rb).apply(m)
    assert ra.snapshot() == rb.snapshot()


# endregion

# region: LOD cadence


def test_far_updates_defer_to_cadence_and_never_drop():
    plane = FakePlane()
    mgr = InterestManager(near_radius=10.0, far_every_k=4)
    viewer = uuid.uuid4()
    vp = plane.pid(viewer)
    # the viewer's own entity anchors its subscription center
    plane.put(0, uuid.uuid4(), (0, 0, 0), owner=viewer)
    near, far = uuid.uuid4(), uuid.uuid4()
    plane.put(1, near, (1, 0, 0))
    plane.put(2, far, (100, 0, 0))
    vis = {0: [vp], 1: [vp], 2: [vp]}
    rc = ReplayClient()
    for m, _ in run_tick(mgr, plane, vis):
        rc.apply(m)
    assert rc.worlds["arena"][far] == (100.0, 0.0, 0.0)

    # move BOTH every tick for a full far period: the near entity
    # updates every tick, the far one exactly once — and its one
    # update carries the LATEST position (deferral is lossless)
    far_updates = 0
    for t in range(1, 5):
        plane._pos[1] = (1.0 + t, 0.0, 0.0)
        plane._pos[2] = (100.0 + t, 0.0, 0.0)
        for m, _ in run_tick(mgr, plane, vis):
            before = rc.worlds["arena"].get(far)
            rc.apply(m)
            if rc.worlds["arena"].get(far) != before:
                far_updates += 1
        assert rc.worlds["arena"][near] == (1.0 + t, 0.0, 0.0)
    assert far_updates == 1
    # the one update carried the position AS OF its due tick; the tail
    # move is deferred, not dropped — it ships on the next due tick
    assert rc.worlds["arena"][far] == (103.0, 0.0, 0.0)
    for _ in range(4):
        for m, _ in run_tick(mgr, plane, vis):
            rc.apply(m)
    assert rc.worlds["arena"][far] == (104.0, 0.0, 0.0)
    assert rc.stats()["gaps_seen"] == 0
    st = mgr.stats()
    assert st["near"] >= 1 and st["far"] >= 1


def test_far_departure_defers_to_cadence_then_tombstones():
    plane = FakePlane()
    mgr = InterestManager(near_radius=10.0, far_every_k=4)
    viewer = uuid.uuid4()
    vp = plane.pid(viewer)
    plane.put(0, uuid.uuid4(), (0, 0, 0), owner=viewer)
    far = uuid.uuid4()
    plane.put(1, far, (50, 0, 0))
    rc = ReplayClient()
    for m, _ in run_tick(mgr, plane, {0: [vp], 1: [vp]}):
        rc.apply(m)
    assert far in rc.worlds["arena"]
    plane.drop(1)
    # the leave ships on the next far-due tick, not instantly — but it
    # DOES ship within one full period
    for _ in range(4):
        for m, _ in run_tick(mgr, plane, {0: [vp]}):
            rc.apply(m)
    assert far not in rc.worlds.get("arena", {})
    assert rc.stats()["deltas_refused"] == 0


def test_governor_shed_widens_far_cadence_and_degrades_near():
    mgr = InterestManager(near_radius=10.0, far_every_k=4)
    assert mgr.stats()["far_every_k"] == 4
    mgr.note_governor(2, False)
    assert mgr.stats()["far_every_k"] == 16
    mgr.note_governor(9, True)          # level clamps at 3
    assert mgr.stats()["far_every_k"] == 32

    # degraded tick tier halves the near cadence but stays lossless
    plane = FakePlane()
    viewer = uuid.uuid4()
    vp = plane.pid(viewer)
    ent = uuid.uuid4()
    plane.put(0, ent, (1, 0, 0))
    mgr2 = InterestManager()
    rc = ReplayClient()
    for m, _ in run_tick(mgr2, plane, {0: [vp]}):
        rc.apply(m)
    mgr2.note_governor(0, True)
    sent = 0
    for t in range(1, 5):
        plane._pos[0] = (1.0 + t, 0.0, 0.0)
        pairs = run_tick(mgr2, plane, {0: [vp]})
        sent += len(pairs)
        for m, _ in pairs:
            rc.apply(m)
    assert sent == 2                    # every other tick
    # the tail move rides the next due tick — deferred, never lost
    for m, _ in run_tick(mgr2, plane, {0: [vp]}):
        rc.apply(m)
    assert rc.worlds["arena"][ent] == (5.0, 0.0, 0.0)


# endregion

# region: bandwidth budgets


def bw_manager(rate=100):
    now = [1000.0]
    mgr = InterestManager(bandwidth_bytes=rate, clock=lambda: now[0])
    return mgr, now


def test_unaffordable_tick_defers_whole_and_walks_demote_ladder():
    mgr, now = bw_manager()
    plane = FakePlane()
    viewer = uuid.uuid4()
    vp = plane.pid(viewer)
    ent = uuid.uuid4()
    plane.put(0, ent, (1, 0, 0))
    rc = ReplayClient()
    for m, _ in run_tick(mgr, plane, {0: [vp]}):
        rc.apply(m)
    st = mgr._peers[viewer]

    # drain the bucket; with a frozen clock nothing refills
    st.tokens = 0.0
    plane._pos[0] = (2, 0, 0)
    assert run_tick(mgr, plane, {0: [vp]}) == []       # deferred whole
    assert st.demote == 1 and mgr.deferrals == 1
    assert st.seq == 1                                  # no seq burned
    # at demote=FAR the retry waits for the far cadence; walk ticks
    # (still broke) until the due-tick attempt escalates the ladder
    for _ in range(mgr.far_every_k):
        if st.demote == DEMOTE_KEYFRAME:
            break
        plane._pos[0] += 1.0
        assert run_tick(mgr, plane, {0: [vp]}) == []
        st.tokens = 0.0
    assert st.demote == DEMOTE_KEYFRAME and mgr.bytes_shed == 0

    # refill: the peer is in keyframe-only mode, so the catch-up frame
    # is a FULL on the far cadence — and it carries the latest state
    st.tokens = mgr.bandwidth_burst
    for _ in range(mgr.far_every_k):
        plane._pos[0] = (9, 0, 0)
        for m, _ in run_tick(mgr, plane, {0: [vp]}):
            rc.apply(m)
    assert rc.worlds["arena"][ent] == (9.0, 0.0, 0.0)
    assert rc.stats()["deltas_refused"] == 0
    assert st.demote < DEMOTE_KEYFRAME                  # walked back up


def test_bytes_shed_counts_only_unaffordable_keyframes():
    mgr, now = bw_manager()
    plane = FakePlane()
    viewer = uuid.uuid4()
    vp = plane.pid(viewer)
    plane.put(0, uuid.uuid4(), (1, 0, 0))
    run_tick(mgr, plane, {0: [vp]})
    st = mgr._peers[viewer]
    st.tokens = 0.0
    st.demote = DEMOTE_KEYFRAME
    # keyframe-only + unaffordable on a due tick: the ONE shed point
    shed = 0
    for _ in range(mgr.far_every_k + 1):
        plane._pos[0] += 1.0
        run_tick(mgr, plane, {0: [vp]})
        shed = mgr.bytes_shed
        st.tokens = 0.0
    assert shed > 0
    assert mgr.stats()["bytes_shed"] == shed


def test_zero_budget_means_no_bandwidth_gating():
    plane = FakePlane()
    mgr = InterestManager(bandwidth_bytes=0)
    viewer = uuid.uuid4()
    vp = plane.pid(viewer)
    plane.put(0, uuid.uuid4(), (1, 0, 0))
    for t in range(5):
        plane._pos[0] = (1.0 + t, 0, 0)
        assert len(run_tick(mgr, plane, {0: [vp]})) == 1
    assert mgr.deferrals == 0 and mgr.bytes_shed == 0


# endregion

# region: chunking + oversized deltas


def test_large_keyframe_chunks_full_then_fullc():
    plane = FakePlane(cap=2048)
    mgr = InterestManager()
    viewer = uuid.uuid4()
    vp = plane.pid(viewer)
    n = FRAME_CHUNK + 40
    ents = [uuid.uuid4() for _ in range(n)]
    vis = {}
    for i, e in enumerate(ents):
        plane.put(i, e, (float(i), 0, 0))
        vis[i] = [vp]
    pairs = run_tick(mgr, plane, vis)
    kinds = [parse_stamp(m.parameter)[0] for m, _ in pairs]
    assert kinds == [PARAM_FULL, PARAM_FULL_CONT]
    assert [parse_stamp(m.parameter)[2] for m, _ in pairs] == [0, 1]
    rc = ReplayClient()
    for m, _ in pairs:
        assert rc.apply(m)
    assert len(rc.worlds["arena"]) == n


def test_oversized_delta_escalates_to_epoch_bump_keyframes():
    plane = FakePlane(cap=2048)
    mgr = InterestManager()
    viewer = uuid.uuid4()
    vp = plane.pid(viewer)
    n = FRAME_CHUNK + 40
    vis = {}
    for i in range(n):
        plane.put(i, uuid.uuid4(), (float(i), 0, 0))
        vis[i] = [vp]
    rc = ReplayClient()
    for m, _ in run_tick(mgr, plane, vis):
        rc.apply(m)
    # every entity moves: a >FRAME_CHUNK delta beats no full frame —
    # the manager DECLARES a resync instead of shipping a monster
    plane._pos[:n, 1] = 7.0
    pairs = run_tick(mgr, plane, vis)
    stamps = [parse_stamp(m.parameter) for m, _ in pairs]
    assert stamps[0] == (PARAM_FULL, 2, 0)
    assert all(s[1] == 2 for s in stamps)
    for m, _ in pairs:
        assert rc.apply(m)
    assert all(
        p == (float(i), 7.0, 0.0)
        for i, p in ((i, rc.worlds["arena"][uuid.UUID(
            bytes=plane._uuid_bytes[i].tobytes()
        )]) for i in range(n))
    )
    assert rc.stats()["deltas_refused"] == 0


# endregion

# region: ReplayClient oracle semantics


def _frame(kind, epoch, seq, world="arena", ents=()):
    from worldql_server_tpu.protocol.types import (
        NIL_UUID, Entity, Instruction, Message, Vector3,
    )

    return Message(
        instruction=Instruction.LOCAL_MESSAGE,
        parameter=stamp(kind, epoch, seq),
        sender_uuid=NIL_UUID,
        world_name=world,
        entities=[
            Entity(uuid=e, position=Vector3(*p), world_name=world,
                   flex=b"\x00" if dead else None)
            for e, p, dead in ents
        ],
    )


def test_replay_refuses_deltas_past_a_gap_until_new_epoch():
    rc = ReplayClient()
    e = uuid.uuid4()
    assert rc.apply(_frame(PARAM_FULL, 1, 0, ents=[(e, (1, 1, 1), False)]))
    # seq 1 lost; seq 2 arrives: gap -> desync, frame discarded
    assert not rc.apply(_frame(PARAM_DELTA, 1, 2, ents=[(e, (9, 9, 9), False)]))
    assert rc.gaps_seen == 1 and rc.deltas_refused == 1
    assert rc.worlds["arena"][e] == (1.0, 1.0, 1.0)    # state unpoisoned
    # more same-epoch traffic stays refused
    assert not rc.apply(_frame(PARAM_DELTA, 1, 3))
    assert rc.deltas_refused == 2
    # recovery REQUIRES a new epoch opening with full@0
    assert not rc.apply(_frame(PARAM_DELTA, 2, 0))      # delta can't open
    assert rc.deltas_refused == 3
    assert rc.apply(_frame(PARAM_FULL, 3, 0, ents=[(e, (2, 2, 2), False)]))
    assert not rc.desync
    assert rc.worlds["arena"][e] == (2.0, 2.0, 2.0)


def test_replay_discards_stale_epoch_stragglers():
    rc = ReplayClient()
    assert rc.apply(_frame(PARAM_FULL, 2, 0))
    assert not rc.apply(_frame(PARAM_DELTA, 1, 5))      # closed epoch
    assert rc.discarded == 1 and rc.deltas_refused == 0
    assert not rc.apply(_frame(PARAM_FULL, 2, 0))       # replayed dup
    assert rc.gaps_seen == 1                            # seq 0 != next 1


def test_replay_full_replaces_world_and_fullc_appends():
    rc = ReplayClient()
    a, b, c = uuid.uuid4(), uuid.uuid4(), uuid.uuid4()
    rc.apply(_frame(PARAM_FULL, 1, 0, ents=[(a, (1, 0, 0), False)]))
    rc.apply(_frame(PARAM_DELTA, 1, 1, ents=[(b, (2, 0, 0), False)]))
    # a new full REPLACES the world; its fullc continuation appends
    rc.apply(_frame(PARAM_FULL, 1, 2, ents=[(c, (3, 0, 0), False)]))
    rc.apply(_frame(PARAM_FULL_CONT, 1, 3, ents=[(a, (4, 0, 0), False)]))
    assert rc.snapshot() == {"arena": {
        c: (3.0, 0.0, 0.0), a: (4.0, 0.0, 0.0),
    }}


def test_legacy_client_folds_frames_and_removes():
    from worldql_server_tpu.protocol.types import (
        Entity, Instruction, Message, Vector3,
    )

    lc = LegacyClient()
    e = uuid.uuid4()
    lc.apply(Message(
        instruction=Instruction.LOCAL_MESSAGE, parameter="entity.frame",
        sender_uuid=uuid.uuid4(), world_name="w",
        entities=[Entity(uuid=e, position=Vector3(1, 2, 3), world_name="w")],
    ))
    assert lc.snapshot() == {"w": {e: (1.0, 2.0, 3.0)}}
    lc.apply(Message(
        instruction=Instruction.LOCAL_MESSAGE, parameter="entity.remove",
        sender_uuid=uuid.uuid4(), world_name="w",
        entities=[Entity(uuid=e)],
    ))
    assert lc.snapshot() == {}


# endregion

# region: encode parity (native vs object path) + off-path pin


def _entries(n, wid=0, tomb_every=0):
    out = []
    for i in range(n):
        dead = tomb_every and i % tomb_every == 0
        out.append((
            uuid.uuid4().bytes, wid,
            np.array([i, i * 2, i * 3], np.float32).tobytes(), bool(dead),
        ))
    return sorted(out)


def test_template_native_matches_object_path_byte_for_byte():
    from worldql_server_tpu.protocol import entity_wire

    wire = entity_wire.shared()
    if wire is None or not wire.can_encode_interest:
        pytest.skip("native interest encoder unavailable")
    plane = FakePlane()
    mgr = InterestManager()
    for entries in (_entries(3, tomb_every=2), _entries(1), []):
        plane._wire = wire
        native = mgr._encode_template(plane, PARAM_DELTA, 0, entries)
        plane._wire = None
        obj = mgr._encode_template(plane, PARAM_DELTA, 0, entries)
        assert native == obj
        # and the patched result still deserializes with the stamp
        buf = bytearray(native[0])
        buf[native[1]:native[1] + 8] = b"0000000a"
        buf[native[2]:native[2] + 8] = b"00000005"
        msg = deserialize_message(bytes(buf))
        assert parse_stamp(msg.parameter) == (PARAM_DELTA, 10, 5)


def test_interest_off_is_the_default_and_legacy_frames_unstamped():
    config = Config()
    assert config.interest == "off"
    # the legacy broadcast parameter is NOT a stamped frame: off-path
    # wire bytes carry no sequence fields at all
    from worldql_server_tpu.entities import PARAM_FRAME

    assert parse_stamp(PARAM_FRAME) is None


def test_config_validates_interest_fields():
    def errs(**kw):
        c = Config()
        c.store_url = "memory://"
        for k, v in kw.items():
            setattr(c, k, v)
        try:
            c.validate()
        except ValueError as exc:
            return str(exc)
        return ""

    assert "interest" in errs(interest="sometimes")
    assert "entity_sim" in errs(interest="on", entity_sim=False)
    assert errs(interest="on", entity_sim=True, spatial_backend="tpu",
                tick_interval=0.05) == ""
    assert "lod_near_radius" in errs(lod_near_radius=-1)
    assert "lod_far_every_k" in errs(lod_far_every_k=0)
    assert "peer_bandwidth_bytes" in errs(peer_bandwidth_bytes=-5)


# endregion

# region: churn property on a REAL plane


@pytest.mark.parametrize("delta_ticks", ["off", "on"])
def test_churn_property_replay_matches_ground_truth(delta_ticks):
    """>=200 ticks of joins/leaves/movers/forced drops/cadence changes
    against a real EntityPlane. With LOD off, every tick's diff is
    complete, so each peer's ReplayClient must equal the ground-truth
    visible set — the exact state the ``--interest off`` stream
    conveys — after EVERY tick, and ``deltas_refused`` stays 0."""
    from tests.test_entity_sim import ent_msg, make_plane
    from worldql_server_tpu.protocol.types import Entity, Vector3

    backend, plane = make_plane(k=4)
    if delta_ticks == "on":
        assert backend.configure_delta_ticks("on")
        plane._delta_ticks = True
    mgr = InterestManager()
    plane.interest = mgr

    rng = random.Random(0xC0FFEE)
    peers = [uuid.uuid4() for _ in range(6)]
    owned: dict[uuid.UUID, list] = {p: [] for p in peers}
    clients = {p: ReplayClient() for p in peers}
    ids = itertools.count()

    def spawn(peer):
        e = uuid.uuid4()
        p = Vector3(rng.uniform(0, 60), rng.uniform(0, 60), 0.0)
        plane.ingest(ent_msg(peer, [
            Entity(uuid=e, position=p, world_name="w")
        ]))
        owned[peer].append(e)

    for p in peers[:4]:
        spawn(p)
        spawn(p)

    frames_total = delta_frames = 0
    for t in range(220):
        roll = rng.random()
        if roll < 0.15 and any(owned.values()):
            peer = rng.choice([p for p in peers if owned[p]])
            e = owned[peer].pop(rng.randrange(len(owned[peer])))
            plane.ingest(ent_msg(peer, [Entity(uuid=e)],
                                 parameter="entity.remove"))
        elif roll < 0.35:
            spawn(rng.choice(peers))
        elif roll < 0.45:
            # forced drop / reconnect: any loss path lands here
            victim = rng.choice(peers)
            mgr.mark_resync(victim)
        elif roll < 0.5:
            mgr.note_governor(rng.randrange(3), rng.random() < 0.5)
            mgr.note_governor(0, False)     # back to full cadence
        # movers
        for peer in peers:
            for e in owned[peer]:
                if rng.random() < 0.5:
                    plane.ingest(ent_msg(peer, [Entity(
                        uuid=e,
                        position=Vector3(rng.uniform(0, 60),
                                         rng.uniform(0, 60), 0.0),
                        world_name="w",
                    )]))

        handle = plane.dispatch_tick()
        if handle is None:
            continue
        result = plane.collect_tick(handle)
        cap = result["cap"]
        pairs = plane.apply(result)
        for m, targets in pairs:
            frames_total += 1
            if parse_stamp(m.parameter)[0] == PARAM_DELTA:
                delta_frames += 1
            for peer in targets:
                assert clients[peer].apply(m)

        # ground truth straight off the plane columns: what a
        # --interest off recipient would have been told this tick
        for peer in peers:
            pid = plane._peer_ids.get(peer)
            if pid is None:
                continue
            st = mgr._peers.get(peer)
            expect = {}
            if st is not None:
                for key, (wid, pos_b) in st.state.items():
                    x, y, z = np.frombuffer(pos_b, np.float32)
                    expect[uuid.UUID(bytes=key)] = (
                        float(x), float(y), float(z)
                    )
            got = clients[peer].snapshot().get("w", {})
            assert got == expect, f"tick {t} peer divergence"

    # the oracle's core guarantees, over the whole run
    for rc in clients.values():
        s = rc.stats()
        assert s["deltas_refused"] == 0
        assert s["gaps_seen"] == 0
    assert frames_total > 0 and delta_frames > 0
    assert mgr.resyncs > 0


def test_churn_ledger_equals_visible_set_without_lod():
    """The ledger-vs-targets cross-check the property above leans on:
    with LOD off and no bandwidth cap, the manager's committed state
    for a peer IS the visible set from the tick's targets matrix."""
    from tests.test_entity_sim import ent_msg, make_plane
    from worldql_server_tpu.protocol.types import Entity, Vector3

    backend, plane = make_plane(k=4)
    mgr = InterestManager()
    plane.interest = mgr
    rng = random.Random(7)
    peers = [uuid.uuid4() for _ in range(3)]
    ents = {}
    for p in peers:
        for _ in range(3):
            e = uuid.uuid4()
            ents[e] = p
            plane.ingest(ent_msg(p, [Entity(
                uuid=e, position=Vector3(rng.uniform(0, 30),
                                         rng.uniform(0, 30), 0.0),
                world_name="w",
            )]))
    handle = plane.dispatch_tick()
    result = plane.collect_tick(handle)
    cap = result["cap"]
    targets = np.array(result["targets"])
    plane.apply(result)
    live = plane._live[:cap]
    for peer in peers:
        pid = plane._peer_ids[peer]
        visible_rows = {
            int(r) for r in np.flatnonzero(live)
            if pid in targets[r][targets[r] >= 0]
        }
        st = mgr._peers.get(peer)
        ledger_rows = set()
        if st is not None:
            key_to_row = {
                plane._uuid_bytes[r].tobytes(): int(r)
                for r in np.flatnonzero(live)
            }
            ledger_rows = {key_to_row[k] for k in st.state}
        assert ledger_rows == visible_rows


# endregion
