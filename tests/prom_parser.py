"""Strict Prometheus text-exposition (0.0.4) parser for golden tests.

Implements the subset of the scrape grammar a real scraper enforces on
``render_prometheus()`` output, and fails loudly on anything it would
reject: malformed sample lines, duplicate ``# TYPE`` declarations,
samples without a ``TYPE``, non-monotone ``_bucket`` series,
out-of-order ``le`` bounds, a ``+Inf`` bucket that disagrees with
``_count``, or a histogram missing ``_sum``/``_count``.
"""

from __future__ import annotations

import math
import re

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
SAMPLE_RE = re.compile(
    rf"^(?P<name>{_NAME})(?:\{{(?P<labels>[^}}]*)\}})? (?P<value>\S+)$"
)
LABEL_RE = re.compile(rf'^(?P<k>{_NAME})="(?P<v>[^"]*)"$')
TYPE_RE = re.compile(rf"^# TYPE (?P<name>{_NAME}) (?P<kind>\w+)$")


def _parse_value(raw: str) -> float:
    if raw == "+Inf":
        return math.inf
    if raw == "-Inf":
        return -math.inf
    return float(raw)  # raises on garbage — that's the point


def parse_exposition(text: str):
    """→ (types, samples): ``types`` maps metric name → kind, asserting
    no duplicate TYPE lines; ``samples`` is a list of
    ``(name, labels_dict, value)`` with every line strictly matched."""
    types: dict[str, str] = {}
    samples: list[tuple[str, dict, float]] = []
    assert text.endswith("\n"), "exposition must end with a newline"
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            m = TYPE_RE.match(line)
            assert m is not None, f"malformed comment line: {line!r}"
            name = m.group("name")
            assert name not in types, f"duplicate # TYPE for {name}"
            types[name] = m.group("kind")
            continue
        m = SAMPLE_RE.match(line)
        assert m is not None, f"malformed sample line: {line!r}"
        labels: dict[str, str] = {}
        if m.group("labels"):
            for part in m.group("labels").split(","):
                lm = LABEL_RE.match(part)
                assert lm is not None, f"malformed label in {line!r}"
                assert lm.group("k") not in labels, f"dup label in {line!r}"
                labels[lm.group("k")] = lm.group("v")
        samples.append(
            (m.group("name"), labels, _parse_value(m.group("value")))
        )
    return types, samples


def base_name(sample_name: str, types: dict) -> str:
    """The TYPE-declared metric a sample belongs to (histogram series
    samples carry _bucket/_sum/_count suffixes)."""
    for suffix in ("_bucket", "_sum", "_count"):
        stripped = sample_name.removesuffix(suffix)
        if stripped != sample_name and types.get(stripped) == "histogram":
            return stripped
    return sample_name


def validate_exposition(text: str):
    """Full strict pass; returns (types, samples) for extra assertions.

    * every sample's base metric carries exactly one ``# TYPE``
    * histogram ``le`` bounds strictly ascend and end at ``+Inf``
    * cumulative bucket counts are monotone non-decreasing
    * the ``+Inf`` bucket equals ``_count``
    * every histogram has ``_sum`` and ``_count``
    """
    types, samples = parse_exposition(text)
    by_hist: dict[str, dict] = {}
    for name, labels, value in samples:
        base = base_name(name, types)
        assert base in types, f"sample {name} has no # TYPE"
        if types[base] != "histogram":
            # a gauge legitimately named *_bucket (tick.compaction_bucket)
            # is legal with its own TYPE; only a name that aliases a
            # DECLARED histogram's series would confuse a scraper
            stripped = name.removesuffix("_bucket")
            assert stripped == name or types.get(stripped) != "histogram", (
                f"{name} collides with histogram {stripped}'s series"
            )
            continue
        h = by_hist.setdefault(
            base, {"buckets": [], "sum": None, "count": None}
        )
        if name == base + "_bucket":
            assert set(labels) == {"le"}, f"{name}: bucket needs only le"
            h["buckets"].append((_parse_value(labels["le"]), value))
        elif name == base + "_sum":
            h["sum"] = value
        elif name == base + "_count":
            h["count"] = value
    for base, h in by_hist.items():
        bounds = [le for le, _ in h["buckets"]]
        assert bounds == sorted(bounds), f"{base}: le bounds out of order"
        assert len(set(bounds)) == len(bounds), f"{base}: duplicate le"
        assert bounds and bounds[-1] == math.inf, f"{base}: no +Inf bucket"
        counts = [c for _, c in h["buckets"]]
        assert counts == sorted(counts), (
            f"{base}: non-monotone cumulative bucket counts {counts}"
        )
        assert h["sum"] is not None, f"{base}: missing _sum"
        assert h["count"] is not None, f"{base}: missing _count"
        assert counts[-1] == h["count"], (
            f"{base}: +Inf bucket {counts[-1]} != _count {h['count']}"
        )
    return types, samples
