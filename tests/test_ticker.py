"""Tick-batched LocalMessage routing (engine/ticker.py)."""

import asyncio
import uuid

import pytest

from worldql_server_tpu.engine.config import Config
from worldql_server_tpu.engine.peers import Peer, PeerMap
from worldql_server_tpu.engine.router import Router
from worldql_server_tpu.engine.ticker import TickBatcher
from worldql_server_tpu.protocol import deserialize_message
from worldql_server_tpu.protocol.types import Instruction, Message, Vector3
from worldql_server_tpu.spatial.cpu_backend import CpuSpatialBackend
from worldql_server_tpu.spatial.tpu_backend import TpuSpatialBackend
from worldql_server_tpu.storage.memory_store import MemoryRecordStore


def run(coro):
    return asyncio.run(coro)


class Harness:
    def __init__(self, backend_cls, interval=0.03, max_batch=16_384):
        config = Config()
        self.backend = backend_cls(config.sub_region_size)
        self.store = MemoryRecordStore(config)
        self.peer_map = PeerMap(on_remove=self.backend.remove_peer)
        self.ticker = TickBatcher(
            self.backend, self.peer_map, interval, max_batch=max_batch
        )
        self.router = Router(
            self.peer_map, self.backend, self.store, ticker=self.ticker
        )
        self.inboxes: dict[uuid.UUID, list[Message]] = {}

    async def add_peer(self) -> uuid.UUID:
        peer_uuid = uuid.uuid4()
        inbox: list[Message] = []
        self.inboxes[peer_uuid] = inbox

        async def send_raw(data: bytes) -> None:
            inbox.append(deserialize_message(data))

        await self.peer_map.insert(Peer(peer_uuid, "loopback", send_raw, "test"))
        return peer_uuid

    def locals_for(self, peer_uuid):
        return [
            m for m in self.inboxes[peer_uuid]
            if m.instruction == Instruction.LOCAL_MESSAGE
        ]

    async def subscribe(self, peer, pos):
        await self.router.handle_message(Message(
            instruction=Instruction.AREA_SUBSCRIBE, sender_uuid=peer,
            world_name="world", position=pos,
        ))

    async def local(self, sender, pos, parameter=None):
        await self.router.handle_message(Message(
            instruction=Instruction.LOCAL_MESSAGE, sender_uuid=sender,
            world_name="world", position=pos, parameter=parameter,
        ))


@pytest.mark.parametrize("backend_cls", [CpuSpatialBackend, TpuSpatialBackend])
def test_messages_deliver_on_tick_not_immediately(backend_cls):
    async def scenario():
        h = Harness(backend_cls)
        h.ticker.start()
        a = await h.add_peer()
        b = await h.add_peer()
        pos = Vector3(5, 5, 5)
        await h.subscribe(a, pos)
        await h.subscribe(b, pos)

        await h.local(a, pos, "m1")
        await h.local(a, pos, "m2")
        assert h.locals_for(b) == []  # queued, not resolved yet

        # > interval; generous ceiling for first-use jit compile
        for _ in range(600):
            await asyncio.sleep(0.05)
            if len(h.locals_for(b)) >= 2:
                break
        got = h.locals_for(b)
        assert [m.parameter for m in got] == ["m1", "m2"]  # arrival order
        assert h.locals_for(a) == []  # EXCEPT_SELF
        assert h.ticker.ticks >= 1
        assert h.ticker.messages == 2
        await h.ticker.stop()

    run(scenario())


def test_size_cap_flushes_early():
    async def scenario():
        h = Harness(TpuSpatialBackend, interval=60.0, max_batch=3)
        a = await h.add_peer()
        b = await h.add_peer()
        pos = Vector3(5, 5, 5)
        await h.subscribe(a, pos)
        await h.subscribe(b, pos)

        for i in range(3):  # hits max_batch → immediate flush, no timer
            await h.local(a, pos, f"m{i}")
        assert [m.parameter for m in h.locals_for(b)] == ["m0", "m1", "m2"]

    run(scenario())


def test_stop_drains_queue():
    async def scenario():
        h = Harness(TpuSpatialBackend, interval=60.0)
        h.ticker.start()
        a = await h.add_peer()
        b = await h.add_peer()
        pos = Vector3(5, 5, 5)
        await h.subscribe(a, pos)
        await h.subscribe(b, pos)
        await h.local(a, pos, "pending")
        assert h.locals_for(b) == []
        await h.ticker.stop()  # cancel timer, drain queue
        assert [m.parameter for m in h.locals_for(b)] == ["pending"]

    run(scenario())


def test_mutations_between_ticks_apply_before_flush():
    async def scenario():
        h = Harness(TpuSpatialBackend, interval=60.0)
        a = await h.add_peer()
        b = await h.add_peer()
        pos = Vector3(5, 5, 5)
        await h.subscribe(a, pos)
        await h.local(a, pos, "m")  # b not subscribed yet
        await h.subscribe(b, pos)   # subscribe lands before the flush
        await h.ticker.flush()
        assert [m.parameter for m in h.locals_for(b)] == ["m"]

    run(scenario())


def test_cancel_mid_flush_does_not_redeliver():
    """A stop() landing mid-flush must not double-send (ADVICE r1).
    With batched delivery the window is two-sided: a cancel BEFORE the
    device collect re-queues the whole batch for the drain flush; a
    cancel once delivery has started counts the batch as delivered
    (fast-path frames are already in transport buffers)."""

    async def scenario():
        h = Harness(CpuSpatialBackend, interval=60.0)
        a = await h.add_peer()
        b = await h.add_peer()
        pos = Vector3(5, 5, 5)
        await h.subscribe(a, pos)
        await h.subscribe(b, pos)
        for i in range(4):
            await h.local(a, pos, f"m{i}")

        # Case 1: cancel INSIDE the device collect (before delivery):
        # everything re-queues, nothing was sent.
        real_dispatch = h.backend.dispatch_local_batch

        def dispatch_cancels(queries):
            raise asyncio.CancelledError

        h.backend.dispatch_local_batch = dispatch_cancels
        with pytest.raises(asyncio.CancelledError):
            await h.ticker.flush()
        h.backend.dispatch_local_batch = real_dispatch
        assert h.locals_for(b) == []

        # Case 2: cancel INSIDE the delivery: the batch counts as
        # delivered — the drain flush must not double-send.
        real_deliver = h.peer_map.deliver_batch

        async def deliver_then_cancel(pairs, t_ingress_ns=0):
            await real_deliver(pairs, t_ingress_ns)
            raise asyncio.CancelledError

        h.peer_map.deliver_batch = deliver_then_cancel
        with pytest.raises(asyncio.CancelledError):
            await h.ticker.flush()
        h.peer_map.deliver_batch = real_deliver

        await h.ticker.flush()  # drain: nothing left to deliver twice
        assert [m.parameter for m in h.locals_for(b)] == [
            "m0", "m1", "m2", "m3"
        ]

    run(scenario())


def test_sender_disconnect_before_flush_is_safe():
    async def scenario():
        h = Harness(TpuSpatialBackend, interval=60.0)
        a = await h.add_peer()
        b = await h.add_peer()
        pos = Vector3(5, 5, 5)
        await h.subscribe(a, pos)
        await h.subscribe(b, pos)
        await h.local(a, pos, "m")
        await h.peer_map.remove(b)  # target vanishes pre-flush
        await h.ticker.flush()      # must not raise
        assert h.locals_for(a) == []

    run(scenario())


def test_second_cancel_still_completes_inflight_delivery():
    """ADVICE r5 (engine/ticker.py:130): the protective wait used
    ``suppress(Exception)``, which does not cover CancelledError — a
    SECOND cancellation during the protective await abandoned the wait
    (and a bare ``await deliver_task`` would have cancelled the
    delivery itself). The shield-and-re-await loop must ride out
    repeated cancellations until the in-flight delivery lands."""

    async def scenario():
        h = Harness(CpuSpatialBackend, interval=60.0)
        a = await h.add_peer()
        b = await h.add_peer()
        pos = Vector3(5, 5, 5)
        await h.subscribe(a, pos)
        await h.subscribe(b, pos)
        await h.local(a, pos, "m0")

        started = asyncio.Event()
        release = asyncio.Event()
        real_deliver = h.peer_map.deliver_batch
        delivered: list[int] = []

        async def slow_deliver(pairs, t_ingress_ns=0):
            started.set()
            await release.wait()
            await real_deliver(pairs, t_ingress_ns)
            delivered.append(len(pairs))

        h.peer_map.deliver_batch = slow_deliver
        flush_task = asyncio.create_task(h.ticker.flush())
        await started.wait()

        flush_task.cancel()       # 1st: enters the protective wait
        for _ in range(3):
            await asyncio.sleep(0)
        flush_task.cancel()       # 2nd: lands inside the protective wait
        for _ in range(3):
            await asyncio.sleep(0)
        assert not flush_task.done()  # still guarding the delivery
        release.set()

        with pytest.raises(asyncio.CancelledError):
            await flush_task
        # the in-flight delivery completed exactly once, frames intact
        assert delivered == [1]
        assert [m.parameter for m in h.locals_for(b)] == ["m0"]

    run(scenario())
