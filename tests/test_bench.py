"""bench.py harness smoke test: runs tiny shapes, checks the JSON
contract line (driver protocol: ONE json object on stdout)."""

import json
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def test_bench_contract():
    out = subprocess.run(
        [sys.executable, str(ROOT / "bench.py"), "--subs", "4000",
         "--queries", "256", "--ticks", "6", "--cpu-ticks", "2"],
        capture_output=True, text=True, timeout=600, cwd=ROOT,
        env={"PATH": "/usr/bin:/bin:/usr/local/bin",
             "JAX_PLATFORM_NAME": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"stdout must be one JSON line, got: {lines}"
    rec = json.loads(lines[0])
    assert rec["metric"] == "local_fanout_sustained_tick_ms"
    assert rec["unit"] == "ms"
    assert rec["value"] > 0
    assert "vs_baseline" in rec
    assert "parity check" in out.stderr
