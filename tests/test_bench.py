"""bench.py harness smoke tests: run tiny shapes, check the JSON
contract (driver protocol: one json object per line on stdout)."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent

ENV = {
    "PATH": "/usr/bin:/bin:/usr/local/bin",
    "JAX_PLATFORM_NAME": "cpu",
    # JAX_PLATFORMS (plural) is load-bearing: with libtpu installed but
    # no TPU attached, backend enumeration in the child initializes the
    # TPU plugin anyway and sleeps forever in its device-discovery
    # retry loop — the subprocess then idles out the full 600 s timeout.
    # Restricting the platform set keeps the child CPU-only.
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}


def run_bench(*argv: str) -> tuple[list[dict], str]:
    import pytest

    out = subprocess.run(
        [sys.executable, str(ROOT / "bench.py"), *argv],
        capture_output=True, text=True, timeout=600, cwd=ROOT, env=ENV,
    )
    if out.returncode != 0 and "No module named 'websockets'" in out.stderr:
        pytest.skip("bench config needs websockets (not installed here)")
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    records = [json.loads(l) for l in lines]
    for rec in records:
        assert rec["value"] > 0
        assert rec["unit"] == "ms"
        assert "vs_baseline" in rec
    return records, out.stderr


def test_bench_default_contract():
    """Default invocation: ONE line, the config-5 headline metric —
    the ENGINE-side tick (link excluded; the pair probe shows the
    tunnel hard-serializes) — still carrying the north-star e2e
    p50/p99 latency keys (VERDICT r2 #3, r4 next #2)."""
    records, stderr = run_bench(
        "--subs", "4000", "--queries", "256", "--ticks", "6",
        "--cpu-ticks", "2", "--delivery-clients", "256",
    )
    assert len(records) == 1, records
    rec = records[0]
    assert rec["metric"] == "local_fanout_engine_tick_ms"
    # the sharded-plane delivery variant rode along (ISSUE 6): same
    # workload through worker processes, zero lost frames
    workers = rec["server_delivery"]["workers"]
    assert workers["n_workers"] >= 2
    assert workers["lost_frames"] == 0
    assert workers["deliveries_per_s"] > 0
    assert workers["per_worker_deliveries_per_s"] > 0
    assert workers["workers_for_1m_per_s"] >= 1
    assert sum(w.get("deliveries", 0) for w in workers["per_worker"]) > 0
    assert rec["engine_p99_ms"] >= rec["value"] > 0
    assert rec["sustained_e2e_tick_ms"] > 0
    assert rec["p99_ms_depth1"] > 0
    assert rec["p99_ms_depth2"] > 0
    assert rec["p50_ms_depth1"] <= rec["p99_ms_depth1"]
    assert rec["target_p99_ms"] == 5.0
    # the correctness oracle must have actually run
    assert "parity check" in stderr


def test_bench_config1_ws_echo():
    """Config 1: the real server + WS clients echo loop."""
    records, _ = run_bench("--config", "1", "--quick")
    assert len(records) == 1
    rec = records[0]
    assert rec["metric"] == "ws_echo_delivery_p99_ms"
    assert rec["deliveries_per_s"] > 0
    assert rec["clients"] == 64


def test_bench_config2_random_walk():
    """Config 2: bulk resubscribe churn through compaction warmup —
    the riskiest index path the harness drives."""
    records, stderr = run_bench("--config", "2", "--quick")
    assert len(records) == 1
    rec = records[0]
    assert rec["metric"] == "random_walk_tick_ms"
    assert rec["clients"] == 1000
    assert rec["resubs_per_tick"] > 0
    assert rec["iter_p50_ms"] <= rec["iter_p99_ms"]
    assert rec["measurement"] == "pipelined-depth2-v3"
    assert "warmup" in stderr


def test_bench_config3_knn():
    records, _ = run_bench("--config", "3", "--quick")
    rec = records[0]
    assert rec["metric"] == "knn_tick_ms"
    assert rec["entities"] == 8192
    assert rec["entity_queries_per_s"] > 0


def test_bench_config4_sharded():
    records, _ = run_bench("--config", "4", "--quick")
    rec = records[0]
    assert rec["metric"] == "sharded_worlds_tick_ms"
    assert rec["worlds"] == 8
    assert rec["mesh"] == {"batch": 1, "space": 1}


def test_bench_config6_record_op_durability():
    """Config 6: RecordCreate handler latency per durability mode —
    the BENCH-trajectory fields that track handler p99 with
    durability on (ISSUE 2)."""
    records, stderr = run_bench("--config", "6", "--quick")
    assert len(records) == 1
    rec = records[0]
    assert rec["metric"] == "record_op_handler_p99_ms"
    for mode in ("off", "wal", "sync"):
        assert rec[f"{mode}_p99_ms"] > 0
        assert rec[f"{mode}_p50_ms"] <= rec[f"{mode}_p99_ms"]
    assert rec["value"] == rec["wal_p99_ms"]
    assert rec["ops"] == 300
    assert "durability=wal" in stderr


@pytest.mark.slow   # CI's bench-smoke step runs this path directly
def test_bench_smoke_forces_compacted_collect():
    """--smoke (the CI regression gate for ISSUE 3): config-5 on tiny
    CPU shapes with the on-device result compaction forced on and the
    WS delivery pump skipped. The run itself asserts the compacted
    collect path fired; the JSON carries the fetch counters and the
    pipeline-fill tick recorded outside the percentiles."""
    records, stderr = run_bench("--config", "5", "--smoke")
    assert len(records) == 1
    rec = records[0]
    assert rec["metric"] == "local_fanout_engine_tick_ms"
    assert rec["compact_fetches"] > 0
    assert rec["server_delivery"] is None
    assert rec["first_tick_ms_depth2"] > 0
    assert "smoke:" in stderr and "parity check" in stderr


def test_bench_all_emits_one_line_per_config():
    """--all: nine configs, nine JSON lines, in config order
    (config 7 re-execs with a forced device topology and runs
    standalone)."""
    records, _ = run_bench(
        "--all", "--quick", "--subs", "4000", "--queries", "256",
        "--ticks", "6", "--cpu-ticks", "2",
    )
    assert [rec["config"] for rec in records] == [1, 2, 3, 4, 5, 6, 8, 9, 10]
    assert len({rec["metric"] for rec in records}) == 9


def test_bench_config8_entity_sim():
    """Config 8 (ISSUE 9 + 11): entity-sim workload — columnar
    wire→SoA→device ingest, device kNN tick with incremental H2D, e2e
    frame latency over real ZMQ. --smoke additionally asserts the
    device path AND the native columnar decode fired (both legs),
    churn forced a compaction, and frames were delivered."""
    records, stderr = run_bench("--config", "8", "--smoke")
    assert len(records) == 1
    rec = records[0]
    assert rec["metric"] == "entity_sim_knn_ms"
    block = rec["entity_sim"]
    assert block["updates_per_s"] > 0
    assert block["updates_per_s_sustained"] > 0
    assert block["wire_native"] is True
    assert block["wire_rows"] > 0
    assert block["h2d_scatter"] > 0
    assert block["e2e_wire_rows"] > 0
    assert block["knn_ms"] > 0
    assert block["e2e_p99_ms"] > 0
    assert block["e2e_frames"] > 0
    assert block["frames_native"] > 0
    assert block["compactions"] >= 1
    assert block["sim_retraces_quiet"] == 0
    assert "entity_sim:" in stderr


@pytest.mark.slow   # three real-ZMQ load windows + drains: ~30 s
def test_bench_config9_overload():
    """Config 9 (ISSUE 10): the overload-storm admission workload —
    saturation / 2x / 10x offered-load windows over real ZMQ with the
    governor on. --smoke additionally asserts the saturation storm
    escalated the governor and shed (accounted exactly), the record
    stream landed, and the governor recovered to OK. CI runs the same
    smoke directly in the bench step; this pins the harness shape."""
    records, stderr = run_bench("--config", "9", "--smoke")
    assert len(records) == 1
    rec = records[0]
    assert rec["metric"] == "overload_admitted_at_10x_per_s"
    block = rec["overload"]
    assert block["sustainable_per_s"] > 0
    for name in ("saturation", "2x", "10x"):
        phase = block["phases"][name]
        assert phase["audit_exact"] is True
        assert phase["offered_per_s"] > 0
    sat = block["phases"]["saturation"]
    assert sat["shed_at_ingest"] + sat["drop_oldest"] > 0
    assert sat["governor_peak_level"] >= 1
    assert block["recovered_to_ok_within_ticks"] is not None
    assert "overload:" in stderr


@pytest.mark.slow   # two jax boots + per-mesh compiles: minutes on CPU
def test_bench_config7_sharded_overhead():
    """Config 7 (ISSUE 6 satellite / ROADMAP item 3): the sharded
    backend's 1→N-device scaling curve. In this CPU container the
    bench re-execs itself with 8 virtual host devices; quick mode
    times the 1- and 2-shard meshes against single-device."""
    records, stderr = run_bench(
        "--config", "7", "--quick", "--subs", "4000", "--queries",
        "256", "--ticks", "4",
    )
    assert len(records) == 1
    rec = records[0]
    assert rec["metric"] == "sharded_overhead_tick_ms"
    block = rec["sharded_overhead"]
    assert block["single_device_tick_ms"] > 0
    devices = [p["devices"] for p in block["curve"]]
    assert devices == [1, 2]
    for point in block["curve"]:
        assert point["tick_ms"] > 0 and point["vs_single"] > 0
    assert block["shard_map_pmax_overhead_x"] == block["curve"][0][
        "vs_single"
    ]
    assert "sharded_overhead" in stderr
