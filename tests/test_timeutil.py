"""parse_epoch_millis semantics (reference: utils/time.rs:6-16 — u64 parse)."""

from datetime import datetime, timezone

import pytest

from worldql_server_tpu.utils import parse_epoch_millis


def test_parses_exact_millis():
    ts = parse_epoch_millis("1645000000123")
    assert ts == datetime(2022, 2, 16, 8, 26, 40, 123000, tzinfo=timezone.utc)
    assert ts.microsecond == 123000  # exact, no float drift


@pytest.mark.parametrize(
    "bad", ["", "-1000", " 5 ", "1_000", "1.5", "abc", "+10", str(2**64)]
)
def test_rejects_non_u64(bad):
    with pytest.raises(ValueError):
        parse_epoch_millis(bad)


def test_large_exact():
    # 1-4 us float drift would show here with naive /1000.0 division.
    ts = parse_epoch_millis("35331730553994")
    assert ts.microsecond == 994000
