"""BASELINE benchmark harness — all five load configs (BASELINE.md).

Default (no --config) runs config 5, the north star: batched
LocalMessage fan-out at 1M entities. Prints ONE JSON line on stdout:

  {"metric": "local_fanout_engine_tick_ms", "value": ..., "unit": "ms",
   "vs_baseline": <cpu_p99 / engine_tick>, "engine_p99_ms": ...,
   "sustained_e2e_tick_ms": ..., "p50_ms_depth1": ...,
   "p99_ms_depth1": ..., "p50_ms_depth2": ..., "p99_ms_depth2": ...,
   "target_p99_ms": 5.0}

The headline ``value`` is the ENGINE-side tick — host encode + H2D
enqueue (``dispatch_ms``) + device compute, link excluded: the
concurrency probe (``pair_overlap_ratio``) shows this tunneled chip
hard-serializes independent dispatches, so any wall that includes the
link measures tunnel congestion (~100 ms RTT, several-fold swings),
not the code. The e2e numbers stay alongside: ``sustained_e2e_tick_ms``
(best-of-3 depth-8 pipelined wall) and the p50/p99 keys — per-tick
dispatch→collect wall at depth 1 (unpipelined: the honest request
latency on THIS link) and depth 2 (double buffered). ``vs_baseline``
for config 5 is the CPU reference backend's p99 over the engine tick
(throughput advantage); for the latency-budget configs (1, 2, 3, 4)
it is budget/actual, so > 1.0 means the budget is met.

`--config N` selects a BASELINE config (one JSON line each):
  1  256 WS clients echo loop through the REAL server on the CPU
     backend — correctness oracle + CPU transport baseline
     (metric: end-to-end delivery p99 vs the 5 ms budget)
  2  10k random-walk clients, churn resubscribes + radius broadcast,
     20 tick/s budget on the device backend
  3  100k entities, fully-on-device kNN (k=32) tick, single chip
  4  64 worlds x 10k clients on the mesh-sharded backend
  5  1M-entity Zipf-hotspot fan-out (default)
  6  record-op durability workload: RecordCreate handler latency on
     the SQLite store with durability off / wal / sync (metric:
     wal-mode handler p99; vs_baseline = inline-commit p99 over it)
`--all` runs every config, one JSON line per config, config order.

Diagnostics go to stderr. --quick shrinks every shape for smoke runs.
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import json
import os
import sys
import time
import uuid as uuid_mod
from collections import deque

import numpy as np

from worldql_server_tpu.observability import FlightRecorder, Tracer
from worldql_server_tpu.observability.spans import NULL_TRACE
from worldql_server_tpu.spatial.hashing import next_pow2


TARGET_P99_MS = 5.0  # BASELINE.md: p99 broadcast fan-out < 5 ms
TICK_BUDGET_MS = 50.0  # BASELINE.md: 20 ticks/s


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def emit(rec: dict) -> None:
    print(json.dumps(rec), flush=True)


def pctl(samples_ms, q: float) -> float:
    return float(np.percentile(np.asarray(samples_ms), q))


def chained_slopes_ms(chains: dict, args: tuple, reps_pair: tuple,
                      *, max_reps: int = 4096) -> dict:
    """Per-iteration DEVICE time of one or more jitted chained loops:
    best-of-3 wall at two rep counts (first call per count excluded —
    compile), then the slope. The fixed per-call overhead — link round
    trip, dispatch, D2H of the scalar result — cancels in the
    difference; only the per-iteration device work scales with reps.
    Single timing discipline for EVERY device probe in this file.

    When several chains are passed (the stage-attribution prefixes),
    every sampling sweep times ALL of them round-robin at the same rep
    count, so a link-congestion epoch inflates each chain's sample
    alike and cancels in the stage DIFFERENCES. Timing the chains in
    separate passes put them in different congestion epochs and made
    the per-stage splits swing run to run — up to a zero-by-difference
    artifact on the largest stage (VERDICT r4 weak #2).

    Three hard-won rules on this tunneled backend (all observed):
    * each chain takes a SALT as its first argument, folded into the
      loop-carried state — identical dispatches are served from a
      relay cache in microseconds, so every timed call must differ;
    * the result is FETCHED (``int()``), never just
      ``block_until_ready`` — the axon client's block returns before
      the device finishes; only a D2H read truly synchronizes;
    * if the hi-lo wall delta of the CHEAPEST chain doesn't clear link
      jitter, the rep pair escalates (×4) until it does or hits
      ``max_reps`` — a slope inside the noise floor would otherwise
      clamp to a fake 0.
    """
    import jax.numpy as jnp

    salt_rng = np.random.default_rng(0xC0FFEE)
    jitter_floor_s = 0.08

    def timed_all(reps: int) -> dict:
        for fn in chains.values():
            int(fn(jnp.int32(1), *args, reps))  # compile
        best = {name: float("inf") for name in chains}
        for _ in range(3):
            for name, fn in chains.items():
                salt = jnp.int32(salt_rng.integers(1, 1 << 20))
                t0 = time.perf_counter()
                int(fn(salt, *args, reps))
                best[name] = min(best[name], time.perf_counter() - t0)
        return best

    lo, hi = reps_pair
    t_lo, t_hi = timed_all(lo), timed_all(hi)
    while (min(t_hi[n] - t_lo[n] for n in chains) < jitter_floor_s
           and hi * 4 <= max_reps):
        lo, t_lo = hi, t_hi
        hi *= 4
        t_hi = timed_all(hi)
    return {n: (t_hi[n] - t_lo[n]) / (hi - lo) * 1e3 for n in chains}


def chained_slope_ms(chained, args: tuple, reps_pair: tuple,
                     *, max_reps: int = 4096) -> float:
    """Single-chain convenience wrapper over :func:`chained_slopes_ms`."""
    return chained_slopes_ms(
        {"_": chained}, args, reps_pair, max_reps=max_reps
    )["_"]


# --------------------------------------------------------------------
# shared workload generation (configs 2, 4, 5)
# --------------------------------------------------------------------


#: config-5 crowd model (BASELINE "Zipf hotspot"): cube popularity
ZIPF_S = 1.0
#: physical occupancy bound per 16 m subscription cube — an MMO siege
#: packs a few hundred players into one cube, not tens of thousands;
#: overflow spills down the popularity ranking like a crowd overflowing
#: a plaza. Also the fan-out degree bound (K = next_pow2 of max run).
OCCUPANCY_CAP = 256

_zipf_stats: dict = {}


def make_positions(rng: np.random.Generator, n: int) -> np.ndarray:
    """Zipf(s=ZIPF_S)-popularity crowd over subscription cubes: cube
    rank r draws mass ∝ 1/r^s, occupancy capped at OCCUPANCY_CAP with
    waterfill spill to the next ranks. Positions are uniform WITHIN
    each entity's cube. This is the distribution the two-tier gather's
    overflow budget was built for — the uniform-core model it replaces
    (5% of entities in a ±40 box) concentrated orders of magnitude
    less (VERDICT r4 weak #4). Stats of the LAST build are published
    via ``_zipf_stats``."""
    span, cube = 800.0, 16.0
    cells_axis = int(span * 2 / cube)              # 100 per axis
    n_ranked = min(max(n // 4, 1024), cells_axis ** 3)
    # ranked cube list: a shuffled slice of the grid, so popularity is
    # spatially scattered (hotspots are towns, not one mega-blob)
    cell_ids = rng.permutation(cells_axis ** 3)[:n_ranked]
    p = 1.0 / np.arange(1, n_ranked + 1, dtype=np.float64) ** ZIPF_S
    counts = rng.multinomial(n, p / p.sum())
    # waterfill the over-cap excess down the ranking
    excess = int(np.maximum(counts - OCCUPANCY_CAP, 0).sum())
    counts = np.minimum(counts, OCCUPANCY_CAP)
    if excess:
        free = OCCUPANCY_CAP - counts
        take = np.minimum(free, np.maximum(
            excess - (np.cumsum(free) - free), 0
        ))
        counts += take
        assert int(counts.sum()) == n, "waterfill must conserve entities"
    _zipf_stats.update(
        zipf_s=ZIPF_S,
        occupancy_cap=OCCUPANCY_CAP,
        max_cube_occupancy=int(counts.max()),
        occupied_cubes=int((counts > 0).sum()),
        top10_occupancy=[int(c) for c in np.sort(counts)[::-1][:10]],
    )
    cid = np.repeat(cell_ids, counts)
    ix = cid % cells_axis
    iy = (cid // cells_axis) % cells_axis
    iz = cid // (cells_axis * cells_axis)
    corners = np.stack([ix, iy, iz], axis=1) * cube - span
    return corners + rng.uniform(0.0, cube, (n, 3))


def build_index(backend, rng: np.random.Generator, n_subs: int, n_worlds: int):
    from worldql_server_tpu.spatial.quantize import cube_coords_batch

    positions = make_positions(rng, n_subs)
    cubes = cube_coords_batch(positions, backend.cube_size)
    peers = [uuid_mod.UUID(int=i + 1) for i in range(n_subs)]
    world_ids = np.arange(n_subs) * n_worlds // n_subs
    t0 = time.perf_counter()
    for w in range(n_worlds):
        sel = world_ids == w
        backend.bulk_add_subscriptions(
            f"world_{w}", [peers[i] for i in np.flatnonzero(sel)], cubes[sel]
        )
    log(f"index build: {n_subs} subs in {time.perf_counter() - t0:.1f}s")
    return peers, positions, world_ids


def make_query_batch(rng, sub_positions, sub_world_ids, m: int):
    """Queries model entities broadcasting at their own positions: each
    draws a random subscriber and speaks from its cube (20% from a
    fresh random point — mostly-miss traffic)."""
    n_subs = len(sub_positions)
    senders = rng.integers(0, n_subs, m)
    world_ids = sub_world_ids[senders].astype(np.int32)
    positions = sub_positions[senders].copy()
    miss = rng.random(m) < 0.2
    positions[miss] = make_positions(rng, int(miss.sum()))
    return world_ids, positions, senders.astype(np.int32), np.zeros(m, np.int8)


def _force(result) -> int:
    """Materialize a CSR result triple on host (full fetch — warmups
    and paths that need the whole flat array); returns total fan-out."""
    counts, flat, total = result
    np.asarray(counts)
    np.asarray(flat)
    return int(total)


def _collect_compact(backend, result) -> int:
    """Materialize a CSR result the way the server's collect does
    (ISSUE 3): total → counts → on-device pack of the lanes actually
    owed, full fetch only as the fallback — so the timed D2H scales
    with the tick's real fan-out, not the capacity tier. Returns the
    total fan-out."""
    counts, flat, total = result
    total = int(total)
    t_cap = flat.shape[0]
    if total > t_cap:
        return total     # overflow — caller retries with a bigger cap
    np.asarray(counts)
    if backend._compact_fetch(counts, flat, total, t_cap) is None:
        np.asarray(flat)
    return total


def run_pipelined(backend, batches, csr_cap: int, depth: int, tracer=None):
    """Drive the fan-out engine at a fixed pipeline depth.

    Returns ``(per_tick_latency_ms, sustained_ms, total_fanout)`` where
    latency is each tick's dispatch→collect wall time (the fan-out
    latency a client observes) and sustained is wall/ticks (the
    throughput figure). depth=1 is the unpipelined request latency;
    deeper overlaps transfer and compute of adjacent ticks. The
    collect path is the server's compacted fetch (_collect_compact).

    With an observability ``tracer``, each tick records a span trace
    (dispatch / collect stages) into the tracer's sink — the same
    flight-recorder substrate the server runs, so a 207 s outlier in a
    BENCH run now leaves its own span tree behind (ISSUE 5).
    """
    lat, inflight, total_fanout = [], deque(), 0
    overflow = 0
    # The device buffer is the next power-of-two tier above csr_cap —
    # results are intact (and exact) up to that, so only count a real
    # truncation/overflow-tier sentinel as overflow.
    t_cap = next_pow2(csr_cap)
    t_start = time.perf_counter()

    def drain():
        nonlocal total_fanout, overflow
        t0, trace, (m, result) = inflight.popleft()
        with trace.span("tick.collect"):
            n = _collect_compact(backend, result)
        if n > t_cap:
            overflow += 1
        else:
            total_fanout += n
        trace.tag(fanout=n, overflowed=n > t_cap)
        trace.finish()
        lat.append((time.perf_counter() - t0) * 1e3)

    for i, b in enumerate(batches):
        trace = (
            tracer.begin("tick", tick=i, depth=depth)
            if tracer is not None else NULL_TRACE
        )
        t0 = time.perf_counter()
        with trace.span("tick.dispatch"):
            handle = backend.match_arrays_async(*b, csr_cap=csr_cap)
        inflight.append((t0, trace, handle))
        if len(inflight) >= depth:
            drain()
    while inflight:
        drain()
    sustained = (time.perf_counter() - t_start) / len(batches) * 1e3
    return np.asarray(lat), sustained, total_fanout, overflow


def steady(lat, depth: int):
    """Steady-state latency samples: at depth > 1 the FIRST drained
    tick's wall clock includes the pipeline fill (depth-1 extra
    dispatch walls) plus any first-use-at-this-shape stall — BENCH_r05
    recorded a 207 s first depth-2 tick against a ~1 s steady state
    (see CHANGES.md). It is reported separately, never inside a
    percentile."""
    return lat[1:] if depth > 1 and len(lat) > 1 else lat


def run_pipelined_adaptive(backend, batches, csr_cap: int, depth: int,
                           tracer=None):
    """run_pipelined with capacity retry: the CSR result buffer is the
    dominant device→host payload, so it is sized to the workload's real
    fan-out rather than a worst-case bound — on overflow (total >
    csr_cap, tail dropped on device) the run repeats with double the
    capacity. Returns (lat, sustained, total_fanout, csr_cap)."""
    while True:
        lat, sustained, total, overflow = run_pipelined(
            backend, batches, csr_cap, depth, tracer=tracer
        )
        if not overflow:
            return lat, sustained, total, csr_cap
        csr_cap *= 2
        log(f"csr overflow x{overflow} — retrying with csr_cap={csr_cap}")
        # compile the new shape tier OUTSIDE the timed retry
        _force(backend.match_arrays_async(*batches[0], csr_cap=csr_cap)[1])


# --------------------------------------------------------------------
# real-server delivery phase (part of config 5's JSON): ticker →
# router → PeerMap → live WS sockets, counted at the clients
# --------------------------------------------------------------------


class _RawWs:
    """Minimal RFC 6455 client over raw asyncio streams, for the
    delivery benchmark's counting clients: the measurement must stress
    the SERVER's pump, so the client side cannot afford a full
    WebSocket library parse per frame (~25 µs — it was the bottleneck
    and capped the observed rate at ~10K/s). Sends use a zero mask key
    (legal per RFC: masked bit set, key 0 ⇒ payload XOR is identity),
    so a connection's broadcast frame serializes exactly once."""

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer

    @classmethod
    async def connect(cls, port: int) -> "_RawWs":
        import base64
        import os

        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        key = base64.b64encode(os.urandom(16)).decode()
        writer.write(
            (f"GET / HTTP/1.1\r\nHost: 127.0.0.1:{port}\r\n"
             "Upgrade: websocket\r\nConnection: Upgrade\r\n"
             f"Sec-WebSocket-Key: {key}\r\n"
             "Sec-WebSocket-Version: 13\r\n\r\n").encode()
        )
        await writer.drain()
        status = await reader.readuntil(b"\r\n\r\n")
        if b" 101 " not in status.split(b"\r\n", 1)[0]:
            raise ConnectionError(f"upgrade refused: {status[:80]!r}")
        return cls(reader, writer)

    async def recv_frame(self) -> tuple[int, bytes]:
        """→ (opcode, payload). Server frames are unmasked."""
        h = await self.reader.readexactly(2)
        ln = h[1] & 0x7F
        if ln == 126:
            ln = int.from_bytes(await self.reader.readexactly(2), "big")
        elif ln == 127:
            ln = int.from_bytes(await self.reader.readexactly(8), "big")
        return h[0] & 0x0F, await self.reader.readexactly(ln)

    @staticmethod
    def frame(payload: bytes, opcode: int = 0x2) -> bytes:
        """Complete client→server frame (FIN, zero mask)."""
        n = len(payload)
        if n < 126:
            head = bytes([0x80 | opcode, 0x80 | n])
        elif n < 1 << 16:
            head = bytes([0x80 | opcode, 0x80 | 126]) + n.to_bytes(2, "big")
        else:
            head = bytes([0x80 | opcode, 0x80 | 127]) + n.to_bytes(8, "big")
        return head + b"\x00\x00\x00\x00" + payload

    def send_binary(self, payload: bytes) -> None:
        self.writer.write(self.frame(payload))

    async def close(self) -> None:
        try:
            self.writer.write(self.frame(b"\x03\xe8", opcode=0x8))
            self.writer.close()
        except Exception:
            pass


def _delivery_client_main(port, n_conns, group_base, group, rounds,
                          round_interval, out_q, barrier, done_barrier):
    """One client process: ``n_conns`` live WS connections, co-located
    in cubes of ``group`` peers. Every connection broadcasts once per
    round; every LOCAL_MESSAGE frame any connection receives is counted
    (instruction peeked from the raw frame — no full parse). Reports
    (sent, received, recv_elapsed_s) where recv_elapsed runs from the
    barrier to the LAST delivery — the honest pump window even when
    the server saturates."""
    import asyncio
    import time

    async def run():
        from worldql_server_tpu.protocol import (
            Instruction, Message, deserialize_message, serialize_message,
        )
        from worldql_server_tpu.protocol.types import Replication, Vector3
        import uuid as uuid_mod

        sem = asyncio.Semaphore(64)

        async def connect_one(i):
            async with sem:
                c = await _RawWs.connect(port)
                # server-assigned-uuid handshake (websocket.rs:51-87)
                op, payload = await c.recv_frame()
                handshake = deserialize_message(payload)
                assert handshake.instruction == Instruction.HANDSHAKE
                my_uuid = uuid_mod.UUID(handshake.parameter)
                gid = group_base + i // group
                pos = Vector3(100.0 * gid, 5.0, 5.0)
                c.send_binary(serialize_message(Message(
                    instruction=Instruction.HANDSHAKE,
                    sender_uuid=my_uuid,
                )))
                c.send_binary(serialize_message(Message(
                    instruction=Instruction.AREA_SUBSCRIBE,
                    world_name="bench", position=pos,
                    sender_uuid=my_uuid,
                )))
                await c.writer.drain()
                return c, my_uuid, gid

        clients = await asyncio.gather(
            *(connect_one(i) for i in range(n_conns))
        )
        state = {"count": 0, "last": 0.0}

        async def drain(c: _RawWs):
            """Chunked frame counter: between the barriers the ONLY
            binary frames the server sends are the LocalMessage
            fan-out (connect/disconnect storms happen outside the
            measured window), so counting opcode-0x2 frames measures
            deliveries without paying any parse. Chunked reads +
            manual walk keep the client at well under 1 µs/frame —
            on this single-core machine every client cycle is stolen
            from the server under test."""
            reader = c.reader
            buf = b""
            need_skip = 0       # oversized-frame payload left to skip
            try:
                while True:
                    chunk = await reader.read(1 << 16)
                    if not chunk:
                        return
                    buf += chunk
                    pos = 0
                    n = len(buf)
                    counted = 0
                    while True:
                        if need_skip:
                            skip = min(need_skip, n - pos)
                            pos += skip
                            need_skip -= skip
                            if need_skip:
                                break
                        if pos + 2 > n:
                            break
                        b0, b1 = buf[pos], buf[pos + 1]
                        ln = b1 & 0x7F
                        head = 2
                        if ln == 126:
                            if pos + 4 > n:
                                break
                            ln = int.from_bytes(buf[pos + 2:pos + 4], "big")
                            head = 4
                        elif ln == 127:
                            if pos + 10 > n:
                                break
                            ln = int.from_bytes(buf[pos + 2:pos + 10], "big")
                            head = 10
                        op = b0 & 0x0F
                        if ln > (1 << 16):
                            # larger than a read chunk: count and
                            # stream-skip (control frames are <= 125 B
                            # by RFC, so never take this path)
                            if op == 0x2:
                                counted += 1
                            pos += head
                            need_skip = ln
                            continue
                        if pos + head + ln > n:
                            break   # wait for the rest of the frame
                        if op == 0x2:
                            counted += 1
                        elif op == 0x9:
                            # pong MUST echo the ping payload (RFC 6455
                            # §5.5.3) or the server's keepalive treats
                            # the connection as dead after ~40 s
                            c.writer.write(_RawWs.frame(
                                buf[pos + head:pos + head + ln],
                                opcode=0xA,
                            ))
                        elif op == 0x8:   # close
                            return
                        pos += head + ln
                    buf = buf[pos:]
                    if counted:
                        state["count"] += counted
                        state["last"] = time.perf_counter()
            except Exception:
                pass

        drains = [asyncio.create_task(drain(c)) for c, _, _ in clients]
        # each connection's broadcast frame, fully framed, built once
        frames = [
            _RawWs.frame(serialize_message(Message(
                instruction=Instruction.LOCAL_MESSAGE,
                world_name="bench",
                position=Vector3(100.0 * gid, 5.0, 5.0),
                replication=Replication.EXCEPT_SELF,
                sender_uuid=my_uuid,
            )))
            for _, my_uuid, gid in clients
        ]

        # quiesce before the barrier: the connection storm's
        # PeerConnect broadcasts (O(n²) frames) must fully drain, or
        # their tail is counted as deliveries (observed: +66%)
        quiet = 0
        while quiet < 10:
            before = state["count"]
            await asyncio.sleep(0.1)
            quiet = quiet + 1 if state["count"] == before else 0
        state["count"] = 0
        await asyncio.to_thread(barrier.wait)
        t0 = time.perf_counter()
        state["last"] = t0
        sent = 0
        for r in range(rounds):
            for (c, _, _), data in zip(clients, frames):
                c.writer.write(data)
            for c, _, _ in clients:
                await c.writer.drain()
            sent += len(clients)
            pace = t0 + (r + 1) * round_interval - time.perf_counter()
            if pace > 0:
                await asyncio.sleep(pace)
        # wait for the delivery tail: done the moment the full expected
        # count lands (groups never span processes, so this process
        # knows its own total), else when the count stops moving for
        # 2 s — a warm server can pause >0.5 s mid-flush (GC, tick
        # stalls), and a short settle window mistook that pause for
        # the end of the tail (observed: 85% delivery on a re-run in
        # the same interpreter vs 100% fresh)
        expected_here = len(clients) * (group - 1) * rounds
        settled = 0
        while settled < 20 and state["count"] < expected_here:
            before = state["count"]
            await asyncio.sleep(0.1)
            settled = settled + 1 if state["count"] == before else 0
        out_q.put((sent, state["count"], state["last"] - t0))
        # hold the connections until EVERY process has reported: an
        # early close floods the server with PeerDisconnect broadcast
        # storms that stall the other processes' still-running
        # measurement (observed as a cascading early-settle)
        await asyncio.to_thread(done_barrier.wait)
        for d in drains:
            d.cancel()
        for c, _, _ in clients:
            await c.close()

    asyncio.run(run())


def bench_delivery(args, *, delivery_workers: int = 0,
                   n_procs: int = 2, conns_per_proc: int | None = None,
                   ) -> dict:
    """Drive the REAL server's full delivery path at config-5 message
    rates: N live WS peers in co-located groups, every peer
    broadcasting per round, resolution through the tick batcher and
    delivery through PeerMap.deliver_batch's sync fast path — or,
    with ``delivery_workers`` > 0, through the sharded delivery plane
    (shared-memory rings + sender worker processes, ISSUE 6). The
    metric is deliveries/s observed at the client side of the sockets
    — the number the engine's queries/s has to be multiplied down by
    until this path keeps up (VERDICT r4 weak #3)."""
    import asyncio
    import multiprocessing as mp

    # one client process per ~512 connections: this sandbox is a
    # single core, so every client process cycle competes with the
    # server under test — fewer, leaner processes measure more server
    if conns_per_proc is None:
        conns_per_proc = 64 if args.quick else 512
    group = 8
    rounds = 20 if args.quick else 100
    round_interval = 0.05          # every peer speaks at 20 Hz
    n_clients = n_procs * conns_per_proc

    async def scenario():
        from tests.client_util import free_port
        from worldql_server_tpu.engine.config import Config
        from worldql_server_tpu.engine.server import WorldQLServer

        config = Config()
        config.store_url = "memory://"
        config.ws_port = free_port()
        config.http_enabled = False
        config.zmq_enabled = False
        config.spatial_backend = "cpu"
        config.tick_interval = 0.05
        config.delivery_workers = delivery_workers
        # one tick's worth of frames per shard at peak, with headroom
        config.delivery_ring_bytes = 32 * 1024 * 1024
        server = WorldQLServer(config)
        await server.start()
        ctx = mp.get_context("spawn")
        barrier = ctx.Barrier(n_procs + 1)
        done_barrier = ctx.Barrier(n_procs)
        out_q = ctx.Queue()
        procs = [
            ctx.Process(
                target=_delivery_client_main,
                args=(config.ws_port, conns_per_proc,
                      p * (conns_per_proc // group), group, rounds,
                      round_interval, out_q, barrier, done_barrier),
                daemon=True,
            )
            for p in range(n_procs)
        ]
        # per-core efficiency (ROADMAP item 1): deliveries ÷ CPU-seconds
        # actually burned by the server-side processes (this process +
        # sender workers) over the measured window — the same
        # /proc-based accounting behind the router's live
        # deliveries_per_s_per_core gauge, so the gate floor and the
        # fleet gauge speak one unit
        from worldql_server_tpu.cluster.federation import _proc_cpu_s

        def server_cpu_s() -> float:
            total = _proc_cpu_s(os.getpid())
            plane_ = server.delivery_plane
            if plane_ is not None:
                for shard in plane_._shards:
                    if shard.proc is not None and shard.proc.pid:
                        total += _proc_cpu_s(shard.proc.pid)
            return total

        try:
            for p in procs:
                p.start()
            # the barrier releases once every client is connected and
            # subscribed; connection-storm traffic (PeerConnect
            # broadcasts) happens before it and is not counted. A dead
            # child would strand the barrier — bounded wait + liveness
            # check instead of hanging the whole bench.
            await asyncio.to_thread(barrier.wait, 120)
            cpu0 = server_cpu_s()
            results = [
                await asyncio.to_thread(out_q.get, True, 180)
                for _ in procs
            ]
            cpu_used_s = max(server_cpu_s() - cpu0, 0.0)
            for p in procs:
                p.join(timeout=30)
            ticker = server.ticker
            plane = server.delivery_plane
            plane_stats = None
            if plane is not None:
                await asyncio.sleep(0.4)  # one worker-stats interval
                plane_stats = {
                    "plane": plane.stats(),
                    "per_worker": [
                        plane.worker_stats(i)
                        for i in range(delivery_workers)
                    ],
                }
            # frame clock (ISSUE 7): dispatch-stamp → socket-write-
            # complete, closed in the worker for the sharded plane and
            # at batch completion for the in-process pump — the honest
            # p99-fan-out number the 5 ms SLO is quoted against
            lat = server.metrics.snapshot()["latency"]
            e2e = {
                "frame": lat.get("frame.e2e_ms"),
                "delivery": lat.get("delivery.e2e_ms"),
            }
            return results, e2e, {
                "ticks": ticker.ticks if ticker else 0,
                "server_cpu_s": cpu_used_s,
                # outbound frame bytes at the delivery boundary
                # (PeerMap.bytes_delivered, ISSUE 18) — the volume the
                # interest manager exists to shrink
                "bytes_delivered": server.peer_map.bytes_delivered,
                "delta_ratio": server.metrics.snapshot()["gauges"].get(
                    "frame.delta_ratio"
                ),
                "last_batch": ticker.last_batch if ticker else 0,
                "last_tick_ms": round(ticker.last_tick_ms, 2)
                if ticker else None,
                "last_resolve_ms": round(ticker.last_resolve_ms, 2)
                if ticker else None,
                "last_deliver_ms": round(ticker.last_deliver_ms, 2)
                if ticker else None,
            }, plane_stats
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            await server.stop()

    results, e2e, tick_stats, plane_stats = asyncio.run(scenario())
    sent = sum(r[0] for r in results)
    received = sum(r[1] for r in results)
    elapsed = max(r[2] for r in results)
    expected = sent * (group - 1)
    rate = received / elapsed if elapsed > 0 else 0.0
    frame_e2e = e2e.get("frame") or {}
    log(f"delivery[workers={delivery_workers}]: {n_clients} WS peers "
        f"x{group} groups, {sent} msgs in, {received}/{expected} "
        f"deliveries in {elapsed:.2f}s ({rate:,.0f}/s)  "
        f"e2e p50 {frame_e2e.get('p50_ms', 0):.2f} "
        f"p99 {frame_e2e.get('p99_ms', 0):.2f} ms  ticks={tick_stats}")
    out = {
        "clients": n_clients,
        "groups_of": group,
        "messages_sent": sent,
        "deliveries": received,
        "deliveries_expected": expected,
        "deliveries_per_s": round(rate, 1),
        "elapsed_s": round(elapsed, 2),
        # honest fan-out latency: ticker-flush dispatch stamp →
        # socket-write-complete (in the owning worker for the sharded
        # plane), histogram-estimated percentiles
        "e2e_p50_ms": round(frame_e2e.get("p50_ms", 0.0), 3),
        "e2e_p99_ms": round(frame_e2e.get("p99_ms", 0.0), 3),
        "e2e_frames": frame_e2e.get("count", 0),
        # plane-entry → write-complete (ring dwell + write for worker
        # shards; the same stamp on the in-process pump, so the two
        # variants compare like for like)
        "delivery_e2e": e2e.get("delivery"),
        "server_ticks": tick_stats["ticks"],
        # byte-volume accounting (ISSUE 18): lower is better — the
        # perf gate pins these via tools/bench_diff's _BYTES_LOWER
        "delivered_bytes_per_tick": round(
            tick_stats["bytes_delivered"]
            / max(tick_stats["ticks"], 1), 1
        ),
        "bytes_per_recipient_per_s": round(
            tick_stats["bytes_delivered"] / n_clients
            / max(elapsed, 1e-9), 1
        ),
        "frame_delta_ratio": tick_stats["delta_ratio"] or 0.0,
        # per-core efficiency floor (ROADMAP item 1): deliveries per
        # CPU-second burned server-side over the measured window —
        # tools/bench_diff treats this higher-is-better and the CI
        # gate holds an absolute floor on it, so a change that keeps
        # raw throughput by burning proportionally more CPU still fails
        "server_cpu_s": round(tick_stats["server_cpu_s"], 3),
        "deliveries_per_s_per_core": round(
            received / tick_stats["server_cpu_s"], 1
        ) if tick_stats["server_cpu_s"] > 0 else 0.0,
    }
    if plane_stats is not None:
        out["n_workers"] = delivery_workers
        out["per_worker"] = plane_stats["per_worker"]
        out["ring_full_drops"] = plane_stats["plane"]["ring_full_drops"]
        alive = max(plane_stats["plane"]["alive"], 1)
        per_worker_rate = rate / alive
        out["per_worker_deliveries_per_s"] = round(per_worker_rate, 1)
        # the 1M deliveries/s sizing doc: shards are share-nothing, so
        # the config scales by adding workers until N × per-worker rate
        # clears the target — ON HARDWARE WITH N CORES; this container
        # time-shares every process on one core, which caps the
        # observed aggregate (the per-worker rate is the honest unit)
        out["workers_for_1m_per_s"] = (
            int(np.ceil(1_000_000 / per_worker_rate))
            if per_worker_rate > 0 else None
        )
    return out


def bench_delivery_suite(args) -> dict:
    """``server_delivery`` block: the single-loop pump (comparable to
    every prior round) plus the sharded-plane ``workers`` variant —
    same workload through ``--delivery-workers N`` at the ISSUE 6
    acceptance shape (≥4K live clients in full mode; override with
    ``--delivery-clients`` to bound a CI run)."""
    single = bench_delivery(args)
    n_workers = 2 if args.quick else 4
    clients = args.delivery_clients
    if clients is None:
        clients = 128 if args.quick else 4096
    n_procs = max(2, min(4, clients // 512))
    workers = bench_delivery(
        args,
        delivery_workers=n_workers,
        n_procs=n_procs,
        conns_per_proc=max(1, clients // n_procs),
    )
    single_rate = single["deliveries_per_s"] or 1.0
    workers["speedup_vs_single_loop"] = round(
        workers["deliveries_per_s"] / single_rate, 2
    )
    workers["lost_frames"] = (
        workers["deliveries_expected"] - workers["deliveries"]
    )
    single["workers"] = workers
    return single


# --------------------------------------------------------------------
# config 5 (default): 1M-entity Zipf-hotspot fan-out
# --------------------------------------------------------------------


def bench_config5(args) -> dict:
    # Real-server delivery pump first (multiprocessing spawn + live
    # sockets — cleanest before the device backend spins up). Smoke
    # mode (CI regression gate) skips it: the pump needs websockets +
    # spawned client processes and exercises nothing the compaction/
    # pipeline gate cares about.
    delivery = None if args.smoke else bench_delivery_suite(args)

    from worldql_server_tpu.spatial.backend import LocalQuery
    from worldql_server_tpu.spatial.cpu_backend import CpuSpatialBackend
    from worldql_server_tpu.spatial.tpu_backend import TpuSpatialBackend
    from worldql_server_tpu.protocol.types import Replication, Vector3

    import jax

    n_worlds = 8
    rng = np.random.default_rng(42)
    tpu = TpuSpatialBackend(cube_size=16)
    if args.smoke:
        # tiny smoke shapes sit under the compaction's min-cap gate;
        # open it so the CI pass exercises the pack/decode path
        tpu.compact_fetch_min_cap = 0
        tpu.compact_min_bucket = 8
    peers, sub_positions, sub_world_ids = build_index(
        tpu, rng, args.subs, n_worlds
    )
    # snapshot the SUBSCRIBER build's crowd stats before per-tick miss
    # traffic (also Zipf-drawn) overwrites them
    zipf_info = dict(_zipf_stats)
    log(f"zipf crowd: {zipf_info}")

    t0 = time.perf_counter()
    tpu.flush()
    log(f"device flush: {time.perf_counter() - t0:.1f}s "
        f"stats={tpu.device_stats()} device={jax.devices()[0].platform}")

    batches = [
        make_query_batch(rng, sub_positions, sub_world_ids, args.queries)
        for _ in range(args.ticks)
    ]

    # Warmup: compile + size the CSR result to the observed ROW-PADDED
    # footprint (1.5x headroom) — counts are exact even when the warm
    # dispatch itself overflows, so sizing needs no retry ladder.
    from worldql_server_tpu.spatial.tpu_backend import padded_slots

    warm_padded = 1
    for b in batches[:2]:
        _, res = tpu.match_arrays_async(*b, csr_cap=args.queries * 4)
        warm_padded = max(warm_padded, padded_slots(np.asarray(res[0])))
    csr_cap = max(2048, warm_padded * 5 // 4)
    # Steady state: the bulk load leaves most rows in the delta log
    # with a compaction in flight; measuring against that transient
    # (compile + device folds contending with dispatches) would time
    # the warmup, not the engine.
    t0 = time.perf_counter()
    tpu.wait_compaction()
    log(f"compaction drain: {time.perf_counter() - t0:.1f}s "
        f"stats={tpu.device_stats()}")
    for b in batches[:2]:
        _, res = tpu.match_arrays_async(*b, csr_cap=csr_cap)
        _force(res)                  # full-fetch path (fallback tier)
        _collect_compact(tpu, res)   # pack kernel at this bucket tier

    # Boot-time tier precompilation (ISSUE 8): walk every CSR capacity
    # tier, pack bucket and query-cap shape the run can reach, so the
    # sustained passes below hit only warm kernel caches — the retrace
    # GUARD delta across them is the acceptance number (== 0).
    from worldql_server_tpu.spatial.precompile import precompile_tiers
    from worldql_server_tpu.utils.retrace import GUARD

    t0 = time.perf_counter()
    pc_stats = precompile_tiers(
        tpu, max_batch=args.queries, t_tiers=4, max_compiles=64,
        delivery_cap=csr_cap,
    )
    log(f"tier precompile: {pc_stats} "
        f"({time.perf_counter() - t0:.1f}s)")
    guard_before = GUARD.snapshot()

    profile_ctx = (
        jax.profiler.trace(args.profile) if args.profile
        else contextlib.nullcontext()
    )
    # Best-of-3 sustained passes: the tunneled link's congestion swings
    # a single pass several-fold while device compute stays flat — the
    # min is the code's number, the attribution probes below say how
    # much link remains even in it.
    sust_runs = []
    with profile_ctx:
        for _ in range(3):
            _, sustained, total_fanout, csr_cap = run_pipelined_adaptive(
                tpu, batches, csr_cap, depth=8
            )
            sust_runs.append(sustained)
    sustained = min(sust_runs)
    # retrace-GUARD verification of the precompilation: the sustained
    # window must compile NOTHING (a mid-serving trace inside a 5 ms
    # budget is the regression precompile exists to kill)
    retrace_delta = GUARD.delta(guard_before)
    retraces = sum(retrace_delta.values())
    log(f"sustained-window retraces: {retraces} {retrace_delta or ''}")
    if args.profile:
        log(f"jax profiler trace written to {args.profile}")
    log(f"tpu: sustained {sustained:.2f} ms/tick "
        f"(runs: {', '.join(f'{s:.1f}' for s in sust_runs)})  "
        f"avg fan-out {total_fanout / (len(batches) * args.queries):.2f}  "
        f"csr_cap {csr_cap}  "
        f"({args.queries / (sustained / 1e3):,.0f} queries/s)")

    # Run-length accounting under the Zipf crowd: the run-window CSR
    # has no per-query gather bound, so the honest load descriptors are
    # the raw run-length distribution a tick resolves and the CSR
    # retry (capacity-overflow) frequency.
    runlens = []
    for b in batches[:4]:
        cnts = np.asarray(
            tpu.match_arrays_async(*b, csr_cap=csr_cap)[1][0]
        )
        runlens.append(cnts.sum(axis=1)[: args.queries])
    rl = np.concatenate(runlens)
    zipf_info.update(
        run_p50=int(np.percentile(rl, 50)),
        run_p99=int(np.percentile(rl, 99)),
        run_max=int(rl.max()),
        # fraction of queries resolving a hot run (> one CSR row)
        overflow_rate=round(float((rl > 8).mean()), 4),
    )
    log(f"zipf runs: p50 {zipf_info['run_p50']}  p99 "
        f"{zipf_info['run_p99']}  max {zipf_info['run_max']}  "
        f"hot-rate {zipf_info['overflow_rate']}")

    # The north-star metric: per-tick fan-out latency, unpipelined and
    # double-buffered. The first depth-2 tick (pipeline fill + any
    # first-use stall — the BENCH_r05 207 s outlier) reports
    # separately, outside the percentiles.
    # Flight recorder on for the latency runs (ISSUE 5): every tick
    # leaves a span trace, and the WORST tick reports its per-stage
    # breakdown instead of hiding inside a bare p99 — the next 207 s
    # outlier (BENCH_r05) names its stage.
    tracer = Tracer(enabled=True)
    flight = FlightRecorder(depth=2 * len(batches) + 8)
    tracer.on_trace = flight.record
    lat1, _, _, _ = run_pipelined_adaptive(tpu, batches, csr_cap, depth=1,
                                           tracer=tracer)
    lat2_all, _, _, _ = run_pipelined_adaptive(tpu, batches, csr_cap,
                                               depth=2, tracer=tracer)
    lat2 = steady(lat2_all, 2)
    first_tick2 = float(lat2_all[0])
    worst = flight.worst_tick()
    worst_tick = None
    if worst is not None:
        worst_tick = {
            "wall_ms": round(worst.dur_ms, 3),
            "tags": dict(worst.tags),
            "stage_ms": {
                k: round(v, 3) for k, v in sorted(worst.stage_ms().items())
            },
        }
    log(f"latency depth1: p50 {pctl(lat1, 50):.2f} p99 {pctl(lat1, 99):.2f} ms"
        f"  depth2: p50 {pctl(lat2, 50):.2f} p99 {pctl(lat2, 99):.2f} ms"
        f"  first depth-2 tick {first_tick2:.2f} ms"
        f"  (budget {TARGET_P99_MS} ms)")
    log(f"worst recorded tick: {worst_tick}")

    # Attribution probes: how much of the latency is host↔device link
    # round trip (on tunneled devices: ~all of it) vs device compute —
    # and which kernel stage owns the compute.
    rtt_ms, compute_ms, stages = _device_probes(tpu, batches[0], csr_cap)
    log(f"probes: link rtt {rtt_ms:.2f} ms  "
        f"device compute {compute_ms:.3f} ms/tick  stages={stages}")
    lat_attr = _latency_probe(tpu, batches, csr_cap)
    log(f"latency attribution: {lat_attr}")

    # Dispatch-path probe (ISSUE 8): the per-tick encode/h2d/compute/
    # d2h split through the SERVER's dispatch surface, staged columnar
    # vs legacy object-list, parity pinned lane-for-lane.
    path_probe = _dispatch_path_probe(tpu, peers, batches[0])
    log(f"dispatch paths: staged {path_probe['staged']}  "
        f"list {path_probe['list']}  parity {path_probe['parity']}")

    # CPU reference baseline: identical index + queries, per-message
    # dict resolution like the reference's hot path.
    cpu = CpuSpatialBackend(cube_size=16)
    rng2 = np.random.default_rng(42)
    build_index(cpu, rng2, args.subs, n_worlds)

    cpu_times = []
    for b in batches[: args.cpu_ticks]:
        world_ids, positions, sender_ids, repls = b
        queries = [
            LocalQuery(
                f"world_{world_ids[i]}",
                Vector3(*positions[i]),
                peers[sender_ids[i]],
                Replication.EXCEPT_SELF,
            )
            for i in range(len(world_ids))
        ]
        t0 = time.perf_counter()
        cpu.match_local_batch(queries)
        cpu_times.append(time.perf_counter() - t0)
    cpu_times_ms = np.array(cpu_times) * 1e3
    cpu_p99 = pctl(cpu_times_ms, 99)
    log(f"cpu: mean {cpu_times_ms.mean():.2f} ms  p99 {cpu_p99:.2f} ms")

    _parity_check(tpu, cpu, peers, batches[0])

    # Uniform-crowd reference point: the SAME engine over a 1M-sub
    # index with the pre-Zipf uniform-core crowd (5% in a ±40 box) —
    # the distribution the <5 ms budget was originally quoted under,
    # kept for round-over-round comparability.
    uniform = None
    if not args.quick:
        uniform = _uniform_reference(args)
        log(f"uniform-crowd reference: {uniform}")

    # Queries-per-tick scaling sweep (device compute by chained slope,
    # CPU reference at the SAME batch size). Workload model: each tick,
    # M of the 1M subscribed entities broadcast a LocalMessage from
    # their own position (20% from a fresh random point — miss
    # traffic). M/subs is the per-tick speak fraction: 16K/tick at
    # 20 t/s = every entity broadcasting every ~3s (MMO presence
    # cadence); the 1M point is every entity broadcasting every tick —
    # the literal 20M queries/s reading of the north star.
    sweep = []
    if not args.quick:
        sweep = _sweep_config5(tpu, cpu, rng, sub_positions, sub_world_ids,
                               peers, args)

    # Temporal-coherence low-churn sustained pass (ROADMAP 2): runs
    # LAST among the tpu probes — its index churn would desync the CPU
    # twin the parity probes above compare against.
    delta_probe = _delta_probe(
        tpu, peers, sub_positions, sub_world_ids, batches[0], args
    )
    log(f"delta ticks: {delta_probe}")

    # Headline: the ENGINE-side tick (host encode + H2D enqueue +
    # device compute, link excluded) — the pair probe shows this
    # tunnel hard-serializes independent dispatches (pair_overlap_ratio
    # ~0.7-1.0), so the e2e wall measures the link, not the code. The
    # e2e sustained/percentile numbers stay in the JSON below;
    # deployments with locally-attached chips pay PCIe (~100 µs), not
    # this tunnel's ~100 ms RTT. (VERDICT r4 next #2's prescription.)
    # engine_p99's tail is the HOST side (p99 over up to 15 dispatch
    # walls); the compute term is the chained-slope estimate — device
    # compute is flat across trials (±0.02 ms on back-to-back stage
    # probes), so the host is where an engine-tick tail lives.
    engine_tick_ms = lat_attr["dispatch_ms"] + compute_ms
    engine_p99_ms = lat_attr["dispatch_p99_ms"] + compute_ms
    if args.smoke:
        # the CI gate's whole point: the compacted collect path must
        # have actually run (a regression that silently reverts to the
        # full fetch fails the build here, not the nightly bench)
        assert tpu.compact_fetches > 0, \
            "smoke: compacted collect path never fired"
        log(f"smoke: {tpu.compact_fetches} compacted / "
            f"{tpu.full_fetches} full fetches")
        # ISSUE 8 gates: the staged columnar path actually fired, its
        # output is lane-identical to the object-list path, its encode
        # leg is strictly below the list path's on the same shapes, and
        # the precompiled sustained window re-traced NOTHING
        assert tpu.staged_dispatches > 0, \
            "smoke: staged dispatch path never fired"
        assert path_probe["parity"], \
            "smoke: staged/list dispatch outputs diverged"
        assert (
            path_probe["staged"]["encode_ms"]
            < path_probe["list"]["encode_ms"]
        ), (
            "smoke: staged encode not below list-path encode: "
            f"{path_probe['staged']['encode_ms']} vs "
            f"{path_probe['list']['encode_ms']}"
        )
        assert retraces == 0, (
            "smoke: sustained window re-traced despite precompilation: "
            f"{retrace_delta}"
        )
        log(f"smoke: staged encode {path_probe['staged']['encode_ms']}"
            f" ms < list encode {path_probe['list']['encode_ms']} ms; "
            f"retraces {retraces}")
        # ISSUE 13 gates: delta ticks replayed the clean majority of a
        # low-churn pass, lane-for-lane identical to full recompute,
        # and the per-tick device wall dropped >= 5x vs the full
        # recompute path at identical shapes (the acceptance bar;
        # measured 30x at smoke shapes on the 1-core container)
        assert delta_probe["parity"], \
            "smoke: delta ticks diverged from full recompute"
        assert delta_probe["reuse_fraction"] > 0.8, (
            "smoke: delta reuse collapsed: "
            f"{delta_probe['reuse_fraction']}"
        )
        assert delta_probe["speedup"] >= 5.0, (
            "smoke: delta device wall not >= 5x below full recompute: "
            f"{delta_probe['delta_update_ms']} vs "
            f"{delta_probe['rebuild_ms']} ms"
        )
        log(f"smoke: delta reuse {delta_probe['reuse_fraction']}  "
            f"update {delta_probe['delta_update_ms']} ms vs rebuild "
            f"{delta_probe['rebuild_ms']} ms "
            f"({delta_probe['speedup']}x)")
    return {
        "metric": "local_fanout_engine_tick_ms",
        "value": round(engine_tick_ms, 3),
        "unit": "ms",
        "vs_baseline": round(cpu_p99 / engine_tick_ms, 2),
        # honest-baseline calibration (ROADMAP 5a): vs_baseline grades
        # us against our OWN Python oracle; vs_reference grades the
        # same shapes against a native micro-port of the reference
        # implementation's AreaMap lookup (single thread, lookup only
        # — a floor for the reference's per-query cost, deliberately
        # generous to it). Absent when the native library predates the
        # probe symbol.
        "vs_reference": _vs_reference(args, engine_tick_ms),
        "engine_p99_ms": round(engine_p99_ms, 3),
        "sustained_e2e_tick_ms": round(sustained, 3),
        "p50_ms_depth1": round(pctl(lat1, 50), 3),
        "p99_ms_depth1": round(pctl(lat1, 99), 3),
        "p50_ms_depth2": round(pctl(lat2, 50), 3),
        "p99_ms_depth2": round(pctl(lat2, 99), 3),
        # pipeline-fill tick, excluded from the p50/p99 above (the
        # BENCH_r05 207 s outlier was this sample)
        "first_tick_ms_depth2": round(first_tick2, 3),
        # flight-recorder attribution of the slowest latency-run tick:
        # wall + per-stage span breakdown (dispatch vs compacted fetch)
        "worst_tick": worst_tick,
        "compact_fetches": tpu.compact_fetches,
        "full_fetches": tpu.full_fetches,
        # per-tick device-timing split through the server's dispatch
        # surface (ISSUE 8, satellite: top-level so the encode win is
        # visible in the BENCH_*.json trajectory without /debug/ticks);
        # encode_ms is the STAGED columnar path — the serving
        # configuration — with the legacy object-list encode alongside
        # for the wall the staging removed
        "encode_ms": path_probe["staged"]["encode_ms"],
        "h2d_ms": path_probe["staged"]["h2d_ms"],
        "compute_ms": path_probe["staged"]["compute_ms"],
        "d2h_ms": path_probe["staged"]["d2h_ms"],
        "encode_ms_list": path_probe["list"]["encode_ms"],
        "staged_parity": path_probe["parity"],
        "staged_dispatches": tpu.staged_dispatches,
        # retrace-GUARD accounting of the sustained window with
        # precompilation on (acceptance: retraces == 0)
        "device": {
            "retraces": retraces,
            "retrace_delta": retrace_delta,
            "precompile": pc_stats,
        },
        # temporal-coherence pass (ROADMAP 2): reuse_fraction +
        # delta_update_ms vs rebuild_ms at identical shapes; the
        # acceptance bar is speedup >= 5 on the full-shape pass
        "delta": delta_probe,
        "link_rtt_ms": round(rtt_ms, 3),
        "device_compute_ms": round(compute_ms, 4),
        # the engine's own rate, net of the tunnel: what a deployment
        # with locally-attached chips gets per chip (null when the
        # kernel is too small for the slope to resolve — quick mode)
        "device_queries_per_s": (
            round(args.queries / (compute_ms / 1e3))
            if compute_ms >= MIN_RESOLVED_MS else None
        ),
        "device_stage_ms": stages,
        "latency_attribution": lat_attr,
        "uniform_crowd": uniform,
        "zipf": zipf_info,
        "server_delivery": delivery,
        # frame-clock fan-out latency through the REAL server (ISSUE
        # 7): dispatch-stamp → socket-write-complete percentiles from
        # the in-process server_delivery variant, surfaced at top
        # level next to the engine numbers (null in --smoke, which
        # skips the delivery pump)
        "e2e_p50_ms": delivery.get("e2e_p50_ms") if delivery else None,
        "e2e_p99_ms": delivery.get("e2e_p99_ms") if delivery else None,
        "sustained_runs_ms": [round(s, 3) for s in sust_runs],
        "queries_per_tick_sweep": sweep,
        # chunk-tier characterization of the 262K-query throughput dip
        # (BENCH_r05: 2.68M q/s vs 3.65M at 16K) — the 262K sweep
        # record carries the full per-tier table under "tier_sweep"
        "sweep_notes": next(
            (rec["tier_sweep"]["notes"] for rec in sweep
             if rec.get("tier_sweep")), None,
        ),
        "target_p99_ms": TARGET_P99_MS,
        "config": 5,
    }


def _vs_reference(args, engine_tick_ms: float) -> dict | None:
    """The ``vs_reference`` calibration row: the native AreaMap probe
    (spatial.cpp::wql_areamap_probe — a micro-port of the reference
    Rust server's cube→peers HashMap hot path) timed at THIS run's
    sub/query shapes on THIS machine, next to the engine's measured
    per-query cost. The ratio is engine queries/s over reference-probe
    lookups/s; the note spells out the asymmetry so nobody reads a
    lookup-only floor as an end-to-end comparison."""
    from worldql_server_tpu.spatial.native_keys import areamap_probe

    probe = areamap_probe(args.subs, args.queries, cube_size=16, seed=11)
    if probe is None:
        return None
    lookup_ns = probe["lookup_ns_per_query"]
    ref_qps = 1e9 / lookup_ns if lookup_ns > 0 else None
    engine_qps = (
        args.queries / (engine_tick_ms / 1e3) if engine_tick_ms > 0 else None
    )
    ratio = (
        round(engine_qps / ref_qps, 3)
        if engine_qps and ref_qps else None
    )
    return {
        "probe": "areamap_native",
        **probe,
        "ref_lookups_per_s": round(ref_qps) if ref_qps else None,
        "engine_queries_per_s": round(engine_qps) if engine_qps else None,
        "engine_per_ref_ratio": ratio,
        "note": (
            "reference probe is the AreaMap lookup alone — no fan-out "
            "assembly, no serialization, no transport; a calibration "
            "floor for the reference's cost, not an e2e comparison"
        ),
    }


def _uniform_reference(args) -> dict:
    """Device compute at 16K queries / 1M subs under the UNIFORM-core
    crowd (the round-3/4 workload: 5% of entities in a ±40 box) — the
    comparability anchor for the <=1.5 ms engine target."""
    from worldql_server_tpu.spatial.tpu_backend import (
        TpuSpatialBackend, padded_slots,
    )

    rng = np.random.default_rng(77)
    tpu = TpuSpatialBackend(cube_size=16)
    n = args.subs

    def uniform_positions(rng_, k):
        hot = rng_.random(k) < 0.05
        pos = rng_.uniform(-800.0, 800.0, (k, 3))
        pos[hot] = rng_.uniform(-40.0, 40.0, (int(hot.sum()), 3))
        return pos

    global make_positions
    zipf_fn = make_positions
    make_positions = uniform_positions
    try:
        peers, sub_positions, sub_world_ids = build_index(tpu, rng, n, 8)
        tpu.flush()
        tpu.wait_compaction()
        batch = make_query_batch(
            rng, sub_positions, sub_world_ids, 16_384
        )
        cnts = np.asarray(
            tpu.match_arrays_async(*batch, csr_cap=16_384 * 16)[1][0]
        )
        csr_cap = max(2048, padded_slots(cnts) * 5 // 4)
        _, dev_ms, stages = _device_probes(tpu, batch, csr_cap)
        return {
            "queries": 16_384,
            "device_compute_ms": round(dev_ms, 3),
            "device_stage_ms": stages,
            "engine_target_ms": 1.5,
        }
    finally:
        make_positions = zipf_fn
        del tpu


def _sweep_config5(tpu, cpu, rng, sub_positions, sub_world_ids, peers,
                   args) -> list[dict]:
    """Device + CPU cost vs queries-per-tick batch size over the same
    1M-subscription index. Device numbers are chained-slope (link
    cancelled); CPU is the reference backend resolving the identical
    batch."""
    from worldql_server_tpu.spatial.backend import LocalQuery
    from worldql_server_tpu.protocol.types import Replication, Vector3

    out = []
    for m in (16_384, 65_536, 262_144, 1_048_576):
        batch = make_query_batch(rng, sub_positions, sub_world_ids, m)
        # size the CSR buffer off the row-padded footprint at this
        # batch (counts stay exact even if the warm dispatch overflows)
        from worldql_server_tpu.spatial.tpu_backend import padded_slots

        cnts = np.asarray(
            tpu.match_arrays_async(*batch, csr_cap=m * 4)[1][0]
        )
        csr_cap = max(2048, padded_slots(cnts) * 5 // 4)
        try:
            _, dev_ms, _ = _device_probes(
                tpu, batch, csr_cap, stages=False,
                reps_pair=(2, 8) if m >= 262_144 else (8, 64),
            )
        except Exception as exc:  # e.g. HBM OOM on the Zipf 1M batch
            log(f"sweep m={m}: device probe failed "
                f"({type(exc).__name__}) — result footprint "
                f"{csr_cap} slots")
            out.append({
                "queries": m,
                "speak_fraction": round(m / args.subs, 4),
                "device_compute_ms": None,
                "device_queries_per_s": None,
                "error": type(exc).__name__,
            })
            continue

        world_ids, positions, sender_ids, repls = batch
        cpu_n = min(m, 65_536)  # CPU cost is linear; sample and scale
        queries = [
            LocalQuery(
                f"world_{world_ids[i]}", Vector3(*positions[i]),
                peers[sender_ids[i]], Replication.EXCEPT_SELF,
            )
            for i in range(cpu_n)
        ]
        t0 = time.perf_counter()
        cpu.match_local_batch(queries)
        cpu_ms = (time.perf_counter() - t0) * 1e3 * (m / cpu_n)
        resolved = dev_ms >= MIN_RESOLVED_MS
        rec = {
            "queries": m,
            "speak_fraction": round(m / args.subs, 4),
            "device_compute_ms": round(dev_ms, 3),
            "device_queries_per_s": (
                round(m / (dev_ms / 1e3)) if resolved else None
            ),
            "cpu_ms": round(cpu_ms, 1),
            "vs_cpu": round(cpu_ms / dev_ms, 1) if resolved else None,
        }
        if m == 262_144:
            # the BENCH_r05 throughput dip (2.68M q/s here vs 3.65M at
            # 16K and 3.1M at 1M): sweep the zone-B chunk tiers at
            # exactly this shape so the JSON carries the
            # characterization (ISSUE 6 satellite / VERDICT weak #7's
            # sibling). Each tier pair re-traces the assembly with a
            # different (full, tail) lax.map block split.
            rec["tier_sweep"] = _zone_b_tier_sweep(
                tpu, batch, csr_cap, round(dev_ms, 3)
            )
        out.append(rec)
        log(f"sweep m={m}: device {dev_ms:.2f} ms "
            f"({rec['device_queries_per_s']}/s)  cpu {cpu_ms:.0f} ms  "
            f"({rec['vs_cpu']}x)")
    return out


def _zone_b_tier_sweep(tpu, batch, csr_cap: int, default_ms: float) -> dict:
    """Re-time the device kernel at one batch shape under alternate
    zone-B chunk tiers (tpu_backend._ZONE_B_CHUNK/_ZONE_B_TAIL_CHUNK).
    The probes build FRESH jitted closures, so the patched globals
    re-trace cleanly; the backend's registered kernels are untouched.
    Returns the per-tier timings plus a ``notes`` string naming either
    the better boundary or the measured root cause."""
    import worldql_server_tpu.spatial.tpu_backend as tb

    orig = (tb._ZONE_B_CHUNK, tb._ZONE_B_TAIL_CHUNK)
    tiers = [(17, 14), (16, 14), (16, 13), (15, 13), (14, 12), (17, 16)]
    results = []
    try:
        for chunk_exp, tail_exp in tiers:
            tb._ZONE_B_CHUNK = 1 << chunk_exp
            tb._ZONE_B_TAIL_CHUNK = 1 << tail_exp
            try:
                _, ms, _ = _device_probes(
                    tpu, batch, csr_cap, stages=False, reps_pair=(2, 8),
                )
                results.append({
                    "chunk": f"2^{chunk_exp}", "tail": f"2^{tail_exp}",
                    "device_compute_ms": round(ms, 3),
                })
                log(f"tier sweep 2^{chunk_exp}/2^{tail_exp}: {ms:.3f} ms")
            except Exception as exc:
                results.append({
                    "chunk": f"2^{chunk_exp}", "tail": f"2^{tail_exp}",
                    "device_compute_ms": None,
                    "error": type(exc).__name__,
                })
    finally:
        tb._ZONE_B_CHUNK, tb._ZONE_B_TAIL_CHUNK = orig
    timed = [r for r in results if r["device_compute_ms"] is not None]
    notes = "tier sweep produced no timings"
    if timed:
        best = min(timed, key=lambda r: r["device_compute_ms"])
        default = next(
            (r for r in timed if r["chunk"] == "2^17" and r["tail"] == "2^14"),
            None,
        )
        base_ms = default["device_compute_ms"] if default else default_ms
        if base_ms and best["device_compute_ms"] < 0.9 * base_ms:
            notes = (
                f"262K dip: tier {best['chunk']}/{best['tail']} beats the "
                f"default 2^17/2^14 by "
                f"{base_ms / best['device_compute_ms']:.2f}x at this shape "
                "— the default boundary leaves the batch mostly in one "
                "full chunk + a long tail-tier run; consider a shape-"
                "keyed tier table"
            )
        else:
            notes = (
                "262K dip: chunk-tier split is NOT the cause (all tiers "
                f"within 10% of {base_ms} ms at this shape) — the dip "
                "tracks the zone-B rows/query ratio of the Zipf crowd at "
                "this speak fraction, not assembly codegen"
            )
    return {"default_ms": default_ms, "tiers": results, "notes": notes}


def _device_probes(tpu, batch, csr_cap: int, *, stages: bool = True,
                   reps_pair: tuple = (8, 64)):
    """(link round-trip ms, device compute ms/tick, per-stage ms dict).

    The rtt probe is a 4-byte H2D+D2H. The compute probes chain R
    kernel iterations inside ONE jitted ``fori_loop`` (every iteration
    runs the SAME multiset of queries rotated by a result-derived
    shift, so the workload is representative AND nothing is cached,
    hoisted, or dead-code stripped) and report the slope between two
    rep counts: per-tick DEVICE time with the link round-trip fully
    subtracted out. Naive probes (timing pipelined dispatches) measure
    the tunnel's pipelining limit instead and misreported the engine by
    2-3x.

    Three chained loops of increasing prefix depth attribute the total
    over the run-window CSR kernel (tpu_backend.match_run_csr):
    ``bounds`` (per-segment probe-table run-bounds lookup), ``layout``
    (+ the row-padded CSR layout: prefix sums and the owner map —
    index math, no data movement), ``full`` (+ the window gathers that
    assemble the flat result and the filter lanes). The differences
    are the per-stage costs; ``full`` is the headline
    device_compute_ms."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    from worldql_server_tpu.spatial.tpu_backend import (
        CSR_ROW, CSR_ROW_B, SEG_ARRAYS, csr_layout, match_run_csr,
        run_bounds_all, zone_b_cnts,
    )

    one = np.zeros(1, np.int32)
    rtts = []
    for _ in range(12):
        t0 = time.perf_counter()
        np.asarray(jax.device_put(one))
        rtts.append((time.perf_counter() - t0) * 1e3)

    world_ids, positions, sender_ids, repls = batch
    m, result = tpu.match_arrays_async(
        world_ids, positions, sender_ids, repls, csr_cap=csr_cap
    )
    jax.block_until_ready(result)
    segs, ks, kinds = tpu._segments()
    flat_segs = tuple(a for seg in segs for a in seg)
    t_cap = next_pow2(csr_cap)
    nseg = len(segs)
    queries = tuple(jax.device_put(q) for q in tpu._prepare_queries(
        world_ids, positions, sender_ids, repls
    ))
    jax.block_until_ready(queries)
    mq = queries[0].shape[0]
    na = SEG_ARRAYS

    def make_chained(stage: str):
        @partial(jax.jit, static_argnames=("reps",))
        def chained(salt, queries, flat_segs, reps):
            q_key, q_key2, q_sender, q_repl = queries
            seg_tuples = [
                tuple(flat_segs[na * i:na * i + na])
                for i in range(nseg)
            ]

            def body(i, carry):
                acc, shift = carry
                rolled = tuple(jnp.roll(q, shift) for q in
                               (q_key, q_key2, q_sender, q_repl))
                if stage == "bounds":
                    los, cnts = run_bounds_all(seg_tuples, rolled)
                    fold = jnp.int32(0)
                    for lo, cnt in zip(los, cnts):
                        fold = fold ^ lo.sum(dtype=jnp.int32) \
                            ^ cnt.sum(dtype=jnp.int32)
                elif stage == "layout":
                    los, cnts = run_bounds_all(seg_tuples, rolled)
                    counts, row_start, owner, total_rows = csr_layout(
                        zone_b_cnts(cnts),
                        max((t_cap - mq * CSR_ROW * nseg) // CSR_ROW_B,
                            1),
                        CSR_ROW_B,
                    )
                    fold = (
                        counts.sum(dtype=jnp.int32)
                        ^ owner.sum(dtype=jnp.int32)
                        ^ row_start.sum(dtype=jnp.int32)
                        ^ total_rows
                    )
                    for lo in los:
                        fold = fold ^ lo.sum(dtype=jnp.int32)
                else:
                    counts, flat, total = match_run_csr(
                        flat_segs + rolled, nseg, t_cap,
                    )
                    # consume `flat` too, so the window-gather assembly
                    # stays live inside the timed loop
                    fold = total ^ flat.sum(dtype=jnp.int32) \
                        ^ counts.sum(dtype=jnp.int32)
                nxt = (fold & jnp.int32(mq - 1)) + jnp.int32(1)
                return acc + fold.astype(jnp.int64), nxt
            acc, _ = jax.lax.fori_loop(
                0, reps, body, (jnp.int64(0), (salt & jnp.int32(mq - 1)) + 1)
            )
            return acc
        return chained

    # monotone clamp chain (0 <= bounds <= layout <= full): a
    # sub-jitter kernel (tiny quick-mode shapes) can produce
    # meaningless negative slopes, and the emitted stages must never
    # sum past the total they attribute. All prefixes are timed
    # INTERLEAVED (see chained_slopes_ms) so link drift cancels in the
    # differences instead of masquerading as a stage.
    stage_ms = {}
    if stages:
        slopes = chained_slopes_ms(
            {s: make_chained(s) for s in ("bounds", "layout", "full")},
            (queries, flat_segs), reps_pair,
        )
        bounds_ms = max(slopes["bounds"], 0.0)
        layout_ms = max(slopes["layout"], bounds_ms)
        full_ms = max(slopes["full"], layout_ms)
        stage_ms = {
            "run_bounds_ms": round(bounds_ms, 4),
            "csr_layout_ms": round(layout_ms - bounds_ms, 4),
            "window_gather_ms": round(full_ms - layout_ms, 4),
        }
    else:
        full_ms = max(
            chained_slope_ms(
                make_chained("full"), (queries, flat_segs), reps_pair
            ),
            0.0,
        )
    return pctl(rtts, 50), full_ms, stage_ms


def _latency_probe(tpu, batches, csr_cap: int) -> dict:
    """Attribute the depth-1 dispatch→collect latency (VERDICT r4
    weak #1: 265 ms p50 vs a 109 ms link RTT, unexplained).

    Phases of ONE tick, wall-timed separately over several reps:
    ``dispatch`` (host encode + H2D + launch — returns immediately),
    then the sequential D2H phases the server's collect pays:
    ``total`` (scalar sync), ``counts`` ([M, nseg]), and ``flat`` —
    which since ISSUE 3 is the ON-DEVICE COMPACTED fetch (pack the
    owed lanes into a power-of-two bucket, ship O(actual fan-out)
    bytes; the cap-padded full fetch only as fallback). fetch_ms.flat
    therefore scales with real fan-out, not the capacity tier —
    BENCH_r05 measured ≈ 956 ms of cap padding here.

    Concurrency probe: two INDEPENDENT dispatches (different batches —
    the relay cannot serve one from the other) collected in dispatch
    order. If the link pipelines, the pair's wall is ~1 RTT over a
    single tick's; a hard-serializing tunnel costs ~2x a single."""

    def one(batch):
        t0 = time.perf_counter()
        _, res = tpu.match_arrays_async(*batch, csr_cap=csr_cap)
        t1 = time.perf_counter()
        parts = {}
        ta = time.perf_counter()
        total = int(res[2])
        parts["total"] = (time.perf_counter() - ta) * 1e3
        ta = time.perf_counter()
        np.asarray(res[0])
        parts["counts"] = (time.perf_counter() - ta) * 1e3
        ta = time.perf_counter()
        t_cap = res[1].shape[0]
        if (
            total > t_cap
            or tpu._compact_fetch(res[0], res[1], total, t_cap) is None
        ):
            np.asarray(res[1])   # overflow / fallback: full fetch
        parts["flat"] = (time.perf_counter() - ta) * 1e3
        return (t1 - t0) * 1e3, parts, (time.perf_counter() - t0) * 1e3

    # warm
    one(batches[0])
    reps = [one(batches[i % len(batches)]) for i in range(5)]
    dispatch_walls = [r[0] for r in reps]
    # Extra dispatch samples for the p99, on DISTINCT batches only (an
    # identical re-dispatch could be served by the relay cache) and
    # synced via the scalar ``total`` fetch (~1 RTT) instead of the
    # full flat-result fetch (~1 s on this tunnel) — the flat fetch
    # adds nothing to a dispatch-wall sample.
    for b in batches[5:15]:
        t0 = time.perf_counter()
        _, res = tpu.match_arrays_async(*b, csr_cap=csr_cap)
        dispatch_walls.append((time.perf_counter() - t0) * 1e3)
        np.asarray(res[2])
    dispatch_ms = float(np.median(dispatch_walls))
    dispatch_p99_ms = pctl(dispatch_walls, 99)
    fetch = {
        k: round(float(np.median([r[1][k] for r in reps])), 1)
        for k in ("counts", "flat", "total")
    }
    single_ms = float(np.median([r[2] for r in reps]))

    # two independent ticks, dispatched back-to-back, collected in
    # dispatch order — overlap measurement
    def pair():
        t0 = time.perf_counter()
        h1 = tpu.match_arrays_async(*batches[0], csr_cap=csr_cap)[1]
        h2 = tpu.match_arrays_async(*batches[1], csr_cap=csr_cap)[1]
        _collect_compact(tpu, h1)
        _collect_compact(tpu, h2)
        return (time.perf_counter() - t0) * 1e3

    pair()
    pair_ms = float(np.median([pair() for _ in range(3)]))
    return {
        "dispatch_ms": round(dispatch_ms, 1),
        "dispatch_p99_ms": round(dispatch_p99_ms, 1),
        "fetch_ms": fetch,
        "single_tick_ms": round(single_ms, 1),
        "independent_pair_ms": round(pair_ms, 1),
        "pair_overlap_ratio": round(pair_ms / (2 * single_ms), 3),
        # what the LAST collect shipped (pack bucket 0 = full fetch)
        "compaction": dict(tpu.last_collect_stats),
    }


def _dispatch_path_probe(tpu, peers, batch, reps: int = 7) -> dict:
    """Drive the SERVER's two dispatch paths over the same batch and
    report the per-tick device-timing split of each (ISSUE 8):

    * ``list`` — ``dispatch_local_batch`` over LocalQuery objects (the
      legacy path: per-query interning loops inside the dispatch wall);
    * ``staged`` — ``dispatch_staged_batch`` over the columnar arrays
      the ticker's staging buffers would hold (interning already done
      at enqueue time; the dispatch wall is just the fused vectorized
      encode + launch).

    Collect output is compared lane-for-lane (identical UUID fan-out
    lists), and the encode legs are the bench JSON's top-level
    ``encode_ms`` (staged — the serving path) vs ``encode_ms_list``.
    Medians over ``reps`` so one scheduler hiccup can't flip the
    comparison."""
    from worldql_server_tpu.spatial.backend import LocalQuery
    from worldql_server_tpu.protocol.types import Replication, Vector3

    world_ids, positions, sender_ids, repls = batch
    m = len(world_ids)
    queries = [
        LocalQuery(
            f"world_{world_ids[i]}",
            Vector3(*positions[i]),
            peers[sender_ids[i]],
            Replication.EXCEPT_SELF,
        )
        for i in range(m)
    ]
    # the staged columns: exactly what engine/staging.py's enqueue-time
    # encode produces — ids interned through the backend's own dicts
    wid_col = np.fromiter(
        (tpu._world_ids.get(f"world_{w}", -1) for w in world_ids),
        np.int32, count=m,
    )
    sid_col = np.fromiter(
        (tpu._peer_ids.get(peers[s], -1) for s in sender_ids),
        np.int32, count=m,
    )
    pos_col = np.ascontiguousarray(positions, np.float64)
    repl_col = np.full(m, int(Replication.EXCEPT_SELF), np.int8)

    legs = ("encode_ms", "h2d_ms", "compute_ms", "d2h_ms")

    def run(dispatch):
        out, timings = None, []
        for _ in range(reps):
            out = tpu.collect_local_batch(dispatch())
            timings.append(dict(tpu.last_device_timing))
        med = {
            leg: round(float(np.median(
                [t.get(leg, 0.0) for t in timings]
            )), 4)
            for leg in legs
        }
        med["path"] = timings[-1].get("path")
        return out, med

    out_list, t_list = run(lambda: tpu.dispatch_local_batch(queries))
    out_staged, t_staged = run(
        lambda: tpu.dispatch_staged_batch(
            wid_col, pos_col, sid_col, repl_col
        )
    )
    return {
        "queries": m,
        "parity": out_staged == out_list,
        "staged": t_staged,
        "list": t_list,
    }


#: slopes under this are link noise, not a resolved kernel time — rates
#: derived from them would be absurd (a 16K-query tick is never 10 µs)
MIN_RESOLVED_MS = 0.01


def _parity_check(tpu, cpu, peers, batch, samples: int = 64) -> None:
    from worldql_server_tpu.spatial.backend import LocalQuery
    from worldql_server_tpu.protocol.types import Replication, Vector3

    world_ids, positions, sender_ids, repls = batch
    idx = np.linspace(0, len(world_ids) - 1, samples).astype(int)
    tgt = tpu.match_arrays(*batch)
    for i in idx:
        want = cpu.match_local_batch([
            LocalQuery(
                f"world_{world_ids[i]}",
                Vector3(*positions[i]),
                peers[sender_ids[i]],
                Replication.EXCEPT_SELF,
            )
        ])[0]
        got = {int(t) for t in tgt[i] if t >= 0}
        want_ids = {tpu._peer_ids[p] for p in want}
        assert got == want_ids, f"parity diverged at query {i}"
    log(f"parity check: {samples} sampled queries agree with CPU reference")


def _delta_parity_check(args) -> bool:
    """Dual-backend lane-for-lane parity of delta ticks vs full
    recompute over a churned schedule (small shapes; the randomized
    property suite in tests/test_delta_ticks.py is the exhaustive
    version — this is the bench-smoke pin that the gate asserts)."""
    from worldql_server_tpu.spatial.quantize import cube_coords_batch
    from worldql_server_tpu.spatial.tpu_backend import TpuSpatialBackend

    bes = [TpuSpatialBackend(16), TpuSpatialBackend(16)]
    assert bes[0].configure_delta_ticks("on")
    n, mq = 512, 128
    peers = [uuid_mod.UUID(int=i + 1) for i in range(n)]
    pos = np.random.default_rng(5).uniform(-300, 300, (n, 3))
    cubes = cube_coords_batch(pos, 16)
    for be in bes:
        be.bulk_add_subscriptions("w", peers, cubes)
        be.flush()
    qrng = np.random.default_rng(7)
    q_pos = pos[qrng.integers(0, n, mq)].copy()
    wid = np.zeros(mq, np.int32)
    sid = np.full(mq, -1, np.int32)
    repl = np.zeros(mq, np.int8)
    crng = np.random.default_rng(11)
    for _ in range(12):
        rows = np.unique(crng.integers(0, mq, 4))
        q_pos[rows] = pos[crng.integers(0, n, rows.size)]
        mv = np.unique(crng.integers(0, n, 4))
        new_cubes = cube_coords_batch(
            crng.uniform(-300, 300, (mv.size, 3)), 16
        )
        for be in bes:
            be.bulk_move_subscriptions(
                "w", [peers[i] for i in mv], cubes[mv],
                [peers[i] for i in mv], new_cubes,
            )
        cubes[mv] = new_cubes
        outs = [
            be.collect_local_batch(
                be.dispatch_staged_batch(wid, q_pos, sid, repl)
            )
            for be in bes
        ]
        if outs[0] != outs[1]:
            return False
    return bes[0].delta_reused > 0


def _delta_probe(tpu, peers, sub_positions, sub_world_ids, batch,
                 args) -> dict:
    """Low-churn sustained delta pass (ROADMAP 2 acceptance): the SAME
    query batch re-dispatches tick over tick with ~1% fresh query rows
    and ~0.05% index churn per tick — the steady-MMO regime — once
    with delta ticks off (full recompute: every tick re-resolves all M
    queries) and once on (only the dirty partition enters the device
    batch; clean queries replay). ``delta_update_ms`` vs ``rebuild_ms``
    is the mean per-tick device wall (compute + H2D launch) of each
    mode at IDENTICAL shapes; the acceptance bar is a >= 5x drop.
    Runs LAST in config 5 — the index churn it applies would desync
    the earlier CPU-reference parity probes."""
    from worldql_server_tpu.protocol.types import Replication
    from worldql_server_tpu.spatial.quantize import cube_coords_batch

    ticks = 8 if args.quick else 24
    warm = 3
    world_ids, positions, sender_ids, _ = batch
    m = len(world_ids)
    wid_col = np.fromiter(
        (tpu._world_ids.get(f"world_{w}", -1) for w in world_ids),
        np.int32, count=m,
    )
    sid_col = np.fromiter(
        (tpu._peer_ids.get(peers[s], -1) for s in sender_ids),
        np.int32, count=m,
    )
    repl_col = np.full(m, int(Replication.EXCEPT_SELF), np.int8)
    n_subs = len(sub_positions)
    churn_q = max(2, m // 100)
    churn_s = max(2, n_subs // 2000)
    sub_cubes = cube_coords_batch(sub_positions, tpu.cube_size)

    def run(mode):
        tpu.configure_delta_ticks(mode)
        rng = np.random.default_rng(4242)
        pos_col = np.ascontiguousarray(positions, np.float64).copy()
        walls, reuse, dirty, churn_rows = [], [], [], []
        for t in range(warm + ticks):
            rows = np.unique(rng.integers(0, m, churn_q))
            pos_col[rows] = sub_positions[
                rng.integers(0, n_subs, rows.size)
            ]
            mv = np.unique(rng.integers(0, n_subs, churn_s))
            new_cubes = cube_coords_batch(
                make_positions(rng, mv.size), tpu.cube_size
            )
            for w in np.unique(sub_world_ids[mv]):
                sel = sub_world_ids[mv] == w
                tpu.bulk_move_subscriptions(
                    f"world_{w}",
                    [peers[i] for i in mv[sel]], sub_cubes[mv[sel]],
                    [peers[i] for i in mv[sel]], new_cubes[sel],
                )
            sub_cubes[mv] = new_cubes
            tpu.collect_local_batch(tpu.dispatch_staged_batch(
                wid_col, pos_col, sid_col, repl_col
            ))
            if t < warm:
                continue  # sub-tier compiles land in the warmup
            timing = tpu.last_device_timing
            walls.append(
                timing.get("compute_ms", 0.0) + timing.get("h2d_ms", 0.0)
            )
            if mode == "on":
                st = tpu.last_delta_stats
                reuse.append(st["reused"] / max(st["batch"], 1))
                dirty.append(st["dirty_cubes"])
                churn_rows.append(st["churn_rows"])
        return walls, reuse, dirty, churn_rows

    rebuild_walls, _, _, _ = run("off")
    scat0, sort0 = tpu.delta_sync_scatters, tpu.delta_sync_sorts
    update_walls, reuse, dirty, churn_rows = run("on")
    tpu.configure_delta_ticks("off")  # leave the shared backend as built
    # medians: the per-tick wall at steady state — a residual one-off
    # tier compile (new dirty-count pow2 mid-pass) must not masquerade
    # as recurring device work in either mode
    rebuild_ms = float(np.median(rebuild_walls))
    update_ms = float(np.median(update_walls))
    reuse_fraction = float(np.mean(reuse)) if reuse else 0.0
    return {
        "ticks": ticks,
        "churn_queries_per_tick": churn_q,
        "churn_subs_per_tick": churn_s,
        "reuse_fraction": round(reuse_fraction, 4),
        # the CI perf-gate leaf (bench_diff direction-aware,
        # percentage-scaled so a collapse clears the --min-abs floor)
        "reuse_pct": round(reuse_fraction * 100.0, 2),
        "dirty_cubes": int(np.mean(dirty)) if dirty else 0,
        "churn_rows_per_tick": int(np.mean(churn_rows)) if churn_rows
        else 0,
        "delta_update_ms": round(update_ms, 4),
        "rebuild_ms": round(rebuild_ms, 4),
        "speedup": round(rebuild_ms / max(update_ms, 1e-9), 2),
        "sync_scatters": tpu.delta_sync_scatters - scat0,
        "sync_sorts": tpu.delta_sync_sorts - sort0,
        "parity": 1 if _delta_parity_check(args) else 0,
    }


# --------------------------------------------------------------------
# config 1: 256 WS clients echo loop through the real server
# --------------------------------------------------------------------


def bench_config1(args) -> dict:
    import asyncio

    n_clients = 64 if args.quick else 256
    rounds = 5 if args.quick else 20
    group = 8  # co-located clients per cube: each message fans to 7

    async def scenario():
        from tests.client_util import WsClient, free_port
        from worldql_server_tpu.engine.config import Config
        from worldql_server_tpu.engine.server import WorldQLServer
        from worldql_server_tpu.protocol.types import (
            Instruction, Message, Replication, Vector3,
        )

        config = Config()
        config.store_url = "memory://"
        config.ws_port = free_port()
        config.http_enabled = False
        config.zmq_enabled = False
        config.spatial_backend = "cpu"
        server = WorldQLServer(config)
        await server.start()
        latencies: list[float] = []
        try:
            clients = []
            for i in range(n_clients):
                c = await WsClient.connect(config.ws_port)
                clients.append(c)
            positions = [
                Vector3(100.0 * (i // group), 5.0, 5.0)
                for i in range(n_clients)
            ]
            for c, pos in zip(clients, positions):
                await c.send(Message(
                    instruction=Instruction.AREA_SUBSCRIBE,
                    world_name="bench", position=pos,
                ))
            await asyncio.sleep(0.3)

            expected_per_client = group - 1

            async def recv_all(c):
                got = 0
                while got < expected_per_client * rounds:
                    m = await asyncio.wait_for(c.recv(timeout=30), 30)
                    if m.instruction != Instruction.LOCAL_MESSAGE:
                        continue
                    sent_at = float(m.parameter)
                    latencies.append((time.perf_counter() - sent_at) * 1e3)
                    got += 1

            receivers = [asyncio.create_task(recv_all(c)) for c in clients]
            # Rounds are paced by COMPLETION, not a fixed sleep: each
            # round's wall time runs from the first send until every
            # delivery of that round has landed, so the throughput
            # figure is the server's, not the pacer's.
            elapsed = 0.0
            expected_total = n_clients * expected_per_client
            for r in range(rounds):
                t0 = time.perf_counter()
                for c, pos in zip(clients, positions):
                    await c.send(Message(
                        instruction=Instruction.LOCAL_MESSAGE,
                        world_name="bench", position=pos,
                        parameter=repr(time.perf_counter()),
                        replication=Replication.EXCEPT_SELF,
                    ))
                # Bounded wait that surfaces receiver failures: a lost
                # delivery (e.g. a subscription that raced round 0) must
                # fail crisply, not spin this loop forever.
                deadline = t0 + 60.0
                while len(latencies) < expected_total * (r + 1):
                    dead = next(
                        (t for t in receivers
                         if t.done() and t.exception() is not None),
                        None,
                    )
                    if dead is not None:
                        raise dead.exception()
                    if time.perf_counter() > deadline:
                        raise RuntimeError(
                            f"config1 round {r}: {len(latencies)} of "
                            f"{expected_total * (r + 1)} deliveries after 60s"
                        )
                    await asyncio.sleep(0.002)
                elapsed += time.perf_counter() - t0
            await asyncio.gather(*receivers)
            for c in clients:
                await c.close()
            return latencies, elapsed
        finally:
            await server.stop()

    latencies, elapsed = asyncio.run(scenario())
    deliveries = len(latencies)
    p50, p99 = pctl(latencies, 50), pctl(latencies, 99)
    log(f"ws echo: {n_clients} clients, {deliveries} deliveries in "
        f"{elapsed:.2f}s ({deliveries / elapsed:,.0f}/s)  "
        f"p50 {p50:.2f} ms  p99 {p99:.2f} ms")
    return {
        "metric": "ws_echo_delivery_p99_ms",
        "value": round(p99, 3),
        "unit": "ms",
        "vs_baseline": round(TARGET_P99_MS / p99, 2),
        "p50_ms": round(p50, 3),
        "deliveries_per_s": round(deliveries / elapsed, 1),
        "clients": n_clients,
        "target_p99_ms": TARGET_P99_MS,
        "config": 1,
    }


# --------------------------------------------------------------------
# config 2: 10k random-walk clients, churn + broadcast @ 20 tick/s
# --------------------------------------------------------------------


def bench_config2(args) -> dict:
    from worldql_server_tpu.spatial.quantize import cube_coords_batch
    from worldql_server_tpu.spatial.tpu_backend import TpuSpatialBackend

    n = 1_000 if args.quick else 10_000
    ticks = 10 if args.quick else 50
    world = "walk"
    rng = np.random.default_rng(11)

    backend = TpuSpatialBackend(cube_size=16)
    positions = rng.uniform(-400.0, 400.0, (n, 3))
    velocities = rng.uniform(-30.0, 30.0, (n, 3))
    peers = [uuid_mod.UUID(int=i + 1) for i in range(n)]
    peer_arr = np.array(peers)
    cubes = cube_coords_batch(positions, backend.cube_size)
    backend.bulk_add_subscriptions(world, peers, cubes)
    backend.flush()

    world_ids = np.zeros(n, np.int32)
    sender_ids = np.arange(n, dtype=np.int32)
    repls = np.zeros(n, np.int8)
    csr_cap = n * 8

    # per-phase wall accumulators: churn (host bulk mutation
    # bookkeeping), flush (delta chunk H2D + device sort dispatch),
    # dispatch (query launch). Separating them is the attribution the
    # 50 ms budget claim needs — the link inflates flush+dispatch, the
    # device probes below say by how much.
    phase = {"churn": 0.0, "flush": 0.0, "dispatch": 0.0, "ticks": 0}

    def churn_tick():
        nonlocal positions
        t0 = time.perf_counter()
        positions += velocities * 0.05
        out = np.abs(positions) > 400.0
        velocities[out] = -velocities[out]
        np.clip(positions, -400.0, 400.0, out=positions)
        new_cubes = cube_coords_batch(positions, backend.cube_size)
        moved = (new_cubes != cubes).any(axis=1)
        n_moved = 0
        if moved.any():
            midx = np.flatnonzero(moved)
            backend.bulk_remove_subscriptions(
                world, peer_arr[midx].tolist(), cubes[midx]
            )
            backend.bulk_add_subscriptions(
                world, peer_arr[midx].tolist(), new_cubes[midx]
            )
            cubes[midx] = new_cubes[midx]
            n_moved = int(midx.size)
        t1 = time.perf_counter()
        backend.flush()
        t2 = time.perf_counter()
        handle = backend.match_arrays_async(
            world_ids, positions, sender_ids, repls, csr_cap=csr_cap
        )[1]
        t3 = time.perf_counter()
        phase["churn"] += t1 - t0
        phase["flush"] += t2 - t1
        phase["dispatch"] += t3 - t2
        phase["ticks"] += 1
        return n_moved, handle

    def collect(handle) -> None:
        total = _force(handle)
        assert total <= next_pow2(csr_cap), "csr_cap overflow"

    # Warmup: churn until the index has been through full compaction
    # cycles AND the shape-tier set has stabilized — a tier first seen
    # inside the measured loop would charge a 10s+ XLA compile to one
    # tick (observed as a 7s p99 outlier with a count-based warmup).
    warm, stable, seen = 0, 0, set()
    while warm < 80 and (backend.compactions < 2 or stable < 10):
        collect(churn_tick()[1])
        warm += 1
        tier = (backend._delta_buf_cap, backend._delta_k, backend._base_k)
        if tier in seen:
            stable += 1
        else:
            seen.add(tier)
            stable = 0
    backend.wait_compaction()
    log(f"warmup: {warm} churn ticks, {backend.compactions} compactions, "
        f"{len(seen)} shape tiers")

    # Double-buffered like the server's tick batcher: tick t's fan-out
    # is collected after tick t+1 dispatches, overlapping the device
    # round trip with the next tick's host-side churn. Primed with one
    # untimed tick so EVERY timed iteration includes a collect.
    lat = []
    churn_total = 0
    _, pending = churn_tick()
    collect_pending = pending
    phase.update(churn=0.0, flush=0.0, dispatch=0.0, ticks=0)
    t_start = time.perf_counter()
    for _ in range(ticks):
        t0 = time.perf_counter()
        moved, handle = churn_tick()
        churn_total += moved
        collect(collect_pending)
        collect_pending = handle
        lat.append((time.perf_counter() - t0) * 1e3)
    collect(collect_pending)
    sustained = (time.perf_counter() - t_start) / ticks * 1e3
    p50, p99 = pctl(lat, 50), pctl(lat, 99)
    nt = max(phase["ticks"], 1)
    churn_ms = phase["churn"] / nt * 1e3
    flush_ms = phase["flush"] / nt * 1e3
    dispatch_ms = phase["dispatch"] / nt * 1e3

    # device-side attribution, net of the link: chained-slope the delta
    # sort at the steady-state shape (the only device work flush does).
    # Clamped at 0: a sub-0.1ms sort can drown in link-jitter noise.
    sort_ms = max(_churn_sort_slope_ms(backend), 0.0)

    log(f"random-walk: {n} clients, {churn_total / ticks:.0f} resubs/tick, "
        f"sustained {sustained:.2f} ms/tick  iter p50 {p50:.2f}  "
        f"p99 {p99:.2f} (budget {TICK_BUDGET_MS} ms)")
    log(f"phases: churn {churn_ms:.2f}  flush {flush_ms:.2f} "
        f"(device sort {sort_ms:.2f})  dispatch {dispatch_ms:.2f} ms/tick")
    return {
        "metric": "random_walk_tick_ms",
        "value": round(sustained, 3),
        "unit": "ms",
        "vs_baseline": round(TICK_BUDGET_MS / max(sustained, 1e-9), 2),
        # pipelined loop-iteration time (dispatch t + collect t-1), NOT
        # per-message dispatch→collect latency — config 5 reports that
        "iter_p50_ms": round(p50, 3),
        "iter_p99_ms": round(p99, 3),
        # per-tick attribution: host churn bookkeeping; flush wall
        # (delta H2D + sort dispatch — link-inflated on tunneled
        # devices); the flush's true device sort cost by chained slope;
        # dispatch wall (query launch, link-inflated)
        "churn_host_ms": round(churn_ms, 3),
        "flush_ms": round(flush_ms, 3),
        "flush_device_sort_ms": round(sort_ms, 3),
        "dispatch_ms": round(dispatch_ms, 3),
        "measurement": "pipelined-depth2-v3",
        "clients": n,
        "resubs_per_tick": round(churn_total / ticks, 1),
        "budget_ms": TICK_BUDGET_MS,
        "config": 2,
    }


def _churn_sort_slope_ms(backend) -> float:
    """Per-flush DEVICE cost of the delta sort (sort + run-remainder +
    probe build — the fused launch `_sort_segment_dev`), by chained
    slope at the backend's current delta-buffer shape. Each iteration
    sorts the same rows rotated by a result-derived shift: identical
    workload, nothing hoistable."""
    import jax.numpy as jnp
    from functools import partial

    import jax

    from worldql_server_tpu.spatial.tpu_backend import (
        _sort_segment_dev, probe_buckets_for,
    )

    bufs = backend._delta_buf
    if bufs is None:
        return 0.0
    n_buckets = probe_buckets_for(len(backend._delta_key_count))

    @partial(jax.jit, static_argnames=("reps",))
    def chained(salt, bufs, reps):
        k, k2, p = bufs

        def body(i, carry):
            acc, shift = carry
            out = _sort_segment_dev(
                jnp.roll(k, shift), jnp.roll(k2, shift), jnp.roll(p, shift),
                n_buckets=n_buckets,
            )
            fold = jnp.int64(0)
            for o in out:  # every output stays live
                fold = fold ^ o.sum(dtype=jnp.int64)
            nxt = (fold.astype(jnp.int32) & jnp.int32(1023)) + jnp.int32(1)
            return acc + fold, nxt

        acc, _ = jax.lax.fori_loop(
            0, reps, body,
            (jnp.int64(0), (salt & jnp.int32(1023)) + jnp.int32(1))
        )
        return acc

    return chained_slope_ms(chained, (bufs,), (4, 16))


# --------------------------------------------------------------------
# config 3: 100k entities, on-device kNN (k=32) tick, single chip
# --------------------------------------------------------------------


def _tick_parity_check(n: int = 8_192) -> None:
    """Run one batch through BOTH fan-out resolvers on the current
    device — the fused Pallas kernel and the XLA stencil — and assert
    exact equality before anything is timed. On TPU this is the real
    (non-interpret) Pallas lowering; the CPU test suite only ever sees
    interpret mode."""
    import jax

    from worldql_server_tpu.ops.tick import example_state, make_tick_fn

    state = example_state(n=n, n_worlds=8)
    _, tgt_p, cnt_p = jax.jit(make_tick_fn(cube_size=16, k=32,
                                           pallas=True))(state)
    _, tgt_x, cnt_x = jax.jit(make_tick_fn(cube_size=16, k=32,
                                           pallas=False))(state)
    assert (np.asarray(cnt_p) == np.asarray(cnt_x)).all(), \
        "pallas/xla count divergence"
    assert (np.asarray(tgt_p) == np.asarray(tgt_x)).all(), \
        "pallas/xla target divergence"
    log(f"pallas parity: {n} entities, pallas == xla stencil on "
        f"{jax.devices()[0].platform}")


def _tick_device_slope_ms(n: int, k: int, reps_pair=(2, 8)) -> float:
    """Per-tick DEVICE time for the n-entity simulation tick by
    chained slope: the tick naturally threads state, and the fan-out
    targets fold back into the velocity via a +0-magnitude term (an
    f32 add of ~1e-30 — real data dependency, zero value change), so
    no stage can be elided or hoisted and the link round-trip cancels
    in the slope."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    from worldql_server_tpu.ops.tick import (
        EntityState, example_state, make_tick_fn,
    )

    tick = make_tick_fn(cube_size=16, k=k)
    state = example_state(n=n, n_worlds=8)

    @partial(jax.jit, static_argnames=("reps",))
    def chained(salt, state, reps):
        # salt perturbs the initial state below f32 resolution: every
        # dispatch differs (relay cache) while the workload doesn't
        state = EntityState(
            state.position,
            state.velocity + salt.astype(jnp.float32) * jnp.float32(1e-30),
            state.world, state.peer,
        )

        def body(i, st):
            new, targets, counts = tick(st)
            fold = (targets.sum(dtype=jnp.int32)
                    ^ counts.sum(dtype=jnp.int32)).astype(jnp.float32)
            return EntityState(
                new.position,
                new.velocity + fold * jnp.float32(1e-30),
                new.world, new.peer,
            )
        out = jax.lax.fori_loop(0, reps, body, state)
        # scalar fold: the caller FETCHES the result to synchronize
        return out.velocity.sum(dtype=jnp.float32)

    return chained_slope_ms(chained, (state,), reps_pair)


def bench_config3(args) -> dict:
    import jax

    from worldql_server_tpu.ops.tick import example_state, make_tick_fn

    n = 8_192 if args.quick else 100_000
    n_big = 4_096 if args.quick else 1_000_000
    ticks = 10 if args.quick else 30
    tick = jax.jit(make_tick_fn(cube_size=16, k=32))
    state = example_state(n=n, n_worlds=8)

    # the two resolver paths must agree on-device before timing (quick
    # mode shrinks it: Pallas interpret on CPU is minutes at 8K)
    _tick_parity_check(512 if args.quick else 8_192)

    # warmup / compile — and force a readback so the device is in real
    # (non-elided) execution mode before anything is timed
    state, targets, counts = tick(state)
    np.asarray(counts)

    # Sustained: the tick chains state on device, so the honest
    # steady-state figure streams the whole run and syncs once — a
    # per-tick block would measure the host↔device link RTT, not the
    # simulation (the game loop only reads results it needs, it never
    # round-trips per frame). The sync is a FETCH: on the axon
    # backend block_until_ready returns before execution finishes —
    # only a D2H read is a true barrier.
    t_start = time.perf_counter()
    for _ in range(ticks):
        state, targets, counts = tick(state)
    np.asarray(counts)
    sustained = (time.perf_counter() - t_start) / ticks * 1e3

    # Latency: one synchronized tick — execution complete with the
    # per-entity counts on host. The dense [N, K] fan-out table stays
    # on device: a real consumer CSR-compacts it (config 5's path)
    # rather than shipping N*K ints, so fetching it here would time an
    # access pattern nothing uses.
    lat = []
    for _ in range(max(5, ticks // 4)):
        t0 = time.perf_counter()
        state, targets, counts = tick(state)
        np.asarray(counts)
        lat.append((time.perf_counter() - t0) * 1e3)
    p50, p99 = pctl(lat, 50), pctl(lat, 99)
    rate = n / (sustained / 1e3)
    log(f"knn tick: {n} entities k=32, sustained {sustained:.2f} ms/tick "
        f"sync p50 {p50:.2f} p99 {p99:.2f} ({rate:,.0f} entity-queries/s)")

    # the literal BASELINE config-5 workload: per-tick spatial-hash
    # rebuild at 1M entities, device time by chained slope
    big_ms = _tick_device_slope_ms(
        n_big, k=32, reps_pair=(1, 3) if args.quick else (2, 8)
    )
    big_rate = n_big / (big_ms / 1e3)
    log(f"knn tick {n_big}: device {big_ms:.2f} ms/tick "
        f"({big_rate:,.0f} entity-queries/s)")

    return {
        "metric": "knn_tick_ms",
        "value": round(sustained, 3),
        "unit": "ms",
        "vs_baseline": round(TICK_BUDGET_MS / max(sustained, 1e-9), 2),
        # fully-synchronized single-tick latency (small sample)
        "sync_p50_ms": round(p50, 3),
        "sync_p99_ms": round(p99, 3),
        "measurement": "streamed-v2",
        "entities": n,
        "entity_queries_per_s": round(rate),
        # 1M-entity per-tick rebuild (BASELINE config 5's literal
        # workload), device compute by chained slope
        "tick_1m_entities": n_big,
        "tick_1m_device_ms": round(big_ms, 3),
        "tick_1m_entity_queries_per_s": round(big_rate),
        "pallas_parity": "pass",
        "budget_ms": TICK_BUDGET_MS,
        "config": 3,
    }


# --------------------------------------------------------------------
# config 4: 64 worlds x 10k clients, mesh-sharded backend
# --------------------------------------------------------------------


def bench_config4(args) -> dict:
    import jax

    from worldql_server_tpu.parallel import (
        ShardedTpuSpatialBackend, make_fanout_mesh,
    )

    n_worlds = 8 if args.quick else 64
    per_world = 1_000 if args.quick else 10_000
    n_subs = n_worlds * per_world
    queries = 2_048 if args.quick else 16_384
    ticks = 10 if args.quick else 30

    mesh = make_fanout_mesh(1, len(jax.devices()))
    backend = ShardedTpuSpatialBackend(cube_size=16, mesh=mesh)
    rng = np.random.default_rng(21)
    peers, sub_positions, sub_world_ids = build_index(
        backend, rng, n_subs, n_worlds
    )
    t0 = time.perf_counter()
    backend.flush()
    log(f"device flush: {time.perf_counter() - t0:.1f}s "
        f"mesh={dict(mesh.shape)} stats={backend.device_stats()}")

    batches = [
        make_query_batch(rng, sub_positions, sub_world_ids, queries)
        for _ in range(ticks)
    ]
    csr_cap = queries * 4
    for b in batches[:2]:
        _, res = backend.match_arrays_async(*b, csr_cap=csr_cap)
        _force(res)                      # full-fetch path
        _collect_compact(backend, res)   # sharded pack kernel
    backend.wait_compaction()

    _, sustained, total_fanout, csr_cap = run_pipelined_adaptive(
        backend, batches, csr_cap, depth=8
    )
    lat2, _, _, _ = run_pipelined_adaptive(backend, batches, csr_cap, depth=2)
    lat2 = steady(lat2, 2)   # pipeline-fill tick: see steady()
    p50, p99 = pctl(lat2, 50), pctl(lat2, 99)
    log(f"sharded {n_worlds} worlds: sustained {sustained:.2f} ms/tick  "
        f"depth2 p50 {p50:.2f} p99 {p99:.2f}  "
        f"avg fan-out {total_fanout / (ticks * queries):.2f}")
    return {
        "metric": "sharded_worlds_tick_ms",
        "value": round(sustained, 3),
        "unit": "ms",
        "vs_baseline": round(TARGET_P99_MS / max(p99, 1e-9), 2),
        "p50_ms_depth2": round(p50, 3),
        "p99_ms_depth2": round(p99, 3),
        "worlds": n_worlds,
        "subscriptions": n_subs,
        "mesh": dict(mesh.shape),
        "target_p99_ms": TARGET_P99_MS,
        "config": 4,
    }


# --------------------------------------------------------------------
# config 7: sharded-backend scaling curve (ROADMAP item 3)
# --------------------------------------------------------------------


def bench_config7(args) -> dict:
    """``sharded_overhead``: ShardedTpuSpatialBackend per-tick cost on
    a 1→8-device mesh vs the single-device backend on the SAME
    workload — the shard_map dispatch + pmax merge overhead the
    multi-chip story pays per tick (ROADMAP item 3 / VERDICT weak #7:
    the sharded backend had parity proof but zero perf evidence). On a
    host without >= 8 attached devices the bench re-execs itself with
    ``--xla_force_host_platform_device_count=8``: a VIRTUAL host-device
    mesh times real dispatch/collective overhead, not kernel FLOP
    scaling — the ``platform`` field names which regime produced the
    numbers."""
    import os
    import jax

    if len(jax.devices()) >= 8:
        return _sharded_overhead_inner(args)

    import re
    import subprocess

    env = dict(os.environ)
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", ""),
    )
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
    # a TPU-less host with libtpu installed would hang enumerating the
    # plugin; the virtual mesh is host-platform by definition
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [
        sys.executable, os.path.abspath(__file__), "--config", "7",
        "--subs", str(args.subs), "--queries", str(args.queries),
        "--ticks", str(args.ticks),
    ]
    if args.quick:
        cmd.append("--quick")
    log("config 7: re-exec with 8 virtual host devices "
        f"(this process has {len(jax.devices())})")
    out = subprocess.run(
        cmd, capture_output=True, text=True, env=env, timeout=3000,
    )
    for line in out.stderr.splitlines():
        log(f"[sharded-overhead] {line}")
    if out.returncode != 0:
        raise RuntimeError(
            f"sharded-overhead child failed (rc={out.returncode})"
        )
    return json.loads(out.stdout.strip().splitlines()[-1])


def _sharded_overhead_inner(args) -> dict:
    import jax

    from worldql_server_tpu.parallel import (
        ShardedTpuSpatialBackend, make_fanout_mesh,
    )
    from worldql_server_tpu.spatial.tpu_backend import TpuSpatialBackend

    devices = jax.devices()
    platform = devices[0].platform
    # quick (CI) keeps the compile bill to two meshes; the full curve
    # needs the 4- and 8-shard points that expose collective scaling
    shard_counts = [c for c in ((1, 2) if args.quick else (1, 2, 4, 8))
                    if c <= len(devices)]
    n_worlds = 8
    subs = min(args.subs, 200_000)  # 5 index builds — bound the bill
    queries = args.queries
    ticks = max(4, min(args.ticks, 12))

    def measure(backend) -> float:
        from worldql_server_tpu.spatial.tpu_backend import padded_slots

        rng = np.random.default_rng(5)
        _, sub_positions, sub_world_ids = build_index(
            backend, rng, subs, n_worlds
        )
        backend.flush()
        backend.wait_compaction()
        batches = [
            make_query_batch(rng, sub_positions, sub_world_ids, queries)
            for _ in range(ticks)
        ]
        # size the CSR buffer from the observed row-padded footprint
        # (config-5 discipline) so every backend runs the SAME capacity
        # tier — mid-measure overflow retries would skew the comparison
        cnts = np.asarray(
            backend.match_arrays_async(*batches[0], csr_cap=queries * 16)[1][0]
        )
        csr_cap = max(2048, padded_slots(cnts) * 3 // 2)
        # warm EVERY batch once through the compacted collect: each
        # distinct fan-out total can land a new pack-bucket tier, and
        # at these small tick counts one stray compile would dominate
        # the sustained mean (the 207s-outlier lesson, in miniature)
        for b in batches:
            _collect_compact(
                backend, backend.match_arrays_async(*b, csr_cap=csr_cap)[1]
            )
        best = None
        for _ in range(3):
            _, sustained, _, _ = run_pipelined_adaptive(
                backend, batches, csr_cap, depth=1
            )
            best = sustained if best is None else min(best, sustained)
        return best

    single_ms = measure(TpuSpatialBackend(cube_size=16))
    log(f"sharded_overhead: single-device {single_ms:.3f} ms/tick "
        f"({platform})")
    curve = []
    for c in shard_counts:
        mesh = make_fanout_mesh(1, c, devices[:c])
        ms = measure(ShardedTpuSpatialBackend(cube_size=16, mesh=mesh))
        curve.append({
            "devices": c,
            "tick_ms": round(ms, 3),
            "vs_single": round(ms / single_ms, 2),
        })
        log(f"sharded_overhead: {c} space shards {ms:.3f} ms/tick "
            f"({ms / single_ms:.2f}x single)")
    return {
        "metric": "sharded_overhead_tick_ms",
        "value": curve[-1]["tick_ms"],
        "unit": "ms",
        # < 1 means the mesh run is SLOWER than single-device — the
        # honest overhead framing, not a speedup claim
        "vs_baseline": round(single_ms / max(curve[-1]["tick_ms"], 1e-9), 2),
        "platform": platform,
        "sharded_overhead": {
            "single_device_tick_ms": round(single_ms, 3),
            "curve": curve,
            # the 1-shard point IS the pure shard_map+pmax wrapper cost
            "shard_map_pmax_overhead_x": curve[0]["vs_single"],
            "note": (
                "virtual host-device mesh: dispatch + collective "
                "overhead is real, kernel FLOP scaling is not"
                if platform == "cpu" else
                "attached accelerator mesh: end-to-end per-tick scaling"
            ),
        },
        "subscriptions": subs,
        "queries": queries,
        "config": 7,
    }


def bench_config6(args) -> dict:
    """Record-op durability workload (ISSUE 2): RecordCreate handler
    latency through the REAL Router against the SQLite store, once per
    durability mode. 'off' awaits the store commit inline (the
    reference's synchronous-persist shape), 'wal' acks after the
    group-commit fsync + enqueue, 'sync' pays WAL fsync AND the inline
    commit. The headline is wal-mode p99 — what a record write costs
    the event loop with durability ON."""
    import shutil
    import tempfile

    from worldql_server_tpu.durability import (
        DurabilityPipeline, WriteAheadLog,
    )
    from worldql_server_tpu.engine.config import Config
    from worldql_server_tpu.engine.peers import PeerMap
    from worldql_server_tpu.engine.router import Router
    from worldql_server_tpu.protocol import Instruction, Message, Record
    from worldql_server_tpu.protocol.types import Vector3
    from worldql_server_tpu.spatial.cpu_backend import CpuSpatialBackend
    from worldql_server_tpu.storage.store import open_store

    ops = 300 if args.quick else 2_000
    recs_per_op = 4
    rng = np.random.default_rng(17)
    sender = uuid_mod.uuid4()

    def make_messages():
        msgs = []
        for i in range(ops):
            records = [
                Record(
                    uuid=uuid_mod.UUID(int=i * recs_per_op + j + 1),
                    position=Vector3(*rng.uniform(-500, 500, 3)),
                    world_name="bench",
                    data="x" * 64,
                )
                for j in range(recs_per_op)
            ]
            msgs.append(Message(
                instruction=Instruction.RECORD_CREATE,
                sender_uuid=sender, world_name="bench", records=records,
            ))
        return msgs

    results = {}
    for mode in ("off", "wal", "sync"):
        tmp = tempfile.mkdtemp(prefix=f"wql-bench6-{mode}-")

        async def scenario(mode=mode, tmp=tmp):
            config = Config(
                store_url=f"sqlite://{tmp}/records.db",
                durability=mode, wal_dir=f"{tmp}/wal",
            )
            store = open_store(config.store_url, config)
            await store.init()
            wal = None
            durability = None
            if mode != "off":
                wal = WriteAheadLog(
                    config.wal_dir,
                    fsync_ms=0.0 if mode == "sync" else config.wal_fsync_ms,
                    segment_bytes=config.wal_segment_bytes,
                )
                wal.start()
                durability = DurabilityPipeline(
                    store, mode=mode, wal=wal, config=config,
                )
                durability.start()
            router = Router(
                PeerMap(), CpuSpatialBackend(config.sub_region_size),
                store, durability=durability,
            )
            lat = []
            for msg in make_messages():
                t0 = time.perf_counter()
                await router.handle_message(msg)
                lat.append((time.perf_counter() - t0) * 1e3)
            if durability is not None:
                drained = await durability.stop()
                assert drained, "write-behind queue failed to drain"
                await wal.close()
            await store.close()
            return lat

        lat = asyncio.run(scenario())
        shutil.rmtree(tmp, ignore_errors=True)
        results[mode] = (pctl(lat, 50), pctl(lat, 99))
        log(f"durability={mode}: handler p50 {results[mode][0]:.3f} ms "
            f"p99 {results[mode][1]:.3f} ms  ({ops} ops x "
            f"{recs_per_op} records)")

    return {
        "metric": "record_op_handler_p99_ms",
        "value": round(results["wal"][1], 4),
        "unit": "ms",
        # speedup of the write-behind handler over the reference's
        # inline-commit shape (> 1.0 = durability off the hot path)
        "vs_baseline": round(
            results["off"][1] / max(results["wal"][1], 1e-9), 2
        ),
        "off_p50_ms": round(results["off"][0], 4),
        "off_p99_ms": round(results["off"][1], 4),
        "wal_p50_ms": round(results["wal"][0], 4),
        "wal_p99_ms": round(results["wal"][1], 4),
        "sync_p50_ms": round(results["sync"][0], 4),
        "sync_p99_ms": round(results["sync"][1], 4),
        "ops": ops,
        "records_per_op": recs_per_op,
        "config": 6,
    }


def bench_config8(args) -> dict:
    """Entity simulation workload (ISSUE 9 + 11): the device-resident
    moving-object plane. Three legs:

    * **ingest** — PRE-ENCODED wire buffers through the columnar
      wire→SoA path (``ColumnarIngest`` → ``wql_decode_entities`` →
      ``EntityPlane.ingest_columns``, zero per-entity Python) with the
      per-tick index churn flowing through the LSM base+delta path
      (``bulk_move_subscriptions``) → ``updates_per_s`` (wire→staged
      columns) and ``updates_per_s_sustained`` (including every device
      tick in the wall), plus ``churn_rows_per_s``;
    * **device tick** — steady-state integrate + kNN resolve
      (one fused ops/tick.py kernel) → ``knn_ms`` (p50 of the
      dispatch+collect wall over a quiet window), with incremental H2D
      (only touched slots ship — ``h2d_scatter``/``h2d_full``);
    * **e2e** — a REAL server over ZMQ: clients register entities and
      stream updates through the transport's columnar drain, neighbor
      frames ride the delivery path cohort-encoded in native code, and
      ``frame.e2e_ms`` p99 (the PR 7 frame clock) is the honest
      dispatch→socket-write number → ``e2e_p99_ms``.

    ``--smoke`` shrinks shapes, forces a small compaction threshold,
    and asserts the device path fired, the NATIVE columnar decode fired
    (both legs), at least one delta compaction ran mid-stream, the
    steady window re-traced nothing, and frames were delivered — the
    CI gate for the subsystem."""
    import struct
    import uuid as _uuid

    from tests.client_util import ZmqClient, free_port
    from worldql_server_tpu.engine.config import Config
    from worldql_server_tpu.engine.peers import PeerMap
    from worldql_server_tpu.engine.server import WorldQLServer
    from worldql_server_tpu.entities import ColumnarIngest, EntityPlane
    from worldql_server_tpu.protocol import (
        Instruction,
        Message,
        deserialize_message,
        serialize_message,
    )
    from worldql_server_tpu.protocol.types import Entity, Vector3
    from worldql_server_tpu.spatial.tpu_backend import TpuSpatialBackend
    from worldql_server_tpu.utils.retrace import GUARD

    quick = args.quick
    n_entities = 768 if quick else 16_384
    n_peers = 32 if quick else 512
    ticks = 8 if quick else 30
    batch_per_msg = 64
    rng = np.random.default_rng(23)

    backend = TpuSpatialBackend(
        16, compact_threshold=(256 if args.smoke else None)
    )
    plane = EntityPlane(
        backend, PeerMap(), cube_size=16, k=8, dt=0.05,
        bounds=1000.0, max_entities=max(n_entities * 2, 1 << 16),
    )
    peers = [_uuid.uuid4() for _ in range(n_peers)]
    ents = [_uuid.uuid4() for _ in range(n_entities)]
    positions = rng.uniform(-800, 800, (n_entities, 3))
    velocities = rng.uniform(-120, 120, (n_entities, 3)).astype(np.float32)

    def owner_msgs(idx) -> list:
        """Update batches grouped BY OWNER (ownership is enforced)."""
        by_peer: dict[int, list[int]] = {}
        for i in idx:
            by_peer.setdefault(int(i) % n_peers, []).append(int(i))
        msgs = []
        for p, rows in by_peer.items():
            for lo in range(0, len(rows), batch_per_msg):
                chunk = rows[lo:lo + batch_per_msg]
                msgs.append(Message(
                    instruction=Instruction.LOCAL_MESSAGE,
                    sender_uuid=peers[p], world_name="bench",
                    entities=[
                        Entity(
                            uuid=ents[i],
                            position=Vector3(*positions[i]),
                            world_name="bench",
                            flex=struct.pack("<3f", *velocities[i]),
                        ) for i in chunk
                    ],
                ))
        return msgs

    def tick_once() -> float:
        t0 = time.perf_counter()
        handle = plane.dispatch_tick()
        result = plane.collect_tick(handle)
        device_ms = (time.perf_counter() - t0) * 1e3
        plane.apply(result)
        return device_ms

    # -- leg 1: registration (object path — control plane), then the
    # columnar wire ingest: every round's update batches are encoded
    # to wire bytes OUTSIDE the timed loop (the measured leg is
    # wire→SoA→device, not the client-side encoder), then batch-decode
    # + stage through the same ColumnarIngest the transport uses --
    t0 = time.perf_counter()
    for msg in owner_msgs(np.arange(n_entities)):
        plane.ingest(msg)
    register_wall = time.perf_counter() - t0
    plane.precompile()  # tick tier + scatter ladder, PR 8 discipline
    tick_once()  # first tick: full-tier twin upload
    compile_guard = GUARD.snapshot()

    ingest = ColumnarIngest(plane, sender_known=lambda u: True)
    wire_native = ingest.active
    rounds = []
    for t in range(ticks):
        # re-position a rotating half of the population onto fresh
        # random cubes: the NEXT applied tick re-quantizes them and
        # the move flows through bulk_move_subscriptions (delta path)
        half = np.arange(t % 2, n_entities, 2)
        positions[half] = rng.uniform(-800, 800, (half.size, 3))
        rounds.append([serialize_message(m) for m in owner_msgs(half)])

    churn0 = plane.index_moves
    applied_box = [0]
    ingest_wall_box = [0.0]

    async def drive():
        async def slow_route(data):
            plane.ingest(deserialize_message(data))

        for datas in rounds:
            before = plane.updates
            ti = time.perf_counter()
            await ingest.process_batch(list(datas), slow_route)
            ingest_wall_box[0] += time.perf_counter() - ti
            applied_box[0] += plane.updates - before
            tick_once()

    t0 = time.perf_counter()
    asyncio.run(drive())
    ingest_e2e_wall = time.perf_counter() - t0
    total_updates = applied_box[0]
    ingest_wall = max(ingest_wall_box[0], 1e-9)
    backend.wait_compaction()
    churn_rows = plane.index_moves - churn0

    # -- leg 2: quiet device window (no ingest) → knn_ms + retrace --
    quiet_ms = sorted(tick_once() for _ in range(max(5, ticks // 2)))
    knn_ms = quiet_ms[len(quiet_ms) // 2]
    retrace_delta = GUARD.delta(compile_guard)
    sim_retraces = retrace_delta.get("entities.sim_tick", 0)

    # CPU-reference ratio: the reference-class per-tick work is one
    # proximity resolve per entity against a dict cube index (the
    # per-message hot loop of SURVEY §3.2, batch-shaped). It skips
    # integration and ordering entirely, so the ratio UNDERSTATES the
    # device tick — an honest floor, not a flattering one.
    from worldql_server_tpu.spatial.backend import LocalQuery
    from worldql_server_tpu.spatial.cpu_backend import CpuSpatialBackend

    cpu = CpuSpatialBackend(16)
    live = plane._live[: plane._cap]
    for slot in np.flatnonzero(live).tolist():
        cpu.add_subscription(
            plane._world_names[int(plane._wid[slot])],
            plane._peer_uuids[int(plane._pid[slot])],
            tuple(int(c) for c in plane._cube[slot]),
        )
    queries = [
        LocalQuery(
            world=plane._world_names[int(plane._wid[slot])],
            position=Vector3(*plane._pos[slot].tolist()),
            sender=plane._peer_uuids[int(plane._pid[slot])],
        )
        for slot in np.flatnonzero(live).tolist()
    ]
    t0 = time.perf_counter()
    cpu.match_local_batch(queries)
    cpu_ref_ms = (time.perf_counter() - t0) * 1e3

    # -- leg 3: e2e over a real server + ZMQ transport. Shapes are
    # sized for SUSTAINABLE load (every co-cube entity produces a
    # frame every tick): the number is per-frame latency at steady
    # state, not a saturation probe — server_delivery (config 5)
    # already owns the throughput-ceiling question. --
    e2e_entities = 32 if quick else 512
    e2e_seconds = 2.0 if quick else 6.0
    e2e_tick = 0.05

    async def e2e_scenario():
        config = Config()
        config.store_url = "memory://"
        config.http_enabled = False
        config.ws_enabled = False
        config.zmq_server_port = free_port()
        config.zmq_server_host = "127.0.0.1"
        config.spatial_backend = "tpu"
        config.tick_interval = e2e_tick
        config.entity_sim = True
        config.entity_k = 8
        server = WorldQLServer(config)
        await server.start()
        try:
            a = await ZmqClient.connect(config.zmq_server_port)
            b = await ZmqClient.connect(config.zmq_server_port)
            # pairwise co-cube entities from DIFFERENT peers so every
            # tick produces cross-peer neighbor frames
            eids = [_uuid.uuid4() for _ in range(e2e_entities)]
            for i, eid in enumerate(eids):
                client = a if i % 2 == 0 else b
                base = (i // 2) * 64.0
                await client.send(Message(
                    instruction=Instruction.LOCAL_MESSAGE,
                    world_name="bench",
                    entities=[Entity(
                        uuid=eid,
                        position=Vector3(base + 1.0 + (i % 2), 1.0, 1.0),
                        world_name="bench",
                    )],
                ))

            async def drain(client):
                try:
                    while True:
                        await client.recv(timeout=0.5)
                except (asyncio.TimeoutError, asyncio.CancelledError):
                    pass

            drains = [asyncio.ensure_future(drain(a)),
                      asyncio.ensure_future(drain(b))]
            # warmup: wait until the simulation actually ticks at
            # rate (the first tick jit-compiles the sim kernel — whole
            # seconds on a CPU container) and the compile caches went
            # quiet, THEN restart the frame clock: the measured window
            # is steady-state serving, not jit walls
            plane_ = server.entity_plane
            expect = max(3, int(0.5 / e2e_tick) - 3)
            prev_ticks, prev_compiles, stable = -1, -1, 0
            for _ in range(60):  # bounded: <= 30 s
                await asyncio.sleep(0.5)
                ticks_now = plane_.applied_ticks
                compiles = sum(GUARD.counts().values())
                if (prev_ticks >= 0
                        and ticks_now - prev_ticks >= expect
                        and compiles == prev_compiles):
                    stable += 1
                    if stable >= 2:
                        break
                else:
                    stable = 0
                prev_ticks, prev_compiles = ticks_now, compiles
            server.metrics.histograms.pop("frame.e2e_ms", None)
            bytes0 = server.peer_map.bytes_delivered
            ticks0 = plane_.applied_ticks
            end = time.perf_counter() + e2e_seconds
            while time.perf_counter() < end:
                # stream updates to a rotating slice
                for i in range(0, e2e_entities, 8):
                    client = a if i % 2 == 0 else b
                    base = (i // 2) * 64.0
                    await client.send(Message(
                        instruction=Instruction.LOCAL_MESSAGE,
                        world_name="bench",
                        entities=[Entity(
                            uuid=eids[i],
                            position=Vector3(base + 1.0 + (i % 2), 1.0, 1.0),
                            world_name="bench",
                        )],
                    ))
                await asyncio.sleep(e2e_tick * 2)
            for d in drains:
                d.cancel()
            await asyncio.gather(*drains, return_exceptions=True)
            hist = server.metrics.histograms.get("frame.e2e_ms")
            snap = hist.snapshot() if hist is not None else None
            stats = server.entity_plane.stats()
            # byte volume over the measured window (ISSUE 18):
            # bytes/tick at the delivery boundary plus the per-client
            # wire rate — the leaves the interest bench (config 13)
            # compares off vs on
            bytes_win = server.peer_map.bytes_delivered - bytes0
            ticks_win = plane_.applied_ticks - ticks0
            vol = {
                "delivered_bytes_per_tick": round(
                    bytes_win / max(ticks_win, 1), 1
                ),
                "bytes_per_recipient_per_s": round(
                    bytes_win / 2 / e2e_seconds, 1
                ),
                "frame_delta_ratio": server.metrics.snapshot()[
                    "gauges"
                ].get("frame.delta_ratio") or 0.0,
            }
            await a.close()
            await b.close()
            return snap, stats, vol
        finally:
            await server.stop()

    e2e_hist, e2e_stats, e2e_vol = asyncio.run(e2e_scenario())

    if args.smoke:
        assert plane.dispatches > 0, "smoke: sim device path never fired"
        assert wire_native and plane.wire_rows > 0, (
            "smoke: native columnar decode never fired on the ingest "
            f"leg ({ingest.stats()})"
        )
        assert ingest.slow_messages == 0, (
            f"smoke: update batches fell off the fast path "
            f"({ingest.stats()})"
        )
        assert e2e_stats["wire_rows"] > 0, (
            "smoke: e2e server ingest never took the columnar path "
            f"({e2e_stats})"
        )
        assert plane.h2d_scatter > 0, (
            "smoke: incremental H2D scatter never fired"
        )
        assert backend.compactions >= 1, (
            "smoke: churn never forced a delta compaction"
        )
        assert sim_retraces == 0, (
            f"smoke: quiet sim window re-traced: {retrace_delta}"
        )
        assert e2e_stats["frames"] > 0, (
            "smoke: no neighbor frames delivered e2e"
        )
        log(f"smoke: {backend.compactions} compactions, "
            f"{e2e_stats['frames']} e2e frames "
            f"({e2e_stats['frames_native']} native-encoded), "
            f"{plane.wire_rows} columnar rows, 0 quiet retraces")

    updates_per_s = total_updates / max(ingest_wall, 1e-9)
    updates_sustained = total_updates / max(ingest_e2e_wall, 1e-9)
    result = {
        "metric": "entity_sim_knn_ms",
        "value": round(knn_ms, 4),
        "unit": "ms",
        # CPU dict-index resolve of the same per-entity queries vs the
        # device integrate+kNN tick (see leg-2 comment: a floor)
        "vs_baseline": round(cpu_ref_ms / max(knn_ms, 1e-9), 2),
        "entity_sim": {
            "cpu_reference_ms": round(cpu_ref_ms, 4),
            # wire→staged-columns ingest throughput (the PR 11 lever)
            "updates_per_s": round(updates_per_s, 1),
            # the same updates with every device tick in the wall —
            # the sustainable end-to-end rate on this host
            "updates_per_s_sustained": round(updates_sustained, 1),
            "wire_native": wire_native,
            "wire_rows": plane.wire_rows,
            "wire_slow_rows": plane.wire_slow_rows,
            "column_flips": plane.column_flips,
            "h2d_scatter": plane.h2d_scatter,
            "h2d_full": plane.h2d_full,
            "frames_native": plane.frames_native,
            "knn_ms": round(knn_ms, 4),
            "e2e_p99_ms": (
                round(e2e_hist["p99_ms"], 3) if e2e_hist else None
            ),
            "e2e_p50_ms": (
                round(e2e_hist["p50_ms"], 3) if e2e_hist else None
            ),
            "e2e_frames": e2e_stats["frames"],
            "e2e_wire_rows": e2e_stats["wire_rows"],
            "delivered_bytes_per_tick": e2e_vol[
                "delivered_bytes_per_tick"
            ],
            "bytes_per_recipient_per_s": e2e_vol[
                "bytes_per_recipient_per_s"
            ],
            "frame_delta_ratio": e2e_vol["frame_delta_ratio"],
            "entities": n_entities,
            "peers": n_peers,
            "k": 8,
            "register_per_s": round(n_entities / max(register_wall, 1e-9), 1),
            "churn_rows_per_s": round(
                churn_rows / max(ingest_e2e_wall, 1e-9), 1
            ),
            "index_moves": churn_rows,
            "compactions": backend.compactions,
            "sim_retraces_quiet": sim_retraces,
            "delta_rows": backend.device_stats()["delta_rows"],
        },
        "config": 8,
    }
    log(f"entity_sim: {updates_per_s:,.0f} updates/s wire ingest "
        f"({updates_sustained:,.0f}/s sustained incl. ticks), "
        f"knn {knn_ms:.3f} ms @ {n_entities} entities, "
        f"e2e p99 {result['entity_sim']['e2e_p99_ms']} ms, "
        f"{backend.compactions} compactions")
    return result


# --------------------------------------------------------------------


def bench_config9(args) -> dict:
    """Overload-storm admission workload (ISSUE 10): a real server
    over real ZMQ with the OverloadGovernor on, deliberately throttled
    (tiny tick budget → degraded admitted tier) so a single client can
    offer sustained multiples of the sustainable rate even on a 1-core
    container. Three legs:

    * **sustainable** — unpaced flood, governor engaged → the admitted
      ceiling ``sustainable_per_s`` (the 1x reference);
    * **2x / 10x** — offered load paced to 2x and 10x of that ceiling
      while a record-op stream (durability='wal', acked at the fsync)
      runs through the SAME router → per-phase admitted-vs-offered
      rate, shed fraction by class, governor peak state, and the
      admitted record-op p99;
    * **audit** — after each phase drains, offered == flushed +
      drop-oldest + shed-at-ingest, exactly (shed work is never
      silent).

    ``--smoke`` shrinks the windows and asserts the 10x phase actually
    engaged the governor, shed work, kept the audit exact, and landed
    every record op — the CI gate for the overload plane."""
    import tempfile

    from tests.client_util import ZmqClient, free_port
    from worldql_server_tpu.engine.config import Config
    from worldql_server_tpu.engine.server import WorldQLServer
    from worldql_server_tpu.protocol import Instruction, Message
    from worldql_server_tpu.protocol.types import Record, Vector3

    quick = args.quick
    base_s = 0.8 if quick else 3.0
    phase_s = 1.0 if quick else 4.0
    record_rate = 25  # record ops per second, through the wal path

    tmp = tempfile.TemporaryDirectory(prefix="wql-overload-bench-")
    config = Config(
        store_url=f"sqlite://{tmp.name}/bench.db",
        durability="wal", wal_dir=f"{tmp.name}/wal",
        checkpoint_interval=0.5,
        http_enabled=False, ws_enabled=False,
        zmq_server_host="127.0.0.1", zmq_server_port=free_port(),
        spatial_backend="cpu", tick_interval=0.01,
        max_batch=256, overload="on",
        overload_tick_budget_ms=0.5, overload_min_batch=8,
        overload_deadline_k=2, overload_recover_ticks=5,
    )

    async def scenario() -> dict:
        server = WorldQLServer(config)
        await server.start()
        gov = server.governor
        metrics = server.metrics
        try:
            client = await ZmqClient.connect(config.zmq_server_port)

            def counters() -> dict:
                snap = metrics.snapshot()["counters"]
                return {
                    "seen": snap.get("messages.local_message", 0),
                    "flushed": snap.get("tick.messages", 0),
                    "dropped": gov.drop_oldest,
                    "shed": gov.shed["local"],
                    "limited": gov.rate_limited,
                }

            async def flood(duration: float, rate: float | None):
                """Offer locals for ``duration``; None = unpaced.
                Returns (offered, wall)."""
                sent = 0
                t0 = time.perf_counter()
                end = t0 + duration
                while time.perf_counter() < end:
                    for _ in range(32):
                        await client.send(Message(
                            instruction=Instruction.LOCAL_MESSAGE,
                            world_name="bench",
                            position=Vector3(1.0, 1.0, 1.0),
                            parameter="s",
                        ))
                        sent += 1
                    if rate is not None:
                        pace = t0 + sent / rate - time.perf_counter()
                        if pace > 0:
                            await asyncio.sleep(pace)
                        else:
                            await asyncio.sleep(0)
                return sent, time.perf_counter() - t0

            async def drain():
                for _ in range(1000):
                    if (
                        not server.ticker._queue
                        and not server.ticker.inflight()
                    ):
                        return
                    await asyncio.sleep(0.01)

            record_seq = [0]

            async def record_ops(duration: float) -> list:
                walls = []
                end = time.perf_counter() + duration
                while time.perf_counter() < end:
                    record_seq[0] += 1
                    i = record_seq[0]
                    t0 = time.perf_counter()
                    await server.router.durability.insert_records([
                        Record(
                            uuid=uuid_mod.UUID(int=i), world_name="w",
                            position=Vector3(1, 2, 3), data=f"r{i}",
                        )
                    ])
                    walls.append((time.perf_counter() - t0) * 1e3)
                    await asyncio.sleep(1.0 / record_rate)
                return walls

            async def run_phase(duration: float, rate: float | None):
                """One offered-load window: flood (paced or unpaced)
                + the concurrent record stream, drained, audited."""
                before = counters()
                gov.peak_level = gov.level  # peak WITHIN this phase
                (offered, wall), walls = await asyncio.gather(
                    flood(duration, rate), record_ops(duration),
                )
                await drain()
                after = counters()
                delta = {k: after[k] - before[k] for k in after}
                walls.sort()
                shed_total = delta["dropped"] + delta["shed"]
                return {
                    "offered_per_s": round(offered / wall, 1),
                    "admitted_per_s": round(delta["flushed"] / wall, 1),
                    "shed_fraction_local": round(
                        shed_total / max(delta["seen"], 1), 4
                    ),
                    "drop_oldest": delta["dropped"],
                    "shed_at_ingest": delta["shed"],
                    "rate_limited": delta["limited"],
                    "governor_peak_level": gov.peak_level,
                    "record_ops": len(walls),
                    "record_p99_ms": round(
                        walls[max(0, int(len(walls) * 0.99) - 1)], 3
                    ) if walls else None,
                    # the exactness invariant, reported not assumed
                    "audit_exact": (
                        delta["seen"] == delta["flushed"] + shed_total
                    ),
                }

            # -- leg 1: saturation storm (unpaced = everything the
            # client can offer). What the governed server SERVES under
            # it is the sustainable ceiling — the 1x reference for the
            # paced legs — and the shedding here is guaranteed, which
            # is what the smoke gate pins.
            saturation = await run_phase(base_s, None)
            sustainable = max(saturation["admitted_per_s"], 1.0)
            phases = {"saturation": saturation}

            # -- legs 2+3: paced at 2x and 10x the sustained ceiling --
            for factor in (2, 10):
                phase = await run_phase(phase_s, sustainable * factor)
                phase["target_factor"] = factor
                phase["achieved_factor"] = round(
                    phase["offered_per_s"] / sustainable, 2
                )
                phases[f"{factor}x"] = phase

            # recovery: back to OK after the storm (bounded wait)
            recovered_ticks = None
            ticks0 = gov.ticks
            for _ in range(600):
                if gov.state == "ok" and not gov.degraded():
                    recovered_ticks = gov.ticks - ticks0
                    break
                await asyncio.sleep(0.01)

            await client.close()
            return {
                "sustainable_per_s": round(sustainable, 1),
                "phases": phases,
                "recovered_to_ok_within_ticks": recovered_ticks,
                "transitions": gov.transitions,
                "coalesced": int(
                    metrics.snapshot()["counters"].get(
                        "overload.coalesced", 0
                    )
                ),
                "record_ops_total": record_seq[0],
            }
        finally:
            await server.stop()
            tmp.cleanup()

    overload = asyncio.run(scenario())

    if args.smoke:
        sat = overload["phases"]["saturation"]
        assert sat["governor_peak_level"] >= 1, (
            "smoke: saturation storm never escalated the governor"
        )
        assert sat["drop_oldest"] + sat["shed_at_ingest"] > 0, (
            "smoke: saturation storm shed nothing"
        )
        for phase in overload["phases"].values():
            assert phase["audit_exact"], (
                f"smoke: shed accounting mismatch: {phase}"
            )
        assert sat["record_ops"] > 0 and sat["record_p99_ms"], (
            "smoke: record stream never ran under the storm"
        )
        assert overload["recovered_to_ok_within_ticks"] is not None, (
            "smoke: governor never returned to OK after the storm"
        )
        log(
            f"smoke: saturation shed {sat['shed_fraction_local']:.1%}, "
            f"audit exact, record p99 {sat['record_p99_ms']} ms, "
            f"OK after {overload['recovered_to_ok_within_ticks']} ticks"
        )

    p10 = overload["phases"]["10x"]
    result = {
        "metric": "overload_admitted_at_10x_per_s",
        "value": p10["admitted_per_s"],
        "unit": "per_s",
        "overload": overload,
        "config": 9,
    }
    log(
        f"overload: sustainable {overload['sustainable_per_s']:,.0f}/s; "
        f"10x offered {p10['offered_per_s']:,.0f}/s -> admitted "
        f"{p10['admitted_per_s']:,.0f}/s, shed "
        f"{p10['shed_fraction_local']:.1%}, record p99 "
        f"{p10['record_p99_ms']} ms"
    )
    return result


def bench_config10(args) -> dict:
    """Adversarial scenario suite (ISSUE 12, ROADMAP 5b): run the
    first-class scenario library — flash-crowd migration, battle-royale
    shrinking bounds, hostile-swarm reconnect storm, mixed game-tick —
    each a REAL server over real ZMQ with declared survival + SLO
    checks, and emit the suite as one bench record. ``--smoke`` asserts
    every check green (the CI gate); the perf gate then diffs the
    stable leaves (check_failures, lost_subscriptions/entities,
    resumed counts) against the baseline, so one newly failing
    scenario assertion — or one lost resumed row — fails the build."""
    from worldql_server_tpu.scenarios import run_scenario

    shape = "smoke" if args.quick else "full"
    names = ["flash_crowd", "battle_royale", "reconnect_storm", "game_tick"]
    reports = {}
    check_failures = 0
    for name in names:
        log(f"scenario {name} ({shape})...")
        report = run_scenario(name, shape=shape)
        reports[name] = report
        check_failures += report["checks_failed"]
        log(
            f"scenario {name}: "
            f"{'PASS' if report['checks_failed'] == 0 else 'FAIL'} "
            f"in {report['wall_s']}s "
            f"({report['checks_failed']} failed checks)"
        )

    if args.smoke:
        for name, report in reports.items():
            failed = [c["name"] for c in report["checks"] if not c["ok"]]
            assert not failed, (
                f"smoke: scenario {name} failed checks: {failed}"
            )
        log("smoke: all scenario survival + SLO checks green")

    storm = reports["reconnect_storm"]["slo"]
    return {
        "metric": "scenario_check_failures",
        "value": check_failures,
        "unit": "count",
        # the tentpole guarantee as first-class gated leaves: resumed
        # sessions lose NOTHING ("lost"-named → lower-is-better gated)
        "lost_subscriptions": max(
            0,
            storm.get("subscriptions_before", 0)
            - storm.get("subscriptions_after", 0),
        ),
        "lost_entities": (
            storm.get("entities_before", 0)
            - storm.get("entities_after", 0)
        ),
        "sessions_resumed": storm.get("resumed", 0),
        "resume_p99_ms": storm.get("resume_p99_ms"),
        "scenarios": {
            name: {
                "survived": report["survived"],
                "check_failures": report["checks_failed"],
                "wall_s": report["wall_s"],
                "slo": report["slo"],
            }
            for name, report in reports.items()
        },
        "config": 10,
    }


async def _cluster_point(n_shards: int, window_s: float,
                         max_batch: int) -> dict:
    """One cluster_scaling point: boot a router + ``n_shards`` shard
    server subprocesses, drive a paced-burst LocalMessage storm spread
    over one world per shard, and close the books with the EXACT shed
    audit: offered == admitted + shed-at-router + shed-at-shard
    (admitted = shard-arrived − shard-shed; the router's forward leg
    is lossless ZMQ, so offered − router-shed must equal arrived)."""
    import uuid as uuid_mod

    from worldql_server_tpu.cluster import ClusterRuntime, WorldMap
    from worldql_server_tpu.engine.config import Config
    from worldql_server_tpu.protocol.types import (
        Instruction as Ins, Message as Msg, Vector3 as V3,
    )
    from worldql_server_tpu.scenarios.client import ZmqPeer, free_port_block

    config = Config(
        store_url="memory://",
        http_enabled=False, ws_enabled=False,
        zmq_server_host="127.0.0.1",
        zmq_server_port=free_port_block(n_shards + 1),
        spatial_backend="cpu", tick_interval=0.02,
        max_batch=max_batch, overload="on",
        supervisor_backoff=0.005,
        cluster_shards=n_shards,
    )
    world_map = WorldMap(n_shards)

    def world_for(shard: int) -> str:
        for i in range(10_000):
            name = f"scale{i}"
            if world_map.shard_of_world(name) == shard:
                return name
        raise AssertionError("no world for shard")

    def uuid_for(shard: int) -> uuid_mod.UUID:
        while True:
            u = uuid_mod.uuid4()
            if world_map.shard_of_peer(u) == shard:
                return u

    worlds = [world_for(i) for i in range(n_shards)]
    pos = V3(5.0, 5.0, 5.0)
    runtime = ClusterRuntime(config)
    await runtime.start()
    # prime the per-core efficiency gauge's sampling window: the final
    # read then rates Δdeliveries / Δcpu-seconds over the load phases
    runtime.router.federation.deliveries_per_s_per_core()
    clients: list[ZmqPeer] = []
    try:
        async def connect(**kw) -> ZmqPeer:
            last = None
            for _ in range(100):
                try:
                    peer = await ZmqPeer.connect(
                        config.zmq_server_port, **kw
                    )
                    clients.append(peer)
                    return peer
                except Exception as exc:
                    last = exc
                    await asyncio.sleep(0.05)
            raise AssertionError(f"bench client connect failed: {last!r}")

        flooders = [
            (await connect(), worlds[i % n_shards])
            for i in range(2 * n_shards)
        ]
        for client, world in flooders:
            await client.send(Msg(
                instruction=Ins.AREA_SUBSCRIBE, world_name=world,
                position=pos,
            ))
        # cross-shard latency pair (n >= 2): receiver homed on shard
        # 0, world owned by shard 1 — every frame crosses the 1→0 ring.
        # Latency is NOT timed harness-side anymore: the shards close
        # cluster.e2e_ms / cluster.xshard_ms live at socket-write-
        # complete and the router federates them (ISSUE 15); the
        # receiver below only drains its socket.
        rx = tx = None
        xshard_received = 0
        if n_shards >= 2:
            rx = await connect(peer_uuid=uuid_for(0))
            tx = await connect(peer_uuid=uuid_for(1))
            for c in (rx, tx):
                await c.send(Msg(
                    instruction=Ins.AREA_SUBSCRIBE,
                    world_name=worlds[1], position=pos,
                ))
        await asyncio.sleep(0.3)

        stop = asyncio.Event()

        async def flood(client: ZmqPeer, world: str,
                        pace_s: float) -> int:
            sent = 0
            while not stop.is_set():
                for _ in range(16):
                    await client.send(Msg(
                        instruction=Ins.LOCAL_MESSAGE, world_name=world,
                        position=pos, parameter="load",
                    ))
                    sent += 1
                await asyncio.sleep(pace_s)
            return sent

        async def xshard_traffic() -> int:
            sent = 0
            while not stop.is_set():
                await tx.send(Msg(
                    instruction=Ins.LOCAL_MESSAGE, world_name=worlds[1],
                    position=pos, parameter=f"x:{time.monotonic_ns()}",
                ))
                sent += 1
                await asyncio.sleep(0.05)
            return sent

        async def xshard_receiver() -> None:
            nonlocal xshard_received
            while True:
                got = await rx.recv(30)
                if (
                    got.instruction == Ins.LOCAL_MESSAGE
                    and got.parameter
                    and got.parameter.startswith("x:")
                ):
                    xshard_received += 1

        async def stopper(for_s: float):
            await asyncio.sleep(for_s)
            stop.set()

        # settle helper: shard counters arrive on ~1s state pushes —
        # wait until two consecutive reads agree (queues drained,
        # books closed) before reading a phase's totals
        def shard_counters() -> list[dict]:
            return [
                dict(runtime.supervisor.shard_state(i).get(
                    "counters", {}
                ))
                for i in range(n_shards)
            ]

        async def settle() -> list[dict]:
            prev = shard_counters()
            deadline = time.perf_counter() + 20
            while time.perf_counter() < deadline:
                await asyncio.sleep(1.3)
                cur = shard_counters()
                if cur == prev and all(c for c in cur):
                    return cur
                prev = cur
            return prev

        def totals(counters: list[dict]) -> tuple[int, int]:
            arrived = sum(
                c.get("messages.local_message", 0) for c in counters
            )
            shed = sum(
                c.get("overload.shed_local", 0)
                + c.get("overload.drop_oldest", 0)
                for c in counters
            )
            return arrived, shed

        receiver = (
            asyncio.ensure_future(xshard_receiver())
            if rx is not None else None
        )
        try:
            # phase 1 — BALANCED: every flooder bursts its own shard's
            # world; this is the admitted-throughput measurement
            tasks = [flood(c, w, 0.002) for c, w in flooders]
            if tx is not None:
                tasks.append(xshard_traffic())
            tasks.append(stopper(window_s))
            results = await asyncio.gather(*tasks)
            offered_balanced = sum(results[: len(flooders)])
            offered = offered_balanced
            if tx is not None:
                offered += results[len(flooders)]
            await asyncio.sleep(1.0)  # in-flight frames land
            arrived1, shed1 = totals(await settle())
            admitted_balanced = arrived1 - shed1

            # phase 2 — HOTSPOT: the whole fleet converges on shard
            # 0's world until it REJECTs and the refusals move to the
            # router tier (the shed-accounting leg of the audit)
            stop.clear()
            hot_tasks = [
                flood(c, worlds[0], 0.001) for c, _ in flooders
            ]
            hot_tasks.append(stopper(min(window_s, 1.5)))
            hot_results = await asyncio.gather(*hot_tasks)
            offered += sum(hot_results[: len(flooders)])
            await asyncio.sleep(1.0)
        finally:
            if receiver is not None:
                receiver.cancel()
                try:
                    await receiver
                except (asyncio.CancelledError, Exception):
                    pass

        arrived, shed_shard = totals(await settle())
        snapshot = runtime.metrics.snapshot()
        router_counters = snapshot["counters"]
        shed_router = router_counters.get("cluster.router_shed_local", 0)
        admitted = arrived - shed_shard
        audit_exact = offered == admitted + shed_shard + shed_router
        # ISSUE 15: latency leaves come from the LIVE federated
        # histograms the shards closed at socket-write-complete —
        # the router's one /metrics registry, not harness clocks
        latency = snapshot["latency"]
        e2e = latency.get("cluster.e2e_ms") or {}
        xshard = latency.get("cluster.xshard_ms") or {}
        per_core = runtime.router.federation.deliveries_per_s_per_core()
        return {
            "shards": n_shards,
            "offered": offered,
            "arrived": arrived,
            "admitted": admitted,
            "admitted_per_s": round(admitted_balanced / window_s, 1),
            "shed_router": shed_router,
            "shed_shard": shed_shard,
            "audit_exact": bool(audit_exact),
            "cluster_e2e_frames": int(e2e.get("count", 0)),
            "cluster_e2e_p99_ms": (
                round(e2e["p99_ms"], 2) if e2e.get("count") else None
            ),
            "xshard_frames": int(xshard.get("count", 0)),
            "xshard_received": xshard_received,
            "xshard_p99_ms": (
                round(xshard["p99_ms"], 2) if xshard.get("count") else None
            ),
            "deliveries_per_s_per_core": per_core,
            "router_forwarded":
                router_counters.get("cluster.router_forwarded", 0),
        }
    finally:
        for client in clients:
            try:
                client.close()
            except Exception:
                pass
        await runtime.stop()


def bench_config11(args) -> dict:
    """Cluster horizontal-serving scaling curve (ISSUE 14): 1→N shard
    processes behind the router tier on THIS container, admitted
    LocalMessage throughput and cross-shard delivery p99 per point,
    with the router-tier shed accounting closed EXACTLY per point
    (offered == admitted + shed-at-router + shed-at-shard). On a
    1-core box the shards time-share the core, so the curve measures
    the serving stack's overhead and accounting honesty, not speedup —
    the near-linear claim belongs to a multi-core/multi-chip run.
    Latency leaves (``cluster_e2e_p99_ms`` / ``xshard_p99_ms``) read
    the LIVE federated histograms the shards close at socket-write-
    complete (ISSUE 15), not harness-side clocks, and
    ``deliveries_per_s_per_core`` is the ROADMAP item 4 efficiency
    gauge (Δdeliveries ÷ Δcpu-seconds across the fleet).
    ``--smoke`` asserts every point's audit is exact, the router tier
    provably shed for a drowning shard, cross-shard delivery flowed,
    and the live histograms + per-core gauge advanced. NOTE: shard
    subprocesses inherit the environment — on a
    TPU-less box with libtpu installed, JAX_PLATFORMS=cpu must be set
    (the CI bench step does)."""
    shard_counts = [1, 2] if args.quick else [1, 2, 4]
    window_s = 1.5 if args.quick else 5.0
    max_batch = 32 if args.quick else 256
    points = []
    for n in shard_counts:
        log(f"cluster point: {n} shard(s), {window_s}s window...")
        point = asyncio.run(_cluster_point(n, window_s, max_batch))
        log(
            f"  {n} shard(s): offered {point['offered']:,} -> admitted "
            f"{point['admitted']:,} ({point['admitted_per_s']:,}/s), "
            f"router shed {point['shed_router']:,}, shard shed "
            f"{point['shed_shard']:,}, audit "
            f"{'EXACT' if point['audit_exact'] else 'BROKEN'}, "
            f"e2e p99 {point['cluster_e2e_p99_ms']} ms (live hist, "
            f"{point['cluster_e2e_frames']:,} frames), xshard p99 "
            f"{point['xshard_p99_ms']} ms, "
            f"{point['deliveries_per_s_per_core']:,}/s/core"
        )
        points.append(point)

    audit_failures = sum(1 for p in points if not p["audit_exact"])
    if args.smoke:
        assert audit_failures == 0, (
            f"smoke: shed accounting broke: {points}"
        )
        assert all(p["shed_router"] > 0 for p in points), (
            "smoke: the router tier never shed for a drowning shard"
        )
        assert all(p["admitted"] > 0 for p in points)
        multi = [p for p in points if p["shards"] >= 2]
        assert multi and all(p["xshard_frames"] > 0 for p in multi), (
            "smoke: cross-shard delivery never flowed"
        )
        # ISSUE 15: the latency leaves must come from the LIVE
        # federated histograms — frames closed on the shards, merged
        # at the router — and the per-core gauge must have rated
        assert all(p["cluster_e2e_frames"] > 0 for p in points), (
            "smoke: no shard ever closed the router-ingress frame "
            "clock (cluster.e2e_ms empty in the federated registry)"
        )
        assert all(
            p["xshard_p99_ms"] is not None for p in multi
        ), "smoke: live cluster.xshard_ms histogram never advanced"
        assert any(
            p["deliveries_per_s_per_core"] > 0 for p in points
        ), "smoke: deliveries_per_s_per_core never rated"
        log("smoke: cluster audit exact at every point, router-tier "
            "shed fired, cross-shard delivery flowed, live e2e/xshard "
            "histograms + per-core gauge advanced")
    return {
        "metric": "cluster_audit_failures",
        "value": audit_failures,
        "unit": "count",
        "audit_failures": audit_failures,
        "max_admitted_per_s": max(p["admitted_per_s"] for p in points),
        "deliveries_per_s_per_core": max(
            p["deliveries_per_s_per_core"] for p in points
        ),
        "points": points,
        "config": 11,
    }


def _kind_cols(rng, m: int, kind_id: int):
    """→ (kinds i8 [m], params f64 [m, 6]) staged columns for one kind,
    parameters drawn exactly as the wire parsers clamp them (cube 16,
    stencil 3, ray steps 64)."""
    from worldql_server_tpu.queries.kinds import (
        KIND_CONE, KIND_DENSITY, KIND_KNN, KIND_RAYCAST, PARAM_LANES,
        RAY_ALL_HITS, RAY_FIRST_HIT,
    )

    kinds = np.full(m, kind_id, np.int8)
    params = np.zeros((m, PARAM_LANES), np.float64)
    if kind_id in (KIND_CONE, KIND_RAYCAST):
        d = rng.normal(size=(m, 3))
        d /= np.linalg.norm(d, axis=1, keepdims=True)
        params[:, 0:3] = d
    if kind_id == KIND_CONE:
        params[:, 3] = np.cos(np.radians(rng.uniform(20.0, 80.0, m)))
        params[:, 4] = rng.uniform(12.0, 48.0, m)
    elif kind_id == KIND_RAYCAST:
        params[:, 3] = rng.uniform(16.0, 64.0, m)          # max_t
        params[:, 4] = np.where(
            rng.random(m) < 0.5, RAY_FIRST_HIT, RAY_ALL_HITS
        )
    elif kind_id == KIND_KNN:
        params[:, 0] = rng.integers(1, 12, m).astype(np.float64)
        params[:, 1] = rng.uniform(12.0, 48.0, m)          # max_range
    elif kind_id == KIND_DENSITY:
        params[:, 0] = rng.integers(1, 3, m).astype(np.float64)
        params[:, 1] = 8.0                                 # top_n
    return kinds, params


def _query_results_match(got, want) -> bool:
    """Lane-for-lane result equality across the two collect shapes:
    KindResult triples for library kinds, peer sets for radius rows
    (radius peer ORDER is an index-layout artifact on both paths)."""
    from worldql_server_tpu.queries.results import KindResult

    if isinstance(got, KindResult) or isinstance(want, KindResult):
        return (
            isinstance(got, KindResult)
            and isinstance(want, KindResult)
            and got.kind == want.kind
            and list(got.peers) == list(want.peers)
            and got.extra == want.extra
        )
    return set(got) == set(want)


def bench_config12(args) -> dict:
    """Spatial query library (ISSUE 17): per-kind device throughput of
    the staged kind pipeline (cone / raycast / filtered-kNN / density
    expanded into probe rows riding the radius hash-probe), the
    mixed-kind batch's p50/p99 next to a pure-radius batch of the SAME
    size (the cost of carrying the library), and CPU-oracle parity
    sampled across every kind in the mixed batch. ``--smoke`` asserts
    the kind-expansion path actually fired, parity held on every
    sampled lane, and the timed window re-traced nothing after the
    boot tier walk (precompile.py's kind leg). The gate leaves are the
    parity/retrace COUNTS — the rates are 1-core-bound and pruned from
    the checked-in baseline."""
    from worldql_server_tpu.queries.kinds import (
        KIND_CONE, KIND_DENSITY, KIND_KNN, KIND_RADIUS, KIND_RAYCAST,
        PARAM_LANES,
    )
    from worldql_server_tpu.spatial.backend import LocalQuery
    from worldql_server_tpu.spatial.cpu_backend import CpuSpatialBackend
    from worldql_server_tpu.spatial.precompile import precompile_tiers
    from worldql_server_tpu.spatial.tpu_backend import TpuSpatialBackend
    from worldql_server_tpu.utils.retrace import GUARD
    from worldql_server_tpu.protocol.types import Replication, Vector3

    n_worlds = 4
    m = min(args.queries, 512 if args.quick else 4096)
    reps = 5 if args.quick else 11
    rng = np.random.default_rng(17)
    tpu = TpuSpatialBackend(cube_size=16)
    peers, sub_positions, sub_world_ids = build_index(
        tpu, rng, args.subs, n_worlds
    )
    tpu.flush()
    tpu.wait_compaction()

    # staged columns, interned exactly as engine/staging.py encodes
    senders = rng.integers(0, len(peers), m)
    wid_col = np.fromiter(
        (tpu._world_ids.get(f"world_{w}", -1)
         for w in sub_world_ids[senders]),
        np.int32, count=m,
    )
    sid_col = np.fromiter(
        (tpu._peer_ids.get(peers[s], -1) for s in senders),
        np.int32, count=m,
    )
    pos_col = np.ascontiguousarray(sub_positions[senders], np.float64)
    repl_col = np.full(m, int(Replication.EXCEPT_SELF), np.int8)

    kind_ids = {
        "cone": KIND_CONE, "raycast": KIND_RAYCAST,
        "knn": KIND_KNN, "density": KIND_DENSITY,
    }
    pure = {
        name: _kind_cols(rng, m, kid) for name, kid in kind_ids.items()
    }
    # mixed batch: every kind plus a radius share, interleaved
    mixed_kinds = np.zeros(m, np.int8)
    mixed_params = np.zeros((m, PARAM_LANES), np.float64)
    lanes = [KIND_RADIUS, *kind_ids.values()]
    for j, kid in enumerate(lanes):
        sel = np.flatnonzero(np.arange(m) % len(lanes) == j)
        mixed_kinds[sel] = kid
        if kid != KIND_RADIUS:
            _, p = _kind_cols(rng, sel.size, kid)
            mixed_params[sel] = p

    def run_once(kinds, params):
        t0 = time.perf_counter()
        out = tpu.collect_local_batch(
            tpu.dispatch_staged_batch(
                wid_col, pos_col, sid_col, repl_col, kinds, params
            )
        )
        return out, (time.perf_counter() - t0) * 1e3

    # discovery pass: kind expansion turns m queries into (many more)
    # probe rows, and THOSE are the tiers the radius pipeline runs at —
    # size the boot walk to the largest probe batch, not to m
    probe_rows = m
    for kinds, params in (*pure.values(), (mixed_kinds, mixed_params)):
        handle = tpu.dispatch_staged_batch(
            wid_col, pos_col, sid_col, repl_col, kinds, params
        )
        probe_rows = max(
            probe_rows, int(handle[1][1].probe_owner.shape[0])
        )
        tpu.collect_local_batch(handle)
    pc_stats = precompile_tiers(
        tpu, max_batch=probe_rows, t_tiers=2, max_compiles=128
    )
    log(f"tier precompile (probe tier {probe_rows}): {pc_stats}")
    for kinds, params in (*pure.values(), (mixed_kinds, mixed_params),
                          (None, None)):
        run_once(kinds, params)        # warm every shape once
        run_once(kinds, params)
    guard_before = GUARD.snapshot()

    per_kind = {}
    for name, (kinds, params) in pure.items():
        walls = [run_once(kinds, params)[1] for _ in range(reps)]
        wall = float(np.median(walls))
        per_kind[name] = {
            "device_queries_per_s": round(m / (wall / 1e3)),
            "wall_ms": round(wall, 3),
        }
        log(f"{name}: {wall:.2f} ms/batch "
            f"({per_kind[name]['device_queries_per_s']:,}/s)")
    mixed_out, _ = run_once(mixed_kinds, mixed_params)
    mixed_walls = np.array(
        [run_once(mixed_kinds, mixed_params)[1] for _ in range(reps)]
    )
    radius_walls = np.array(
        [run_once(None, None)[1] for _ in range(reps)]
    )
    retrace_delta = GUARD.delta(guard_before)
    retraces = sum(retrace_delta.values())
    log(f"mixed: p50 {pctl(mixed_walls, 50):.2f} p99 "
        f"{pctl(mixed_walls, 99):.2f} ms  radius: p50 "
        f"{pctl(radius_walls, 50):.2f} p99 {pctl(radius_walls, 99):.2f} "
        f"ms  retraces {retraces} {retrace_delta or ''}")

    # CPU-oracle parity, stratified across every kind in the mixed
    # batch (the randomized property suite in tests/test_queries.py is
    # the exhaustive version; this pins the BENCH shapes)
    cpu = CpuSpatialBackend(cube_size=16)
    build_index(cpu, np.random.default_rng(17), args.subs, n_worlds)
    parity = {name: True for name in ("radius", *kind_ids)}
    by_id = {0: "radius", **{v: k for k, v in kind_ids.items()}}
    sample = []
    for kid in (KIND_RADIUS, *kind_ids.values()):
        sample.extend(np.flatnonzero(mixed_kinds == kid)[:12])
    for i in sample:
        want = cpu.match_local_batch([
            LocalQuery(
                f"world_{sub_world_ids[senders[i]]}",
                Vector3(*pos_col[i]),
                peers[senders[i]],
                Replication.EXCEPT_SELF,
                kind=int(mixed_kinds[i]),
                params=tuple(mixed_params[i]),
            )
        ])[0]
        if not _query_results_match(mixed_out[i], want):
            parity[by_id[int(mixed_kinds[i])]] = False
            log(f"parity diverged: query {i} kind {mixed_kinds[i]}: "
                f"{mixed_out[i]!r} vs {want!r}")
    parity_failures = sum(1 for ok in parity.values() if not ok)
    log(f"parity: {parity_failures} failure(s) across "
        f"{len(sample)} sampled lanes {parity}")

    if args.smoke:
        assert tpu.kind_expansions > 0, \
            "smoke: the kind-expansion path never fired"
        assert parity_failures == 0, \
            f"smoke: kind results diverged from the CPU oracle: {parity}"
        assert retraces == 0, (
            "smoke: the timed window re-traced despite the kind tier "
            f"walk: {retrace_delta}"
        )
        log(f"smoke: {tpu.kind_expansions} kind expansions, parity "
            f"green on every kind, retraces {retraces}")
    return {
        "metric": "query_parity_failures",
        "value": parity_failures,
        "unit": "count",
        "parity_failures": parity_failures,
        "parity": {k: int(v) for k, v in parity.items()},
        "retraces": retraces,
        "kind_expansions": int(tpu.kind_expansions),
        "kinds": per_kind,
        "mixed_p50_ms": round(pctl(mixed_walls, 50), 3),
        "mixed_p99_ms": round(pctl(mixed_walls, 99), 3),
        "radius_p50_ms": round(pctl(radius_walls, 50), 3),
        "radius_p99_ms": round(pctl(radius_walls, 99), 3),
        "mixed_over_radius": round(
            float(np.median(mixed_walls) / np.median(radius_walls)), 2
        ),
        "config": 12,
    }


# --------------------------------------------------------------------


def bench_config13(args) -> dict:
    """Interest-managed fan-out (ISSUE 18): the game_tick shape — a
    mostly-static population with a small moving minority — run twice
    at IDENTICAL shapes over real ZMQ sockets, ``--interest off`` then
    ``on``. The off leg re-broadcasts every visible entity every tick;
    the on leg ships per-recipient deltas on the stamped epoch:seq
    wire. Reported: delivered bytes/tick and bytes/recipient/s for
    both legs, the reduction ratio, the on-leg ``frame.delta_ratio``,
    and the eventual-state parity verdict — one observer's socket is
    replayed through the :class:`ReplayClient` oracle and compared
    against the server's own per-peer ledger after quiescing.

    ``--smoke`` asserts parity is green (zero refused deltas, zero
    gaps, snapshot == ledger), deltas actually flowed, and the
    reduction clears 2x; the record run must clear the ISSUE's 5x."""
    import struct
    import uuid as _uuid

    from tests.client_util import ZmqClient, free_port
    from worldql_server_tpu.engine.config import Config
    from worldql_server_tpu.engine.server import WorldQLServer
    from worldql_server_tpu.interest import ReplayClient
    from worldql_server_tpu.protocol import Instruction, Message
    from worldql_server_tpu.protocol.types import Entity, Vector3
    from worldql_server_tpu.utils.retrace import GUARD

    quick = args.quick
    n_watchers = 4 if quick else 8
    ents_per_watcher = 4 if quick else 12
    n_movers = 2 if quick else 8
    measure_s = 2.0 if quick else 6.0
    tick = 0.05
    rng = np.random.default_rng(1813)

    async def variant(interest: str) -> dict:
        config = Config()
        config.store_url = "memory://"
        config.http_enabled = False
        config.ws_enabled = False
        config.zmq_server_port = free_port()
        config.zmq_server_host = "127.0.0.1"
        config.spatial_backend = "tpu"
        config.tick_interval = tick
        config.entity_sim = True
        config.entity_k = 8
        config.interest = interest
        server = WorldQLServer(config)
        await server.start()
        try:
            clients = [
                await ZmqClient.connect(config.zmq_server_port)
                for _ in range(n_watchers)
            ]
            observer = clients[-1]
            # static majority: a co-located cluster inside one cube
            for c in clients:
                await c.send(Message(
                    instruction=Instruction.LOCAL_MESSAGE,
                    world_name="bench",
                    entities=[Entity(
                        uuid=_uuid.uuid4(),
                        position=Vector3(*rng.uniform(4, 12, 3)),
                        world_name="bench",
                    ) for _ in range(ents_per_watcher)],
                ))
            # moving minority: velocity-integrated by the device tick,
            # no further client sends needed to generate churn
            movers = [_uuid.uuid4() for _ in range(n_movers)]
            await clients[0].send(Message(
                instruction=Instruction.LOCAL_MESSAGE,
                world_name="bench",
                entities=[Entity(
                    uuid=m, position=Vector3(*rng.uniform(6, 10, 3)),
                    world_name="bench",
                    flex=struct.pack("<3f", 1.0, 0.5, 0.0),
                ) for m in movers],
            ))

            oracle = ReplayClient() if interest == "on" else None
            observed = [0]

            async def drain(client, sink=None):
                try:
                    while True:
                        m = await client.recv(timeout=0.5)
                        if sink is not None \
                                and m.instruction == Instruction.LOCAL_MESSAGE:
                            sink.apply(m)
                            observed[0] += 1
                except (asyncio.TimeoutError, asyncio.CancelledError):
                    pass

            drains = [
                asyncio.ensure_future(drain(c, oracle if c is observer
                                            else None))
                for c in clients
            ]
            # warmup: past the jit walls, ticking at rate (config 8's
            # bounded stability loop)
            plane_ = server.entity_plane
            expect = max(3, int(0.5 / tick) - 3)
            prev_ticks, prev_compiles, stable = -1, -1, 0
            for _ in range(60):
                await asyncio.sleep(0.5)
                ticks_now = plane_.applied_ticks
                compiles = sum(GUARD.counts().values())
                if (prev_ticks >= 0
                        and ticks_now - prev_ticks >= expect
                        and compiles == prev_compiles):
                    stable += 1
                    if stable >= 2:
                        break
                else:
                    stable = 0
                prev_ticks, prev_compiles = ticks_now, compiles

            bytes0 = server.peer_map.bytes_delivered
            ticks0 = plane_.applied_ticks
            await asyncio.sleep(measure_s)
            bytes_win = server.peer_map.bytes_delivered - bytes0
            ticks_win = max(plane_.applied_ticks - ticks0, 1)
            # sample the per-tick delta ratio INSIDE the loaded window
            # — after quiescing the last tick carries no frames and
            # the gauge honestly reads 0
            ratio_at_load = (
                server.interest.stats()["delta_ratio"]
                if server.interest is not None else None
            )

            out = {
                "delivered_bytes_per_tick": round(
                    bytes_win / ticks_win, 1
                ),
                "bytes_per_recipient_per_s": round(
                    bytes_win / n_watchers / measure_s, 1
                ),
                "measured_ticks": ticks_win,
                "frames_observed": 0,
            }
            parity = None
            if interest == "on":
                # quiesce: zero the movers' velocity, let the last
                # deltas land, then the oracle must equal the server's
                # own ledger for the observer — eventual-state parity
                await clients[0].send(Message(
                    instruction=Instruction.LOCAL_MESSAGE,
                    world_name="bench",
                    entities=[Entity(
                        uuid=m,
                        position=Vector3(*rng.uniform(6, 10, 3)),
                        world_name="bench",
                        flex=struct.pack("<3f", 0.0, 0.0, 0.0),
                    ) for m in movers],
                ))
                settled = observed[0] - 1
                for _ in range(40):
                    await asyncio.sleep(0.25)
                    if observed[0] == settled:
                        break
                    settled = observed[0]
                mgr = server.interest
                st = mgr._peers.get(observer.uuid)
                ledger = {}
                if st is not None:
                    for key, (_wid, pos_b) in st.state.items():
                        x, y, z = np.frombuffer(pos_b, np.float32)
                        ledger[_uuid.UUID(bytes=key)] = (
                            float(x), float(y), float(z)
                        )
                got = oracle.snapshot().get("bench", {})
                s = oracle.stats()
                parity = {
                    "entities_match": int(got == ledger),
                    "entities": len(got),
                    "deltas_refused": s["deltas_refused"],
                    "gaps_seen": s["gaps_seen"],
                    "deltas_applied": s["deltas_applied"],
                    "fulls_applied": s["fulls_applied"],
                }
                ist = mgr.stats()
                out["frame_delta_ratio"] = ratio_at_load
                out["resyncs"] = ist["resyncs"]
                out["templates_reused"] = ist["templates_reused"]
                out["bytes_shed"] = ist["bytes_shed"]
            for d in drains:
                d.cancel()
            await asyncio.gather(*drains, return_exceptions=True)
            out["frames_observed"] = observed[0] if oracle else None
            for c in clients:
                await c.close()
            return out, parity
        finally:
            await server.stop()

    off, _ = asyncio.run(variant("off"))
    on, parity = asyncio.run(variant("on"))
    reduction = (
        off["delivered_bytes_per_tick"]
        / max(on["delivered_bytes_per_tick"], 1e-9)
    )

    if args.smoke:
        assert parity is not None and parity["entities_match"], (
            f"smoke: replay oracle diverged from the server ledger: "
            f"{parity}"
        )
        assert parity["deltas_refused"] == 0 and parity["gaps_seen"] == 0, (
            f"smoke: sequencing broke on a clean stream: {parity}"
        )
        assert parity["deltas_applied"] > 0, (
            "smoke: movement never rode a delta frame"
        )
        floor = 2.0
        assert reduction >= floor, (
            f"smoke: interest reduced bytes/tick only {reduction:.2f}x "
            f"(off {off['delivered_bytes_per_tick']} -> on "
            f"{on['delivered_bytes_per_tick']}), need >= {floor}x"
        )
        log(f"smoke: {reduction:.1f}x byte reduction, parity green "
            f"({parity['deltas_applied']} deltas, "
            f"{parity['fulls_applied']} fulls, 0 refused)")
    else:
        assert reduction >= 5.0, (
            f"ISSUE 18 acceptance: need >= 5x fewer bytes/tick with "
            f"interest on, got {reduction:.2f}x"
        )

    log(f"interest: off {off['delivered_bytes_per_tick']:,.0f} B/tick "
        f"-> on {on['delivered_bytes_per_tick']:,.0f} B/tick "
        f"({reduction:.1f}x), delta_ratio "
        f"{on.get('frame_delta_ratio')}, parity {parity}")
    return {
        "metric": "interest_bytes_reduction_x",
        "value": round(reduction, 2),
        "unit": "x",
        # named like vs_baseline so the perf gate reads shrinkage of
        # this leaf as the good direction
        "vs_baseline": round(reduction, 2),
        "interest": {
            "off": off,
            "on": on,
            "parity": parity,
            "watchers": n_watchers,
            "entities": n_watchers * ents_per_watcher + n_movers,
            "movers": n_movers,
        },
        "config": 13,
    }


async def _reshard_run(window_s: float) -> dict:
    """One live-resharding run: boot a 2-shard cluster, home a hot
    world on shard 0 with a cross-shard subscriber, keep LocalMessage
    + record traffic flowing, migrate the world to shard 1 mid-stream,
    and close the books: per-state wall times (harness-polled state
    transitions), the longest delivery gap the subscriber saw across
    the freeze window, parked/replayed/shed counts from the transfer
    buffer, and the zero-loss audit (every record offered before,
    during and after the migration reads back from the new owner)."""
    import uuid as uuid_mod

    from worldql_server_tpu.cluster import ClusterRuntime, WorldMap
    from worldql_server_tpu.engine.config import Config
    from worldql_server_tpu.protocol.types import (
        Instruction as Ins, Message as Msg, Record as Rec, Vector3 as V3,
    )
    from worldql_server_tpu.scenarios.client import ZmqPeer, free_port_block

    config = Config(
        store_url="memory://",
        http_enabled=False, ws_enabled=False,
        zmq_server_host="127.0.0.1",
        zmq_server_port=free_port_block(3),
        spatial_backend="cpu", tick_interval=0.02,
        overload="on",
        supervisor_backoff=0.005,
        cluster_shards=2,
    )
    world_map = WorldMap(2)
    world = next(
        f"hot{i}" for i in range(10_000)
        if world_map.shard_of_world(f"hot{i}") == 0
    )
    pos = V3(5.0, 5.0, 5.0)
    runtime = ClusterRuntime(config)
    await runtime.start()
    clients: list[ZmqPeer] = []
    try:
        async def connect(**kw) -> ZmqPeer:
            last = None
            for _ in range(100):
                try:
                    peer = await ZmqPeer.connect(
                        config.zmq_server_port, **kw
                    )
                    clients.append(peer)
                    return peer
                except Exception as exc:
                    last = exc
                    await asyncio.sleep(0.05)
            raise AssertionError(f"bench client connect failed: {last!r}")

        router = runtime.router

        def uuid_for(shard: int) -> uuid_mod.UUID:
            while True:
                u = uuid_mod.uuid4()
                if world_map.shard_of_peer(u) == shard:
                    return u

        # subscriber homed on the DESTINATION shard: its deliveries
        # ride the ring before the flip and stay local after it
        rx = await connect(peer_uuid=uuid_for(1))
        tx = await connect(peer_uuid=uuid_for(0))
        await rx.send(Msg(
            instruction=Ins.AREA_SUBSCRIBE, world_name=world,
            position=pos,
        ))
        await asyncio.sleep(0.3)

        want: set = set()

        async def put_record(tag: str) -> None:
            rec = uuid_mod.uuid4()
            await tx.send(Msg(
                instruction=Ins.RECORD_CREATE, world_name=world,
                records=[Rec(uuid=rec, position=pos, world_name=world,
                             data=tag)],
            ))
            want.add(rec)

        for i in range(50):
            await put_record(f"pre{i}")

        stop = asyncio.Event()
        offered_locals = 0
        arrivals: list[float] = []

        async def traffic() -> None:
            nonlocal offered_locals
            n = 0
            while not stop.is_set():
                await tx.send(Msg(
                    instruction=Ins.LOCAL_MESSAGE, world_name=world,
                    position=pos, parameter="load",
                ))
                offered_locals += 1
                n += 1
                if n % 4 == 0:
                    await put_record(f"mid{n}")
                # paced fast relative to the ~10ms migration so the
                # freeze window reliably parks frames (replayed > 0
                # is a smoke gate, not a coincidence)
                await asyncio.sleep(0.002)

        async def receiver() -> None:
            while True:
                got = await rx.recv(30)
                if got.instruction == Ins.LOCAL_MESSAGE:
                    arrivals.append(time.perf_counter())

        traffic_task = asyncio.ensure_future(traffic())
        receiver_task = asyncio.ensure_future(receiver())
        state_at: dict[str, float] = {}
        try:
            await asyncio.sleep(window_s)

            t_start = time.perf_counter()
            xfer = router.start_reshard(world, 1, reason="bench")
            assert xfer is not None, "reshard refused"
            while router.migration.state not in ("done", "aborted"):
                state_at.setdefault(
                    router.migration.state, time.perf_counter()
                )
                await asyncio.sleep(0.001)
            state_at.setdefault(
                router.migration.state, time.perf_counter()
            )
            migration_ms = (time.perf_counter() - t_start) * 1e3

            await asyncio.sleep(window_s)  # post-flip delivery window
            stop.set()
            await traffic_task
        finally:
            stop.set()
            for task in (traffic_task, receiver_task):
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass

        mig = router.migration
        # zero-loss audit: every offered record reads back through the
        # router from the NEW owner (retry: creates are async)
        seen: set = set()
        deadline = time.perf_counter() + 30
        while time.perf_counter() < deadline and not want <= seen:
            await tx.send(Msg(
                instruction=Ins.RECORD_READ, world_name=world,
                position=pos,
            ))
            try:
                reply = await tx.recv_until(Ins.RECORD_REPLY, 5)
            except asyncio.TimeoutError:
                continue
            seen |= {r.uuid for r in reply.records}
        lost = len(want - seen)

        # per-state wall times from the first-seen transition stamps
        order = [s for s in (
            "freeze", "streaming", "importing", "flipping",
            "replaying", "tombstoning", "done", "aborted",
        ) if s in state_at]
        state_ms = {
            a: round((state_at[b] - state_at[a]) * 1e3, 2)
            for a, b in zip(order, order[1:])
        }
        # the longest gap between consecutive subscriber deliveries
        # that overlaps the migration — the freeze-window pause
        pause_ms = 0.0
        for a, b in zip(arrivals, arrivals[1:]):
            if b >= t_start and a <= t_start + migration_ms / 1e3:
                pause_ms = max(pause_ms, (b - a) * 1e3)
        post_flip = sum(1 for t in arrivals if t > state_at[order[-1]])

        return {
            "state": mig.state,
            "lost_records": lost,
            "records_offered": len(want),
            "buffer": mig.buffer.stats(),
            "replayed": mig.replayed,
            "rerouted": runtime.metrics.snapshot()["counters"].get(
                "cluster.router_reroutes", 0
            ),
            "epoch": router.world_map.epoch,
            "owner": router.world_map.shard_of_world(world),
            "offered_locals": offered_locals,
            "delivered_locals": len(arrivals),
            "delivered_post_flip": post_flip,
            "migration_ms": round(migration_ms, 2),
            "state_ms": state_ms,
            "delivery_pause_ms": round(pause_ms, 2),
        }
    finally:
        for client in clients:
            try:
                client.close()
            except Exception:
                pass
        await runtime.stop()


def bench_config14(args) -> dict:
    """Live resharding under load (ISSUE 19): migrate a hot world
    between two real shard subprocesses while LocalMessage + record
    traffic flows, and report the migration wall time split by
    protocol state, the longest delivery gap a cross-shard subscriber
    saw across the freeze window, the transfer-buffer park/replay/shed
    books, and the zero-loss audit. ``--smoke`` asserts the migration
    COMPLETED, no record was lost, the freeze window actually parked
    and replayed traffic, nothing was shed, and delivery resumed on
    the new owner after the flip. The gate leaves are the counts
    (``lost_records`` / ``shed`` / ``aborted``); the wall times are
    1-core-box noise and pruned from the checked-in baseline."""
    window_s = 0.4 if args.quick else 1.5
    log(f"resharding: 2 shards, {window_s}s load windows...")
    run = asyncio.run(_reshard_run(window_s))
    log(
        f"  migration {run['state']} in {run['migration_ms']} ms "
        f"(states {run['state_ms']}), parked "
        f"{run['buffer']['parked_frames']} -> replayed "
        f"{run['replayed']}, shed {run['buffer']['shed']}, rerouted "
        f"{run['rerouted']}, pause {run['delivery_pause_ms']} ms, "
        f"records {run['records_offered'] - run['lost_records']}/"
        f"{run['records_offered']}, epoch {run['epoch']}, owner "
        f"shard {run['owner']}"
    )
    aborted = 1 if run["state"] != "done" else 0
    if args.smoke:
        assert aborted == 0, f"smoke: migration did not complete: {run}"
        assert run["lost_records"] == 0, (
            f"smoke: records lost across the migration: {run}"
        )
        assert run["replayed"] > 0, (
            "smoke: the freeze window never parked+replayed traffic — "
            "the migration raced no load"
        )
        assert run["buffer"]["shed"] == 0, (
            f"smoke: transfer buffer shed under bench load: {run}"
        )
        assert run["owner"] == 1 and run["epoch"] >= 1, (
            f"smoke: placement never flipped: {run}"
        )
        assert run["delivered_post_flip"] > 0, (
            "smoke: no delivery observed on the new owner post-flip"
        )
        log("smoke: migration done, zero loss, freeze window "
            "parked+replayed, nothing shed, delivery resumed post-flip")
    return {
        "metric": "reshard_lost_records",
        "value": run["lost_records"],
        "unit": "count",
        "lost_records": run["lost_records"],
        "reshard_aborted": aborted,
        "reshard": run,
        "config": 14,
    }


def bench_config15(args) -> dict:
    """SLO compliance under the game-tick shape (ISSUE 20): boot the
    REAL server with the burn-rate engine ON — the DEFAULT objective
    set (frame e2e p99, ring drops, interest resyncs, …) at
    bench-tight windows so a few seconds of load fills both burn
    windows the way a minute fills production's — and drive the
    config-13 game_tick shape over real ZMQ: a static co-located
    majority plus velocity-integrated movers with interest-managed
    fan-out. Reported per objective: compliance (fraction of
    evaluations spent at OK, as a percentage so the perf gate's
    --min-abs floor can't mute it) and the worst burn rate either
    window saw. ``--smoke`` asserts the supervised slo-eval task
    judged every objective, the frame clock closed real frames (the
    e2e objective must not be grading an empty series), and nothing
    entered BURNING at the quick shape — then the compliance_pct
    leaves diff against the baseline (higher is better): a latency
    regression that starts torching the error budget fails CI even
    while every raw *_per_s leaf holds."""
    import struct
    import tempfile
    import uuid as _uuid

    from tests.client_util import ZmqClient, free_port
    from worldql_server_tpu.engine.config import Config
    from worldql_server_tpu.engine.server import WorldQLServer
    from worldql_server_tpu.observability.slo import (
        BURNING, DEFAULT_OBJECTIVES, OK,
    )
    from worldql_server_tpu.protocol import Instruction, Message
    from worldql_server_tpu.protocol.types import Entity, Vector3
    from worldql_server_tpu.utils.retrace import GUARD

    quick = args.quick
    n_watchers = 4 if quick else 8
    ents_per_watcher = 4 if quick else 12
    n_movers = 2 if quick else 8
    measure_s = 3.0 if quick else 8.0
    tick = 0.05
    fast_s, slow_s, eval_s = 1.0, 3.0, 0.2
    rng = np.random.default_rng(2013)

    # the DEFAULT objective set at bench-tight windows — except the
    # frame-clock target, which is re-quoted at the tick budget: the
    # production 5 ms p99 belongs to hardware (ROADMAP item 1), while
    # this 1-core box time-shares the device tick with every client
    # and honestly lands most frames past 5 ms. Judging against the
    # 50 ms tick budget keeps the baseline at 100% compliance, so a
    # latency regression (frames creeping past a tick) flags instead
    # of drowning in an always-burning leaf. 50 is a bucket edge, so
    # the burn accounting stays exact.
    objectives = []
    for obj in DEFAULT_OBJECTIVES:
        obj = dict(obj, fast_s=fast_s, slow_s=slow_s)
        if obj["name"] == "frame_e2e_p99":
            obj["target_ms"] = TICK_BUDGET_MS
        objectives.append(obj)
    slo_spec = {"eval_interval_s": eval_s, "objectives": objectives}

    async def run() -> tuple[dict, dict, int]:
        with tempfile.NamedTemporaryFile(
            "w", suffix=".json", delete=False
        ) as fh:
            json.dump(slo_spec, fh)
            slo_file = fh.name
        config = Config()
        config.store_url = "memory://"
        config.http_enabled = False
        config.ws_enabled = False
        config.zmq_server_port = free_port()
        config.zmq_server_host = "127.0.0.1"
        config.spatial_backend = "tpu"
        config.tick_interval = tick
        config.entity_sim = True
        config.entity_k = 8
        config.interest = "on"
        config.slo_file = slo_file
        server = WorldQLServer(config)
        await server.start()
        try:
            clients = [
                await ZmqClient.connect(config.zmq_server_port)
                for _ in range(n_watchers)
            ]
            for c in clients:
                await c.send(Message(
                    instruction=Instruction.LOCAL_MESSAGE,
                    world_name="bench",
                    entities=[Entity(
                        uuid=_uuid.uuid4(),
                        position=Vector3(*rng.uniform(4, 12, 3)),
                        world_name="bench",
                    ) for _ in range(ents_per_watcher)],
                ))
            # moving minority: velocity-integrated by the device tick
            await clients[0].send(Message(
                instruction=Instruction.LOCAL_MESSAGE,
                world_name="bench",
                entities=[Entity(
                    uuid=_uuid.uuid4(),
                    position=Vector3(*rng.uniform(6, 10, 3)),
                    world_name="bench",
                    flex=struct.pack("<3f", 1.0, 0.5, 0.0),
                ) for _ in range(n_movers)],
            ))

            async def drain(client):
                try:
                    while True:
                        await client.recv(timeout=0.5)
                except (asyncio.TimeoutError, asyncio.CancelledError):
                    pass

            drains = [asyncio.ensure_future(drain(c)) for c in clients]
            # warmup: past the jit walls, ticking at rate (config 8's
            # bounded stability loop)
            plane_ = server.entity_plane
            expect = max(3, int(0.5 / tick) - 3)
            prev_ticks, prev_compiles, stable = -1, -1, 0
            for _ in range(60):
                await asyncio.sleep(0.5)
                ticks_now = plane_.applied_ticks
                compiles = sum(GUARD.counts().values())
                if (prev_ticks >= 0
                        and ticks_now - prev_ticks >= expect
                        and compiles == prev_compiles):
                    stable += 1
                    if stable >= 2:
                        break
                else:
                    stable = 0
                prev_ticks, prev_compiles = ticks_now, compiles
            # age the warmup (jit-wall latencies included) out of the
            # slow burn window before judging — the engine's ring only
            # looks back slow_s, so after this sleep every window the
            # measured evaluations see is pure steady-state load
            await asyncio.sleep(slow_s + 2 * eval_s)
            t0 = time.monotonic()
            await asyncio.sleep(measure_s)
            status = server.slo.status()
            frame_hist = server.metrics.export_histograms(
                ("frame.e2e_ms",)
            ).get("frame.e2e_ms")
            frames = frame_hist["total"] if frame_hist else 0
            trajs = {
                name: [
                    e for e in server.slo.trajectory(name)
                    if e["t"] >= t0
                ]
                for name in status["objectives"]
            }
            for d in drains:
                d.cancel()
            await asyncio.gather(*drains, return_exceptions=True)
            for c in clients:
                await c.close()
            return status, trajs, frames
        finally:
            await server.stop()
            os.unlink(slo_file)

    log(f"slo_compliance: game_tick shape, {n_watchers} watchers, "
        f"{n_movers} movers, windows {fast_s}/{slow_s}s at "
        f"{eval_s}s evals, {measure_s}s judged window...")
    status, trajs, frames = asyncio.run(run())

    objectives = {}
    breaches = 0
    worst_level = 0
    for name, entries in trajs.items():
        ok = sum(1 for e in entries if e["level"] == OK)
        burning = sum(1 for e in entries if e["level"] == BURNING)
        breaches += burning
        worst_level = max(
            worst_level, max((e["level"] for e in entries), default=0)
        )
        objectives[name] = {
            "compliance_pct": round(
                100.0 * ok / max(len(entries), 1), 1
            ),
            "worst_burn_fast": max(
                (e["burn_fast"] for e in entries), default=0.0
            ),
            "worst_burn_slow": max(
                (e["burn_slow"] for e in entries), default=0.0
            ),
            "evals": len(entries),
            "final_state": status["objectives"][name]["state"],
        }
        log(f"  {name}: {objectives[name]['compliance_pct']}% ok "
            f"({len(entries)} evals, worst burn "
            f"{objectives[name]['worst_burn_slow']}x slow), final "
            f"{objectives[name]['final_state']}")

    if args.smoke:
        assert set(objectives) == {o["name"] for o in DEFAULT_OBJECTIVES}, (
            f"smoke: objective set drifted: {sorted(objectives)}"
        )
        assert all(o["evals"] >= 5 for o in objectives.values()), (
            f"smoke: the slo-eval task barely ran inside the judged "
            f"window: {objectives}"
        )
        assert frames > 0, (
            "smoke: the frame clock never closed a frame — "
            "frame_e2e_p99 judged an empty series (burn 0 would be a "
            "dead green light, not compliance)"
        )
        assert breaches == 0, (
            f"smoke: an objective entered BURNING at the quick "
            f"shape: {objectives}"
        )
        log(f"smoke: all {len(objectives)} objectives judged on live "
            f"series ({frames} frames closed), zero breach evals")

    return {
        "metric": "slo_breach_evals",
        "value": breaches,
        "unit": "count",
        "slo_breach_evals": breaches,
        "worst_level": worst_level,
        # volatile (wall-clock frame count) — pruned from the gate
        # baseline; the bench keeps reporting it
        "frames_judged": frames,
        "windows": {
            "fast_s": fast_s, "slow_s": slow_s,
            "eval_interval_s": eval_s,
        },
        "objectives": objectives,
        "config": 15,
    }


# --------------------------------------------------------------------


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int,
                    choices=[1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13,
                             14, 15],
                    help="BASELINE config to run (default: 5); 6 = "
                         "record-op durability workload; 7 = sharded-"
                         "backend 1→8-device scaling curve "
                         "(sharded_overhead); 8 = entity-simulation "
                         "plane (update ingest through the delta "
                         "path, device kNN tick, e2e frame latency); "
                         "9 = overload-storm admission (admitted vs "
                         "offered at 2x/10x, shed fractions, record "
                         "p99 under storm); 10 = adversarial scenario "
                         "suite (flash crowd, battle royale, "
                         "reconnect storm, game tick — survival + SLO "
                         "checks over real ZMQ); 11 = cluster_scaling "
                         "(1→N shard server processes behind the "
                         "router tier: admitted msgs/s + cross-shard "
                         "p99 per point, exact router/shard shed "
                         "audit); 12 = query_library (per-kind "
                         "cone/raycast/kNN/density device throughput, "
                         "mixed-kind batch p50/p99 vs a pure-radius "
                         "batch of the same size, CPU-oracle parity); "
                         "13 = interest-managed fan-out (delivered "
                         "bytes/tick --interest off vs on at the "
                         "game_tick shape over real ZMQ, replay-"
                         "oracle parity, ISSUE 18 5x acceptance); "
                         "14 = live resharding (migrate a hot world "
                         "between shard processes under load: "
                         "per-state wall times, freeze-window "
                         "delivery pause, park/replay/shed books, "
                         "zero-loss audit); 15 = slo_compliance (the "
                         "burn-rate engine judging the game_tick "
                         "shape live: per-objective compliance "
                         "fractions + worst burn rate)")
    ap.add_argument("--all", action="store_true",
                    help="run every config, one JSON line each")
    ap.add_argument("--subs", type=int, default=None)
    ap.add_argument("--queries", type=int, default=None)
    ap.add_argument("--ticks", type=int, default=None)
    ap.add_argument("--cpu-ticks", type=int, default=5)
    ap.add_argument("--delivery-clients", type=int, default=None,
                    help="live WS clients for the server_delivery "
                         "workers variant (default: 4096 full / 128 "
                         "quick — lower it to bound a CI run)")
    ap.add_argument("--quick", action="store_true",
                    help="small shapes for smoke-testing the harness")
    ap.add_argument("--smoke", action="store_true",
                    help="CI regression gate: --quick shapes on the "
                         "CPU backend with the result compaction "
                         "forced on and the WS delivery pump skipped — "
                         "fails if the compacted collect path never "
                         "fires (config 5), or if the entity-sim "
                         "device path / delta compaction / e2e frames "
                         "never fire (config 8)")
    ap.add_argument("--profile", metavar="DIR",
                    help="capture a jax.profiler trace of the sustained "
                         "run (config 5) into DIR (view with xprof/"
                         "tensorboard)")
    args = ap.parse_args()
    if args.smoke:
        args.quick = True
    # --quick shrinks the DEFAULT shapes; explicit flags still win
    quick_defaults = (20_000, 1_024, 10) if args.quick \
        else (1_000_000, 16_384, 50)
    for name, dflt in zip(("subs", "queries", "ticks"), quick_defaults):
        if getattr(args, name) is None:
            setattr(args, name, dflt)

    benches = {
        1: bench_config1, 2: bench_config2, 3: bench_config3,
        4: bench_config4, 5: bench_config5, 6: bench_config6,
        7: bench_config7, 8: bench_config8, 9: bench_config9,
        10: bench_config10, 11: bench_config11, 12: bench_config12,
        13: bench_config13, 14: bench_config14, 15: bench_config15,
    }
    if args.all:
        # config 7 is EXCLUDED from --all on purpose: it re-execs with
        # a forced 8-device host topology (where needed), which cannot
        # compose with the other configs' already-initialized runtime —
        # run it standalone like the multichip bench.
        selected = [1, 2, 3, 4, 5, 6, 8, 9, 10, 11, 12, 13, 14, 15]
    else:
        selected = [args.config or 5]
    for n in selected:
        log(f"=== BASELINE config {n} ===")
        emit(benches[n](args))


if __name__ == "__main__":
    main()
