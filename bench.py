"""North-star benchmark: batched LocalMessage fan-out at 1M entities.

Measures end-to-end per-tick latency of the device fan-out engine —
host-side f64 quantization + key hashing, host→device transfer, the
fused match kernel, and device→host result transfer — against the
dict-based CPU reference backend resolving the identical queries
(the reference's per-message architecture, SURVEY §3.2).

Workload (BASELINE config-5 shape): N subscriptions across 8 worlds,
95% uniform over a ±800 box (≈1M cubes at size 16) + 5% Zipf-style
hotspot in a ±40 box (dense cubes, large fan-outs); M queries per tick
drawn from the same mixture.

The engine runs pipelined (depth-8 double buffering, CSR-compacted
results, async D2H) — the sustained per-tick time is the steady-state
tick latency of a real deployment. Prints ONE JSON line on stdout:
  {"metric": "local_fanout_sustained_tick_ms", "value": ..., "unit": "ms",
   "vs_baseline": <cpu_p99 / tpu_sustained>}
Diagnostics go to stderr. Flags: --subs, --queries, --ticks, --quick.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import uuid as uuid_mod

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_positions(rng: np.random.Generator, n: int) -> np.ndarray:
    hot = rng.random(n) < 0.05
    pos = rng.uniform(-800.0, 800.0, (n, 3))
    pos[hot] = rng.uniform(-40.0, 40.0, (int(hot.sum()), 3))
    return pos


def build_index(backend, rng: np.random.Generator, n_subs: int, n_worlds: int):
    from worldql_server_tpu.spatial.quantize import cube_coords_batch

    positions = make_positions(rng, n_subs)
    cubes = cube_coords_batch(positions, backend.cube_size)
    peers = [uuid_mod.UUID(int=i + 1) for i in range(n_subs)]
    world_ids = np.arange(n_subs) * n_worlds // n_subs
    t0 = time.perf_counter()
    for w in range(n_worlds):
        sel = world_ids == w
        backend.bulk_add_subscriptions(
            f"world_{w}", [peers[i] for i in np.flatnonzero(sel)], cubes[sel]
        )
    log(f"index build: {n_subs} subs in {time.perf_counter() - t0:.1f}s")
    return peers, positions, world_ids


def make_query_batch(rng, sub_positions, sub_world_ids, m: int):
    """Queries model entities broadcasting at their own positions: each
    draws a random subscriber and speaks from its cube (20% from a
    fresh random point — mostly-miss traffic)."""
    n_subs = len(sub_positions)
    senders = rng.integers(0, n_subs, m)
    world_ids = sub_world_ids[senders].astype(np.int32)
    positions = sub_positions[senders].copy()
    miss = rng.random(m) < 0.2
    positions[miss] = make_positions(rng, int(miss.sum()))
    return world_ids, positions, senders.astype(np.int32), np.zeros(m, np.int8)


def _drain(inflight, total_fanout, overflow, csr_cap):
    m, (counts, flat, total) = inflight.popleft()
    n = int(total)
    if n > csr_cap:
        overflow += 1
    # Static-shape fetches, host-side trim (a device-side dynamic slice
    # would recompile per distinct total).
    np.asarray(counts)
    np.asarray(flat)
    total_fanout += n
    return total_fanout, overflow


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--subs", type=int, default=1_000_000)
    ap.add_argument("--queries", type=int, default=16_384)
    ap.add_argument("--ticks", type=int, default=50)
    ap.add_argument("--cpu-ticks", type=int, default=5)
    ap.add_argument("--quick", action="store_true",
                    help="small shapes for smoke-testing the harness")
    args = ap.parse_args()
    if args.quick:
        args.subs, args.queries, args.ticks = 20_000, 1_024, 10

    from worldql_server_tpu.spatial.backend import LocalQuery
    from worldql_server_tpu.spatial.cpu_backend import CpuSpatialBackend
    from worldql_server_tpu.spatial.tpu_backend import TpuSpatialBackend
    from worldql_server_tpu.protocol.types import Replication, Vector3

    import jax

    n_worlds = 8
    rng = np.random.default_rng(42)
    tpu = TpuSpatialBackend(cube_size=16)
    peers, sub_positions, sub_world_ids = build_index(
        tpu, rng, args.subs, n_worlds
    )

    t0 = time.perf_counter()
    tpu.flush()
    log(f"device flush: {time.perf_counter() - t0:.1f}s "
        f"stats={tpu.device_stats()} device={jax.devices()[0].platform}")

    # Pre-draw per-tick query batches (workload generation is not the
    # thing under test).
    batches = [
        make_query_batch(rng, sub_positions, sub_world_ids, args.queries)
        for _ in range(args.ticks)
    ]

    csr_cap = args.queries * 4  # total fan-out pairs per tick headroom

    # Warmup: compile every shape tier.
    for b in batches[:2]:
        _, res = tpu.match_arrays_async(*b, csr_cap=csr_cap)
        jax.block_until_ready(res)

    # Pipelined steady state: dispatch tick t+DEPTH while fetching tick
    # t, overlapping host encode, transfer and device compute the way a
    # double-buffered server tick loop does.
    from collections import deque

    depth = 8
    inflight = deque()
    total_fanout = 0
    overflow = 0
    t_start = time.perf_counter()
    for b in batches:
        inflight.append(tpu.match_arrays_async(*b, csr_cap=csr_cap))
        if len(inflight) >= depth:
            total_fanout, overflow = _drain(
                inflight, total_fanout, overflow, csr_cap
            )
    while inflight:
        total_fanout, overflow = _drain(
            inflight, total_fanout, overflow, csr_cap
        )
    t_total = time.perf_counter() - t_start

    sustained = t_total / len(batches) * 1e3
    assert overflow == 0, "csr_cap overflow — raise the headroom"
    log(f"tpu: sustained {sustained:.2f} ms/tick  "
        f"avg fan-out {total_fanout / (len(batches) * args.queries):.2f}  "
        f"({args.queries / (t_total / len(batches)):,.0f} queries/s)")

    # CPU reference baseline: identical index + queries, per-message
    # dict resolution like the reference's hot path.
    cpu = CpuSpatialBackend(cube_size=16)
    rng2 = np.random.default_rng(42)
    build_index(cpu, rng2, args.subs, n_worlds)

    cpu_times = []
    for b in batches[: args.cpu_ticks]:
        world_ids, positions, sender_ids, repls = b
        queries = [
            LocalQuery(
                f"world_{world_ids[i]}",
                Vector3(*positions[i]),
                peers[sender_ids[i]],
                Replication.EXCEPT_SELF,
            )
            for i in range(len(world_ids))
        ]
        t0 = time.perf_counter()
        cpu.match_local_batch(queries)
        cpu_times.append(time.perf_counter() - t0)
    cpu_times_ms = np.array(cpu_times) * 1e3
    cpu_p99 = float(np.percentile(cpu_times_ms, 99))
    log(f"cpu: mean {cpu_times_ms.mean():.2f} ms  p99 {cpu_p99:.2f} ms")

    # Parity spot-check so a broken kernel can't post a good number.
    _parity_check(tpu, cpu, peers, batches[0])

    print(json.dumps({
        "metric": "local_fanout_sustained_tick_ms",
        "value": round(sustained, 3),
        "unit": "ms",
        "vs_baseline": round(cpu_p99 / sustained, 2),
    }))


def _parity_check(tpu, cpu, peers, batch, samples: int = 64) -> None:
    from worldql_server_tpu.spatial.backend import LocalQuery
    from worldql_server_tpu.protocol.types import Replication, Vector3

    world_ids, positions, sender_ids, repls = batch
    idx = np.linspace(0, len(world_ids) - 1, samples).astype(int)
    tgt = tpu.match_arrays(*batch)
    for i in idx:
        want = cpu.match_local_batch([
            LocalQuery(
                f"world_{world_ids[i]}",
                Vector3(*positions[i]),
                peers[sender_ids[i]],
                Replication.EXCEPT_SELF,
            )
        ])[0]
        got = {int(t) for t in tgt[i] if t >= 0}
        want_ids = {tpu._peer_ids[p] for p in want}
        assert got == want_ids, f"parity diverged at query {i}"
    log(f"parity check: {samples} sampled queries agree with CPU reference")


if __name__ == "__main__":
    main()
