"""Interest-managed fan-out (ROADMAP item 3).

Delta ticks stopped *recomputing* the world that didn't change; this
package stops *sending* it. :class:`~.manager.InterestManager` turns
the entity plane's per-tick neighbor results into per-recipient delta
frames (entered/left/moved vs the last state that peer provably
received) under an epoch:seq stamped wire contract, partitions
recipients into near/far LOD cadence tiers, and enforces per-peer
bandwidth budgets by lossless deferral — never by truncating a delta.

``--interest off`` (the default) never imports this package on the hot
path: the delivery pipeline stays byte for byte the pre-interest one.
"""

from .manager import (  # noqa: F401
    PARAM_FULL,
    PARAM_FULL_CONT,
    PARAM_DELTA,
    InterestManager,
    parse_stamp,
    stamp,
)
from .replay import ReplayClient  # noqa: F401
