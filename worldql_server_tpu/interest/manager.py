"""Per-recipient interest management: delta frames, LOD cadence,
bandwidth budgets.

The entity plane's tick result says, for every entity row, which peers
should see it. The pre-interest pipeline ships that as one
``entity.frame`` LocalMessage per (entity, tick) to every recipient —
O(recipients × neighbors × tick-rate) wire bytes no matter how little
moved. The :class:`InterestManager` replaces that leg per recipient
with a DIFF against the last state the peer provably received:

* **wire contract** — every frame's parameter is stamped by
  :func:`stamp`: ``entity.frame.full:<epoch>:<seq>`` /
  ``entity.frame.fullc:<epoch>:<seq>`` (chunk continuation) /
  ``entity.frame.delta:<epoch>:<seq>`` with fixed-width hex fields.
  ``seq`` is monotone and contiguous per peer within an ``epoch``; any
  loss path bumps the epoch and forces the next frame full, so a
  client (and the parity oracle) can PROVE it never applied a delta
  against a frame it never got: a same-epoch gap is a server bug, an
  epoch bump is a declared resync. Entered/moved neighbors ride as
  normal positioned entities; departed neighbors ride the same frame
  as tombstones (1-byte ``flex`` marker — short flex is already
  ignored by the velocity decode, so old readers see a harmless
  entity).
* **resync contract** — :meth:`InterestManager.mark_resync` is the ONE
  hook every loss path calls: reconnect/session-resume, undelivered
  frames to a parked session, ring-full drops, worker loss, overload
  eviction. It is idempotent and cheap (a flag); the next built frame
  for that peer opens a new epoch with a complete keyframe.
* **LOD cadence** — recipients partition per tick into near/far by the
  distance of each neighbor row to the recipient's own entity centroid
  (``lod_near_radius``; 0 = all near). Near rows deliver every tick;
  far rows every ``lod_far_every_k`` ticks (per-peer phase, so far
  bursts de-synchronize). Deferral is LOSSLESS: an off-cadence far
  update is simply retained in the diff base and ships on the next due
  tick — never dropped. The overload governor widens k
  (:meth:`note_governor`) instead of skipping frames blindly.
* **bandwidth budgets** — a token bucket per peer
  (``peer_bandwidth_bytes``/s). An unaffordable tick is DEFERRED whole
  (no state commit, no seq consumed — the diff accumulates), and the
  peer walks a demotion ladder: normal → forced-far cadence →
  keyframe-only. Only an unaffordable *keyframe* at the bottom of the
  ladder counts ``delivery.bytes_shed``; a delta is never truncated,
  so eventual-state parity holds under any budget.
* **cohort dedup** — peers whose frame would carry identical content
  share ONE encode (native or object path); per-peer epoch:seq stamps
  are byte-patched into a copy. This generalizes PR 14's
  ``delta.frames_reused`` from clean-cohort replay to dirty cohorts
  with identical diffs.

This module is also the sequence-stamp authority: the ``tools/check``
rule ``unsequenced-frame`` fails any stamped-frame parameter literal
built outside it.
"""

from __future__ import annotations

import logging
import time
import uuid as uuid_mod

import numpy as np

from ..protocol.types import NIL_UUID, Entity, Instruction, Message, Vector3

logger = logging.getLogger(__name__)

#: stamped-frame parameter bases (see :func:`stamp`) — the lint rule
#: `unsequenced-frame` pins construction of these to THIS module
PARAM_FULL = "entity.frame.full"
PARAM_FULL_CONT = "entity.frame.fullc"
PARAM_DELTA = "entity.frame.delta"

#: max entities per frame: chunked fulls stay under the native decode
#: object cap (WQL_MAX_OBJS = 1024) with headroom
FRAME_CHUNK = 512

#: 1-byte flex marking a departed neighbor (any flex < 12 bytes is
#: ignored by the entity velocity decode, so pre-interest readers see
#: a harmless entity at its last position)
TOMBSTONE_FLEX = b"\x00"

#: demotion ladder states (bandwidth pressure)
DEMOTE_NONE = 0      # normal near/far cadence
DEMOTE_FAR = 1       # every row on the far cadence
DEMOTE_KEYFRAME = 2  # full keyframes on the far cadence, nothing else

_NIL_KEY = NIL_UUID.bytes


def stamp(kind: str, epoch: int, seq: int) -> str:
    """The ONE constructor for stamped frame parameters:
    ``<kind>:<epoch hex8>:<seq hex8>``. Fixed-width fields make every
    stamp of a kind the same length, which is what lets a cohort
    template be byte-patched per peer."""
    return f"{kind}:{epoch & 0xFFFFFFFF:08x}:{seq & 0xFFFFFFFF:08x}"


def parse_stamp(parameter: str) -> tuple[str, int, int] | None:
    """``(kind, epoch, seq)`` from a stamped frame parameter, or None
    when the parameter is not a stamped frame (e.g. the legacy
    ``entity.frame``)."""
    if parameter is None or not parameter.startswith("entity.frame."):
        return None
    parts = parameter.rsplit(":", 2)
    if len(parts) != 3:
        return None
    kind = parts[0]
    if kind not in (PARAM_FULL, PARAM_FULL_CONT, PARAM_DELTA):
        return None
    try:
        return kind, int(parts[1], 16), int(parts[2], 16)
    except ValueError:
        return None


class _WireFrame:
    """Pre-encoded outbound frame (mirror of entities.plane.WireFrame,
    local so the manager has no import cycle with the plane)."""

    __slots__ = ("wire", "_msg")

    def __init__(self, wire: bytes):
        self.wire = wire
        self._msg = None

    def __getattr__(self, name):
        msg = object.__getattribute__(self, "_msg")
        if msg is None:
            from ..protocol import deserialize_message

            msg = deserialize_message(self.wire)
            object.__setattr__(self, "_msg", msg)
        return getattr(msg, name)


class _PeerState:
    """One recipient's delivery ledger: the diff base (what the peer
    holds if it applied every frame), the epoch:seq cursor, the resync
    flag, and the bandwidth bucket."""

    __slots__ = (
        "epoch", "seq", "state", "resync", "demote", "tokens",
        "refilled_at", "deferrals",
    )

    def __init__(self, now: float, burst: float):
        self.epoch = 0
        self.seq = 0
        #: uuid16 bytes -> (wid, pos_f32x3 bytes) the peer holds
        self.state: dict[bytes, tuple[int, bytes]] = {}
        self.resync = True          # first frame of a peer is a keyframe
        self.demote = DEMOTE_NONE
        self.tokens = burst
        self.refilled_at = now
        self.deferrals = 0


class InterestManager:
    def __init__(
        self,
        *,
        near_radius: float = 0.0,
        far_every_k: int = 4,
        bandwidth_bytes: int = 0,
        metrics=None,
        clock=time.monotonic,
    ):
        self.near_radius = float(near_radius)
        self.far_every_k = max(1, int(far_every_k))
        self.bandwidth_bytes = int(bandwidth_bytes)
        #: bucket capacity: one second of budget, floored so a single
        #: keyframe at game shapes is always affordable from idle
        self.bandwidth_burst = float(max(self.bandwidth_bytes, 65536)) \
            if self.bandwidth_bytes else 0.0
        self.metrics = metrics
        self._clock = clock
        self._peers: dict[uuid_mod.UUID, _PeerState] = {}
        self._ticks = 0
        self._shed_level = 0
        self._tier_degraded = False
        #: cohort template cache, swapped wholesale per tick like the
        #: plane's _frame_cache: content key -> (template, e_off, s_off)
        self._templates: dict = {}
        # counters / last-tick gauges
        self.resyncs = 0
        self.bytes_shed = 0
        self.deferrals = 0
        self.templates_reused = 0
        self.last_delta_frames = 0
        self.last_full_frames = 0
        self.last_near = 0
        self.last_far = 0
        self.last_demoted = 0
        self.last_bytes = 0

    # region: resync + lifecycle hooks

    def mark_resync(self, peer: uuid_mod.UUID) -> None:
        """THE loss-path hook (idempotent): the next frame built for
        this peer opens a new epoch with a full keyframe. Called on
        ring drops, worker loss, undelivered-to-parked frames, session
        resume, send errors and overload eviction — a delta can never
        leak past a gap because every gap lands here first."""
        st = self._peers.get(peer)
        if st is None or st.resync:
            return
        st.resync = True
        self.resyncs += 1
        if self.metrics is not None:
            self.metrics.inc("interest.resyncs")

    def forget_peer(self, peer: uuid_mod.UUID) -> None:
        self._peers.pop(peer, None)

    def note_governor(self, shed_level: int, tier_degraded: bool) -> None:
        """Overload coupling: SHED tiers widen the far cadence
        (k << level) and a degraded tick tier halves the near cadence —
        the lossless replacement for blind frame skipping."""
        self._shed_level = max(0, min(3, int(shed_level)))
        self._tier_degraded = bool(tier_degraded)

    # endregion

    # region: frame building

    def build_pairs(self, plane, pos, targets, cap: int) -> list:
        """Replace ``EntityPlane._build_frames`` for one applied tick:
        per-recipient delta/full frames instead of per-entity
        broadcast. Returns the same ``(message, [target_uuid])`` pair
        shape ``PeerMap.deliver_batch`` consumes."""
        self._ticks += 1
        live = plane._live[:cap]
        valid = targets >= 0
        rows = np.flatnonzero(live & valid.any(axis=1))

        # invert row->targets into per-recipient visible row lists
        by_pid: dict[int, np.ndarray] = {}
        if rows.size:
            tgt = targets[rows]
            mask = tgt >= 0
            r_idx = np.repeat(rows, tgt.shape[1])[mask.ravel()]
            p_idx = tgt.ravel()[mask.ravel()]
            order = np.argsort(p_idx, kind="stable")
            p_sorted, r_sorted = p_idx[order], r_idx[order]
            bounds = np.flatnonzero(np.diff(p_sorted)) + 1
            for chunk, pid_val in zip(
                np.split(r_sorted, bounds),
                p_sorted[np.concatenate(([0], bounds))],
            ):
                by_pid[int(pid_val)] = np.unique(chunk)

        # peers with retained state but nothing visible still need
        # their departures delivered
        peers = set(by_pid)
        for u, st in self._peers.items():
            if st.state:
                pid = plane._peer_ids.get(u)
                if pid is not None:
                    peers.add(pid)

        near_every = 2 if self._tier_degraded else 1
        far_every = self.far_every_k << self._shed_level
        specs = []      # (uuid, st, frames_spec, new_state, committed_ticks)
        self.last_near = self.last_far = self.last_demoted = 0
        for pid in sorted(peers):
            if pid >= len(plane._peer_uuids):
                continue
            u = plane._peer_uuids[pid]
            st = self._peers.get(u)
            if st is None:
                st = self._peers[u] = _PeerState(
                    self._clock(), self.bandwidth_burst
                )
            spec = self._peer_spec(
                plane, pos, pid, st, by_pid.get(pid),
                near_every, far_every,
            )
            if spec is not None:
                specs.append((u, st) + spec)

        pairs = self._encode_specs(plane, specs)
        self.last_bytes = sum(len(m.wire) for m, _ in pairs)
        return pairs

    def _center_of(self, plane, pid: int):
        """The recipient's subscription center: centroid of its own
        live entities (None = no entities, everything is near)."""
        slots = plane._peer_slots.get(pid)
        if not slots:
            return None
        idx = np.fromiter(slots, np.intp, count=len(slots))
        return plane._pos[idx].mean(axis=0)

    def _peer_spec(self, plane, pos, pid, st, vrows, near_every,
                   far_every):
        """One recipient's frame decision for this tick. Returns
        ``(frame_specs, new_state)`` or None (nothing due). A
        frame_spec is ``(kind, world, entries)`` with entries
        ``[(uuid16, wid, pos_f32_bytes, tombstone)]``; stamping and
        encoding happen later so identical content can share one
        template."""
        demote = st.demote
        if demote:
            self.last_demoted += 1
        phase = (self._ticks + pid) % far_every == 0
        near_due = (self._ticks + pid) % near_every == 0
        resync = st.resync

        center = None
        if self.near_radius > 0.0 and not resync:
            center = self._center_of(plane, pid)

        new_state: dict[bytes, tuple[int, bytes]] = {}
        n_near = n_far = 0
        if vrows is not None and vrows.size:
            vpos = pos[vrows].astype(np.float32, copy=False)
            if resync or (self.near_radius <= 0.0 and demote == DEMOTE_NONE):
                near_mask = np.ones(len(vrows), bool)
            elif demote != DEMOTE_NONE:
                near_mask = np.zeros(len(vrows), bool)
            elif center is None:
                near_mask = np.ones(len(vrows), bool)
            else:
                d2 = ((vpos - center.astype(np.float32)) ** 2).sum(axis=1)
                near_mask = d2 <= np.float32(self.near_radius) ** 2
            n_near = int(near_mask.sum())
            n_far = len(vrows) - n_near
            for i, row in enumerate(vrows.tolist()):
                key = plane._uuid_bytes[row].tobytes()
                wid = int(plane._wid[row])
                prev = st.state.get(key)
                due = near_mask[i] and near_due or (not near_mask[i]) and phase
                if resync or due or prev is None and near_mask[i] and near_due:
                    new_state[key] = (wid, vpos[i].tobytes())
                elif prev is not None:
                    new_state[key] = prev      # off-cadence: retain
                # else: off-cadence far ENTER — defer until due
        self.last_near += n_near
        self.last_far += n_far

        # departures: keys the peer holds that are no longer visible.
        # Far-tier departures (by retained position) defer to the far
        # cadence like every other far change; resync drops the ledger
        # wholesale via the epoch bump.
        if not resync:
            for key, (wid, pos_b) in st.state.items():
                if key in new_state:
                    continue
                is_far = False
                if self.near_radius > 0.0 and center is not None \
                        and st.demote == DEMOTE_NONE:
                    old = np.frombuffer(pos_b, np.float32)
                    d2 = float(((old - center.astype(np.float32)) ** 2).sum())
                    is_far = d2 > self.near_radius ** 2
                elif st.demote != DEMOTE_NONE:
                    is_far = True
                if is_far and not phase:
                    new_state[key] = (wid, pos_b)  # defer the leave

        if resync:
            if not new_state and not st.state:
                return None            # nothing to clear, nothing to send
            frames = self._full_specs(new_state, st.state)
            return frames, new_state, True
        if demote == DEMOTE_KEYFRAME:
            if not phase:
                return None
            frames = self._full_specs(new_state, st.state)
            return (frames, new_state, False) if frames else None

        # delta: entered/moved as positioned entities, left as
        # tombstones, grouped per world
        by_world: dict[int, list] = {}
        for key, (wid, pos_b) in new_state.items():
            prev = st.state.get(key)
            if prev is None or prev[1] != pos_b or prev[0] != wid:
                if prev is not None and prev[0] != wid:
                    # world hop = leave old world + enter new
                    by_world.setdefault(prev[0], []).append(
                        (key, prev[0], prev[1], True)
                    )
                by_world.setdefault(wid, []).append(
                    (key, wid, pos_b, False)
                )
        for key, (wid, pos_b) in st.state.items():
            if key not in new_state:
                by_world.setdefault(wid, []).append((key, wid, pos_b, True))
        if not by_world:
            return None
        total = sum(len(v) for v in by_world.values())
        if total > FRAME_CHUNK:
            # a delta this large beats no full frame — declare a
            # resync (epoch bump) and ship chunked keyframes instead
            frames = self._full_specs(new_state, st.state)
            return frames, new_state, True
        frames = [
            (PARAM_DELTA, wid, sorted(entries))
            for wid, entries in sorted(by_world.items())
        ]
        return frames, new_state, False

    def _full_specs(self, new_state, old_state):
        """Chunked keyframe specs covering every world in the new
        state — plus an EMPTY full for a world the peer still holds
        that vanished entirely (the clear marker)."""
        by_world: dict[int, list] = {}
        for key, (wid, pos_b) in new_state.items():
            by_world.setdefault(wid, []).append((key, wid, pos_b, False))
        for key, (wid, _pos) in old_state.items():
            if wid not in by_world and key not in new_state:
                by_world[wid] = []
        frames = []
        for wid, entries in sorted(by_world.items()):
            entries.sort()
            if not entries:
                frames.append((PARAM_FULL, wid, []))
                continue
            for c0 in range(0, len(entries), FRAME_CHUNK):
                kind = PARAM_FULL if c0 == 0 else PARAM_FULL_CONT
                frames.append((kind, wid, entries[c0:c0 + FRAME_CHUNK]))
        return frames

    def _encode_specs(self, plane, specs) -> list:
        """Encode every peer's frame specs with cross-peer cohort
        dedup, apply bandwidth admission, commit ledgers, and emit
        delivery pairs."""
        next_templates: dict = {}
        pairs = []
        now = self._clock()
        self.last_delta_frames = self.last_full_frames = 0
        for u, st, frames, new_state, is_resync in specs:
            encoded = []
            nbytes = 0
            for kind, wid, entries in frames:
                ckey = (kind, wid, b"".join(
                    e[0] + e[2] + (b"\x01" if e[3] else b"\x00")
                    for e in entries
                ))
                tpl = next_templates.get(ckey)
                if tpl is None:
                    tpl = self._templates.get(ckey)
                    if tpl is not None:
                        self.templates_reused += 1
                        if self.metrics is not None:
                            self.metrics.inc("delta.frames_reused")
                else:
                    self.templates_reused += 1
                    if self.metrics is not None:
                        self.metrics.inc("delta.frames_reused")
                if tpl is None:
                    tpl = self._encode_template(plane, kind, wid, entries)
                next_templates[ckey] = tpl
                encoded.append((kind, tpl))
                nbytes += len(tpl[0])

            if self.bandwidth_bytes and not self._afford(st, nbytes, now):
                # lossless deferral: nothing sent, nothing committed —
                # the diff simply accumulates into the next frame
                self.deferrals += 1
                st.deferrals += 1
                if st.demote < DEMOTE_KEYFRAME:
                    st.demote += 1
                    self.last_demoted += 1
                elif is_resync or st.resync or not any(
                    k == PARAM_DELTA for k, _ in encoded
                ):
                    # bottom of the ladder AND the keyframe itself is
                    # unaffordable: the ONLY shed point, counted
                    self.bytes_shed += nbytes
                    if self.metrics is not None:
                        self.metrics.inc("delivery.bytes_shed", nbytes)
                continue

            if is_resync:
                st.epoch += 1
                st.seq = 0
                st.resync = False
            for kind, (tpl, e_off, s_off) in encoded:
                buf = bytearray(tpl)
                buf[e_off:e_off + 8] = b"%08x" % (st.epoch & 0xFFFFFFFF)
                buf[s_off:s_off + 8] = b"%08x" % (st.seq & 0xFFFFFFFF)
                st.seq += 1
                pairs.append((_WireFrame(bytes(buf)), [u]))
                if kind == PARAM_DELTA:
                    self.last_delta_frames += 1
                else:
                    self.last_full_frames += 1
            st.state = new_state
        self._templates = next_templates
        return pairs

    def _afford(self, st, nbytes: int, now: float) -> bool:
        rate = float(self.bandwidth_bytes)
        st.tokens = min(
            self.bandwidth_burst,
            st.tokens + (now - st.refilled_at) * rate,
        )
        st.refilled_at = now
        if st.tokens >= nbytes:
            st.tokens -= nbytes
            if st.demote and st.tokens >= self.bandwidth_burst * 0.5:
                st.demote -= 1          # headroom: walk back up
            return True
        return False

    def _encode_template(self, plane, kind: str, wid: int, entries):
        """One cohort's wire bytes with a zeroed stamp, plus the byte
        offsets of the epoch/seq hex fields for per-peer patching.
        Native single-pass encode when the library has the symbol; the
        object path is byte-identical (pinned by test)."""
        world = plane._world_names[wid] if 0 <= wid < len(
            plane._world_names
        ) else ""
        placeholder = stamp(kind, 0, 0)
        n = len(entries)
        wire = getattr(plane, "_wire", None)
        if wire is not None and getattr(wire, "can_encode_interest", False):
            keys = np.empty((n, 16), np.uint8)
            pos = np.empty((n, 3), np.float64)
            tomb = np.zeros(n, np.uint8)
            for i, (key, _wid, pos_b, dead) in enumerate(entries):
                keys[i] = np.frombuffer(key, np.uint8)
                pos[i] = np.frombuffer(pos_b, np.float32).astype(np.float64)
                tomb[i] = 1 if dead else 0
            buf = wire.encode_interest_frame(
                placeholder.encode(), world.encode(), keys, pos, tomb
            )
        else:
            ents = []
            for key, _wid, pos_b, dead in entries:
                p = np.frombuffer(pos_b, np.float32)
                ents.append(Entity(
                    uuid=uuid_mod.UUID(bytes=key),
                    position=Vector3(float(p[0]), float(p[1]), float(p[2])),
                    world_name=world,
                    flex=TOMBSTONE_FLEX if dead else None,
                ))
            from ..protocol import serialize_message

            buf = serialize_message(Message(
                instruction=Instruction.LOCAL_MESSAGE,
                parameter=placeholder,
                sender_uuid=NIL_UUID,
                world_name=world,
                entities=ents,
            ))
        needle = placeholder.encode()
        idx = buf.find(needle)
        if idx < 0:  # unreachable: the stamp is always encoded
            raise RuntimeError("stamp placeholder missing from frame")
        e_off = idx + len(kind) + 1
        s_off = e_off + 9
        return bytes(buf), e_off, s_off

    # endregion

    def stats(self) -> dict:
        total = self.last_delta_frames + self.last_full_frames
        return {
            "peers": len(self._peers),
            "near": self.last_near,
            "far": self.last_far,
            "demoted": self.last_demoted,
            "delta_frames": self.last_delta_frames,
            "full_frames": self.last_full_frames,
            "delta_ratio": round(
                self.last_delta_frames / total, 4
            ) if total else 0.0,
            "resyncs": self.resyncs,
            "deferrals": self.deferrals,
            "bytes_shed": self.bytes_shed,
            "templates_reused": self.templates_reused,
            "last_bytes": self.last_bytes,
            "far_every_k": self.far_every_k << self._shed_level,
        }
