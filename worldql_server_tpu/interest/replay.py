"""Wire-level replay clients: the eventual-state parity oracles.

:class:`ReplayClient` consumes an interest-managed stream
(``entity.frame.full`` / ``fullc`` / ``delta`` with epoch:seq stamps)
and maintains the neighbor state a real client would hold. It enforces
the contract the server claims to provide: a delta only ever applies
on a contiguous same-epoch sequence; any gap flips the client into
desync, where every frame is DISCARDED until a new epoch opens with a
keyframe. If the server were to leak a delta past a loss, the oracle
counts it in ``deltas_refused`` instead of silently corrupting state —
that counter staying at zero across the churn property is the proof.

:class:`LegacyClient` consumes the pre-interest stream (one
``entity.frame`` per entity plus ``entity.remove``) into the same
snapshot shape, so tests and the bench can assert byte-for-byte state
parity between ``--interest on`` and ``off``.
"""

from __future__ import annotations

import uuid as uuid_mod

from ..protocol import Message, deserialize_message
from .manager import PARAM_DELTA, PARAM_FULL, PARAM_FULL_CONT, parse_stamp

__all__ = ["ReplayClient", "LegacyClient"]


def _tombstone(entity) -> bool:
    return entity.flex is not None and len(entity.flex) < 12


def _as_message(frame) -> Message:
    if isinstance(frame, Message):
        return frame
    wire = getattr(frame, "wire", frame)
    return deserialize_message(bytes(wire))


class ReplayClient:
    """State a compliant client holds after replaying an interest
    stream. Feed every delivered frame (bytes, Message, or anything
    with ``.wire``) to :meth:`apply` in delivery order."""

    def __init__(self):
        #: world -> {uuid -> (x, y, z)}
        self.worlds: dict[str, dict[uuid_mod.UUID, tuple]] = {}
        self.epoch = -1
        self.next_seq = 0
        self.desync = True        # nothing applies before the first epoch
        self.frames_applied = 0
        self.fulls_applied = 0
        self.deltas_applied = 0
        self.gaps_seen = 0
        self.epochs_seen = 0
        self.deltas_refused = 0   # MUST stay 0: delta past a gap
        self.discarded = 0
        self.last_was_full = False

    def apply(self, frame) -> bool:
        """Apply one delivered frame; returns True if it mutated
        state, False if it was discarded (desync) or not an interest
        frame at all."""
        msg = _as_message(frame)
        stamped = parse_stamp(msg.parameter)
        if stamped is None:
            return False
        kind, epoch, seq = stamped

        if epoch > self.epoch:
            # a new epoch must open with its first keyframe; anything
            # else means we missed the head of the resync burst — stay
            # desynced until the next one
            if kind == PARAM_FULL and seq == 0:
                self.worlds.clear()
                self.epoch = epoch
                self.next_seq = 0
                self.desync = False
                self.epochs_seen += 1
            else:
                if kind == PARAM_DELTA:
                    self.deltas_refused += 1
                self.desync = True
                self.discarded += 1
                return False
        elif epoch < self.epoch:
            self.discarded += 1   # stale straggler from a closed epoch
            return False

        if seq != self.next_seq:
            self.gaps_seen += 1
            self.desync = True
        if self.desync:
            if kind == PARAM_DELTA:
                self.deltas_refused += 1
            self.discarded += 1
            return False
        self.next_seq = seq + 1

        world = self.worlds.setdefault(msg.world_name, {})
        if kind == PARAM_FULL:
            world.clear()
        for ent in msg.entities:
            if _tombstone(ent):
                world.pop(ent.uuid, None)
            else:
                p = ent.position
                world[ent.uuid] = (p.x, p.y, p.z)
        if not world:
            self.worlds.pop(msg.world_name, None)
        self.frames_applied += 1
        self.last_was_full = kind in (PARAM_FULL, PARAM_FULL_CONT)
        if self.last_was_full:
            self.fulls_applied += 1
        else:
            self.deltas_applied += 1
        return True

    def snapshot(self) -> dict:
        """``{world: {uuid: (x, y, z)}}`` — compare against another
        client's snapshot for eventual-state parity."""
        return {w: dict(m) for w, m in self.worlds.items() if m}

    def stats(self) -> dict:
        return {
            "epoch": self.epoch,
            "frames_applied": self.frames_applied,
            "fulls_applied": self.fulls_applied,
            "deltas_applied": self.deltas_applied,
            "epochs_seen": self.epochs_seen,
            "gaps_seen": self.gaps_seen,
            "deltas_refused": self.deltas_refused,
            "discarded": self.discarded,
            "entities": sum(len(m) for m in self.worlds.values()),
        }


class LegacyClient:
    """The pre-interest stream folded into the same snapshot shape:
    every ``entity.frame`` upserts its entities, every
    ``entity.remove`` deletes them."""

    def __init__(self):
        self.worlds: dict[str, dict[uuid_mod.UUID, tuple]] = {}
        self.frames_applied = 0

    def apply(self, frame) -> bool:
        msg = _as_message(frame)
        if msg.parameter == "entity.frame":
            world = self.worlds.setdefault(msg.world_name, {})
            for ent in msg.entities:
                p = ent.position
                world[ent.uuid] = (p.x, p.y, p.z)
        elif msg.parameter == "entity.remove":
            world = self.worlds.get(msg.world_name)
            if world:
                for ent in msg.entities:
                    world.pop(ent.uuid, None)
                if not world:
                    self.worlds.pop(msg.world_name, None)
        else:
            return False
        self.frames_applied += 1
        return True

    def snapshot(self) -> dict:
        return {w: dict(m) for w, m in self.worlds.items() if m}
