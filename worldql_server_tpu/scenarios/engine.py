"""Adversarial scenario engine (ROADMAP item 5b, ISSUE 12).

Every bench config before this PR drove well-behaved synthetic load;
the governor, durability, session and entity planes had never met an
adversary. A :class:`Scenario` here is a first-class, declarative
hostile workload: it boots a REAL :class:`WorldQLServer` over real
ZeroMQ sockets, drives a shaped storm against it, and then evaluates
a declared list of survival + SLO :class:`Check` s — no lost resumed
state, bounded handshake p99, governor back to OK, exact shed
accounting — producing one structured report.

The same library serves three masters:

* ``python -m worldql_server_tpu.scenarios`` — operator/CI CLI
  (``--check`` exits non-zero on any failed check);
* ``bench.py --config 10`` — the scenario suite as a bench record,
  wired into the CI perf gate (``checks_failed`` is a gated leaf: one
  newly failing scenario assertion fails the build);
* pytest — tests/test_scenarios.py runs the smoke shapes directly.

Shapes: every scenario sizes itself from ``shape`` ∈ {"smoke",
"full"} — smoke is tuned for a 1-core CI container (seconds, tiny
tick budgets so storms bite), full for a real box.
"""

from __future__ import annotations

import asyncio
import logging
import time
import traceback
from dataclasses import dataclass

from ..engine.config import Config
from ..engine.server import WorldQLServer
from ..protocol.types import Instruction, Message
from ..robustness import failpoints
from .client import ZmqPeer

logger = logging.getLogger(__name__)


def pctl(samples: list[float], q: float) -> float | None:
    if not samples:
        return None
    ordered = sorted(samples)
    return ordered[min(len(ordered) - 1, max(0, int(len(ordered) * q) - 1))]


@dataclass
class Check:
    """One declared survival/SLO assertion, evaluated post-drive."""

    name: str
    ok: bool
    value: object
    limit: object
    detail: str = ""

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "ok": self.ok,
            "value": self.value,
            "limit": self.limit,
            "detail": self.detail,
        }


class ScenarioContext:
    """What a scenario's ``drive``/``checks`` get to work with: the
    live server plus wire-client and drain/recovery helpers."""

    def __init__(self, server: WorldQLServer, config: Config, shape: str):
        self.server = server
        self.config = config
        self.shape = shape
        self.smoke = shape == "smoke"
        self.clients: list[ZmqPeer] = []

    async def connect(self, attempts: int = 100, **kwargs) -> ZmqPeer:
        last: Exception | str | None = None
        for _ in range(attempts):
            try:
                peer = await ZmqPeer.connect(
                    self.config.zmq_server_port, **kwargs
                )
            except Exception as exc:
                last = exc
                await asyncio.sleep(0.02)
                continue
            if peer.refused:
                # A shed handshake is NOT a connection: the server
                # never registered the peer, so every message it sends
                # from here on is dropped as unknown-sender. Honor the
                # retry-after hint and try again. (Scenarios probing
                # refusal semantics use ZmqPeer.connect directly.)
                last = f"handshake shed, retry-after {peer.retry_after_ms} ms"
                hint_s = (peer.retry_after_ms or 20) / 1000.0
                peer.close()
                await asyncio.sleep(min(hint_s, 0.5))
                continue
            self.clients.append(peer)
            return peer
        raise AssertionError(f"scenario client could not connect: {last!r}")

    def counters(self) -> dict:
        return self.server.metrics.snapshot()["counters"]

    async def drain_ticker(self, timeout_s: float = 10.0) -> bool:
        ticker = self.server.ticker
        if ticker is None:
            return True
        deadline = time.perf_counter() + timeout_s
        while time.perf_counter() < deadline:
            if not ticker._queue and not ticker.inflight():
                return True
            await asyncio.sleep(0.01)
        return False

    async def wait_governor_ok(self, timeout_s: float = 15.0) -> bool:
        gov = self.server.governor
        if gov is None:
            return True
        deadline = time.perf_counter() + timeout_s
        while time.perf_counter() < deadline:
            if gov.state == "ok" and not gov.degraded():
                return True
            await asyncio.sleep(0.02)
        return False

    async def heartbeat_ok(self, peer: ZmqPeer,
                           timeout_s: float = 5.0) -> bool:
        """Survival probe: the broker still answers on the wire."""
        try:
            await peer.send(Message(instruction=Instruction.HEARTBEAT))
            await peer.recv_until(Instruction.HEARTBEAT, timeout_s)
            return True
        except Exception:
            return False


class Scenario:
    """Base: subclasses declare a config, a drive and their checks."""

    name = "scenario"
    description = ""
    #: whether the scenario belongs in the DEFAULT CLI set — the
    #: CI-blocking "Scenario smoke" step runs exactly these; slow or
    #: special-lifecycle scenarios opt out and run by explicit name
    ci_smoke = True
    #: boot the server AS A TASK and hand ``drive`` the in-flight
    #: start (``ctx.start_task``) — for storms that must land
    #: mid-boot, e.g. during WAL replay. The drive owns awaiting it.
    concurrent_boot = False

    def build_config(self, shape: str) -> Config:
        raise NotImplementedError

    def build_backend(self):
        """Optional explicit spatial backend (e.g. a tiny compaction
        threshold so the delta path's full fold is reachable at smoke
        churn volumes); None = the config-built default."""
        return None

    async def drive(self, ctx: ScenarioContext) -> dict:
        """Run the hostile workload; returns the SLO value dict the
        checks (and the bench record) are computed from."""
        raise NotImplementedError

    def checks(self, ctx: ScenarioContext, slo: dict) -> list[Check]:
        raise NotImplementedError


async def _run_async(scenario: Scenario, shape: str) -> dict:
    # scenarios may arm failpoints (deterministic phases); never leak
    # them into the next scenario or the embedding process
    failpoints.registry.reset()
    config = scenario.build_config(shape)
    if getattr(config, "cluster_shards", 0) > 0:
        # cluster scenarios drive the ROUTER TIER — shard server
        # subprocesses plus the in-process router — through the same
        # Scenario surface (the runtime mirrors the server's
        # metrics/shutdown contract; ticker/governor are per shard)
        from ..cluster import ClusterRuntime

        server = ClusterRuntime(config)
    else:
        server = WorldQLServer(config, backend=scenario.build_backend())
    start_task = None
    if scenario.concurrent_boot:
        start_task = asyncio.ensure_future(server.start())
    else:
        await server.start()
    ctx = ScenarioContext(server, config, shape)
    ctx.start_task = start_task
    t0 = time.perf_counter()
    error = None
    slo: dict = {}
    checks: list[Check] = []
    try:
        slo = await scenario.drive(ctx)
        # evaluated BEFORE teardown: checks read live server state
        checks = list(scenario.checks(ctx, slo))
    except Exception as exc:
        error = f"{type(exc).__name__}: {exc}"
        logger.error(
            "scenario %s crashed:\n%s", scenario.name,
            traceback.format_exc(),
        )
    finally:
        for peer in ctx.clients:
            try:
                peer.close()
            except Exception:
                pass
        failpoints.registry.reset()
        if start_task is not None:
            # a concurrent boot must complete (or surface its error)
            # before teardown — stopping a half-started server leaks
            try:
                await start_task
            except Exception as exc:
                error = error or f"boot: {type(exc).__name__}: {exc}"
        await server.stop()
    survived = error is None and not server.shutdown_requested.is_set()
    checks.insert(0, Check(
        "survived", survived, bool(survived), True, error or "",
    ))
    failed = sum(1 for c in checks if not c.ok)
    return {
        "scenario": scenario.name,
        "shape": shape,
        "survived": survived,
        "wall_s": round(time.perf_counter() - t0, 2),
        "slo": slo,
        "checks": [c.as_dict() for c in checks],
        "checks_failed": failed,
        "error": error,
    }


def run_scenario(name: str, shape: str = "smoke") -> dict:
    """Run one catalog scenario to a report dict (new event loop)."""
    from . import CATALOG

    scenario = CATALOG[name]()
    return asyncio.run(_run_async(scenario, shape))


def format_report(report: dict) -> str:
    lines = [
        f"scenario {report['scenario']} ({report['shape']}): "
        f"{'PASS' if report['checks_failed'] == 0 else 'FAIL'} "
        f"in {report['wall_s']}s — "
        f"{report['checks_failed']} failed check(s)"
    ]
    for check in report["checks"]:
        mark = "ok " if check["ok"] else "FAIL"
        lines.append(
            f"  [{mark}] {check['name']}: {check['value']!r}"
            f" (limit {check['limit']!r})"
            + (f" — {check['detail']}" if check["detail"] else "")
        )
    return "\n".join(lines)
