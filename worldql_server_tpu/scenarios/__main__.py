"""Scenario CLI: ``python -m worldql_server_tpu.scenarios [names...]``.

Runs catalog scenarios back to back (each on a fresh server + event
loop) and prints their reports; ``--check`` exits 1 if any declared
survival/SLO check fails — the CI "Scenario smoke" gate. ``--json``
emits one report per line for machine consumers.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import CATALOG, format_report, run_scenario


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m worldql_server_tpu.scenarios",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("names", nargs="*", default=[],
                   help=f"scenarios to run (default: all of "
                        f"{', '.join(sorted(CATALOG))})")
    p.add_argument("--shape", choices=["smoke", "full"], default="smoke",
                   help="workload sizing (smoke = 1-core CI seconds)")
    p.add_argument("--check", action="store_true",
                   help="exit 1 when any scenario check fails")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON report per line")
    args = p.parse_args(argv)

    # default = the CI-smoke set; slow/special scenarios (ci_smoke =
    # False, e.g. reconnect_storm_replay) run by explicit name only
    names = args.names or sorted(
        n for n in CATALOG if CATALOG[n].ci_smoke
    )
    unknown = [n for n in names if n not in CATALOG]
    if unknown:
        p.error(f"unknown scenario(s): {', '.join(unknown)} "
                f"(have: {', '.join(sorted(CATALOG))})")

    failed = 0
    for name in names:
        report = run_scenario(name, shape=args.shape)
        failed += report["checks_failed"]
        if args.json:
            print(json.dumps(report))
        else:
            print(format_report(report))
    if args.check and failed:
        print(f"scenario suite: {failed} failed check(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
