"""Minimal real-wire ZeroMQ peer for the scenario engine.

Speaks the actual wire protocol over actual sockets — the same path an
external game plugin takes — so scenarios exercise transports, codec,
admission and delivery, not in-process shortcuts. Deliberately tiny:
connect/resume handshake (session tokens + retry-after refusals
included), send, recv-until, hard drop.
"""

from __future__ import annotations

import asyncio
import socket
import uuid as uuid_mod

import zmq
import zmq.asyncio

from ..protocol import (
    Instruction,
    Message,
    deserialize_message,
    serialize_message,
)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def free_port_block(n: int, attempts: int = 64) -> int:
    """A base port with ``base..base+n`` all currently free — cluster
    configs derive shard listener ports as ``base + 1 + shard_id``."""
    for _ in range(attempts):
        socks = []
        try:
            first = socket.socket()
            first.bind(("127.0.0.1", 0))
            base = first.getsockname()[1]
            socks.append(first)
            for off in range(1, n + 1):
                s = socket.socket()
                s.bind(("127.0.0.1", base + off))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("could not find a free port block")


class ZmqPeer:
    """One scenario client. ``token`` carries the session token from
    the handshake echo; ``retry_after_ms`` is set instead when the
    handshake was refused by the admission governor."""

    def __init__(self, ctx, push, pull, uuid: uuid_mod.UUID):
        self.ctx = ctx
        self.push = push
        self.pull = pull
        self.uuid = uuid
        self.token: str | None = None
        self.retry_after_ms: int | None = None

    @classmethod
    async def connect(
        cls,
        server_port: int,
        host: str = "127.0.0.1",
        peer_uuid: uuid_mod.UUID | None = None,
        token: str | None = None,
        timeout: float = 5.0,
    ) -> "ZmqPeer":
        ctx = zmq.asyncio.Context()
        pull = ctx.socket(zmq.PULL)
        client_port = pull.bind_to_random_port(f"tcp://{host}")
        push = ctx.socket(zmq.PUSH)
        push.setsockopt(zmq.LINGER, 0)
        push.connect(f"tcp://{host}:{server_port}")
        peer = cls(ctx, push, pull, peer_uuid or uuid_mod.uuid4())
        try:
            await peer.send(Message(
                instruction=Instruction.HANDSHAKE,
                parameter=f"{host}:{client_port}",
                flex=token.encode() if token is not None else None,
            ))
            echo = await peer.recv(timeout)
            assert echo.instruction == Instruction.HANDSHAKE
            if echo.parameter is not None:
                if echo.parameter.startswith("retry-after:"):
                    peer.retry_after_ms = int(echo.parameter.split(":", 1)[1])
                else:
                    peer.token = echo.parameter
        except BaseException:
            peer.close()
            raise
        return peer

    @property
    def refused(self) -> bool:
        return self.retry_after_ms is not None

    async def send(self, message: Message) -> None:
        message.sender_uuid = self.uuid
        await self.push.send(serialize_message(message))

    async def recv(self, timeout: float = 5.0) -> Message:
        data = await asyncio.wait_for(self.pull.recv(), timeout)
        return deserialize_message(data)

    async def recv_until(
        self, instruction: Instruction, timeout: float = 5.0
    ) -> Message:
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            left = deadline - asyncio.get_running_loop().time()
            if left <= 0:
                raise asyncio.TimeoutError()
            message = await self.recv(left)
            if message.instruction == instruction:
                return message

    def close(self) -> None:
        """Hard drop: sockets die with no goodbye — the network-blip
        shape the session plane exists for."""
        self.push.close(linger=0)
        self.pull.close(linger=0)
        self.ctx.term()
