"""The adversarial scenario catalog (ROADMAP 5b).

Four hostile workloads, each giving a different plane its adversary:

* :class:`FlashCrowd` — the whole population converges on ONE cube:
  Zipf hotspot fan-out + overload shedding together.
* :class:`BattleRoyale` — shrinking world bounds force sustained
  position churn through the spatial index's base+delta path.
* :class:`ReconnectStorm` — mass hard-drop then simultaneous resume
  under load, spiked with a 10x new-connect storm: the session plane's
  zero-loss guarantee and the handshake admission class under fire.
* :class:`GameTick` — a mixed record/query/entity-shaped game tick:
  the "boring" workload that must stay boring while governed.

Every scenario sizes itself per shape ("smoke" = 1-core CI seconds,
"full" = a real box) and declares its survival + SLO checks; the
runner (engine.py) turns them into one structured report consumed by
the CLI, bench config 10 and the test suite.
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid as uuid_mod

import numpy as np

from ..engine.config import Config
from ..protocol.types import Entity, Instruction, Message, Record, Vector3
from ..robustness import failpoints
from .client import ZmqPeer, free_port, free_port_block
from .engine import Check, Scenario, ScenarioContext, pctl


def _storm_config(**overrides) -> Config:
    """The deliberately throttled shape every storm scenario starts
    from: a tiny tick budget + tiny admitted floor means ANY sustained
    flood busts the deadline and engages the governor, even on a
    1-core container (the test_overload_storm calibration)."""
    config = Config(
        store_url="memory://",
        http_enabled=False, ws_enabled=False,
        zmq_server_host="127.0.0.1", zmq_server_port=free_port(),
        spatial_backend="cpu", tick_interval=0.02,
        max_batch=64, overload="on",
        overload_tick_budget_ms=0.5, overload_min_batch=8,
        overload_deadline_k=2, overload_recover_ticks=5,
        trace=True,
        supervisor_backoff=0.005,
    )
    for key, value in overrides.items():
        setattr(config, key, value)
    return config


class FlashCrowd(Scenario):
    """Flash-crowd migration: a spread population (one cube each)
    suddenly converges on a single cube and floods it — every local
    fans to everyone, the hotspot stresses fan-out and admission at
    once. Survival means: queue bounded by the admission cap, every
    shed message accounted exactly, governor back to OK after."""

    name = "flash_crowd"
    description = "whole population converges on one cube"

    def build_config(self, shape: str) -> Config:
        return _storm_config()

    async def drive(self, ctx: ScenarioContext) -> dict:
        n_clients = 6 if ctx.smoke else 16
        spread_s = 0.4 if ctx.smoke else 2.0
        converge_s = 1.2 if ctx.smoke else 6.0
        hot = Vector3(5.0, 5.0, 5.0)

        clients = [await ctx.connect() for _ in range(n_clients)]
        # spread phase: everyone in their OWN cube, light paced chat
        for i, c in enumerate(clients):
            await c.send(Message(
                instruction=Instruction.AREA_SUBSCRIBE,
                world_name="arena", position=Vector3(i * 160.0, 0.0, 0.0),
            ))
        end = time.perf_counter() + spread_s
        while time.perf_counter() < end:
            for i, c in enumerate(clients):
                await c.send(Message(
                    instruction=Instruction.LOCAL_MESSAGE,
                    world_name="arena",
                    position=Vector3(i * 160.0, 0.0, 0.0),
                    parameter="spread",
                ))
            await asyncio.sleep(0.02)

        # convergence: everyone subscribes the hot cube, then floods it
        for c in clients:
            await c.send(Message(
                instruction=Instruction.AREA_SUBSCRIBE,
                world_name="arena", position=hot,
            ))
        gov = ctx.server.governor
        offered = 0

        async def flood(client: ZmqPeer) -> int:
            sent = 0
            end = time.perf_counter() + converge_s
            while time.perf_counter() < end:
                await client.send(Message(
                    instruction=Instruction.LOCAL_MESSAGE,
                    world_name="arena", position=hot, parameter="crowd",
                ))
                sent += 1
            return sent

        offered = sum(await asyncio.gather(*(flood(c) for c in clients)))
        queue_peak_bounded = (
            len(ctx.server.ticker._queue) <= gov.local_queue_cap()
        )
        drained = await ctx.drain_ticker()
        recovered = await ctx.wait_governor_ok()
        counters = ctx.counters()
        seen = counters.get("messages.local_message", 0)
        flushed = counters.get("tick.messages", 0)
        alive = await ctx.heartbeat_ok(clients[0])
        return {
            "clients": n_clients,
            "offered": offered,
            "seen": seen,
            "flushed": flushed,
            "drop_oldest": gov.drop_oldest,
            "shed_local": gov.shed["local"],
            "governor_peak_level": gov.peak_level,
            "queue_bounded": queue_peak_bounded,
            "drained": drained,
            "recovered_to_ok": recovered,
            "broker_answers": alive,
        }

    def checks(self, ctx: ScenarioContext, slo: dict) -> list[Check]:
        gov = ctx.server.governor
        shed_total = slo["drop_oldest"] + slo["shed_local"]
        return [
            Check("hotspot_escalated_governor",
                  slo["governor_peak_level"] >= 1,
                  slo["governor_peak_level"], ">= 1"),
            Check("queue_bounded_by_admission_cap", slo["queue_bounded"],
                  slo["queue_bounded"], True),
            Check("shed_accounting_exact",
                  slo["seen"] == slo["flushed"] + shed_total,
                  slo["seen"], slo["flushed"] + shed_total,
                  "seen == flushed + drop_oldest + shed_local"),
            Check("governor_recovered_to_ok", slo["recovered_to_ok"],
                  gov.state, "ok"),
            Check("broker_answers_after_storm", slo["broker_answers"],
                  slo["broker_answers"], True),
        ]


class BattleRoyale(Scenario):
    """Battle-royale shrinking bounds: the play area halves phase
    after phase and every entity's owner streams it toward the center
    — sustained cube churn through the index's base+delta path (fold,
    tombstones, compaction) while the sim tick keeps running."""

    name = "battle_royale"
    description = "shrinking bounds drive sustained base+delta churn"

    def build_config(self, shape: str) -> Config:
        return Config(
            store_url="memory://",
            http_enabled=False, ws_enabled=False,
            zmq_server_host="127.0.0.1", zmq_server_port=free_port(),
            spatial_backend="tpu", tick_interval=0.02,
            entity_sim=True, precompile_tiers=False,
            sub_region_size=16,
        )

    def build_backend(self):
        # a tiny compaction threshold makes the delta path's full
        # base+delta fold reachable at smoke churn volumes (the
        # bench config 8 calibration)
        from ..spatial.tpu_backend import TpuSpatialBackend

        return TpuSpatialBackend(16, compact_threshold=8)

    async def drive(self, ctx: ScenarioContext) -> dict:
        n_entities = 48 if ctx.smoke else 512
        phases = 4 if ctx.smoke else 8
        rng = np.random.default_rng(7)
        owner = await ctx.connect()
        ids = [uuid_mod.uuid4() for _ in range(n_entities)]
        pos = rng.uniform(-600.0, 600.0, size=(n_entities, 3))

        def batch(positions) -> Message:
            return Message(
                instruction=Instruction.LOCAL_MESSAGE,
                world_name="royale",
                entities=[
                    Entity(uuid=ids[i], world_name="royale",
                           position=Vector3(*positions[i]))
                    for i in range(n_entities)
                ],
            )

        await owner.send(batch(pos))
        plane = ctx.server.entity_plane
        deadline = time.perf_counter() + 10.0
        while plane.entity_count < n_entities:
            if time.perf_counter() > deadline:
                raise AssertionError("entity registration never landed")
            await asyncio.sleep(0.02)
        await asyncio.sleep(0.1)  # a few applied ticks at full spread

        backend = ctx.server.backend
        moves0 = plane.index_moves
        for _ in range(phases):
            # the circle shrinks: every entity's owner streams it
            # toward the center — cube crossings ride the delta path
            pos = pos * 0.45
            await owner.send(batch(pos))
            await asyncio.sleep(0.12)  # several applied ticks
        drained = await ctx.drain_ticker()
        # compactions COUNT at the swap-in flush after the background
        # fold completes — drain the worker, then flush once more (the
        # test_entity_sim idiom), so the SLO reads the settled value
        wait = getattr(backend, "wait_compaction", None)
        if wait is not None:
            wait()
            backend.flush()
        alive = await ctx.heartbeat_ok(owner)
        final = plane._pos[: plane._cap][plane._live[: plane._cap]]
        return {
            "entities": plane.entity_count,
            "registered": n_entities,
            "applied_ticks": plane.applied_ticks,
            "dropped_ticks": plane.dropped_ticks,
            "index_moves": plane.index_moves - moves0,
            "compactions": int(getattr(backend, "compactions", 0)),
            "index_rows": len(plane._sub_refs),
            "final_spread": float(np.abs(final).max()) if final.size else 0.0,
            "drained": drained,
            "broker_answers": alive,
        }

    def checks(self, ctx: ScenarioContext, slo: dict) -> list[Check]:
        return [
            Check("population_intact",
                  slo["entities"] == slo["registered"],
                  slo["entities"], slo["registered"]),
            Check("sim_kept_ticking", slo["applied_ticks"] > 0,
                  slo["applied_ticks"], "> 0"),
            Check("no_dropped_ticks", slo["dropped_ticks"] == 0,
                  slo["dropped_ticks"], 0),
            Check("churn_rode_delta_path", slo["index_moves"] > 0,
                  slo["index_moves"], "> 0"),
            Check("delta_churn_compacted", slo["compactions"] >= 1,
                  slo["compactions"], ">= 1"),
            Check("index_rows_bounded",
                  slo["index_rows"] <= slo["registered"],
                  slo["index_rows"], f"<= {slo['registered']}",
                  "refcounted (world,cube,peer) rows never exceed "
                  "the population"),
            Check("broker_answers_after_churn", slo["broker_answers"],
                  slo["broker_answers"], True),
        ]


class ReconnectStorm(Scenario):
    """Hostile-swarm reconnect storm: every client hard-drops at once,
    then resumes simultaneously — under background flood, spiked with
    a 10x new-connect storm — and a deterministic forced-REJECT phase
    proves the admission asymmetry (new sheds with a retry-after hint;
    resume still admitted). The tentpole guarantee under test: zero
    subscription/entity loss for sessions resumed within TTL."""

    name = "reconnect_storm"
    description = "mass drop + simultaneous resume + 10x connect storm"

    def build_config(self, shape: str) -> Config:
        return _storm_config(
            spatial_backend="tpu", entity_sim=True,
            precompile_tiers=False,
            session_ttl=30.0, session_resume_rate=500.0,
            # the adversary here is the CONNECT storm, not the tick
            # budget: the budget must be meetable by an idle device
            # tick on a 1-core container or the governor can never
            # de-escalate after the storm passes
            overload_tick_budget_ms=50.0,
        )

    def build_backend(self):
        from ..spatial.tpu_backend import TpuSpatialBackend

        return TpuSpatialBackend(16)

    async def drive(self, ctx: ScenarioContext) -> dict:
        n = 6 if ctx.smoke else 24
        ents_per = 4
        storm_factor = 10
        server = ctx.server
        plane = server.entity_plane
        sessions = server.sessions

        # population: subscriptions + owned entities per client
        swarm: list[ZmqPeer] = []
        ent_ids: list[list[uuid_mod.UUID]] = []

        async def register(i: int) -> None:
            await swarm[i].send(Message(
                instruction=Instruction.LOCAL_MESSAGE,
                world_name="arena",
                entities=[
                    Entity(uuid=ent_ids[i][j], world_name="arena",
                           position=Vector3(i * 40.0, float(j), 0.0))
                    for j in range(ents_per)
                ],
            ))

        # COLD-JIT WARM-UP. The first device tick with entities staged
        # compiles the tier (precompile_tiers=False): ~1 s on a 1-core
        # container, 20x the 50 ms tick budget, so the governor can
        # escalate straight to REJECT off that single bust and shed
        # one-shot registrations (the intermittent "entity
        # registration never landed" this replaces). Pay the compile
        # ONCE with a throwaway entity — resent until it lands, since
        # the very updates that trigger the compile are also the ones
        # REJECT sheds — then let the governor walk back to OK before
        # the measured population begins.
        warm = await ctx.connect()
        warm_ent = uuid_mod.uuid4()
        deadline = time.perf_counter() + 45.0
        while plane.entity_count < 1:
            if time.perf_counter() > deadline:
                raise AssertionError("warm-up registration never landed")
            await warm.send(Message(
                instruction=Instruction.LOCAL_MESSAGE,
                world_name="arena",
                entities=[Entity(uuid=warm_ent, world_name="arena",
                                 position=Vector3(-40.0, 0.0, 0.0))],
            ))
            await asyncio.sleep(0.25)
        # entity_count advances at STAGING time — before the compile
        # tick even starts — so drain the ticker (the compile runs
        # inside a tick; inflight() covers it) before sampling the
        # governor, or the bust lands right after this wait and the
        # swarm's handshakes walk into the shed window.
        await ctx.drain_ticker(30.0)
        await ctx.wait_governor_ok(30.0)
        base = plane.entity_count

        for i in range(n):
            c = await ctx.connect()
            swarm.append(c)
            ent_ids.append([uuid_mod.uuid4() for _ in range(ents_per)])
            await c.send(Message(
                instruction=Instruction.AREA_SUBSCRIBE,
                world_name="arena", position=Vector3(i * 40.0, 0.0, 0.0),
            ))
            await register(i)
        # Residual shed risk: the population itself can cross a tier
        # boundary and compile AGAIN. Registrations are idempotent LWW
        # upserts keyed by entity uuid, so RESEND until they admit;
        # the deadline still bounds the wait.
        deadline = time.perf_counter() + 45.0
        last_resend = time.perf_counter()
        while plane.entity_count - base < n * ents_per:
            if time.perf_counter() > deadline:
                gov = server.governor
                raise AssertionError(
                    "entity registration never landed: "
                    f"entity_count={plane.entity_count} base={base} "
                    f"target={n * ents_per} "
                    f"governor={gov.state if gov else None} "
                    f"shed={dict(gov.shed) if gov else None} "
                    f"ingest={server.entity_ingest.stats() if server.entity_ingest else None}"
                )
            if time.perf_counter() - last_resend > 1.0:
                last_resend = time.perf_counter()
                for i in range(n):
                    await register(i)
            await asyncio.sleep(0.02)
        await asyncio.sleep(0.1)
        subs0 = server.backend.subscription_count()
        ents0 = plane.entity_count
        tokens = [(c.token, c.uuid) for c in swarm]
        assert all(t for t, _ in tokens), "sessions were not minted"

        # MASS DROP: every socket dies with no goodbye; the server
        # notices through its normal eviction path (the staleness
        # sweep's removal call) and parks each session
        for c in swarm:
            c.close()
        for _, u in tokens:
            await server.peer_map.remove(u)
        parked = sessions.parked_count()

        # RECONNECT STORM: all resumes at once + a 10x new-connect
        # storm + background flood on the hot path
        flooder = await ctx.connect()
        await flooder.send(Message(
            instruction=Instruction.AREA_SUBSCRIBE,
            world_name="arena", position=Vector3(5.0, 5.0, 5.0),
        ))
        stop_flood = False

        async def flood():
            while not stop_flood:
                await flooder.send(Message(
                    instruction=Instruction.LOCAL_MESSAGE,
                    world_name="arena", position=Vector3(5.0, 5.0, 5.0),
                    parameter="bg",
                ))

        resume_walls: list[float] = []
        resumed: dict[int, ZmqPeer] = {}

        async def resume_one(i: int, token: str, peer_uuid) -> None:
            t0 = time.perf_counter()
            peer = await ctx.connect(token=token, peer_uuid=peer_uuid)
            resume_walls.append((time.perf_counter() - t0) * 1e3)
            resumed[i] = peer

        refused_or_timeout = 0

        async def new_connect() -> None:
            nonlocal refused_or_timeout
            try:
                peer = await ZmqPeer.connect(
                    ctx.config.zmq_server_port, timeout=2.0
                )
                if peer.refused:
                    refused_or_timeout += 1
                    peer.close()
                else:
                    ctx.clients.append(peer)
            except Exception:
                refused_or_timeout += 1  # silent shed (hint budget)

        flood_task = asyncio.ensure_future(flood())
        try:
            await asyncio.gather(
                *(resume_one(i, t, u) for i, (t, u) in enumerate(tokens)),
                *(new_connect() for _ in range(storm_factor * n)),
            )
        finally:
            stop_flood = True
            await flood_task
        subs1 = server.backend.subscription_count()
        ents1 = plane.entity_count

        # resumed peers still OWN their parked entities: a post-resume
        # update from every client must apply (ownership is enforced
        # server-side, so this also proves the rebind kept identity)
        updates0 = plane.updates
        for i, peer in resumed.items():
            await peer.send(Message(
                instruction=Instruction.LOCAL_MESSAGE,
                world_name="arena",
                entities=[Entity(
                    uuid=ent_ids[i][0], world_name="arena",
                    position=Vector3(i * 40.0 + 1.0, 0.0, 0.0),
                )],
            ))
        deadline = time.perf_counter() + 5.0
        while plane.updates < updates0 + len(resumed):
            if time.perf_counter() > deadline:
                break
            await asyncio.sleep(0.02)

        # DETERMINISTIC REJECT PHASE: force the state machine to
        # REJECT and pin the admission asymmetry — new connect refused
        # with a retry-after hint, resume still admitted
        failpoints.registry.set("overload.force_state", "state:reject")
        await asyncio.sleep(0.1)  # ticker evaluates → forced state
        probe_new = await ZmqPeer.connect(
            ctx.config.zmq_server_port, timeout=2.0
        )
        ctx.clients.append(probe_new)
        reject_refused = probe_new.refused
        retry_hint = probe_new.retry_after_ms
        victim = resumed[0]
        ownership_held = plane.updates - updates0
        victim_token, victim_uuid = victim.token, victim.uuid
        victim.close()
        await server.peer_map.remove(victim_uuid)
        reresumed = await ctx.connect(
            token=victim_token, peer_uuid=victim_uuid
        )
        reject_resume_ok = reresumed.token == victim_token
        failpoints.registry.clear()

        drained = await ctx.drain_ticker()
        recovered = await ctx.wait_governor_ok()
        gov = server.governor
        alive = await ctx.heartbeat_ok(reresumed)
        return {
            "swarm": n,
            "parked": parked,
            "resumed": len(resume_walls),
            "resume_p99_ms": round(pctl(resume_walls, 0.99) or 0.0, 1),
            "resume_p50_ms": round(pctl(resume_walls, 0.50) or 0.0, 1),
            "new_connect_attempts": storm_factor * n,
            "new_refused_or_shed": refused_or_timeout,
            "subscriptions_before": subs0,
            "subscriptions_after": subs1,
            "entities_before": ents0,
            "entities_after": ents1,
            "post_resume_updates_applied": ownership_held,
            "reject_new_refused": reject_refused,
            "reject_retry_after_ms": retry_hint,
            "reject_resume_admitted": reject_resume_ok,
            "shed_handshake_new": gov.shed["handshake_new"],
            "shed_handshake_resume": gov.shed["handshake_resume"],
            "sessions": sessions.stats(),
            "governor_peak_level": gov.peak_level,
            "drained": drained,
            "recovered_to_ok": recovered,
            "broker_answers": alive,
        }

    def checks(self, ctx: ScenarioContext, slo: dict) -> list[Check]:
        gov = ctx.server.governor
        # "bounded", not "fast": smoke runs a saturating flood + the
        # whole connect storm time-shared on ONE CI core — the bound
        # catches a wedged/livelocked handshake path (tens of seconds
        # to never), not scheduler contention
        p99_limit = 5000.0 if ctx.smoke else 500.0
        return [
            Check("all_sessions_parked", slo["parked"] == slo["swarm"],
                  slo["parked"], slo["swarm"]),
            Check("all_resumes_landed", slo["resumed"] == slo["swarm"],
                  slo["resumed"], slo["swarm"]),
            Check("zero_subscription_loss",
                  slo["subscriptions_after"] >= slo["subscriptions_before"],
                  slo["subscriptions_after"],
                  f">= {slo['subscriptions_before']}",
                  "parked index rows survived the drop+resume cycle"),
            Check("zero_entity_loss",
                  slo["entities_after"] == slo["entities_before"],
                  slo["entities_after"], slo["entities_before"]),
            Check("resumed_peers_kept_ownership",
                  slo["post_resume_updates_applied"] >= slo["swarm"],
                  slo["post_resume_updates_applied"],
                  f">= {slo['swarm']}",
                  "an update per resumed client applied to its own "
                  "parked entity"),
            Check("resume_p99_bounded_under_storm",
                  slo["resume_p99_ms"] <= p99_limit,
                  slo["resume_p99_ms"], f"<= {p99_limit} ms"),
            Check("reject_sheds_new_with_retry_hint",
                  bool(slo["reject_new_refused"])
                  and (slo["reject_retry_after_ms"] or 0) > 0,
                  slo["reject_retry_after_ms"], "> 0 ms",
                  "forced REJECT refused the new connect and hinted"),
            Check("reject_still_admits_resume",
                  bool(slo["reject_resume_admitted"]),
                  slo["reject_resume_admitted"], True),
            Check("handshake_sheds_accounted",
                  gov.shed["handshake_new"] >= 1,
                  gov.shed["handshake_new"], ">= 1"),
            Check("governor_recovered_to_ok", slo["recovered_to_ok"],
                  gov.state, "ok"),
            Check("broker_answers_after_storm", slo["broker_answers"],
                  slo["broker_answers"], True),
        ]


class GameTick(Scenario):
    """Mixed record/query/entity-shaped game tick: every client, at a
    fixed cadence, sends a positioned local (the movement packet), an
    occasional durable record (the inventory write) and a global (the
    chat line). The boring workload that must STAY boring: every
    record lands, fan-out flows, the governor never has to leave OK."""

    name = "game_tick"
    description = "mixed record/query/pub-sub workload at game cadence"

    def build_config(self, shape: str) -> Config:
        return _storm_config(
            # realistic budget: the mixed load is sustainable by
            # design — this scenario proves the governed server at
            # normal load IS the ungoverned server
            overload_tick_budget_ms=50.0,
        )

    async def drive(self, ctx: ScenarioContext) -> dict:
        n_clients = 4 if ctx.smoke else 16
        ticks = 40 if ctx.smoke else 400
        cadence_s = 0.02
        hot = Vector3(3.0, 3.0, 3.0)
        region = Vector3(1.0, 2.0, 3.0)

        clients = [await ctx.connect() for _ in range(n_clients)]
        for c in clients:
            await c.send(Message(
                instruction=Instruction.AREA_SUBSCRIBE,
                world_name="match", position=hot,
            ))
        received = 0
        stop_count = False

        async def count_frames():
            nonlocal received
            while not stop_count:
                try:
                    m = await clients[0].recv(0.25)
                except asyncio.TimeoutError:
                    continue
                if m.instruction == Instruction.LOCAL_MESSAGE:
                    received += 1

        counter_task = asyncio.ensure_future(count_frames())
        hb_walls: list[float] = []
        records_sent = 0
        from ..protocol.types import Record

        try:
            for t in range(ticks):
                t0 = time.perf_counter()
                for i, c in enumerate(clients):
                    await c.send(Message(
                        instruction=Instruction.LOCAL_MESSAGE,
                        world_name="match", position=hot,
                        parameter=f"move{t}",
                    ))
                    if t % 5 == i % 5:
                        records_sent += 1
                        await c.send(Message(
                            instruction=Instruction.RECORD_CREATE,
                            world_name="match",
                            records=[Record(
                                uuid=uuid_mod.uuid4(), position=region,
                                world_name="match", data=f"inv{t}",
                            )],
                        ))
                    if t % 10 == 0 and i == 0:
                        await c.send(Message(
                            instruction=Instruction.GLOBAL_MESSAGE,
                            world_name="match", parameter=f"chat{t}",
                        ))
                if t % 8 == 0:
                    hb0 = time.perf_counter()
                    if await ctx.heartbeat_ok(clients[-1], 5.0):
                        hb_walls.append(
                            (time.perf_counter() - hb0) * 1e3
                        )
                pace = cadence_s - (time.perf_counter() - t0)
                if pace > 0:
                    await asyncio.sleep(pace)
            drained = await ctx.drain_ticker()
            await asyncio.sleep(0.1)
        finally:
            stop_count = True
            await counter_task
        rows = await ctx.server.router.durability.get_records_in_region(
            "match", region
        )
        gov = ctx.server.governor
        counters = ctx.counters()
        return {
            "clients": n_clients,
            "ticks": ticks,
            "records_sent": records_sent,
            "records_stored": len({sr.record.uuid for sr in rows}),
            "locals_seen": counters.get("messages.local_message", 0),
            "frames_received_probe": received,
            "heartbeat_p99_ms": round(pctl(hb_walls, 0.99) or 0.0, 1),
            "governor_peak_level": gov.peak_level,
            "shed_total": gov.drop_oldest + gov.shed["local"],
            "drained": drained,
        }

    def checks(self, ctx: ScenarioContext, slo: dict) -> list[Check]:
        hb_limit = 1000.0 if ctx.smoke else 100.0
        return [
            Check("every_record_landed",
                  slo["records_stored"] == slo["records_sent"],
                  slo["records_stored"], slo["records_sent"]),
            Check("fanout_flowed", slo["frames_received_probe"] > 0,
                  slo["frames_received_probe"], "> 0"),
            Check("nothing_shed_at_game_load", slo["shed_total"] == 0,
                  slo["shed_total"], 0,
                  "a sustainable mixed workload must not be degraded "
                  "by the governor's presence"),
            Check("heartbeat_p99_bounded",
                  slo["heartbeat_p99_ms"] <= hb_limit,
                  slo["heartbeat_p99_ms"], f"<= {hb_limit} ms"),
            Check("queue_drained", slo["drained"], slo["drained"], True),
        ]


def _cube_of(x: float, y: float, z: float, size: int) -> tuple[int, int, int]:
    """The subscription-cube label of a position — computed through the
    REAL quantizer, so scenario expectations can never drift from the
    max-corner grid convention."""
    from ..spatial.quantize import cube_coords_batch

    row = cube_coords_batch(np.array([[x, y, z]], np.float64), size)[0]
    return tuple(int(c) for c in row)


async def _query_roundtrip(peer: ZmqPeer, world: str, position: Vector3,
                           wire: str, payload: dict,
                           timeout: float = 10.0) -> dict:
    """Send one kind query over the wire and await ITS reply frame
    (``<wire>.result``), decoded from the JSON flex body."""
    await peer.send(Message(
        instruction=Instruction.LOCAL_MESSAGE, world_name=world,
        position=position, parameter=wire,
        flex=json.dumps(payload).encode("utf-8"),
    ))
    deadline = time.perf_counter() + timeout
    while True:
        left = deadline - time.perf_counter()
        if left <= 0:
            raise asyncio.TimeoutError(f"no {wire}.result within {timeout}s")
        reply = await peer.recv(left)
        if (
            reply.instruction == Instruction.LOCAL_MESSAGE
            and reply.parameter == f"{wire}.result"
            and reply.flex
        ):
            return json.loads(reply.flex.decode("utf-8"))


class SniperScope(Scenario):
    """Cone-of-sight + raycast over the real wire (ISSUE 17): a sniper
    peer interrogates a laid-out world through ``query.cone`` and
    ``query.raycast`` LocalMessages and every reply frame is checked
    against the EXACT geometric expectation — narrow cone sees only the
    on-axis targets, widening past 90° admits the flanker but never the
    peer behind, first-hit returns the nearest occupied cube before the
    farther one, an empty ray is still answered, the sender never
    appears in its own results, and a hostile malformed payload is
    dropped with a counter while the session survives."""

    name = "sniper_scope"
    description = "cone + raycast queries with exact geometric answers"

    def build_config(self, shape: str) -> Config:
        return Config(
            store_url="memory://",
            http_enabled=False, ws_enabled=False,
            zmq_server_host="127.0.0.1", zmq_server_port=free_port(),
            spatial_backend="tpu", tick_interval=0.02,
            precompile_tiers=False,
            sub_region_size=16,
        )

    async def drive(self, ctx: ScenarioContext) -> dict:
        world = "scope"
        sniper = await ctx.connect()
        # the range: one cube-spaced lane along +x from the sniper, a
        # flanker 90° off-axis, a target square behind the scope
        layout = {
            "near": Vector3(24.0, 8.0, 8.0),
            "far": Vector3(40.0, 8.0, 8.0),
            "flank": Vector3(8.0, 40.0, 8.0),
            "behind": Vector3(-24.0, 8.0, 8.0),
        }
        targets = {name: await ctx.connect() for name in layout}
        apex = Vector3(8.0, 8.0, 8.0)
        await sniper.send(Message(
            instruction=Instruction.AREA_SUBSCRIBE,
            world_name=world, position=apex,
        ))
        for name, peer in targets.items():
            await peer.send(Message(
                instruction=Instruction.AREA_SUBSCRIBE,
                world_name=world, position=layout[name],
            ))
        deadline = time.perf_counter() + 10.0
        while ctx.server.backend.subscription_count() < 5:
            if time.perf_counter() > deadline:
                raise AssertionError("subscriptions never landed")
            await asyncio.sleep(0.02)

        hexes = {name: peer.uuid.hex for name, peer in targets.items()}
        replies: dict[str, dict] = {}
        # first reply pays the kind-kernel jit compile on a cold server
        replies["narrow"] = await _query_roundtrip(
            sniper, world, apex, "query.cone",
            {"dir": [1, 0, 0], "half_angle_deg": 30, "range": 48},
            timeout=90.0,
        )
        replies["wide"] = await _query_roundtrip(
            sniper, world, apex, "query.cone",
            {"dir": [1, 0, 0], "half_angle_deg": 95, "range": 48},
        )
        replies["first_hit"] = await _query_roundtrip(
            sniper, world, apex, "query.raycast",
            {"dir": [1, 0, 0], "max_t": 48},
        )
        replies["all_hits"] = await _query_roundtrip(
            sniper, world, apex, "query.raycast",
            {"dir": [1, 0, 0], "max_t": 48, "mode": "all_hits"},
        )
        replies["empty_ray"] = await _query_roundtrip(
            sniper, world, apex, "query.raycast",
            {"dir": [0, 0, 1], "max_t": 48},
        )

        # hostile payload: not even JSON — dropped at the router with a
        # counter, never a tick or the session
        malformed0 = ctx.counters().get("queries.malformed", 0)
        await sniper.send(Message(
            instruction=Instruction.LOCAL_MESSAGE, world_name=world,
            position=apex, parameter="query.cone", flex=b"{broken",
        ))
        deadline = time.perf_counter() + 5.0
        while ctx.counters().get("queries.malformed", 0) <= malformed0:
            if time.perf_counter() > deadline:
                break
            await asyncio.sleep(0.02)

        drained = await ctx.drain_ticker()
        counters = ctx.counters()
        all_hit_t = dict(zip(
            replies["all_hits"]["peers"], replies["all_hits"]["ts"]
        ))
        sniper_leaked = any(
            sniper.uuid.hex in r.get("peers", ()) for r in replies.values()
        )
        return {
            "hexes": hexes,
            "narrow_peers": sorted(replies["narrow"]["peers"]),
            "wide_peers": sorted(replies["wide"]["peers"]),
            "first_hit_peers": replies["first_hit"]["peers"],
            "first_hit_t": replies["first_hit"]["t"],
            "all_hits_t_by_peer": all_hit_t,
            "empty_ray_peers": replies["empty_ray"]["peers"],
            "empty_ray_t": replies["empty_ray"]["t"],
            "sniper_in_own_results": sniper_leaked,
            "malformed_dropped":
                counters.get("queries.malformed", 0) - malformed0,
            "kind_replies": counters.get("queries.kind_replies", 0),
            "drained": drained,
            "broker_answers": await ctx.heartbeat_ok(sniper),
        }

    def checks(self, ctx: ScenarioContext, slo: dict) -> list[Check]:
        hexes = slo["hexes"]
        lane = sorted([hexes["near"], hexes["far"]])
        wide = sorted([hexes["near"], hexes["far"], hexes["flank"]])
        t_near = slo["all_hits_t_by_peer"].get(hexes["near"])
        t_far = slo["all_hits_t_by_peer"].get(hexes["far"])
        ladder_ok = (
            set(slo["all_hits_t_by_peer"]) == {hexes["near"], hexes["far"]}
            and t_near is not None and t_far is not None
            and 0.0 < t_near < t_far <= 48.0
        )
        return [
            Check("narrow_cone_sees_exactly_the_lane",
                  slo["narrow_peers"] == lane,
                  slo["narrow_peers"], lane),
            Check("wide_cone_admits_flanker_never_behind",
                  slo["wide_peers"] == wide,
                  slo["wide_peers"], wide,
                  "95° half-angle: flanker in, the peer behind out"),
            Check("first_hit_is_the_nearest_cube",
                  slo["first_hit_peers"] == [hexes["near"]]
                  and slo["first_hit_t"] is not None,
                  slo["first_hit_peers"], [hexes["near"]]),
            Check("all_hits_ladder_ordered", ladder_ok,
                  slo["all_hits_t_by_peer"],
                  "near strictly before far, both within max_t"),
            Check("empty_ray_still_answered",
                  slo["empty_ray_peers"] == []
                  and slo["empty_ray_t"] is None,
                  slo["empty_ray_peers"], [],
                  "the sender is owed a reply frame either way"),
            Check("sender_never_in_own_results",
                  not slo["sniper_in_own_results"],
                  slo["sniper_in_own_results"], False),
            Check("malformed_payload_dropped_with_counter",
                  slo["malformed_dropped"] >= 1,
                  slo["malformed_dropped"], ">= 1"),
            Check("kind_replies_accounted",
                  slo["kind_replies"] >= 5,
                  slo["kind_replies"], ">= 5"),
            Check("broker_answers_after_malformed_probe",
                  slo["broker_answers"], slo["broker_answers"], True),
        ]


class ProjectileStorm(Scenario):
    """A sustained mixed kind-query storm (ISSUE 17): a firing line
    with a 3-peer hotspot cube drives ``query.knn`` +
    ``query.raycast`` + ``query.density`` rounds concurrently through
    the batched tick path, request-response paced so every reply is
    accounted. The last round's replies are checked EXACTLY — the kNN
    neighbor ladder (nearest cube first, then the hotspot pair in uuid
    order), the raycast peer→t hit map, the density survey with the
    hotspot count on top — and the density results must have fed the
    live region heatmap the /metrics gauge and /debug/heatmap read."""

    name = "projectile_storm"
    description = "mixed knn/raycast/density storm feeding the heatmap"

    def build_config(self, shape: str) -> Config:
        return Config(
            store_url="memory://",
            http_enabled=False, ws_enabled=False,
            zmq_server_host="127.0.0.1", zmq_server_port=free_port(),
            spatial_backend="tpu", tick_interval=0.02,
            precompile_tiers=False,
            sub_region_size=16,
        )

    async def drive(self, ctx: ScenarioContext) -> dict:
        world = "warzone"
        rounds = 8 if ctx.smoke else 30
        size = ctx.config.sub_region_size
        # three shooters share ONE cube (the hotspot the density query
        # must rank first); two more hold the lane cubes along +x
        spots = [
            Vector3(4.0, 8.0, 8.0), Vector3(8.0, 8.0, 8.0),
            Vector3(12.0, 8.0, 8.0),                       # hotspot cube
            Vector3(24.0, 8.0, 8.0), Vector3(40.0, 8.0, 8.0),
        ]
        shooters = [await ctx.connect() for _ in spots]
        observer = await ctx.connect()
        obs_spot = Vector3(8.0, 40.0, 8.0)
        for peer, spot in zip(shooters, spots):
            await peer.send(Message(
                instruction=Instruction.AREA_SUBSCRIBE,
                world_name=world, position=spot,
            ))
        await observer.send(Message(
            instruction=Instruction.AREA_SUBSCRIBE,
            world_name=world, position=obs_spot,
        ))
        deadline = time.perf_counter() + 10.0
        while ctx.server.backend.subscription_count() < len(spots) + 1:
            if time.perf_counter() > deadline:
                raise AssertionError("subscriptions never landed")
            await asyncio.sleep(0.02)

        requests0 = ctx.counters().get("queries.kind_requests", 0)
        replies0 = ctx.counters().get("queries.kind_replies", 0)
        heatmap = ctx.server.heatmap
        updates0 = heatmap.updates if heatmap is not None else 0
        survey_apex = Vector3(8.0, 8.0, 8.0)
        last: dict[str, dict] = {}
        for i in range(rounds):
            # first round pays the kind-kernel jit compile cold
            timeout = 90.0 if i == 0 else 15.0
            knn, ray, density = await asyncio.gather(
                _query_roundtrip(
                    shooters[4], world, spots[4], "query.knn",
                    {"k": 3, "max_range": 48}, timeout,
                ),
                _query_roundtrip(
                    shooters[0], world, spots[0], "query.raycast",
                    {"dir": [1, 0, 0], "max_t": 64, "mode": "all_hits"},
                    timeout,
                ),
                _query_roundtrip(
                    observer, world, survey_apex, "query.density",
                    {"extent": 2, "top_n": 8}, timeout,
                ),
            )
            last = {"knn": knn, "ray": ray, "density": density}

        drained = await ctx.drain_ticker()
        counters = ctx.counters()
        hot = [s.uuid for s in shooters[:3]]
        from ..queries.results import _uuid_key

        hot_sorted = [u.hex for u in sorted(hot, key=_uuid_key)]
        ray_t = dict(zip(last["ray"]["peers"], last["ray"]["ts"]))
        expected_survey = sorted(
            [
                [*_cube_of(8.0, 8.0, 8.0, size), 3],     # the hotspot
                [*_cube_of(24.0, 8.0, 8.0, size), 1],
                [*_cube_of(40.0, 8.0, 8.0, size), 1],
                [*_cube_of(8.0, 40.0, 8.0, size), 1],    # the observer
            ],
            key=lambda r: (-r[3], r[0], r[1], r[2]),
        )
        return {
            "rounds": rounds,
            "knn_k": last["knn"]["k"],
            "knn_peers": last["knn"]["peers"],
            "knn_expected": [shooters[3].uuid.hex, *hot_sorted[:2]],
            "ray_t_by_peer": ray_t,
            # the shooter's own hotspot cube answers at t=0 (minus the
            # sender), the lane cubes at the first in-cube sample
            "ray_expected": {
                **{h: 0.0 for h in hot_sorted
                   if h != shooters[0].uuid.hex},
                shooters[3].uuid.hex: 16.0,
                shooters[4].uuid.hex: 32.0,
            },
            "density_cubes": last["density"]["cubes"],
            "density_expected": expected_survey,
            "heatmap_top": heatmap.top() if heatmap is not None else [],
            "heatmap_updates":
                (heatmap.updates - updates0) if heatmap is not None else 0,
            "kind_requests":
                counters.get("queries.kind_requests", 0) - requests0,
            "kind_replies":
                counters.get("queries.kind_replies", 0) - replies0,
            "drained": drained,
            "broker_answers": await ctx.heartbeat_ok(observer),
        }

    def checks(self, ctx: ScenarioContext, slo: dict) -> list[Check]:
        n = slo["rounds"] * 3
        top = slo["heatmap_top"]
        hot_cube = slo["density_expected"][0]
        heatmap_hot = (
            bool(top)
            and top[0][0] == "warzone"
            and top[0][1:4] == hot_cube[:3]
            and top[0][4] == 3
        )
        return [
            Check("knn_ladder_exact",
                  slo["knn_k"] == 3
                  and slo["knn_peers"] == slo["knn_expected"],
                  slo["knn_peers"], slo["knn_expected"],
                  "nearest lane cube first, then the hotspot pair in "
                  "uuid order"),
            Check("raycast_hit_map_exact",
                  slo["ray_t_by_peer"] == slo["ray_expected"],
                  slo["ray_t_by_peer"], slo["ray_expected"]),
            Check("density_survey_exact",
                  slo["density_cubes"] == slo["density_expected"],
                  slo["density_cubes"], slo["density_expected"],
                  "hotspot count 3 ranked first, full extent surveyed"),
            Check("heatmap_tracked_the_hotspot", heatmap_hot,
                  top[:1], f"['warzone', *{hot_cube[:3]}, 3]"),
            Check("heatmap_updates_advanced",
                  slo["heatmap_updates"] >= slo["rounds"],
                  slo["heatmap_updates"], f">= {slo['rounds']}"),
            Check("every_query_answered",
                  slo["kind_requests"] >= n and slo["kind_replies"] >= n,
                  (slo["kind_requests"], slo["kind_replies"]),
                  f">= {n} each",
                  "request-response paced: replies never lag requests"),
            Check("queue_drained", slo["drained"], slo["drained"], True),
            Check("broker_answers_after_storm", slo["broker_answers"],
                  slo["broker_answers"], True),
        ]


class ReconnectStormReplay(Scenario):
    """Reconnect storm landing mid-WAL-replay (the PR 12 "still open"
    note): the broker boots with a FAT WAL — acked records from a
    previous life that crashed before its checkpoint — while the
    ``recovery.apply`` failpoint stretches replay, and a connect storm
    hammers the wire from the FIRST instant of boot (``concurrent_boot``:
    the server starts as a task; connects fail-and-retry until the
    transports open, exactly a client fleet reconnecting into a
    recovering broker). Survival means: recovery applies every acked
    entry (ZERO acked-record loss, read back from the store), the
    storm's handshakes land with bounded p99 once serving opens, and
    the broker answers afterwards. Slow-marked: in the catalog for
    operators and the nightly suite, NOT in the CI-blocking smoke set.
    """

    name = "reconnect_storm_replay"
    description = "connect storm during boot-time WAL replay"
    ci_smoke = False
    concurrent_boot = True

    def build_config(self, shape: str) -> Config:
        import tempfile

        from ..durability.wal import MAGIC, encode_insert, frame_entry

        self._wal_dir = tempfile.mkdtemp(prefix="wql-replay-wal-")
        self._n_records = 300 if shape == "smoke" else 3000
        # fabricate the fat WAL directly in the segment format: these
        # entries were ACKED in the previous life — recovery owes the
        # store every one of them
        frames = [MAGIC]
        for i in range(self._n_records):
            frames.append(frame_entry(encode_insert([Record(
                uuid=uuid_mod.UUID(int=i + 1),
                position=Vector3(1.0, 2.0, 3.0),
                world_name="arena",
                data=f"acked-{i}",
            )])))
        import os

        with open(os.path.join(self._wal_dir, "wal-00000000.log"),
                  "wb") as f:
            f.write(b"".join(frames))
        return _storm_config(
            durability="wal",
            wal_dir=self._wal_dir,
            session_ttl=30.0,
            # one failpoint delay per replayed batch: recovery takes
            # ~n_records * delay — long enough that the whole storm
            # provably lands inside it (asserted via the fired count)
            failpoints="recovery.apply=delay:5ms",
            overload_tick_budget_ms=50.0,
        )

    async def drive(self, ctx: ScenarioContext) -> dict:
        n = 8 if ctx.smoke else 32
        handshake_walls: list[float] = []
        refused = 0
        attempts_during_replay = 0

        async def storm_one() -> None:
            nonlocal refused, attempts_during_replay
            t0 = time.perf_counter()
            deadline = t0 + 30.0
            while True:
                if not ctx.start_task.done():
                    attempts_during_replay += 1
                try:
                    peer = await ZmqPeer.connect(
                        ctx.config.zmq_server_port, timeout=0.5,
                    )
                    if peer.refused:
                        refused += 1
                        peer.close()
                    else:
                        ctx.clients.append(peer)
                        handshake_walls.append(
                            (time.perf_counter() - t0) * 1e3
                        )
                        return
                except Exception:
                    pass  # transports not up yet (mid-replay) — retry
                if time.perf_counter() > deadline:
                    raise AssertionError("storm client never connected")
                await asyncio.sleep(0.01)

        # the storm starts NOW — the server is still replaying its WAL
        storm = [asyncio.ensure_future(storm_one()) for _ in range(n)]
        try:
            await asyncio.gather(*storm)
        finally:
            for task in storm:
                task.cancel()
        await ctx.start_task  # boot must have completed under fire
        replay_fires = failpoints.registry.fired("recovery.apply")

        # zero acked-record loss: every fabricated WAL entry reads
        # back from the store after recovery
        stored = await ctx.server.store.get_records_in_region(
            "arena", Vector3(1.0, 2.0, 3.0)
        )
        recovered = len({sr.record.uuid for sr in stored})

        probe = ctx.clients[-1]
        alive = await ctx.heartbeat_ok(probe)
        recovery = ctx.server.last_recovery
        return {
            "wal_records": self._n_records,
            "records_recovered": recovered,
            "replay_batches_fired": replay_fires,
            "storm_clients": n,
            "attempts_during_replay": attempts_during_replay,
            "refused": refused,
            "handshake_p99_ms": round(
                pctl(handshake_walls, 0.99) or 0.0, 1
            ),
            "recovery_errors": len(recovery.errors) if recovery else -1,
            "broker_answers": alive,
        }

    def checks(self, ctx: ScenarioContext, slo: dict) -> list[Check]:
        # bounded, not fast: one CI core time-shares the replay, the
        # storm AND the broker — the bound catches a wedged handshake
        # path, not scheduler contention
        p99_limit = 20000.0 if ctx.smoke else 5000.0
        return [
            Check("zero_acked_record_loss",
                  slo["records_recovered"] == slo["wal_records"],
                  slo["records_recovered"], slo["wal_records"],
                  "every WAL-acked record readable after recovery"),
            Check("storm_landed_mid_replay",
                  slo["attempts_during_replay"] > 0,
                  slo["attempts_during_replay"], "> 0",
                  "connect attempts provably hit the recovering boot"),
            Check("replay_ran", slo["replay_batches_fired"] > 0,
                  slo["replay_batches_fired"], "> 0"),
            Check("all_storm_clients_connected",
                  len(ctx.clients) >= slo["storm_clients"],
                  len(ctx.clients), f">= {slo['storm_clients']}"),
            Check("resume_p99_bounded",
                  slo["handshake_p99_ms"] <= p99_limit,
                  slo["handshake_p99_ms"], f"<= {p99_limit} ms"),
            Check("recovery_clean", slo["recovery_errors"] == 0,
                  slo["recovery_errors"], 0),
            Check("broker_answers_after_replay_storm",
                  slo["broker_answers"], slo["broker_answers"], True),
        ]


class ClusterFlashCrowd(Scenario):
    """Cluster hotspot (ISSUE 14, ROADMAP 5's multi-process leftover):
    a flash crowd drowns ONE shard's world behind the router tier.
    Survival means the overload stays CONTAINED — the hot shard
    escalates and its refusals move to the ROUTER (shed before the
    shard ever sees the bytes), the cold shard keeps serving at OK the
    whole time, every record offered during the storm lands (records
    are never shed at either tier), cross-shard delivery keeps a
    bounded p99 under the storm, and the hot shard walks back to OK
    once the crowd disperses."""

    name = "cluster_flash_crowd"
    description = "hotspot world drowns one shard; router sheds for it"
    #: spawns shard subprocesses — runs in the dedicated "Cluster
    #: smoke" CI step (and by explicit name), not the default set
    ci_smoke = False

    def build_config(self, shape: str) -> Config:
        return Config(
            store_url="memory://",
            http_enabled=False, ws_enabled=False,
            zmq_server_host="127.0.0.1",
            zmq_server_port=free_port_block(3),
            spatial_backend="cpu", tick_interval=0.02,
            max_batch=32, overload="on",
            overload_recover_ticks=5,
            supervisor_backoff=0.005,
            cluster_shards=2,
        )

    async def drive(self, ctx: ScenarioContext) -> dict:
        import uuid as uuid_mod

        runtime = ctx.server
        world_map = runtime.router.world_map
        n_flood = 6 if ctx.smoke else 16
        storm_s = 1.5 if ctx.smoke else 6.0
        n_records = 12 if ctx.smoke else 60

        def world_for(shard: int, stem: str) -> str:
            for i in range(10_000):
                name = f"{stem}{i}"
                if world_map.shard_of_world(name) == shard:
                    return name
            raise AssertionError("no world for shard")

        def uuid_for(shard: int) -> uuid_mod.UUID:
            while True:
                u = uuid_mod.uuid4()
                if world_map.shard_of_peer(u) == shard:
                    return u

        hot = world_for(0, "hotspot")      # owned by shard 0
        cold = world_for(1, "steady")      # owned by shard 1
        hot_pos = Vector3(5.0, 5.0, 5.0)
        cold_pos = Vector3(900.0, 5.0, 5.0)

        flooders = [await ctx.connect() for _ in range(n_flood)]
        # the cold pair: receiver homed on shard 0, so every cold-world
        # frame (resolved on shard 1, the owner) crosses the 1→0 ring
        rx = await ctx.connect(peer_uuid=uuid_for(0))
        tx = await ctx.connect(peer_uuid=uuid_for(1))
        for c in flooders:
            await c.send(Message(
                instruction=Instruction.AREA_SUBSCRIBE,
                world_name=hot, position=hot_pos,
            ))
        for c in (rx, tx):
            await c.send(Message(
                instruction=Instruction.AREA_SUBSCRIBE,
                world_name=cold, position=cold_pos,
            ))
        await asyncio.sleep(0.3)

        counters = runtime.metrics.snapshot()["counters"]
        shed_before = counters.get("cluster.router_shed_local", 0)
        levels = {"hot": 0, "cold": 0}
        xshard_ms: list[float] = []
        stop = asyncio.Event()

        async def flood(client: ZmqPeer) -> int:
            # paced: far beyond the hot shard's 2×max_batch admission
            # cap (REJECT holds for the whole storm) without starving
            # the 1-core router's event loop of the cold traffic this
            # scenario measures against it
            sent = 0
            while not stop.is_set():
                for _ in range(16):
                    await client.send(Message(
                        instruction=Instruction.LOCAL_MESSAGE,
                        world_name=hot, position=hot_pos,
                        parameter="crowd",
                    ))
                    sent += 1
                await asyncio.sleep(0.002)
            return sent

        async def cold_traffic() -> int:
            sent = 0
            while not stop.is_set():
                await tx.send(Message(
                    instruction=Instruction.LOCAL_MESSAGE,
                    world_name=cold, position=cold_pos,
                    parameter=f"x:{time.monotonic_ns()}",
                ))
                sent += 1
                await asyncio.sleep(0.05)
            return sent

        async def cold_receiver() -> None:
            while True:
                got = await rx.recv(30)
                if (
                    got.instruction == Instruction.LOCAL_MESSAGE
                    and got.parameter
                    and got.parameter.startswith("x:")
                ):
                    t_sent = int(got.parameter.split(":", 1)[1])
                    xshard_ms.append(
                        (time.monotonic_ns() - t_sent) / 1e6
                    )

        async def sampler() -> None:
            while not stop.is_set():
                levels["hot"] = max(
                    levels["hot"], runtime.router.mirror.level(0)
                )
                levels["cold"] = max(
                    levels["cold"], runtime.router.mirror.level(1)
                )
                await asyncio.sleep(0.02)

        async def record_stream() -> list:
            created = []
            for i in range(n_records):
                world, pos = ((hot, hot_pos) if i % 2 == 0
                              else (cold, cold_pos))
                rec = uuid_mod.uuid4()
                await tx.send(Message(
                    instruction=Instruction.RECORD_CREATE,
                    world_name=world,
                    records=[Record(uuid=rec, position=pos,
                                    world_name=world, data=f"r{i}")],
                ))
                created.append((world, rec))
                await asyncio.sleep(storm_s / n_records)
            return created

        receiver = asyncio.ensure_future(cold_receiver())
        try:
            async def stopper():
                await asyncio.sleep(storm_s)
                stop.set()

            results = await asyncio.gather(
                *(flood(c) for c in flooders), cold_traffic(),
                record_stream(), sampler(), stopper(),
            )
            offered = sum(results[:n_flood])
            cold_sent = results[n_flood]
            created = results[n_flood + 1]
            # let in-flight cold frames land before closing the books
            await asyncio.sleep(1.0)
        finally:
            receiver.cancel()
            try:
                await receiver
            except (asyncio.CancelledError, Exception):
                pass

        # recovery: the hot shard must walk back to OK and re-report
        recovered = False
        deadline = time.perf_counter() + (15 if ctx.smoke else 30)
        while time.perf_counter() < deadline:
            if runtime.router.mirror.level(0) == 0:
                recovered = True
                break
            await asyncio.sleep(0.1)

        # zero record loss: every record offered during the storm is
        # readable back through the router (records are never shed)
        async def readable(world, pos, want: set) -> int:
            deadline = time.perf_counter() + 20
            seen: set = set()
            while time.perf_counter() < deadline and not want <= seen:
                await rx.send(Message(
                    instruction=Instruction.RECORD_READ,
                    world_name=world, position=pos,
                ))
                try:
                    reply = await rx.recv_until(
                        Instruction.RECORD_REPLY, 5
                    )
                except asyncio.TimeoutError:
                    continue
                seen |= {r.uuid for r in reply.records}
            return len(want & seen)

        hot_want = {r for w, r in created if w == hot}
        cold_want = {r for w, r in created if w == cold}
        hot_found = await readable(hot, hot_pos, hot_want)
        cold_found = await readable(cold, cold_pos, cold_want)

        counters = runtime.metrics.snapshot()["counters"]
        return {
            "offered": offered,
            "cold_sent": cold_sent,
            "cold_received": len(xshard_ms),
            "router_shed_local":
                counters.get("cluster.router_shed_local", 0) - shed_before,
            "router_forwarded":
                counters.get("cluster.router_forwarded", 0),
            "hot_peak_level": levels["hot"],
            "cold_peak_level": levels["cold"],
            "records_offered": len(created),
            "records_found": hot_found + cold_found,
            "xshard_p99_ms": round(pctl(xshard_ms, 0.99) or 0.0, 2),
            "hot_recovered": recovered,
            "broker_answers": await ctx.heartbeat_ok(tx),
        }

    def checks(self, ctx: ScenarioContext, slo: dict) -> list[Check]:
        p99_limit = 2_000 if ctx.smoke else 500
        return [
            Check("hot_shard_escalated", slo["hot_peak_level"] >= 2,
                  slo["hot_peak_level"], ">= 2 (shed_high)"),
            Check("router_shed_for_hot_shard",
                  slo["router_shed_local"] > 0,
                  slo["router_shed_local"], "> 0",
                  "REJECT moved to the router tier"),
            Check("cold_shard_stayed_ok", slo["cold_peak_level"] == 0,
                  slo["cold_peak_level"], 0),
            Check("zero_record_loss",
                  slo["records_found"] == slo["records_offered"],
                  slo["records_found"], slo["records_offered"],
                  "records are never shed at either tier"),
            Check("xshard_delivery_flowed", slo["cold_received"] > 0,
                  slo["cold_received"], "> 0"),
            Check("xshard_p99_bounded",
                  slo["xshard_p99_ms"] <= p99_limit,
                  slo["xshard_p99_ms"], f"<= {p99_limit} ms"),
            Check("hot_shard_recovered_to_ok", slo["hot_recovered"],
                  slo["hot_recovered"], True),
            Check("broker_answers_after_storm", slo["broker_answers"],
                  slo["broker_answers"], True),
        ]


class BandwidthCap(Scenario):
    """Per-peer bandwidth budgets under asymmetric demand (ISSUE 18):
    a victim peer whose interest set is a dense mover swarm outruns
    the per-peer byte budget while two bystanders in quiet pockets
    stay far under it. Survival means the budget degrades the victim's
    CADENCE, never its state: the victim racks up lossless deferrals
    and walks the demote ladder, its replay oracle never refuses a
    delta or sees a gap, and after the swarm quiesces it converges to
    the server's own ledger; the bystanders never defer, never demote,
    and stream at full rate throughout. The accounting is exact — the
    bytes actually put on the victim's wire respect the token-bucket
    bound (burst + rate x elapsed), and ``delivery.bytes_shed`` may
    count only once some peer has bottomed out at keyframe-only."""

    name = "bandwidth_cap"
    description = "over-budget peer degrades cadence, never state"

    def build_config(self, shape: str) -> Config:
        return Config(
            store_url="memory://",
            http_enabled=False, ws_enabled=False,
            zmq_server_host="127.0.0.1", zmq_server_port=free_port(),
            spatial_backend="tpu", tick_interval=0.02,
            entity_sim=True, entity_k=12, interest="on",
            peer_bandwidth_bytes=16384,
            precompile_tiers=False,
            supervisor_backoff=0.005,
        )

    async def drive(self, ctx: ScenarioContext) -> dict:
        import struct

        from ..interest import ReplayClient, parse_stamp
        from ..protocol import deserialize_message

        world = "cap"
        n_movers = 48 if ctx.smoke else 96
        n_victim = 8 if ctx.smoke else 12
        load_s = 3.5 if ctx.smoke else 10.0
        rng = np.random.default_rng(18)

        hub = await ctx.connect()
        victim = await ctx.connect()
        bystanders = [await ctx.connect() for _ in range(2)]

        # the swarm: a co-located mover cluster, velocity-integrated
        # by the device tick — sustained per-tick deltas far beyond
        # the per-peer budget for anyone whose interest set is ALL of
        # it (the hub owns the swarm, so it is over budget too; the
        # victim's checks below are keyed per peer, not globally)
        movers = [uuid_mod.uuid4() for _ in range(n_movers)]
        await hub.send(Message(
            instruction=Instruction.LOCAL_MESSAGE, world_name=world,
            entities=[Entity(
                uuid=m, position=Vector3(*rng.uniform(6.0, 10.0, 3)),
                world_name=world,
                flex=struct.pack("<3f", 2.0, 0.0, 0.0),
            ) for m in movers],
        ))
        # the victim parks its own entities INSIDE the swarm: its kNN
        # interest set is the whole mover cluster
        await victim.send(Message(
            instruction=Instruction.LOCAL_MESSAGE, world_name=world,
            entities=[Entity(
                uuid=uuid_mod.uuid4(),
                position=Vector3(*rng.uniform(6.0, 10.0, 3)),
                world_name=world,
            ) for _ in range(n_victim)],
        ))
        # each bystander lives in a distant pocket of statics plus ONE
        # slow drifter: a small per-tick delta stream that stays well
        # inside the budget for the whole scenario
        drifters: list[tuple[uuid_mod.UUID, float]] = []
        for i, b in enumerate(bystanders):
            base = 300.0 * (i + 1)
            await b.send(Message(
                instruction=Instruction.LOCAL_MESSAGE, world_name=world,
                entities=[Entity(
                    uuid=uuid_mod.uuid4(),
                    position=Vector3(base, 6.0, 6.0),
                    world_name=world,
                )],
            ))
            drifter = uuid_mod.uuid4()
            drifters.append((drifter, base))
            await hub.send(Message(
                instruction=Instruction.LOCAL_MESSAGE, world_name=world,
                entities=[Entity(
                    uuid=drifter if j == 0 else uuid_mod.uuid4(),
                    position=Vector3(
                        base + float(j % 4), 6.0 + float(j // 4), 6.0
                    ),
                    world_name=world,
                    flex=(struct.pack("<3f", 0.3, 0.0, 0.0)
                          if j == 0 else None),
                ) for j in range(13)],
            ))

        oracle_v = ReplayClient()
        oracles_b = [ReplayClient() for _ in bystanders]
        victim_bytes = [0]
        stop = asyncio.Event()

        async def pump(peer, oracle, byte_sink=None):
            # raw socket reads: the byte count must be the exact wire
            # length the budget was charged for, not a re-serialize
            while not stop.is_set():
                try:
                    data = await asyncio.wait_for(peer.pull.recv(), 0.25)
                except asyncio.TimeoutError:
                    continue
                m = deserialize_message(data)
                if (m.instruction == Instruction.LOCAL_MESSAGE
                        and m.parameter
                        and parse_stamp(m.parameter) is not None):
                    if byte_sink is not None:
                        byte_sink[0] += len(data)
                    oracle.apply(m)

        pumps = [asyncio.ensure_future(pump(victim, oracle_v, victim_bytes))]
        for b, o in zip(bystanders, oracles_b):
            pumps.append(asyncio.ensure_future(pump(b, o)))

        mgr = ctx.server.interest
        plane = ctx.server.entity_plane
        try:
            # first keyframes mark the stream (and the buckets) live
            t_start = time.perf_counter()
            deadline = t_start + 90.0
            while (oracle_v.frames_applied < 1
                   or any(o.frames_applied < 1 for o in oracles_b)):
                if time.perf_counter() > deadline:
                    raise AssertionError("first interest frames never landed")
                await asyncio.sleep(0.05)

            # the loaded window, sampling the demote ladder as it moves
            ticks0 = plane.applied_ticks
            max_demote = {"victim": 0, "bystander": 0, "any": 0}

            def sample():
                st_v = mgr._peers.get(victim.uuid)
                if st_v is not None:
                    max_demote["victim"] = max(
                        max_demote["victim"], st_v.demote
                    )
                for b in bystanders:
                    st_b = mgr._peers.get(b.uuid)
                    if st_b is not None:
                        max_demote["bystander"] = max(
                            max_demote["bystander"], st_b.demote
                        )
                for st in mgr._peers.values():
                    max_demote["any"] = max(max_demote["any"], st.demote)

            end = time.perf_counter() + load_s
            while time.perf_counter() < end:
                sample()
                await asyncio.sleep(0.02)
            ticks_loaded = plane.applied_ticks - ticks0
            st_v = mgr._peers.get(victim.uuid)
            victim_deferrals = st_v.deferrals if st_v is not None else 0
            bystander_deferrals = sum(
                mgr._peers[b.uuid].deferrals
                for b in bystanders if b.uuid in mgr._peers
            )

            # quiesce the swarm and the drifters; the victim's pending
            # (deferred) diff must still land — losslessly, on cadence
            await hub.send(Message(
                instruction=Instruction.LOCAL_MESSAGE, world_name=world,
                entities=[Entity(
                    uuid=m, position=Vector3(*rng.uniform(6.0, 10.0, 3)),
                    world_name=world,
                    flex=struct.pack("<3f", 0.0, 0.0, 0.0),
                ) for m in movers] + [Entity(
                    uuid=d, position=Vector3(base, 7.0, 6.0),
                    world_name=world,
                    flex=struct.pack("<3f", 0.0, 0.0, 0.0),
                ) for d, base in drifters],
            ))

            def ledger_of(peer):
                st = mgr._peers.get(peer.uuid)
                if st is None:
                    return None
                out = {}
                for key, (_wid, pos_b) in st.state.items():
                    x, y, z = np.frombuffer(pos_b, np.float32)
                    out[uuid_mod.UUID(bytes=key)] = (
                        float(x), float(y), float(z)
                    )
                return out

            def converged(oracle, peer) -> bool:
                ledger = ledger_of(peer)
                return (ledger is not None
                        and oracle.snapshot().get(world, {}) == ledger)

            deadline = time.perf_counter() + (25.0 if ctx.smoke else 40.0)
            while not (converged(oracle_v, victim) and all(
                converged(o, b) for o, b in zip(oracles_b, bystanders)
            )):
                if time.perf_counter() > deadline:
                    break
                await asyncio.sleep(0.1)
            sample()
            victim_converged = converged(oracle_v, victim)
            bystanders_converged = all(
                converged(o, b) for o, b in zip(oracles_b, bystanders)
            )
            elapsed = time.perf_counter() - t_start
        finally:
            stop.set()
            await asyncio.gather(*pumps, return_exceptions=True)

        drained = await ctx.drain_ticker()
        sv = oracle_v.stats()
        sb = [o.stats() for o in oracles_b]
        # token-bucket conservation: what actually hit the victim's
        # wire can never exceed burst + rate x elapsed (one frame of
        # slack for the read race at the window edge)
        budget_cap = round(
            mgr.bandwidth_burst + mgr.bandwidth_bytes * elapsed + 4096.0
        )
        return {
            "movers": n_movers,
            "ticks_loaded": ticks_loaded,
            "victim_deferrals": victim_deferrals,
            "victim_max_demote": max_demote["victim"],
            "victim_refused": sv["deltas_refused"],
            "victim_gaps": sv["gaps_seen"],
            "victim_deltas": sv["deltas_applied"],
            "victim_fulls": sv["fulls_applied"],
            "victim_converged": victim_converged,
            "victim_bytes": victim_bytes[0],
            "victim_budget_cap": budget_cap,
            "bystander_deferrals": bystander_deferrals,
            "bystander_max_demote": max_demote["bystander"],
            "bystander_refused": sum(s["deltas_refused"] for s in sb),
            "bystander_gaps": sum(s["gaps_seen"] for s in sb),
            "bystander_deltas": sum(s["deltas_applied"] for s in sb),
            "bystanders_converged": bystanders_converged,
            "any_max_demote": max_demote["any"],
            "bytes_shed": mgr.bytes_shed,
            "drained": drained,
            "broker_answers": await ctx.heartbeat_ok(victim),
        }

    def checks(self, ctx: ScenarioContext, slo: dict) -> list[Check]:
        return [
            Check("victim_cadence_degraded",
                  slo["victim_deferrals"] > 0,
                  slo["victim_deferrals"], "> 0",
                  "over-budget ticks became lossless deferrals, "
                  "not truncated sends"),
            Check("victim_walked_the_demote_ladder",
                  slo["victim_max_demote"] >= 1,
                  slo["victim_max_demote"], ">= 1 (far-tier demotion)"),
            Check("victim_correctness_intact",
                  slo["victim_refused"] == 0 and slo["victim_gaps"] == 0,
                  (slo["victim_refused"], slo["victim_gaps"]), (0, 0),
                  "throttling never produced an unappliable delta or "
                  "a sequence gap"),
            Check("victim_converged_to_server_ledger",
                  slo["victim_converged"],
                  slo["victim_converged"], True,
                  "after quiesce the oracle equals the server's own "
                  "per-peer ledger"),
            Check("victim_bytes_within_budget",
                  slo["victim_bytes"] <= slo["victim_budget_cap"],
                  slo["victim_bytes"], f"<= {slo['victim_budget_cap']}",
                  "token-bucket conservation on the actual wire bytes"),
            Check("bystanders_never_deferred",
                  slo["bystander_deferrals"] == 0
                  and slo["bystander_max_demote"] == 0,
                  (slo["bystander_deferrals"], slo["bystander_max_demote"]),
                  (0, 0)),
            Check("bystander_stream_full_rate",
                  slo["bystander_deltas"] > 0
                  and slo["bystander_refused"] == 0
                  and slo["bystander_gaps"] == 0,
                  (slo["bystander_deltas"], slo["bystander_refused"],
                   slo["bystander_gaps"]),
                  ("> 0", 0, 0)),
            Check("bystanders_converged_to_server_ledger",
                  slo["bystanders_converged"],
                  slo["bystanders_converged"], True),
            Check("shed_only_at_ladder_bottom",
                  slo["bytes_shed"] == 0 or slo["any_max_demote"] == 2,
                  (slo["bytes_shed"], slo["any_max_demote"]),
                  "shed 0, or some peer at keyframe-only first",
                  "bytes_shed counts ONLY once cadence demotion is "
                  "exhausted"),
            Check("queue_drained", slo["drained"], slo["drained"], True),
            Check("broker_answers_after_throttle",
                  slo["broker_answers"], slo["broker_answers"], True),
        ]


class MegaCity(Scenario):
    """Live resharding under fire (ISSUE 19): a mega-city world keeps
    one shard hot while district traffic spreads across the cluster,
    and mid-traffic the city is live-resharded to the other shard.
    Survival means the migration is INVISIBLE to the workload: the
    protocol runs to ``done``, the placement epoch advances and the
    city routes to its new owner, every record offered before, during
    and after the move reads back (the freeze window parks frames in
    the bounded transfer buffer and replays them — counted, never
    shed), the pre-move subscription keeps delivering THROUGH the flip
    (subscription rows rode the capsule), and the broker answers
    after."""

    name = "mega_city"
    description = "hot world live-resharded mid-traffic, zero loss"
    #: spawns shard subprocesses — runs in the dedicated "Cluster
    #: smoke" CI step (and by explicit name), not the default set
    ci_smoke = False

    def build_config(self, shape: str) -> Config:
        return Config(
            store_url="memory://",
            http_enabled=False, ws_enabled=False,
            zmq_server_host="127.0.0.1",
            zmq_server_port=free_port_block(3),
            spatial_backend="cpu", tick_interval=0.02,
            max_batch=64, overload="on",
            supervisor_backoff=0.005,
            cluster_shards=2,
        )

    async def drive(self, ctx: ScenarioContext) -> dict:
        runtime = ctx.server
        router = runtime.router
        placement = router.world_map
        n_pre = 10 if ctx.smoke else 40
        n_post = 6 if ctx.smoke else 20
        post_flip_s = 0.8 if ctx.smoke else 2.0

        def world_for(shard: int, stem: str) -> str:
            for i in range(10_000):
                name = f"{stem}{i}"
                if placement.shard_of_world(name) == shard:
                    return name
            raise AssertionError("no world for shard")

        def uuid_for(shard: int) -> uuid_mod.UUID:
            while True:
                u = uuid_mod.uuid4()
                if placement.shard_of_peer(u) == shard:
                    return u

        city = world_for(0, "megacity")        # starts on shard 0
        districts = [world_for(i, "district") for i in (0, 1)]
        pos = Vector3(5.0, 5.0, 5.0)

        # receiver homed on the DESTINATION shard, sender on the
        # source: city delivery crosses the ring before the flip and
        # stays local after — both legs exercised by one subscription
        rx = await ctx.connect(peer_uuid=uuid_for(1))
        tx = await ctx.connect(peer_uuid=uuid_for(0))
        await rx.send(Message(
            instruction=Instruction.AREA_SUBSCRIBE,
            world_name=city, position=pos,
        ))
        await asyncio.sleep(0.3)

        created: list[tuple[str, uuid_mod.UUID]] = []

        async def put(world: str, tag: str) -> None:
            rec = uuid_mod.uuid4()
            await tx.send(Message(
                instruction=Instruction.RECORD_CREATE,
                world_name=world,
                records=[Record(uuid=rec, position=pos,
                                world_name=world, data=tag)],
            ))
            created.append((world, rec))

        for i in range(n_pre):
            await put(city, f"pre{i}")
            await put(districts[i % 2], f"d{i}")
            await asyncio.sleep(0.005)
        await asyncio.sleep(0.2)

        received = {"during": 0, "post": 0}
        phase = {"v": "during"}
        stop = asyncio.Event()

        async def receiver() -> None:
            while True:
                got = await rx.recv(30)
                if (
                    got.instruction == Instruction.LOCAL_MESSAGE
                    and got.parameter
                    and got.parameter.startswith("city:")
                ):
                    received[phase["v"]] += 1

        async def city_traffic() -> int:
            # live locals + mid-flight record creates: the freeze
            # window MUST catch some of these in the transfer buffer
            sent = 0
            while not stop.is_set():
                await tx.send(Message(
                    instruction=Instruction.LOCAL_MESSAGE,
                    world_name=city, position=pos,
                    parameter=f"city:{sent}",
                ))
                sent += 1
                if sent % 4 == 0:
                    await put(city, f"mid{sent}")
                await asyncio.sleep(0.01)
            return sent

        async def reshard():
            await asyncio.sleep(0.4)     # traffic provably flowing
            xfer = router.start_reshard(city, 1, reason="scenario")
            deadline = time.perf_counter() + (30 if ctx.smoke else 60)
            while time.perf_counter() < deadline:
                mig = router.migration
                if mig is not None and mig.state in ("done", "aborted"):
                    return (xfer, mig)
                await asyncio.sleep(0.05)
            return (xfer, router.migration)

        receiver_task = asyncio.ensure_future(receiver())
        try:
            traffic = asyncio.ensure_future(city_traffic())
            xfer, mig = await reshard()
            phase["v"] = "post"
            await asyncio.sleep(post_flip_s)   # post-flip delivery leg
            stop.set()
            sent = await traffic
            for i in range(n_post):
                await put(city, f"post{i}")
            await asyncio.sleep(0.3)
        finally:
            # the receiver must be gone BEFORE the read-back phase —
            # it would steal the RECORD_REPLYs off rx's pull socket
            receiver_task.cancel()
            try:
                await receiver_task
            except (asyncio.CancelledError, Exception):
                pass

        # zero record loss: every record offered around the move is
        # readable back through the router (now via the new owner)
        async def readable(world: str, want: set) -> int:
            deadline = time.perf_counter() + 20
            seen: set = set()
            while time.perf_counter() < deadline and not want <= seen:
                await rx.send(Message(
                    instruction=Instruction.RECORD_READ,
                    world_name=world, position=pos,
                ))
                try:
                    reply = await rx.recv_until(
                        Instruction.RECORD_REPLY, 5
                    )
                except asyncio.TimeoutError:
                    continue
                seen |= {r.uuid for r in reply.records}
            return len(want & seen)

        want_by_world: dict[str, set] = {}
        for world, rec in created:
            want_by_world.setdefault(world, set()).add(rec)
        found = 0
        for world, want in want_by_world.items():
            found += await readable(world, want)

        desc = mig.describe() if mig is not None else {}
        return {
            "xfer": xfer,
            "migration_state": desc.get("state", "missing"),
            "placement_epoch": placement.epoch,
            "owner_after": placement.shard_of_world(city),
            "records_offered": len(created),
            "records_found": found,
            "parked_replayed": desc.get("replayed", 0),
            "buffer_shed": (desc.get("buffer") or {}).get("shed", 0),
            "city_sent": sent,
            "delivered_during": received["during"],
            "delivered_post": received["post"],
            "broker_answers": await ctx.heartbeat_ok(tx),
        }

    def checks(self, ctx: ScenarioContext, slo: dict) -> list[Check]:
        return [
            Check("reshard_completed", slo["migration_state"] == "done",
                  slo["migration_state"], "done"),
            Check("placement_epoch_advanced",
                  slo["placement_epoch"] >= 1,
                  slo["placement_epoch"], ">= 1"),
            Check("ownership_flipped", slo["owner_after"] == 1,
                  slo["owner_after"], 1,
                  "the city routes to its NEW owner"),
            Check("zero_record_loss",
                  slo["records_found"] == slo["records_offered"],
                  slo["records_found"], slo["records_offered"],
                  "records offered before, during and after the move "
                  "all read back"),
            Check("freeze_window_parked_and_replayed",
                  slo["parked_replayed"] > 0,
                  slo["parked_replayed"], "> 0",
                  "live traffic provably crossed the freeze window"),
            Check("transfer_buffer_never_shed",
                  slo["buffer_shed"] == 0, slo["buffer_shed"], 0),
            Check("delivery_through_the_flip",
                  slo["delivered_during"] > 0
                  and slo["delivered_post"] > 0,
                  (slo["delivered_during"], slo["delivered_post"]),
                  ("> 0", "> 0"),
                  "the pre-move subscription rode the capsule"),
            Check("broker_answers_after_reshard",
                  slo["broker_answers"], slo["broker_answers"], True),
        ]


class RollingRestart(Scenario):
    """Rolling cluster restart (ISSUE 19): after a live reshard moved
    a world off its hash-home, SIGKILL every shard in sequence under
    traffic. Survival means the control plane heals itself: the
    supervisor restarts each shard, the placement map (epoch +
    override) replays to every restarted shard so the migrated world
    still routes to its NEW owner, WAL replay recovers every record —
    including the migrated capsule through the destination's OWN WAL
    (the exactly-one-owner invariant) — fresh sessions land and
    subscribe after the roll, and the broker answers."""

    name = "rolling_restart"
    description = "SIGKILL each shard in turn; placement + WAL recover"
    #: spawns shard subprocesses — runs in the dedicated "Cluster
    #: smoke" CI step (and by explicit name), not the default set
    ci_smoke = False

    def build_config(self, shape: str) -> Config:
        import tempfile

        return Config(
            store_url="memory://",
            durability="wal",
            wal_dir=tempfile.mkdtemp(prefix="wql-rolling-"),
            checkpoint_interval=0,  # SIGKILL must find the WAL whole
            http_enabled=False, ws_enabled=False,
            zmq_server_host="127.0.0.1",
            zmq_server_port=free_port_block(3),
            spatial_backend="cpu", tick_interval=0.02,
            max_batch=64,
            supervisor_backoff=0.005,
            cluster_shards=2,
        )

    async def drive(self, ctx: ScenarioContext) -> dict:
        runtime = ctx.server
        router = runtime.router
        placement = router.world_map
        supervisor = runtime.supervisor
        n_records = 8 if ctx.smoke else 30

        def world_for(shard: int, stem: str) -> str:
            for i in range(10_000):
                name = f"{stem}{i}"
                if placement.shard_of_world(name) == shard:
                    return name
            raise AssertionError("no world for shard")

        async def wait_for(predicate, timeout_s: float,
                           what: str) -> bool:
            deadline = time.perf_counter() + timeout_s
            while time.perf_counter() < deadline:
                if predicate():
                    return True
                await asyncio.sleep(0.05)
            return False

        moved = world_for(0, "moved")      # migrates 0 → 1 pre-roll
        steady = world_for(1, "steady")
        pos = Vector3(5.0, 5.0, 5.0)

        tx = await ctx.connect()
        created: dict[str, set] = {moved: set(), steady: set()}
        for i in range(n_records):
            for world in (moved, steady):
                rec = uuid_mod.uuid4()
                await tx.send(Message(
                    instruction=Instruction.RECORD_CREATE,
                    world_name=world,
                    records=[Record(uuid=rec, position=pos,
                                    world_name=world, data=f"r{i}")],
                ))
                created[world].add(rec)
            await asyncio.sleep(0.01)
        await asyncio.sleep(0.3)

        # live reshard FIRST: the roll must not undo the move
        xfer = router.start_reshard(moved, 1, reason="scenario")
        moved_ok = await wait_for(
            lambda: router.migration is not None
            and router.migration.state in ("done", "aborted"),
            30 if ctx.smoke else 60, "reshard",
        )
        migration_state = (
            router.migration.state if router.migration else "missing"
        )
        epoch = placement.epoch

        # the roll: SIGKILL each shard in turn, wait for the
        # supervised restart AND placement re-convergence (the ~1s
        # control-state packets carry the shard's epoch back)
        attempts_during_roll = 0
        roll = {"deaths": 0, "revivals": 0, "converged": 0}
        for idx in range(supervisor.n_shards):
            supervisor.kill_shard(idx)
            if await wait_for(
                lambda: not supervisor.shard_alive(idx), 30, "death"
            ):
                roll["deaths"] += 1
            # traffic provably hits the half-dead cluster (best
            # effort — the point is the cluster survives it)
            for i in range(10):
                try:
                    await tx.send(Message(
                        instruction=Instruction.LOCAL_MESSAGE,
                        world_name=moved if i % 2 else steady,
                        position=pos, parameter="roll",
                    ))
                    attempts_during_roll += 1
                except Exception:
                    pass
            if await wait_for(
                lambda: supervisor.shard_alive(idx), 90, "revival"
            ):
                roll["revivals"] += 1
            if await wait_for(
                lambda: supervisor.shard_state(idx).get(
                    "placement_epoch", -1) >= epoch,
                30, "placement convergence",
            ):
                roll["converged"] += 1

        # post-roll verification rides FRESH sessions (each peer's
        # home shard died at some point in the roll)
        probe = await ctx.connect()
        await probe.send(Message(
            instruction=Instruction.AREA_SUBSCRIBE,
            world_name=moved, position=pos,
        ))
        await asyncio.sleep(0.3)

        post_rec = uuid_mod.uuid4()
        await probe.send(Message(
            instruction=Instruction.RECORD_CREATE, world_name=moved,
            records=[Record(uuid=post_rec, position=pos,
                            world_name=moved, data="post-roll")],
        ))
        created[moved].add(post_rec)

        sender = await ctx.connect()
        await sender.send(Message(
            instruction=Instruction.LOCAL_MESSAGE, world_name=moved,
            position=pos, parameter="after-roll",
        ))
        delivered_after = False
        try:
            while True:
                got = await probe.recv(10)
                if (got.instruction == Instruction.LOCAL_MESSAGE
                        and got.parameter == "after-roll"):
                    delivered_after = True
                    break
        except asyncio.TimeoutError:
            pass

        async def readable(world: str, want: set) -> int:
            deadline = time.perf_counter() + 30
            seen: set = set()
            while time.perf_counter() < deadline and not want <= seen:
                await probe.send(Message(
                    instruction=Instruction.RECORD_READ,
                    world_name=world, position=pos,
                ))
                try:
                    reply = await probe.recv_until(
                        Instruction.RECORD_REPLY, 5
                    )
                except asyncio.TimeoutError:
                    continue
                seen |= {r.uuid for r in reply.records}
            return len(want & seen)

        found = 0
        for world, want in created.items():
            found += await readable(world, want)
        offered = sum(len(want) for want in created.values())

        return {
            "xfer": xfer,
            "reshard_done": moved_ok and migration_state == "done",
            "placement_epoch": epoch,
            "owner_after_roll": placement.shard_of_world(moved),
            "shard_deaths": roll["deaths"],
            "shard_revivals": roll["revivals"],
            "placement_reconverged": roll["converged"],
            "restarts": supervisor.stats()["restarts"],
            "attempts_during_roll": attempts_during_roll,
            "records_offered": offered,
            "records_found": found,
            "delivered_after_roll": delivered_after,
            "broker_answers": await ctx.heartbeat_ok(probe),
        }

    def checks(self, ctx: ScenarioContext, slo: dict) -> list[Check]:
        n = 2
        return [
            Check("reshard_done_before_roll", slo["reshard_done"],
                  slo["reshard_done"], True),
            Check("every_shard_died_and_revived",
                  slo["shard_deaths"] == n
                  and slo["shard_revivals"] == n,
                  (slo["shard_deaths"], slo["shard_revivals"]), (n, n)),
            Check("supervised_restarts_counted",
                  slo["restarts"] >= n, slo["restarts"], f">= {n}"),
            Check("placement_replayed_to_every_restart",
                  slo["placement_reconverged"] == n,
                  slo["placement_reconverged"], n,
                  "each restarted shard re-reported the post-move "
                  "epoch via its control-state packets"),
            Check("migrated_world_stays_moved",
                  slo["owner_after_roll"] == 1,
                  slo["owner_after_roll"], 1,
                  "the roll did not undo the live reshard"),
            Check("traffic_hit_the_roll",
                  slo["attempts_during_roll"] > 0,
                  slo["attempts_during_roll"], "> 0"),
            Check("zero_record_loss_through_roll",
                  slo["records_found"] == slo["records_offered"],
                  slo["records_found"], slo["records_offered"],
                  "WAL replay recovered every record, the migrated "
                  "capsule from the destination's OWN WAL"),
            Check("delivery_after_roll", slo["delivered_after_roll"],
                  slo["delivered_after_roll"], True),
            Check("broker_answers_after_roll", slo["broker_answers"],
                  slo["broker_answers"], True),
        ]
