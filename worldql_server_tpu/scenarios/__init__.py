"""Adversarial scenario library (ROADMAP 5b, ISSUE 12).

First-class hostile workloads driving a real server over real ZeroMQ:
``CATALOG`` maps names to :class:`~.engine.Scenario` classes;
:func:`run_scenario` produces one structured survival + SLO report.
Consumed by ``python -m worldql_server_tpu.scenarios`` (CI scenario
smoke), ``bench.py --config 10`` (the perf-gated suite record) and
tests/test_scenarios.py.
"""

from .catalog import (
    BandwidthCap, BattleRoyale, ClusterFlashCrowd, FlashCrowd, GameTick,
    MegaCity, ProjectileStorm, ReconnectStorm, ReconnectStormReplay,
    RollingRestart, SniperScope,
)
from .engine import Check, Scenario, ScenarioContext, format_report, run_scenario

CATALOG = {
    scenario.name: scenario
    for scenario in (
        FlashCrowd, BattleRoyale, ReconnectStorm, GameTick,
        ReconnectStormReplay, ClusterFlashCrowd,
        SniperScope, ProjectileStorm, BandwidthCap,
        MegaCity, RollingRestart,
    )
}

__all__ = [
    "CATALOG",
    "BandwidthCap",
    "BattleRoyale",
    "Check",
    "ClusterFlashCrowd",
    "FlashCrowd",
    "GameTick",
    "MegaCity",
    "ProjectileStorm",
    "ReconnectStorm",
    "ReconnectStormReplay",
    "RollingRestart",
    "Scenario",
    "ScenarioContext",
    "SniperScope",
    "format_report",
    "run_scenario",
]
