"""Server bootstrap and wiring.

Python rebuild of the reference's main.rs: builds the peer map, spatial
backend, record store and router, starts the enabled transports, and
runs the ZeroMQ-style staleness sweeper (outgoing.rs:28-47,132-150).
The reference's task/channel mesh (main.rs:138-207) collapses into one
asyncio event loop; the transport→router channel hop becomes a direct
awaited call, removing two queue hops from the hot path (SURVEY §3.2).
"""

from __future__ import annotations

import asyncio
import logging

from ..spatial.backend import SpatialBackend
from ..spatial.cpu_backend import CpuSpatialBackend
from ..storage.store import RecordStore, open_store
from .config import Config
from .metrics import Metrics
from .peers import PeerMap
from .router import Router

logger = logging.getLogger(__name__)


def build_backend(config: Config) -> SpatialBackend:
    if config.spatial_backend == "tpu":
        from ..spatial.tpu_backend import TpuSpatialBackend

        return TpuSpatialBackend(config.sub_region_size)
    if config.spatial_backend == "sharded":
        from ..parallel import ShardedTpuSpatialBackend, make_fanout_mesh

        mesh = make_fanout_mesh(
            config.mesh_batch, config.mesh_space or None
        )
        logger.info(
            "sharded spatial backend on mesh batch=%d space=%d",
            mesh.shape["batch"], mesh.shape["space"],
        )
        return ShardedTpuSpatialBackend(config.sub_region_size, mesh)
    return CpuSpatialBackend(config.sub_region_size)


class WorldQLServer:
    def __init__(
        self,
        config: Config,
        backend: SpatialBackend | None = None,
        store: RecordStore | None = None,
    ):
        config.validate()
        self.config = config
        self.backend = backend if backend is not None else build_backend(config)
        self.store = store if store is not None else open_store(
            config.store_url, config
        )
        self.metrics = Metrics()
        self.peer_map = PeerMap(
            on_remove=self._on_peer_remove, metrics=self.metrics
        )
        self.ticker = None
        if config.tick_interval > 0:
            from .ticker import TickBatcher

            self.ticker = TickBatcher(
                self.backend, self.peer_map, config.tick_interval,
                metrics=self.metrics,
            )
        self.router = Router(
            self.peer_map, self.backend, self.store,
            ticker=self.ticker, metrics=self.metrics,
        )
        self._register_gauges()
        self._tasks: list[asyncio.Task] = []
        self._transports: list = []
        self._started = asyncio.Event()

    def _register_gauges(self) -> None:
        self.metrics.gauge("peers", self.peer_map.size)
        self.metrics.gauge(
            "subscriptions", self.backend.subscription_count
            if hasattr(self.backend, "subscription_count") else lambda: None
        )
        if hasattr(self.backend, "device_stats"):
            self.metrics.gauge("spatial_device", self.backend.device_stats)
        if self.ticker is not None:
            self.metrics.gauge(
                "tick",
                lambda: {
                    "interval_s": self.ticker.interval,
                    "last_batch": self.ticker.last_batch,
                    "last_tick_ms": round(self.ticker.last_tick_ms, 3),
                },
            )

    def _on_peer_remove(self, uuid) -> None:
        """Disconnect cleanup: purge the spatial index (the remove_rx
        path, thread.rs:124-126) and let transports drop socket state."""
        self.backend.remove_peer(uuid)
        for transport in self._transports:
            hook = getattr(transport, "on_peer_removed", None)
            if hook is not None:
                hook(uuid)

    async def start(self) -> None:
        """Bring up the store and all enabled transports (main.rs:106-207)."""
        await self.store.init()

        if self.config.ws_enabled:
            from ..transports.websocket import WebSocketTransport

            ws = WebSocketTransport(self)
            self._transports.append(ws)
            await ws.start()

        if self.config.http_enabled:
            from ..transports.http import HttpTransport

            http = HttpTransport(self)
            self._transports.append(http)
            await http.start()

        if self.config.zmq_enabled:
            from ..transports.zeromq import ZmqTransport

            zmq_t = ZmqTransport(self)
            self._transports.append(zmq_t)
            await zmq_t.start()

        if self.config.zmq_enabled:
            self._tasks.append(
                asyncio.create_task(self._staleness_sweeper(), name="stale-sweep")
            )

        if self.ticker is not None:
            self.ticker.start()

        self._started.set()
        logger.info("worldql-server-tpu started")

    async def _staleness_sweeper(self) -> None:
        """Evict heartbeat-tracked peers that went silent
        (outgoing.rs:132-150)."""
        timeout = self.config.zmq_timeout_secs
        while True:
            await asyncio.sleep(timeout)
            for uuid in self.peer_map.stale_peers(timeout):
                logger.info("removing stale peer: %s", uuid)
                await self.peer_map.remove(uuid)

    async def stop(self) -> None:
        if self.ticker is not None:
            await self.ticker.stop()
        for task in self._tasks:
            task.cancel()
        for task in self._tasks:
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()
        for transport in reversed(self._transports):
            await transport.stop()
        self._transports.clear()
        await self.store.close()

    async def run_forever(self) -> None:
        await self.start()
        try:
            await asyncio.Event().wait()
        finally:
            await self.stop()
