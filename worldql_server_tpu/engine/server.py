"""Server bootstrap and wiring.

Python rebuild of the reference's main.rs: builds the peer map, spatial
backend, record store and router, starts the enabled transports, and
runs the ZeroMQ-style staleness sweeper (outgoing.rs:28-47,132-150).
The reference's task/channel mesh (main.rs:138-207) collapses into one
asyncio event loop; the transport→router channel hop becomes a direct
awaited call, removing two queue hops from the hot path (SURVEY §3.2).
"""

from __future__ import annotations

import asyncio
import logging
import os

from ..robustness import failpoints
from ..robustness.supervisor import Supervisor
from ..spatial.backend import SpatialBackend
from ..spatial.cpu_backend import CpuSpatialBackend
from ..storage.store import RecordStore, open_store
from .config import Config
from .metrics import Metrics
from .peers import PeerMap
from .router import Router

logger = logging.getLogger(__name__)


def build_backend(config: Config) -> SpatialBackend:
    if config.spatial_backend == "tpu":
        from ..spatial.tpu_backend import TpuSpatialBackend

        backend = TpuSpatialBackend(config.sub_region_size)
        # delta ticks configure HERE so a resilience rebuild's factory
        # (which calls build_backend again) re-arms the fresh instance
        # — its cache starts cold, never stale
        if config.delta_ticks != "off":
            backend.configure_delta_ticks(config.delta_ticks)
            backend.delta_rebuild_threshold = (
                config.delta_rebuild_threshold
            )
        return backend
    if config.spatial_backend == "sharded":
        from ..parallel import (
            ShardedTpuSpatialBackend,
            make_fanout_mesh,
            maybe_initialize_distributed,
        )

        maybe_initialize_distributed()
        mesh = make_fanout_mesh(
            config.mesh_batch, config.mesh_space or None
        )
        logger.info(
            "sharded spatial backend on mesh batch=%d space=%d",
            mesh.shape["batch"], mesh.shape["space"],
        )
        backend = ShardedTpuSpatialBackend(config.sub_region_size, mesh)
        # result reuse on the mesh: per-shard flat-region replay
        # (clean queries replay host-side; dirty partitions dispatch
        # through the mesh kernels) — armed like the single-chip path
        if config.delta_ticks != "off":
            backend.configure_delta_ticks(config.delta_ticks)
            backend.delta_rebuild_threshold = (
                config.delta_rebuild_threshold
            )
        return backend
    return CpuSpatialBackend(config.sub_region_size)


class WorldQLServer:
    def __init__(
        self,
        config: Config,
        backend: SpatialBackend | None = None,
        store: RecordStore | None = None,
    ):
        config.validate()
        self.config = config
        # Arm fault-injection failpoints BEFORE any subsystem that
        # hosts an injection site comes up. The registry is
        # process-global (like logging); only a non-empty spec touches
        # it, so constructing a second server never disarms points a
        # test configured directly.
        if config.failpoints:
            failpoints.registry.configure(
                config.failpoints, seed=config.failpoints_seed
            )
        elif config.failpoints_seed is not None:
            failpoints.registry.seed(config.failpoints_seed)
        self.backend = backend if backend is not None else build_backend(config)
        if config.resilience == "on":
            from ..robustness.resilient import ResilientBackend

            if not isinstance(self.backend, ResilientBackend):
                inner = self.backend
                self.backend = ResilientBackend(
                    inner,
                    # rebuilds get a fresh backend of the configured
                    # kind; injected test backends can't be re-made
                    factory=(
                        (lambda: build_backend(config))
                        if backend is None else None
                    ),
                    failover_after=config.failover_after,
                )
        self.store = store if store is not None else open_store(
            config.store_url, config
        )
        self.metrics = Metrics()
        # Observability: the tracer ALWAYS exists (router/transports
        # test one `enabled` flag, no None checks on the hot path);
        # the flight recorder + loop monitor only when tracing is on.
        from ..observability import FlightRecorder, LoopMonitor, Tracer
        from ..observability.export import ProfilerHook

        self.tracer = Tracer(enabled=config.trace_enabled)
        self.recorder = None
        self.loop_monitor = None
        self.profiler = ProfilerHook()
        if config.trace_enabled:
            self.loop_monitor = LoopMonitor(metrics=self.metrics)
            self.recorder = FlightRecorder(
                depth=config.flight_recorder_depth,
                slow_tick_ms=config.slow_tick_ms,
                dump_dir=config.slow_tick_dir,
                metrics=self.metrics,
                context=self.loop_monitor.snapshot,
            )
            self.tracer.on_trace = self.recorder.record
        if hasattr(self.backend, "_note_failure"):  # ResilientBackend
            self.backend.metrics = self.metrics
        # Device telemetry (observability/device.py): compile/retrace
        # counters + loose spans, per-tick encode/h2d/compute/d2h
        # split, live buffer gauge. Only for backends with a device
        # side (device_stats); the CPU reference keeps its zero-cost
        # path.
        self.device_telemetry = None
        if config.device_telemetry and hasattr(self.backend, "device_stats"):
            from ..observability.device import DeviceTelemetry

            self.device_telemetry = DeviceTelemetry(
                metrics=self.metrics, tracer=self.tracer,
                backend=self.backend,
            ).install()
        # Escalation contract: when a CRITICAL supervised task (ticker
        # pump, ZMQ recv loop, durability applier) exhausts its restart
        # budget the server requests its own clean shutdown — a broker
        # that can no longer receive or tick must hand control back to
        # the orchestrator, not sit up and deaf.
        self.shutdown_requested = asyncio.Event()
        self.supervisor = Supervisor(
            metrics=self.metrics,
            on_escalate=self._escalate,
            backoff_base=config.supervisor_backoff,
            budget=config.supervisor_budget,
        )
        # Multi-core delivery plane (delivery/plane.py): sender worker
        # processes owning disjoint socket shards, fed by per-worker
        # shared-memory rings. None with --delivery-workers 0 (the
        # default) — the PeerMap then takes its unchanged in-process
        # path and no plane machinery is constructed.
        self.delivery_plane = None
        if config.delivery_workers > 0:
            from ..delivery import DeliveryPlane

            self.delivery_plane = DeliveryPlane(
                config,
                metrics=self.metrics,
                tracer=self.tracer,
                on_peer_lost=self._on_delivery_peer_lost,
            )
            if self.recorder is not None:
                # worker-plane trace stitching: /debug/ticks grafts the
                # workers' ring-dwell + write-time spans under
                # tick.deliver so one tick trace explains the fan-out
                # tail end-to-end
                self.recorder.stitcher = self.delivery_plane.stitch
        self._delivery_evictions: set = set()
        # Session continuity (robustness/sessions.py): with
        # --session-ttl > 0 a dropped peer's logical state parks for
        # the TTL instead of tearing down, and a reconnect presenting
        # the handshake-minted token rebinds to it. None with TTL 0
        # (the default) — every disconnect path keeps the pre-session
        # behavior byte for byte.
        self.sessions = None
        if config.session_ttl > 0:
            from ..robustness.sessions import SessionStore

            self.sessions = SessionStore(
                config.session_ttl,
                metrics=self.metrics,
                on_expire=self._expire_session,
            )
        self.peer_map = PeerMap(
            on_remove=self._on_peer_remove, metrics=self.metrics,
            plane=self.delivery_plane, sessions=self.sessions,
        )
        # Overload control plane (robustness/overload.py): admission
        # governor for router, ticker and entity plane. None with
        # --overload off (the default) — no governor object exists and
        # every gated path keeps today's behavior byte for byte.
        self.governor = None
        if config.overload == "on":
            from ..robustness.overload import OverloadGovernor

            budget_ms = config.overload_tick_budget_ms
            if not budget_ms and config.tick_interval > 0:
                # the deadline IS the tick window: slower can't hold rate
                budget_ms = config.tick_interval * 1e3
            self.governor = OverloadGovernor(
                max_batch=config.max_batch,
                tick_budget_ms=budget_ms,
                deadline_k=config.overload_deadline_k,
                recover_ticks=config.overload_recover_ticks,
                min_batch=min(config.overload_min_batch, config.max_batch),
                peer_rate=config.overload_peer_rate,
                peer_burst=config.overload_peer_burst,
                evict_after=config.overload_evict_after,
                rss_limit_mb=config.overload_rss_limit_mb,
                resume_rate=config.session_resume_rate,
                metrics=self.metrics,
                loop_monitor=self.loop_monitor,
                on_evict=self._on_rate_limit_evict,
            )
        # Spatial query library (worldql_server_tpu/queries): wire-level
        # cone/raycast/kNN/density queries riding the staged columns.
        # 'off' (or an unregistered parameter) keeps every query a plain
        # radius match byte for byte — router parse and backend dispatch
        # both gate on these being None.
        self.query_limits = None
        self.heatmap = None
        if config.query_kinds == "on":
            from ..queries import QueryLimits
            from ..queries.heatmap import RegionHeatmap

            self.query_limits = QueryLimits(
                cube_size=config.sub_region_size,
                stencil_max=config.query_stencil_max,
                ray_steps_max=config.query_ray_steps,
                density_top_n=config.query_density_top_n,
            )
            self.heatmap = RegionHeatmap(top_n=config.query_density_top_n)
            # expansion clamps live on the backend(s): the Resilient
            # wrapper delegates dispatch to .inner and degradation to
            # .mirror, so all three must agree with the parse clamps
            for b in (self.backend, getattr(self.backend, "inner", None),
                      getattr(self.backend, "mirror", None)):
                if b is not None:
                    b.query_stencil_max = config.query_stencil_max
                    b.query_ray_steps = config.query_ray_steps
        # Entity simulation plane (worldql_server_tpu/entities): the
        # device-resident moving-object workload. Constructed only in
        # --entity-sim mode (validate() guarantees a device backend +
        # ticker exist for it); the broker-only path never imports it.
        self.entity_plane = None
        self.entity_ingest = None
        # Interest-managed fan-out (--interest on, ISSUE 18): built
        # below only alongside the entity plane (validate() enforces
        # the pairing); None keeps every delivery path byte for byte.
        self.interest = None
        if config.entity_sim:
            from ..entities import ColumnarIngest, EntityPlane

            self.entity_plane = EntityPlane(
                self.backend, self.peer_map,
                cube_size=config.sub_region_size,
                k=config.entity_k,
                dt=config.tick_interval,
                bounds=config.entity_bounds,
                max_entities=config.entity_max,
                metrics=self.metrics,
                tracer=self.tracer,
                governor=self.governor,
                delta_ticks=config.delta_ticks,
                delta_rebuild_threshold=config.delta_rebuild_threshold,
            )
            # wire→SoA columnar fast path (PR 11): transports hand whole
            # recv batches here; entity-update messages batch-decode
            # natively into the plane's columns, everything else routes
            # through the ordinary codec. Inert when the native library
            # predates the entity codec (active == False).
            self.entity_ingest = ColumnarIngest(
                self.entity_plane,
                sender_known=self.peer_map.__contains__,
                governor=self.governor,
                metrics=self.metrics,
                on_error=lambda: self.metrics.inc("zmq.recv_errors"),
            )
            if config.interest == "on":
                from ..interest import InterestManager

                self.interest = InterestManager(
                    near_radius=config.lod_near_radius,
                    far_every_k=config.lod_far_every_k,
                    bandwidth_bytes=config.peer_bandwidth_bytes,
                    metrics=self.metrics,
                )
                self.entity_plane.interest = self.interest
                # every loss path funnels into ONE resync hook: local
                # map-miss/send-error, worker-plane ring drops, and
                # frames that landed on a parked session
                self.peer_map.on_frame_loss = self.interest.mark_resync
                if self.delivery_plane is not None:
                    self.delivery_plane.on_frame_drop = (
                        self.interest.mark_resync
                    )
                if self.sessions is not None:
                    self.sessions.on_undelivered = (
                        self.interest.mark_resync
                    )
        if self.entity_plane is not None and hasattr(
            self.backend, "_note_failure"
        ):
            # ResilientBackend rebuild/failover swaps the inner index
            # out from under an in-flight sim tick: the entity plane's
            # device twin (and its dirty bitmap) must be invalidated
            # BEFORE the restore so the next dispatch re-ships the
            # host authority instead of scattering onto a stale twin.
            self.backend.on_rebuild = self.entity_plane.abort_tick
        # Cluster shard extension (worldql_server_tpu/cluster): remote
        # peer proxies, the inter-shard ring drain and the control
        # channel to the router tier. Only with --cluster-role shard
        # (spawned by the router's supervisor, which provides the
        # WQL_CLUSTER_SPEC topology); standalone servers never import
        # the cluster package.
        self.cluster = None
        if config.cluster_role == "shard":
            from ..cluster.shard import ClusterShardExtension

            self.cluster = ClusterShardExtension(self)
            if self.recorder is not None:
                # graft router.forward/cluster.ring_dwell spans for
                # drained cross-shard frames under the tick trace at
                # export time — composed with the delivery plane's
                # stitcher when both are built
                self.recorder.stitcher = self.cluster.chain_stitcher(
                    self.recorder.stitcher
                )
        self.ticker = None
        self.staging = None
        if config.tick_interval > 0:
            from .ticker import TickBatcher

            # Columnar query staging (engine/staging.py): enqueue-time
            # encode into double-buffered arrays, so flush dispatches
            # with zero per-query Python. 'auto' binds it exactly when
            # the backend can stage; 'off' keeps the object-list path
            # byte for byte (config.validate rejects 'on' + cpu).
            if (
                config.query_staging != "off"
                and self.backend.supports_staged_dispatch()
            ):
                from .staging import QueryStaging

                self.staging = QueryStaging(self.backend)
            self.ticker = TickBatcher(
                self.backend, self.peer_map, config.tick_interval,
                max_batch=config.max_batch,
                metrics=self.metrics, pipeline=config.tick_pipeline,
                supervisor=self.supervisor, tracer=self.tracer,
                device_telemetry=self.device_telemetry,
                staging=self.staging,
                entity_plane=self.entity_plane,
                governor=self.governor,
                cluster=self.cluster,
                heatmap=self.heatmap,
            )
        self.precompile_stats: dict | None = None
        # Durability engine: WAL + write-behind pipeline. With
        # durability='off' (default) both stay None and the Router's
        # internal pass-through keeps reference-equivalent inline-store
        # behavior.
        self.wal = None
        self.durability = None
        self.last_recovery = None
        if config.durability != "off":
            from ..durability import DurabilityPipeline, WriteAheadLog

            self.wal = WriteAheadLog(
                config.wal_dir,
                # sync mode = fsync per batch, no coalescing wait
                fsync_ms=(
                    0.0 if config.durability == "sync"
                    else config.wal_fsync_ms
                ),
                segment_bytes=config.wal_segment_bytes,
                metrics=self.metrics,
                tracer=self.tracer,
            )
            self.durability = DurabilityPipeline(
                self.store, mode=config.durability, wal=self.wal,
                config=config, metrics=self.metrics, tracer=self.tracer,
            )
        self.router = Router(
            self.peer_map, self.backend, self.store,
            ticker=self.ticker, metrics=self.metrics,
            durability=self.durability, tracer=self.tracer,
            entity_plane=self.entity_plane,
            governor=self.governor,
            query_limits=self.query_limits,
            heatmap=self.heatmap,
        )
        # SLO engine + incident recorder (observability/slo.py,
        # incidents.py): declared objectives over the series this
        # registry already records, judged by a supervised slo-eval
        # task with fast/slow burn windows. Off (default) constructs
        # nothing — no gauge, no routes, no healthz block.
        self.slo = None
        self.incidents = None
        if config.slo_enabled:
            from ..observability.slo import SloEngine, load_objectives

            interval, objectives = load_objectives(config.slo_file)
            self.slo = SloEngine(
                self.metrics, objectives, eval_interval_s=interval
            )
            if config.incident_dir is not None:
                from ..observability.incidents import IncidentRecorder

                self.incidents = IncidentRecorder(
                    config.incident_dir,
                    cooldown_s=config.incident_cooldown,
                    keep=config.incident_keep,
                    metrics=self.metrics,
                )
                self.incidents.collect = self._collect_incident_body
                self.slo.on_burning = self._on_slo_burning
        self._register_gauges()
        self._tasks: list[asyncio.Task] = []
        self._transports: list = []
        self._started = asyncio.Event()
        self._restored_peers: list = []
        self._snapshot_save_disabled = False

    def _register_gauges(self) -> None:
        self.metrics.gauge("peers", self.peer_map.size)
        self.metrics.gauge(
            "subscriptions", self.backend.subscription_count
            if hasattr(self.backend, "subscription_count") else lambda: None
        )
        if hasattr(self.backend, "device_stats"):
            self.metrics.gauge("spatial_device", self.backend.device_stats)
        if self.heatmap is not None:
            # per-region density aggregates (queries/heatmap.py):
            # numeric leaves only — tracked_cubes/worlds/updates plus
            # rank-indexed top-N counts, flattened strict-parser clean
            # as wql_region_density_top0..topN
            self.metrics.gauge("region_density", self.heatmap.gauge)
        if self.config.delta_ticks != "off":
            # flattened into delta.* series by render_prometheus —
            # the e2e acceptance reads delta.reuse_fraction here
            self.metrics.gauge("delta", self._delta_status)
        if self.ticker is not None:
            self.metrics.gauge(
                "tick",
                lambda: {
                    "interval_s": self.ticker.interval,
                    "pipeline": self.ticker.pipeline,
                    "inflight": self.ticker.inflight(),
                    "last_batch": self.ticker.last_batch,
                    "last_tick_ms": round(self.ticker.last_tick_ms, 3),
                    "last_dispatch_ms":
                        round(self.ticker.last_dispatch_ms, 3),
                    "last_collect_ms":
                        round(self.ticker.last_collect_ms, 3),
                    "compaction_bucket":
                        self.ticker.last_compaction_bucket,
                    "staged_flushes": self.ticker.staged_flushes,
                    "staging_fallbacks": self.ticker.staging_fallbacks,
                    **(
                        {"staging": self.staging.stats()}
                        if self.staging is not None else {}
                    ),
                },
            )
        if self.config.precompile_tiers and hasattr(
            self.backend, "_segments"
        ):
            self.metrics.gauge(
                "precompile", lambda: self.precompile_stats
            )
        if self.durability is not None:
            self.metrics.gauge("durability", self.durability_status)
        # Supervision + fault-injection accounting: restart/crash
        # counters and the tasks_unhealthy gauge; per-failpoint fire
        # counts so no injected fault is ever invisible in /metrics.
        self.metrics.gauge("supervisor", self.supervisor.stats)
        self.metrics.gauge(
            "failpoints", failpoints.registry.fired_counts
        )
        if self.delivery_plane is not None:
            # aggregate + per-worker delivery counters: the workers'
            # cumulative stats ride the control channel into these
            # gauges (and diff into delivery.* counters), so /metrics
            # exposes the whole plane from the parent
            self.metrics.gauge("delivery", self.delivery_plane.stats)
            for i in range(self.config.delivery_workers):
                self.metrics.gauge(
                    f"delivery.worker.{i}",
                    lambda i=i: self.delivery_plane.worker_stats(i),
                )
        if self.sessions is not None:
            # session continuity accounting: minted/parked/resumed/
            # expired and the undelivered-frame count are never silent
            self.metrics.gauge("sessions", self.sessions.stats)
        if self.entity_plane is not None:
            self.metrics.gauge("entity_sim", self.entity_plane.stats)
        if self.interest is not None:
            # per-recipient fan-out accounting: resyncs, delta ratio,
            # LOD tier sizes, bandwidth deferrals/shed — the ticker
            # additionally pushes delivery.bytes_per_tick and the
            # frame.delta_ratio / lod point gauges per applied tick
            self.metrics.gauge("interest", self.interest.stats)
        if self.entity_ingest is not None:
            self.metrics.gauge("entity_ingest", self.entity_ingest.stats)
        # codec health: the WQL_MAX_OBJS overflow fallback is counted,
        # never silent (ISSUE 11 satellite)
        from ..protocol import codec_stats

        self.metrics.gauge("codec", lambda: dict(codec_stats))
        if self.governor is not None:
            # governor state + shed/coalesce/rate-limit accounting:
            # nothing the overload plane does is invisible to a scrape
            self.metrics.gauge("overload", self.governor.status)
        if self.cluster is not None:
            # shard-side cluster accounting: remote proxies held,
            # ring send/drop/drain counts, cross-shard frames
            self.metrics.gauge("cluster_shard", self.cluster.stats)
        if self.device_telemetry is not None:
            self.metrics.gauge("device", self.device_telemetry.stats)
        if self.recorder is not None:
            self.metrics.gauge("flight_recorder", self.recorder.stats)
        if self.slo is not None:
            # per-objective burn state: numeric levels flatten to
            # wql_slo_<objective> (0 ok / 1 warn / 2 burning) + worst
            self.metrics.gauge("slo", self.slo.gauge)
        if self.incidents is not None:
            self.metrics.gauge("incidents", self.incidents.stats)
        if self.loop_monitor is not None:
            self.metrics.gauge("loop_health", self.loop_monitor.snapshot)
        if hasattr(self.backend, "status") and hasattr(
            self.backend, "failed_over"
        ):
            self.metrics.gauge("resilience", self.backend.status)

    def resilience_status(self) -> dict | None:
        """Degraded-mode state for /healthz; None without a
        ResilientBackend wrapper."""
        if hasattr(self.backend, "status") and hasattr(
            self.backend, "failed_over"
        ):
            return self.backend.status()
        return None

    def _escalate(self, task_name: str) -> None:
        """Supervisor escalation hook: a critical task is permanently
        dead — request a clean shutdown (run_forever exits its serve
        loop; embedded callers watch ``shutdown_requested``)."""
        logger.critical(
            "critical task %r failed permanently — requesting clean "
            "server shutdown", task_name,
        )
        self.metrics.inc("server.escalations")
        self.shutdown_requested.set()

    def delivery_status(self) -> dict | None:
        """Delivery-plane state for /healthz (worker liveness, restart
        and drop counts, per-worker stats freshness); None with
        --delivery-workers 0. A worker whose stats push went silent
        for 3 control-channel intervals counts as degraded — a
        wedged-but-alive drain loop must not look healthy."""
        if self.delivery_plane is None:
            return None
        status = self.delivery_plane.stats()
        status["degraded"] = self.delivery_plane.degraded()
        status["stats_age_s"] = {
            str(i): (
                round(age, 3)
                if (age := self.delivery_plane.stats_age_s(i)) is not None
                else None
            )
            for i in range(self.config.delivery_workers)
        }
        return status

    def sessions_status(self) -> dict | None:
        """Session-continuity state for /healthz; None with
        --session-ttl 0 (the reference-shaped body stays untouched)."""
        if self.sessions is None:
            return None
        return self.sessions.stats()

    def overload_status(self) -> dict | None:
        """Governor state + shed accounting for /healthz; None with
        --overload off (the reference-shaped body stays untouched)."""
        if self.governor is None:
            return None
        return self.governor.status()

    def slo_status(self) -> dict | None:
        """Compact burn-state block for /healthz; None with --slo off
        (the reference-shaped body stays untouched)."""
        if self.slo is None:
            return None
        return self.slo.healthz()

    def _on_slo_burning(self, objective) -> None:
        """SLO eval hook: an objective just transitioned into BURNING.
        Hand it to the incident recorder (debounce lives there)."""
        if self.incidents is not None:
            self.incidents.trigger(objective, self.slo.status())

    async def _collect_incident_body(self) -> dict:
        """Capsule body for a standalone/shard process: this process's
        subsystem sections (the router overrides this with the fleet
        pull over the shared chunked-dump client)."""
        from ..observability.incidents import capsule_sections

        return {"pid": os.getpid(), "sections": capsule_sections(self)}

    def _delta_status(self) -> dict:
        """Temporal-coherence accounting (the ``delta`` gauge):
        query-path + sim-path reuse counters and the cumulative
        reuse fraction — how much of the world the engine did NOT
        recompute since boot."""
        q_r = int(getattr(self.backend, "delta_reused", 0))
        q_c = int(getattr(self.backend, "delta_recomputed", 0))
        q_f = int(getattr(self.backend, "delta_fallbacks", 0))
        s_r = s_c = s_f = f_r = 0
        if self.entity_plane is not None:
            s_r = self.entity_plane.delta_reused
            s_c = self.entity_plane.delta_recomputed
            s_f = self.entity_plane.delta_fallbacks
            f_r = self.entity_plane.frames_reused
        total = q_r + q_c + s_r + s_c
        return {
            "query_reused": q_r,
            "query_recomputed": q_c,
            "query_fallbacks": q_f,
            "sim_reused": s_r,
            "sim_recomputed": s_c,
            "sim_fallbacks": s_f,
            "frames_reused": f_r,
            "reuse_fraction": (
                round((q_r + s_r) / total, 4) if total else 0.0
            ),
        }

    def durability_status(self) -> dict | None:
        """Queue depth, WAL state, and last recovery for /healthz and
        the ``durability`` gauge; None when durability is off."""
        if self.durability is None:
            return None
        status = self.durability.stats()
        if self.last_recovery is not None:
            status["recovery"] = self.last_recovery.as_dict()
        return status

    def _on_rate_limit_evict(self, uuid) -> None:
        """Overload-governor eviction hook: a peer exhausted its abuse
        budget (``overload_evict_after`` consecutive rate-limited
        messages). Leaves through the normal ``PeerMap.remove`` path —
        PeerDisconnect broadcast, removal hooks, accounting — exactly
        like the failed-send and worker-lost evictions."""
        self.metrics.inc("peers.evicted_rate_limited")
        task = asyncio.get_running_loop().create_task(  # wql: allow(unsupervised-task)
            self.peer_map.remove(uuid)
        )
        self._delivery_evictions.add(task)
        task.add_done_callback(self._delivery_evictions.discard)

    def _on_peer_remove(self, uuid) -> None:
        """Disconnect cleanup. With sessions enabled and a session
        minted for this peer, the TRANSPORT state is released (delivery
        shard slot, connect-back sockets) but the logical state —
        subscription index rows, entity slots, governor bucket — PARKS
        for the TTL; otherwise the full teardown runs as always."""
        if self.sessions is not None and self.sessions.park(uuid):
            if self.interest is not None:
                # the transport died with frames possibly in flight —
                # whatever resumes this session must start from a full
                self.interest.mark_resync(uuid)
            self._release_transport_state(uuid)
            return
        self._teardown_peer_state(uuid)

    def _release_transport_state(self, uuid) -> None:
        """Drop everything bound to the peer's (dead or superseded)
        transport: the delivery-plane shard slot and per-transport
        socket state. Logical state untouched."""
        if self.delivery_plane is not None:
            # worker-owned socket: the owning shard closes its end
            self.delivery_plane.release(uuid)
        for transport in self._transports:
            hook = getattr(transport, "on_peer_removed", None)
            if hook is not None:
                hook(uuid)

    def _teardown_peer_state(self, uuid) -> None:
        """The NORMAL removal path's state teardown: purge the spatial
        index (the remove_rx path, thread.rs:124-126), entity slots,
        governor bookkeeping, and transport/delivery socket state.
        Session expiry funnels through here too — reclamation IS a
        normal removal, just deferred by the TTL."""
        if self.sessions is not None:
            # a torn-down peer's token must never resume
            self.sessions.discard(uuid)
        if self.cluster is not None:
            # a homed peer's full teardown must reap its remote
            # proxies cluster-wide (router re-broadcasts the drop)
            self.cluster.on_peer_torn_down(uuid)
        self.backend.remove_peer(uuid)
        if self.governor is not None:
            # token bucket bookkeeping stays bounded by live peers
            self.governor.forget_peer(uuid)
        if self.entity_plane is not None:
            # entity slots + refcounts of the departed peer; its index
            # rows (entity-derived included) are already purged above
            self.entity_plane.on_peer_removed(uuid)
        self._release_transport_state(uuid)

    def _expire_session(self, uuid) -> None:
        """Session-sweeper expiry hook: the parked state's TTL ran out
        — reclaim through the normal teardown."""
        self._teardown_peer_state(uuid)

    def prepare_rebind(self, uuid):
        """First half of a session resume: silently detach the stale
        old transport binding (no PeerDisconnect broadcast, no state
        teardown) and release its shard slot + sockets, so the caller
        can adopt + rebind the fresh binding — possibly onto a
        different delivery-plane shard. Returns the detached Peer, or
        None when the peer was already out of the map (parked)."""
        old = self.peer_map.detach(uuid)
        self._release_transport_state(uuid)
        if self.interest is not None:
            # resume contract: the rebound binding's first frame is a
            # forced full regardless of what the old transport saw
            self.interest.mark_resync(uuid)
        return old

    def _on_delivery_peer_lost(self, uuid, reason: str) -> None:
        """Delivery-plane eviction hook: a sender worker reported a
        failed/overflowing peer, or died with peers on its shard. The
        PARENT stays authoritative — eviction goes through the normal
        ``PeerMap.remove`` (PeerDisconnect broadcast, removal hooks,
        ``peers.evicted_*`` accounting), exactly like the in-process
        failed-send path."""
        self.metrics.inc(f"peers.evicted_{reason}")
        if self.interest is not None:
            # worker loss / ring eviction: if the peer's session parks
            # and later resumes (possibly adopted on another shard),
            # its next frame must be full — the in-process failed-send
            # path marks the same way via PeerMap.on_frame_loss
            self.interest.mark_resync(uuid)
        task = asyncio.get_running_loop().create_task(  # wql: allow(unsupervised-task)
            self.peer_map.remove(uuid)
        )
        self._delivery_evictions.add(task)
        task.add_done_callback(self._delivery_evictions.discard)

    async def start(self) -> None:
        """Bring up the store and all enabled transports (main.rs:106-207)."""
        failpoints.fire("store.init")
        await self.store.init()
        if self.wal is not None:
            # Replay whatever the last process acked but never applied,
            # THEN open a fresh segment for this process's appends.
            from ..durability.recovery import recover

            self.last_recovery = await recover(
                self.store, self.config.wal_dir, metrics=self.metrics
            )
            self.wal.start()
            self.durability.start(supervisor=self.supervisor)
            if self.config.checkpoint_interval > 0:
                self.supervisor.spawn("checkpoint", self._checkpoint_loop)
        self._restore_index_snapshot()
        self._precompile_tiers()

        if self.loop_monitor is not None:
            # loop-health probe: supervised (a dead probe restarts, and
            # its absence shows in /healthz) but not critical — losing
            # lag samples must never take the broker down
            self.loop_monitor.install()
            self.supervisor.spawn("loop-monitor", self.loop_monitor.run)

        if self.delivery_plane is not None:
            # before any transport: workers must be ready to adopt the
            # first handshake
            await self.delivery_plane.start()

        if self.config.ws_enabled:
            from ..transports.websocket import WebSocketTransport

            ws = WebSocketTransport(self)
            self._transports.append(ws)
            await ws.start()

        if self.config.http_enabled:
            from ..transports.http import HttpTransport

            http = HttpTransport(self)
            self._transports.append(http)
            await http.start()

        if self.config.zmq_enabled:
            from ..transports.zeromq import ZmqTransport

            zmq_t = ZmqTransport(self)
            self._transports.append(zmq_t)
            await zmq_t.start()

        if self.config.zmq_enabled:
            self.supervisor.spawn("stale-sweep", self._staleness_sweeper)

        if self.sessions is not None:
            # supervised reclamation: expired parked sessions leave
            # through the normal teardown even if a sweep pass raises
            self.supervisor.spawn("session-sweep", self.sessions.sweep)

        if self.ticker is not None:
            self.ticker.start()

        if self.slo is not None:
            # SLO sentinel: judges the burn windows every eval tick
            # after the transports are up (so /metrics and the slo
            # gauge agree on what it sees). Supervised — a crashed
            # evaluator restarts and its absence shows in /healthz.
            self.supervisor.spawn("slo-eval", self.slo.run)

        if self.governor is not None and self.ticker is None:
            # immediate-mode servers have no tick clock — a supervised
            # sampler keeps the lag/RSS signals (and state recovery)
            # evaluating; with a ticker, note_tick drives everything
            self.supervisor.spawn("overload-governor", self.governor.run)

        if self._restored_peers:
            self.supervisor.spawn(
                "restored-peer-sweep", self._sweep_restored_peers
            )

        if self.cluster is not None:
            # LAST: the ZMQ listener is bound, so announcing ready to
            # the router can never race a forward into a closed socket
            await self.cluster.start()

        self._started.set()
        logger.info("worldql-server-tpu started")

    def _precompile_tiers(self) -> None:
        """Boot-time tier precompilation (spatial/precompile.py): runs
        after the snapshot restore (the restored index IS the serving
        index — its segment shapes are what the kernels key on) and
        before any transport accepts traffic. Device backends only; an
        empty index skips inside the module with a log line. Failures
        are non-fatal — a server that serves with cold caches beats one
        that won't boot."""
        if not self.config.precompile_tiers:
            return
        if not hasattr(self.backend, "_segments"):
            return  # CPU backend: nothing jitted to warm
        from ..spatial.precompile import precompile_tiers

        max_batch = (
            self.ticker.max_batch if self.ticker is not None else 16_384
        )
        try:
            self.precompile_stats = precompile_tiers(
                self.backend, max_batch=max_batch
            )
        except Exception:
            logger.exception(
                "boot-time tier precompilation failed — serving with "
                "cold kernel caches"
            )
        if self.entity_plane is not None:
            # entity-plane ladder: the sim tick at the boot capacity
            # tier + the incremental-H2D scatter's dirty-bucket ladder
            try:
                stats = self.entity_plane.precompile()
                if self.precompile_stats is None:
                    self.precompile_stats = {"entities": stats}
                else:
                    self.precompile_stats["entities"] = stats
            except Exception:
                logger.exception(
                    "entity tier precompilation failed — serving with "
                    "cold sim kernel caches"
                )

    async def _sweep_stale_once(self) -> int:
        """One staleness pass: evict every silent heartbeat-tracked
        peer. One peer's failing removal hook must not abort the sweep
        over the REST of the stale set (or kill the sweeper task) —
        the peer is already out of the map by the time a hook can
        raise, so continuing is always safe. Returns peers evicted."""
        timeout = self.config.zmq_timeout_secs
        removed = 0
        for uuid in self.peer_map.stale_peers(timeout):
            logger.info("removing stale peer: %s", uuid)
            try:
                await self.peer_map.remove(uuid)
                removed += 1
                self.metrics.inc("peers.evicted_stale")
            except Exception:
                self.metrics.inc("sweeper.remove_errors")
                logger.exception(
                    "stale-peer removal hook failed for %s — continuing "
                    "the sweep", uuid,
                )
        return removed

    async def _staleness_sweeper(self) -> None:
        """Evict heartbeat-tracked peers that went silent
        (outgoing.rs:132-150)."""
        while True:
            await asyncio.sleep(self.config.zmq_timeout_secs)
            await self._sweep_stale_once()

    def _restore_index_snapshot(self) -> None:
        """Reload the subscription index saved by the last shutdown —
        clients that reconnect under the SAME UUID (ZeroMQ peers pick
        their own) keep their area subscriptions across a restart
        instead of the reference's re-subscribe storm (SURVEY §5:
        subscriptions are ephemeral there). Restored rows whose owner
        has not reconnected within the staleness window are swept, so
        departed peers (and WebSocket peers, whose UUIDs are assigned
        per connection) can never inflate the index across restarts.
        A missing file is a fresh start; a bad one is loudly skipped —
        and the shutdown save is then disabled so the failing-but-
        intact file is never clobbered with an empty index."""
        path = self.config.index_snapshot
        if not path:
            return
        import os

        from ..spatial.snapshot import load_snapshot

        if not os.path.exists(path):
            logger.info("index snapshot %s not found — starting empty", path)
            return
        try:
            _, self._restored_peers = load_snapshot(self.backend, path)
        except Exception:
            logger.exception(
                "index snapshot %s failed to load — starting empty; the "
                "file is preserved (shutdown will not overwrite it)", path
            )
            self._snapshot_save_disabled = True

    def _save_index_snapshot(self, sweep_restored: bool = True) -> None:
        path = self.config.index_snapshot
        if not path:
            return
        # Complete any pending restored-peer sweep synchronously first:
        # a restart shorter than the staleness window must not
        # re-persist ghost rows forever. Periodic checkpoints pass
        # sweep_restored=False — mid-serving, restored peers may still
        # be inside their reconnect grace window.
        if sweep_restored:
            for peer in self._restored_peers:
                if self.peer_map.get(peer) is None:
                    self.backend.remove_peer(peer)
            self._restored_peers = []
        if self._snapshot_save_disabled:
            logger.warning(
                "index snapshot %s NOT saved: the boot-time load failed "
                "and overwriting would destroy the previous state", path
            )
            return
        from ..spatial.snapshot import save_snapshot

        try:
            save_snapshot(self.backend, path)
        except Exception:
            logger.exception("index snapshot %s failed to save", path)

    async def _sweep_restored_peers(self) -> None:
        """Evict restored subscriptions whose owners never came back:
        one staleness window after boot, any restored peer absent from
        the peer map loses its rows."""
        await asyncio.sleep(self.config.zmq_timeout_secs)
        swept = 0
        for peer in self._restored_peers:
            if self.peer_map.get(peer) is None:
                if self.backend.remove_peer(peer):
                    swept += 1
        self._restored_peers = []
        if swept:
            logger.info(
                "swept restored subscriptions of %d peers that did not "
                "reconnect", swept,
            )

    async def _checkpoint_loop(self) -> None:
        """Periodic checkpoint timer — bounds the WAL (and therefore
        crash-recovery time) while serving."""
        interval = self.config.checkpoint_interval
        while True:
            await asyncio.sleep(interval)
            try:
                await self.checkpoint()
            except Exception:
                logger.exception("checkpoint failed — will retry")

    async def checkpoint(self) -> bool:
        """Store flush → index snapshot → WAL segment truncation.
        Returns True when the WAL was actually truncated (i.e. every
        pending write-behind op reached the store first).

        Rotates FIRST: ops enqueue before their WAL append (pipeline
        ordering invariant), so once the rotate returns, every entry in
        the sealed segments belongs to an op the drain below covers — a
        handler mid-append can never slip an entry into a segment this
        checkpoint purges. Truncation is skipped entirely once any
        write-behind batch was dropped on a store error: those entries
        exist ONLY in the WAL, and boot-time replay (of the whole
        retained prefix, in order) is what re-applies them."""
        if self.wal is None:
            return False
        boundary = await self.wal.rotate()
        await self.durability.drain()
        self._save_index_snapshot(sweep_restored=False)
        self.metrics.inc("durability.checkpoints")
        if self.durability.dropped_batches:
            logger.warning(
                "checkpoint: %d write-behind batches were dropped on "
                "store errors — WAL truncation skipped; segments are "
                "kept for boot-time replay",
                self.durability.dropped_batches,
            )
            return False
        purged = await self.wal.purge_upto(boundary)
        logger.debug("checkpoint complete: %d WAL segments purged", purged)
        return True

    async def stop(self) -> None:
        # Snapshot FIRST, synchronously: closing transports evicts the
        # still-connected peers (disconnect cleanup would empty the
        # index before a later save), and a cancellation-driven
        # shutdown can interrupt any await below — the checkpoint must
        # capture the SERVING state and must not be skippable.
        self._save_index_snapshot()
        if self.ticker is not None:
            await self.ticker.stop()
        # Ordered teardown of supervised loops: the periodic loops stop
        # FIRST (a checkpoint must not race the shutdown drain below),
        # transports stop their own recv tasks, and the durability
        # applier stays ALIVE until durability.stop() has drained the
        # write-behind queue — only then does the supervisor's final
        # sweep run (by which point every handle is already stopped).
        for name in (
            "checkpoint", "stale-sweep", "restored-peer-sweep",
            "session-sweep", "loop-monitor", "overload-governor",
            "slo-eval", "cluster-control", "cluster-drain",
        ):
            handle = self.supervisor.get(name)
            if handle is not None:
                await handle.stop()
        if self.incidents is not None:
            # after slo-eval stops (no new triggers) — let any
            # in-flight capsule finish writing
            await self.incidents.drain()
        if self.loop_monitor is not None:
            self.loop_monitor.uninstall()
        if self.device_telemetry is not None:
            self.device_telemetry.uninstall()
        for task in self._tasks:
            task.cancel()
        for task in self._tasks:
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()
        for transport in reversed(self._transports):
            await transport.stop()
        self._transports.clear()
        if self.cluster is not None:
            # after the ticker drain (its last flush consumed the
            # final ring records) and transport teardown
            await self.cluster.stop()
        if self.delivery_plane is not None:
            # after the ticker drain (frames are already in the rings)
            # and transport teardown: workers own their sockets
            # independently, so they flush their rings to the clients
            # and exit clean
            await self.delivery_plane.stop()
        if self.durability is not None:
            # Drain the write-behind queue, then truncate the WAL only
            # on a CLEAN drain with no batch ever dropped — a wedged
            # store (timeout) or a dropped batch (store error) keeps
            # the segments for boot-time replay.
            drained = await self.durability.stop()
            if drained and self.durability.dropped_batches == 0:
                try:
                    await self.wal.checkpoint()
                except Exception:
                    logger.exception("shutdown WAL checkpoint failed")
            else:
                logger.warning(
                    "shutdown without WAL truncation (%s) — segments "
                    "kept for boot-time replay",
                    "drain timed out" if not drained else
                    f"{self.durability.dropped_batches} dropped batches",
                )
            await self.wal.close()
        await self.supervisor.stop()
        await self.store.close()

    async def run_forever(self) -> None:
        """Serve until SIGINT/SIGTERM — or a supervisor escalation —
        then shut down gracefully: the index snapshot and transport
        teardown must run on a container stop (SIGTERM), not only on
        Ctrl-C. Registering loop handlers also overrides the SIG_IGN
        that non-interactive shells hand to background processes."""
        import signal

        await self.start()
        stop_requested = asyncio.Event()
        loop = asyncio.get_running_loop()
        hooked = []
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop_requested.set)
                hooked.append(sig)
            except (NotImplementedError, RuntimeError):
                pass  # non-unix / nested loop: fall back to default
        # awaited-in-place waiters, cancelled below (not long-lived
        # loops, so they ride outside the supervisor)
        waiters = [
            asyncio.ensure_future(stop_requested.wait()),  # wql: allow(unsupervised-task)
            asyncio.ensure_future(self.shutdown_requested.wait()),  # wql: allow(unsupervised-task)
        ]
        try:
            await asyncio.wait(waiters, return_when=asyncio.FIRST_COMPLETED)
            if self.shutdown_requested.is_set():
                logger.critical("shutting down on supervisor escalation")
            else:
                logger.info("shutdown signal received")
        finally:
            for waiter in waiters:
                waiter.cancel()
            for sig in hooked:
                loop.remove_signal_handler(sig)
            await self.stop()
