from .config import Config
from .peers import Peer, PeerMap
from .router import Router
from .server import WorldQLServer

__all__ = ["Config", "Peer", "PeerMap", "Router", "WorldQLServer"]
