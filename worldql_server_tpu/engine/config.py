"""Server configuration.

Mirrors the reference's CLI/config surface (worldql_server/src/args.rs):
every flag has an environment-variable fallback, non-zero constraints
are enforced, the ZeroMQ timeout has a 10-second floor
(args.rs:172-182), the DB table size must divide evenly by each region
axis (args.rs:186-226), listening ports must be distinct
(main.rs:73-98), and a sub-region size under 10 logs a performance
warning (args.rs:189-191).

New knobs beyond the reference are grouped at the bottom: spatial
backend selection, the batched tick interval, and store URL (the
reference is Postgres-only; we default to SQLite so the server runs
self-contained).
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)


def _env(name: str, default: str) -> str:
    return os.environ.get(name, default)


@dataclass
class Config:
    # Record store (reference: --psql, args.rs:24-25)
    store_url: str = field(
        default_factory=lambda: _env("WQL_STORE_URL", "sqlite://worldql.db")
    )

    # Subscription cube size (args.rs:30-31)
    sub_region_size: int = field(
        default_factory=lambda: int(_env("WQL_SUBSCRIPTION_REGION_CUBE_SIZE", "16"))
    )

    # DB region/table sharding (args.rs:36-61)
    db_region_x_size: int = field(
        default_factory=lambda: int(_env("WQL_DB_REGION_X_SIZE", "16"))
    )
    db_region_y_size: int = field(
        default_factory=lambda: int(_env("WQL_DB_REGION_Y_SIZE", "256"))
    )
    db_region_z_size: int = field(
        default_factory=lambda: int(_env("WQL_DB_REGION_Z_SIZE", "16"))
    )
    db_table_size: int = field(
        default_factory=lambda: int(_env("WQL_DB_TABLE_SIZE", "1024"))
    )
    db_cache_size: int = field(
        default_factory=lambda: int(_env("WQL_DB_CACHE_SIZE", "1024"))
    )

    # HTTP (args.rs:66-78)
    http_enabled: bool = True
    http_host: str = field(default_factory=lambda: _env("WQL_HTTP_HOST", "0.0.0.0"))
    http_port: int = field(default_factory=lambda: int(_env("WQL_HTTP_PORT", "8080")))
    http_auth_token: str | None = field(
        default_factory=lambda: os.environ.get("WQL_HTTP_AUTH_TOKEN")
    )

    # WebSocket (args.rs:83-95)
    ws_enabled: bool = True
    ws_host: str = field(default_factory=lambda: _env("WQL_WS_HOST", "0.0.0.0"))
    ws_port: int = field(default_factory=lambda: int(_env("WQL_WS_PORT", "8081")))

    # ZeroMQ (args.rs:99-119)
    zmq_enabled: bool = True
    zmq_server_host: str = field(
        default_factory=lambda: _env("WQL_ZMQ_SERVER_HOST", "0.0.0.0")
    )
    zmq_server_port: int = field(
        default_factory=lambda: int(_env("WQL_ZMQ_SERVER_PORT", "5555"))
    )
    zmq_timeout_secs: int = field(
        default_factory=lambda: int(_env("WQL_ZMQ_TIMEOUT_SECS", "25"))
    )

    # Upper bound on one inbound wire message — an unbounded frame is
    # an easy memory-exhaustion vector. WS enforces it on the whole
    # (reassembled) message; ZMQ enforces it per frame at the socket
    # (MAXMSGSIZE) plus on the flattened multipart total. Caveat:
    # libzmq assembles a multipart message atomically before delivery
    # and no socket option bounds that sum, so a peer splitting one
    # logical message into many under-cap frames can still make libzmq
    # buffer up to parts x cap before the drop — the protocol's own
    # clients are single-part, so cap accordingly.
    max_message_size: int = field(
        default_factory=lambda: int(
            _env("WQL_MAX_MESSAGE_SIZE", str(8 * 1024 * 1024))
        )
    )

    verbose: int = 0

    # --- rebuild-specific knobs ------------------------------------
    # 'cpu' | 'tpu' | 'sharded' — which SpatialBackend answers
    # proximity queries ('sharded' = multi-chip over a device mesh).
    spatial_backend: str = field(
        default_factory=lambda: _env("WQL_SPATIAL_BACKEND", "cpu")
    )
    # Batched-tick window in seconds for the TPU backend; 0 = flush
    # per message (reference-equivalent immediate semantics).
    tick_interval: float = field(
        default_factory=lambda: float(_env("WQL_TICK_INTERVAL", "0"))
    )
    # Tick pipeline depth: maximum dispatched-but-undelivered ticks.
    # 1 (default) keeps the sequential flush — dispatch, collect and
    # deliver before the next tick starts. 2 overlaps tick N's device
    # collect + delivery drain with tick N+1's accumulation and
    # dispatch (engine/ticker.py; arrival order is preserved — the
    # collect/deliver stages chain).
    tick_pipeline: int = field(
        default_factory=lambda: int(_env("WQL_TICK_PIPELINE", "1"))
    )
    # Device-mesh shape for spatial_backend='sharded': data-parallel
    # query batch axis × space-sharded index axis. mesh_space=0 means
    # "all remaining devices" (parallel/mesh.py).
    mesh_batch: int = field(
        default_factory=lambda: int(_env("WQL_MESH_BATCH", "1"))
    )
    mesh_space: int = field(
        default_factory=lambda: int(_env("WQL_MESH_SPACE", "0"))
    )
    # Subscription-index snapshot file: loaded at boot if present,
    # saved at shutdown. Empty/None disables (reference semantics:
    # subscriptions are lost on restart).
    index_snapshot: str | None = field(
        default_factory=lambda: os.environ.get("WQL_INDEX_SNAPSHOT")
    )
    # Record durability engine (worldql_server_tpu/durability):
    # 'off'  = reference-equivalent — handlers await the store inline,
    #          no WAL (the default, so tier-1 behavior is unchanged);
    # 'wal'  = handlers ack after the WAL group-commit fsync, store
    #          commits happen write-behind off the event loop;
    # 'sync' = WAL with immediate fsync + inline store commit.
    durability: str = field(
        default_factory=lambda: _env("WQL_DURABILITY", "off")
    )
    # WAL segment directory (created on demand; only used when
    # durability != 'off').
    wal_dir: str = field(default_factory=lambda: _env("WQL_WAL_DIR", "wal"))
    # Group-commit window: appends arriving within this many ms of the
    # first in a batch share one fsync. The default 0 adds NO wait —
    # each drained batch fsyncs immediately, and concurrent appends
    # still coalesce naturally while a sync is in flight (same
    # rationale as Postgres commit_delay=0). Raise it to trade handler
    # latency for fewer syncs under sustained load.
    wal_fsync_ms: float = field(
        default_factory=lambda: float(_env("WQL_WAL_FSYNC_MS", "0"))
    )
    # Segment rotation threshold; sealed segments are deleted at each
    # checkpoint once their entries reached the store.
    wal_segment_bytes: int = field(
        default_factory=lambda: int(
            _env("WQL_WAL_SEGMENT_BYTES", str(64 * 1024 * 1024))
        )
    )
    # Seconds between checkpoints (queue drain → index snapshot → WAL
    # truncation); 0 disables the timer (still checkpoints at
    # shutdown). Bounds crash-recovery time.
    checkpoint_interval: float = field(
        default_factory=lambda: float(_env("WQL_CHECKPOINT_INTERVAL", "60"))
    )
    # Multi-core delivery plane (worldql_server_tpu/delivery): shard
    # outbound fan-out across this many sender WORKER PROCESSES, each
    # draining a shared-memory ring of serialized frames and owning a
    # disjoint slice of the live sockets (WS via fd handoff at
    # handshake, ZMQ via worker-connected PUSH). 0 (the default) keeps
    # the single-process in-process pump byte-for-byte.
    delivery_workers: int = field(
        default_factory=lambda: int(_env("WQL_DELIVERY_WORKERS", "0"))
    )
    # Per-worker fan-out ring capacity in bytes (rounded up to a power
    # of two). Sizing rule of thumb: >= one tick's worth of frames per
    # shard at peak — a full ring degrades (bounded wait then drop,
    # counted in delivery.ring_full_drops), it never wedges the tick.
    delivery_ring_bytes: int = field(
        default_factory=lambda: int(
            _env("WQL_DELIVERY_RING_BYTES", str(4 * 1024 * 1024))
        )
    )
    # Fault-injection failpoints (robustness/failpoints.py): a spec
    # like "store.insert=error:0.2,wal.fsync=delay:5ms" arms named
    # failure sites process-wide. Empty (the default) arms nothing and
    # costs one dict-truthiness check per site.
    failpoints: str = field(
        default_factory=lambda: _env("WQL_FAILPOINTS", "")
    )
    # Deterministic RNG seed for probabilistic failpoints (chaos runs).
    failpoints_seed: int | None = field(
        default_factory=lambda: (
            int(os.environ["WQL_FAILPOINTS_SEED"])
            if os.environ.get("WQL_FAILPOINTS_SEED") else None
        )
    )
    # Expose GET/POST /failpoints on the HTTP admin surface (gated:
    # fault injection must be an explicit operator decision).
    failpoints_admin: bool = field(
        default_factory=lambda: _env("WQL_FAILPOINTS_ADMIN", "0") == "1"
    )
    # Degraded-mode spatial backend (robustness/resilient.py): 'on'
    # wraps the spatial backend in ResilientBackend — contain device
    # failures, rebuild from the authoritative CPU mirror, fail over
    # TPU→CPU after `failover_after` consecutive failures. 'off' (the
    # default) keeps the raw backend, reference-equivalent.
    resilience: str = field(
        default_factory=lambda: _env("WQL_RESILIENCE", "off")
    )
    failover_after: int = field(
        default_factory=lambda: int(_env("WQL_FAILOVER_AFTER", "3"))
    )
    # Supervisor defaults (robustness/supervisor.py): restarts allowed
    # per unhealthy streak and the first-restart backoff in seconds
    # (doubles up to 30 s; a 60 s healthy run refunds the budget).
    supervisor_budget: int = field(
        default_factory=lambda: int(_env("WQL_SUPERVISOR_BUDGET", "5"))
    )
    supervisor_backoff: float = field(
        default_factory=lambda: float(_env("WQL_SUPERVISOR_BACKOFF", "0.5"))
    )
    # Tick flight recorder (worldql_server_tpu/observability): span
    # tracing of every tick/message stage, a ring buffer of the last
    # N tick traces served at GET /debug/ticks, and the event-loop/GC
    # health probes. Off by default — the disabled hot path pays one
    # branch per flush/message (trace.py discipline).
    trace: bool = field(
        default_factory=lambda: _env("WQL_TRACE", "0") == "1"
    )
    # Auto-dump threshold: a tick slower than this many ms dumps its
    # full span tree + loop-health context to
    # <slow_tick_dir>/slow-ticks.jsonl with a CRITICAL log line.
    # 0 dumps EVERY tick (CI smoke); unset/None disables dumping.
    # Setting it implies tracing on (the dump needs the spans).
    slow_tick_ms: float | None = field(
        default_factory=lambda: (
            float(os.environ["WQL_SLOW_TICK_MS"])
            if os.environ.get("WQL_SLOW_TICK_MS") else None
        )
    )
    # Cluster slow-frame auto-dump (cluster/shard.py, ISSUE 15): a
    # cross-shard frame whose router-ingress→socket-write wall exceeds
    # this many ms dumps its stitched router→home→remote stage chain
    # as one JSON line to <slow_tick_dir>/slow-frames.jsonl with a
    # CRITICAL log. Only meaningful on cluster shards (forwarded from
    # the router's config); unset/None disables dumping. Unlike
    # slow_tick_ms it does NOT imply tracing — the frame clocks are
    # always live in cluster mode.
    slow_frame_ms: float | None = field(
        default_factory=lambda: (
            float(os.environ["WQL_SLOW_FRAME_MS"])
            if os.environ.get("WQL_SLOW_FRAME_MS") else None
        )
    )
    flight_recorder_depth: int = field(
        default_factory=lambda: int(_env("WQL_FLIGHT_RECORDER_DEPTH", "64"))
    )
    slow_tick_dir: str = field(
        default_factory=lambda: _env("WQL_SLOW_TICK_DIR", "slow_ticks")
    )
    # Columnar query staging (engine/staging.py): enqueue-time encode
    # of the tick batch into double-buffered columnar arrays, so flush
    # dispatches with zero per-query Python. 'auto' (default) enables
    # it exactly when the spatial backend supports staged dispatch
    # (tpu/sharded); 'off' forces the object-list path everywhere
    # (reference-equivalent); 'on' is auto plus a config error if the
    # backend can't stage (a silent fallback would hide a perf cliff).
    query_staging: str = field(
        default_factory=lambda: _env("WQL_QUERY_STAGING", "auto")
    )
    # Boot-time capacity-tier precompilation (spatial/precompile.py):
    # trace every reachable CSR capacity tier, pack bucket and
    # query-cap shape against the boot index BEFORE serving, so no
    # first-occurrence tier pays a jit trace mid-serving. On by
    # default; only device backends (tpu/sharded) act on it.
    precompile_tiers: bool = field(
        default_factory=lambda: _env("WQL_PRECOMPILE_TIERS", "1") == "1"
    )
    # Entity simulation plane (worldql_server_tpu/entities): clients
    # register/update entities over the wire (the `entities` list on
    # Local/GlobalMessage), and every ticker flush integrates positions
    # + resolves per-entity kNN neighborhoods on device (ops/tick.py),
    # delivering neighbor frames through the normal fan-out path. Off
    # by default — the broker then never constructs the plane. Requires
    # a device backend ('tpu'/'sharded') and tick_interval > 0.
    entity_sim: bool = field(
        default_factory=lambda: _env("WQL_ENTITY_SIM", "0") == "1"
    )
    # Neighbors resolved per entity per tick (the kNN degree; the
    # stencil window is exact while cube occupancy <= k).
    entity_k: int = field(
        default_factory=lambda: int(_env("WQL_ENTITY_K", "8"))
    )
    # World half-extent: integrated positions reflect at ±bounds.
    entity_bounds: float = field(
        default_factory=lambda: float(_env("WQL_ENTITY_BOUNDS", "1000"))
    )
    # Hard cap on live entities (registrations beyond it are rejected
    # with a warning — one peer must not be able to grow device state
    # without bound).
    entity_max: int = field(
        default_factory=lambda: int(_env("WQL_ENTITY_MAX", str(1 << 16)))
    )
    # Tick batch cap: a full queue flushes early (engine/ticker.py).
    # Also the overload governor's full-service admitted tier and the
    # denominator of its queue-pressure signal.
    max_batch: int = field(
        default_factory=lambda: int(_env("WQL_MAX_BATCH", "16384"))
    )
    # Overload control plane (robustness/overload.py): 'on' builds the
    # OverloadGovernor — hysteretic OK→SHED_LOW→SHED_HIGH→REJECT state
    # machine driven by tick wall / queue depth / loop lag / RSS,
    # priority-classed admission at the router (record ops never shed,
    # globals shed last, locals drop-oldest, entity updates coalesce
    # LWW per uuid), per-peer token buckets, and tick-deadline
    # degradation. 'off' (the default) constructs nothing: every
    # ingest path keeps today's behavior byte for byte.
    overload: str = field(
        default_factory=lambda: _env("WQL_OVERLOAD", "off")
    )
    # Tick wall budget in ms for deadline degradation; 0 derives it
    # from tick_interval (the deadline IS the interval — a tick slower
    # than its window can't hold rate).
    overload_tick_budget_ms: float = field(
        default_factory=lambda: float(_env("WQL_OVERLOAD_TICK_BUDGET_MS", "0"))
    )
    # Consecutive over-budget ticks before the admitted batch tier
    # halves (and the governor's tick signal starts voting).
    overload_deadline_k: int = field(
        default_factory=lambda: int(_env("WQL_OVERLOAD_DEADLINE_K", "3"))
    )
    # Consecutive healthy samples before de-escalating ONE state (and
    # before a degraded tier doubles back). Full recovery from REJECT
    # therefore takes at most 3 × this many ticks.
    overload_recover_ticks: int = field(
        default_factory=lambda: int(_env("WQL_OVERLOAD_RECOVER_TICKS", "5"))
    )
    # Floor of the degraded admitted batch tier.
    overload_min_batch: int = field(
        default_factory=lambda: int(_env("WQL_OVERLOAD_MIN_BATCH", "256"))
    )
    # Per-peer token bucket: sustained messages/s per peer (0 = no
    # bucket). Record ops consume tokens but are never dropped.
    overload_peer_rate: float = field(
        default_factory=lambda: float(_env("WQL_OVERLOAD_PEER_RATE", "0"))
    )
    # Bucket burst capacity (0 = 2 × rate).
    overload_peer_burst: int = field(
        default_factory=lambda: int(_env("WQL_OVERLOAD_PEER_BURST", "0"))
    )
    # Evict a peer after this many CONSECUTIVE rate-limited messages
    # (sustained abuse); 0 = never evict, just drop.
    overload_evict_after: int = field(
        default_factory=lambda: int(_env("WQL_OVERLOAD_EVICT_AFTER", "0"))
    )
    # RSS ceiling in MiB for the governor's memory signal (0 = off).
    overload_rss_limit_mb: int = field(
        default_factory=lambda: int(_env("WQL_OVERLOAD_RSS_LIMIT_MB", "0"))
    )
    # Session continuity (robustness/sessions.py): with a TTL > 0 every
    # handshake mints a resumable session token; a dropped peer's
    # subscriptions / owned entities / undelivered-frame accounting are
    # PARKED for this many seconds instead of torn down, and a
    # reconnect presenting the token rebinds the new transport to the
    # parked state with zero index churn. 0 (the default) keeps the
    # pre-session disconnect path byte for byte.
    session_ttl: float = field(
        default_factory=lambda: float(_env("WQL_SESSION_TTL", "0"))
    )
    # Token bucket for resumes the governor still admits in REJECT
    # (resumes/s; handshake admission is only active with --overload
    # on). New connects shed at SHED_HIGH+; resumes shed only beyond
    # this trickle in REJECT.
    session_resume_rate: float = field(
        default_factory=lambda: float(_env("WQL_SESSION_RESUME_RATE", "200"))
    )
    # Delta ticks (spatial/delta_ticks.py, ROADMAP 2): temporal
    # coherence for the tick engine — per-cube dirty bits from the
    # churn stream, a persistent incrementally-updated device hash,
    # and result reuse (a query/entity whose neighborhood is clean
    # replays last tick instead of recomputing). 'auto' (default)
    # enables it exactly where it is proven: the device backends —
    # single-chip TPU, and the sharded mesh via per-shard flat-region
    # replay — and pow2-cube entity planes; 'off' pins the full
    # recompute pipeline byte for byte; 'on' is auto plus a config
    # error where delta ticks cannot run (the cpu backend).
    delta_ticks: str = field(
        default_factory=lambda: _env("WQL_DELTA_TICKS", "auto")
    )
    # Churn fraction above which a delta structure falls back to the
    # full rebuild path: the entity plane's dirty-closure sub-tick and
    # the index's tombstone-scatter delta sync both revert past it.
    delta_rebuild_threshold: float = field(
        default_factory=lambda: float(
            _env("WQL_DELTA_REBUILD_THRESHOLD", "0.5")
        )
    )
    # Horizontal serving (worldql_server_tpu/cluster, ROADMAP 3):
    # with cluster_shards > 0 this process boots the ROUTER TIER — the
    # public ZMQ listener plus N supervised shard server processes,
    # each running the full engine (own device backend, WAL, entity
    # plane, governor) over a stable world→shard map, with cross-shard
    # delivery riding inter-shard shared-memory rings. 0 (the default)
    # never imports the cluster package: the single-process server is
    # byte for byte what it always was.
    cluster_shards: int = field(
        default_factory=lambda: int(_env("WQL_CLUSTER_SHARDS", "0"))
    )
    # Process role inside a cluster: '' (standalone / implied router
    # when cluster_shards > 0), 'router', or 'shard' (spawned by the
    # router-tier supervisor with a WQL_CLUSTER_SPEC topology; attaches
    # the ClusterShardExtension to an otherwise-normal server).
    cluster_role: str = field(
        default_factory=lambda: _env("WQL_CLUSTER_ROLE", "")
    )
    # Live resharding (cluster/resharding, ISSUE 19): 'on' arms the
    # router-side autoshard controller — it watches the federated
    # per-shard overload state and migrates the hottest world off a
    # sustained-hot shard automatically. 'off' (the default) never
    # self-triggers; manual POST /reshard is always available on the
    # router's HTTP surface either way.
    cluster_autoshard: str = field(
        default_factory=lambda: _env("WQL_CLUSTER_AUTOSHARD", "off")
    )
    # Byte budget for the per-migration transfer buffer: while a world
    # migrates, the router PARKS its inbound traffic here for post-flip
    # replay; past the budget frames are shed AND COUNTED
    # (cluster.reshard_buffer_shed) — bounded memory, never silent loss.
    reshard_buffer_bytes: int = field(
        default_factory=lambda: int(
            _env("WQL_RESHARD_BUFFER_BYTES", str(8 * 1024 * 1024))
        )
    )
    # Spatial query library (worldql_server_tpu/queries, ISSUE 17):
    # 'on' (the default) routes LocalMessages whose parameter names a
    # registered query kind (query.cone / query.raycast / query.knn /
    # query.density) through kind-dispatched resolution — staged kind
    # lanes, probe expansion on device backends, CPU oracles elsewhere
    # — and answers each with a reply frame. 'off' pins the
    # pre-library pipeline byte for byte: those parameters ride as
    # plain radius messages.
    query_kinds: str = field(
        default_factory=lambda: _env("WQL_QUERY_KINDS", "on")
    )
    # Stencil clamp: max probe radius in cubes a kind expansion may
    # walk (cone range / knn max-range reaches clamp to it). Part of
    # the query SEMANTICS — oracles and kernels read the same value.
    query_stencil_max: int = field(
        default_factory=lambda: int(_env("WQL_QUERY_STENCIL_MAX", "3"))
    )
    # Raycast march clamp: max half-cube steps along the segment.
    query_ray_steps: int = field(
        default_factory=lambda: int(_env("WQL_QUERY_RAY_STEPS", "64"))
    )
    # Density result clamp: top-N cubes per query.density reply (also
    # the region heatmap's gauge depth).
    query_density_top_n: int = field(
        default_factory=lambda: int(_env("WQL_QUERY_DENSITY_TOP_N", "16"))
    )
    # Device telemetry (observability/device.py): jit compile/retrace
    # counters + flight-recorder loose spans, the per-tick
    # encode/h2d/compute/d2h timing split, and the live
    # device-buffer-bytes gauge. On by default — it only activates
    # when the spatial backend exposes device stats (tpu/sharded), and
    # its tick-path cost is one small dict diff per collect.
    device_telemetry: bool = field(
        default_factory=lambda: _env("WQL_DEVICE_TELEMETRY", "1") == "1"
    )
    # Interest-managed fan-out (worldql_server_tpu/interest, ROADMAP
    # item 3): 'on' replaces the per-entity neighbor-frame broadcast
    # with per-recipient delta frames — each peer receives a diff
    # (entered/left/moved) against its last delivered state under an
    # epoch:seq stamped wire contract (`entity.frame.full` /
    # `entity.frame.delta`), with a forced full-frame resync on every
    # loss path (reconnect, session resume, ring drop, worker loss,
    # overload shed). 'off' (the default) never constructs the
    # manager: the delivery path — frame bytes, parameter strings,
    # sequence-field absence — is byte for byte the pre-interest
    # pipeline.
    interest: str = field(
        default_factory=lambda: _env("WQL_INTEREST", "off")
    )
    # LOD cadence partition: recipients within `lod_near_radius` of a
    # neighbor entity (distance to the recipient's own entity
    # centroid) deliver every tick; farther rows deliver every
    # `lod_far_every_k` ticks (lossless deferral — the diff
    # accumulates, never drops). near_radius 0 puts every row in the
    # near cohort.
    lod_near_radius: float = field(
        default_factory=lambda: float(_env("WQL_LOD_NEAR_RADIUS", "0"))
    )
    lod_far_every_k: int = field(
        default_factory=lambda: int(_env("WQL_LOD_FAR_EVERY_K", "4"))
    )
    # Per-peer bandwidth budget (bytes/s, token bucket, 0 = off): an
    # over-budget peer degrades CADENCE first (forced far tier), then
    # coalesces to keyframe-only, and only then sheds whole keyframes
    # (`delivery.bytes_shed`) — a delta is never silently truncated,
    # so eventual-state parity holds under any budget.
    peer_bandwidth_bytes: int = field(
        default_factory=lambda: int(_env("WQL_PEER_BANDWIDTH_BYTES", "0"))
    )
    # SLO engine: 'off' (default) constructs nothing — no slo gauge,
    # no /debug/slo route, no healthz block, no slo-eval task; the
    # observable surface is byte for byte the pre-SLO server. 'on'
    # evaluates the built-in objective registry; --slo-file (JSON)
    # replaces the registry with per-objective targets/windows and
    # implies 'on'.
    slo: str = field(default_factory=lambda: _env("WQL_SLO", "off"))
    slo_file: str | None = field(
        default_factory=lambda: os.environ.get("WQL_SLO_FILE") or None
    )
    # Incident capsules: written only when incident_dir is set (and the
    # SLO engine is on). One correlated JSON bundle per BURNING
    # transition, debounced by incident_cooldown seconds, newest
    # incident_keep capsules retained.
    incident_dir: str | None = field(
        default_factory=lambda: os.environ.get("WQL_INCIDENT_DIR") or None
    )
    incident_cooldown: float = field(
        default_factory=lambda: float(_env("WQL_INCIDENT_COOLDOWN", "60"))
    )
    incident_keep: int = field(
        default_factory=lambda: int(_env("WQL_INCIDENT_KEEP", "16"))
    )

    def validate(self) -> None:
        """Cross-field validation; raises ValueError on any violation
        (args.rs:145-226, main.rs:73-98)."""
        errors: list[str] = []

        for name in (
            "sub_region_size",
            "db_region_x_size",
            "db_region_y_size",
            "db_region_z_size",
            "db_table_size",
        ):
            if getattr(self, name) <= 0:
                errors.append(f"{name} must be greater than 0")
        if self.db_cache_size < 0:
            errors.append("db_cache_size must be >= 0")

        if self.sub_region_size < 10:
            logger.warning(
                "sub-region sizes less than 10 might impact lookup performance"
            )

        if self.zmq_enabled and self.zmq_timeout_secs < 10:
            errors.append("zmq_timeout_secs must be at least 10 seconds")
        if self.max_message_size <= 0:
            errors.append("max_message_size must be greater than 0")

        for axis in ("x", "y", "z"):
            region = getattr(self, f"db_region_{axis}_size")
            if region > 0 and self.db_table_size % region != 0:
                errors.append(
                    f"db_table_size must be evenly divisible by db_region_{axis}_size"
                )

        ports = []
        if self.http_enabled:
            ports.append(("http_port", self.http_port))
        if self.ws_enabled:
            ports.append(("ws_port", self.ws_port))
        if self.zmq_enabled:
            ports.append(("zmq_server_port", self.zmq_server_port))
        seen: dict[int, str] = {}
        for name, port in ports:
            if port in seen:
                errors.append(f"{name} clashes with {seen[port]} (both {port})")
            else:
                seen[port] = name

        if self.spatial_backend not in ("cpu", "tpu", "sharded"):
            errors.append("spatial_backend must be 'cpu', 'tpu' or 'sharded'")
        if (
            os.environ.get("WQL_DIST_COORDINATOR")
            and self.spatial_backend != "sharded"
        ):
            # only the sharded backend joins the distributed runtime —
            # ignoring the multi-host config would silently run every
            # process single-host
            errors.append(
                "WQL_DIST_COORDINATOR is set but spatial_backend is "
                f"'{self.spatial_backend}' — multi-host requires "
                "'sharded'"
            )
        if self.tick_interval < 0:
            errors.append("tick_interval must be >= 0")
        if self.query_staging not in ("auto", "on", "off"):
            errors.append("query_staging must be 'auto', 'on' or 'off'")
        if self.query_staging == "on" and self.spatial_backend == "cpu":
            errors.append(
                "query_staging='on' requires a staging-capable spatial "
                "backend ('tpu' or 'sharded'); the CPU backend resolves "
                "per query — use 'auto' to enable staging only when "
                "supported"
            )
        if self.tick_pipeline < 1:
            errors.append("tick_pipeline must be >= 1 (1 = no overlap)")
        if self.delivery_workers < 0:
            errors.append("delivery_workers must be >= 0 (0 = in-process)")
        if self.delivery_workers:
            from ..delivery.ring import RING_MIN_BYTES

            if self.delivery_ring_bytes < RING_MIN_BYTES:
                errors.append(
                    f"delivery_ring_bytes must be >= {RING_MIN_BYTES}"
                )
        if self.durability not in ("off", "wal", "sync"):
            errors.append("durability must be 'off', 'wal' or 'sync'")
        elif self.durability != "off" and not self.wal_dir:
            errors.append(f"durability='{self.durability}' requires wal_dir")
        if self.wal_fsync_ms < 0:
            errors.append("wal_fsync_ms must be >= 0")
        if self.wal_segment_bytes <= 0:
            errors.append("wal_segment_bytes must be greater than 0")
        if self.checkpoint_interval < 0:
            errors.append("checkpoint_interval must be >= 0 (0 = no timer)")
        if self.resilience not in ("off", "on"):
            errors.append("resilience must be 'off' or 'on'")
        if self.failover_after < 1:
            errors.append("failover_after must be >= 1")
        if self.supervisor_budget < 0:
            errors.append("supervisor_budget must be >= 0")
        if self.supervisor_backoff < 0:
            errors.append("supervisor_backoff must be >= 0")
        if self.slow_tick_ms is not None and self.slow_tick_ms < 0:
            errors.append("slow_tick_ms must be >= 0 (0 = dump every tick)")
        if self.flight_recorder_depth < 1:
            errors.append("flight_recorder_depth must be >= 1")
        if self.slow_tick_ms is not None and not self.slow_tick_dir:
            errors.append("slow_tick_ms requires slow_tick_dir")
        if self.slow_frame_ms is not None and self.slow_frame_ms < 0:
            errors.append(
                "slow_frame_ms must be >= 0 (0 = dump every frame)"
            )
        if self.slow_frame_ms is not None and not self.slow_tick_dir:
            errors.append("slow_frame_ms requires slow_tick_dir")
        if self.failpoints:
            # fail at config time, not at the first armed boundary
            from ..robustness.failpoints import FailpointSpecError, parse_spec

            try:
                parse_spec(self.failpoints)
            except FailpointSpecError as exc:
                errors.append(f"failpoints: {exc}")
        if self.mesh_batch <= 0:
            errors.append("mesh_batch must be greater than 0")
        if self.mesh_space < 0:
            errors.append("mesh_space must be >= 0 (0 = all remaining devices)")
        if self.query_kinds not in ("on", "off"):
            errors.append("query_kinds must be 'on' or 'off'")
        if self.query_stencil_max < 1:
            errors.append("query_stencil_max must be >= 1")
        if self.query_ray_steps < 1:
            errors.append("query_ray_steps must be >= 1")
        if self.query_density_top_n < 1:
            errors.append("query_density_top_n must be >= 1")
        if self.entity_sim:
            if self.spatial_backend == "cpu":
                errors.append(
                    "entity_sim requires a device spatial backend "
                    "('tpu' or 'sharded') — the simulation tick "
                    "integrates and resolves kNN on device"
                )
            if self.tick_interval <= 0:
                errors.append(
                    "entity_sim requires tick_interval > 0 — the "
                    "simulation advances once per ticker flush"
                )
        if self.max_batch < 1:
            errors.append("max_batch must be >= 1")
        if self.overload not in ("off", "on"):
            errors.append("overload must be 'off' or 'on'")
        if self.overload_tick_budget_ms < 0:
            errors.append(
                "overload_tick_budget_ms must be >= 0 (0 = derive "
                "from tick_interval)"
            )
        if self.overload_deadline_k < 1:
            errors.append("overload_deadline_k must be >= 1")
        if self.overload_recover_ticks < 1:
            errors.append("overload_recover_ticks must be >= 1")
        if self.overload_min_batch < 1:
            errors.append("overload_min_batch must be >= 1")
        if self.overload_peer_rate < 0:
            errors.append("overload_peer_rate must be >= 0 (0 = no bucket)")
        if self.overload_peer_burst < 0:
            errors.append("overload_peer_burst must be >= 0 (0 = 2x rate)")
        if self.overload_evict_after < 0:
            errors.append("overload_evict_after must be >= 0 (0 = never)")
        if self.overload_rss_limit_mb < 0:
            errors.append("overload_rss_limit_mb must be >= 0 (0 = off)")
        if self.overload_evict_after and not self.overload_peer_rate:
            errors.append(
                "overload_evict_after requires overload_peer_rate > 0 "
                "(eviction is driven by the token bucket)"
            )
        if self.session_ttl < 0:
            errors.append("session_ttl must be >= 0 (0 = sessions off)")
        if self.session_resume_rate < 0:
            errors.append(
                "session_resume_rate must be >= 0 (0 = no resumes "
                "admitted in REJECT)"
            )
        if self.delta_ticks not in ("auto", "on", "off"):
            errors.append("delta_ticks must be 'auto', 'on' or 'off'")
        if self.interest not in ("on", "off"):
            errors.append("interest must be 'on' or 'off'")
        if self.interest == "on" and not self.entity_sim:
            errors.append(
                "interest requires entity_sim — the manager diffs the "
                "entity plane's per-tick neighbor frames"
            )
        if self.lod_near_radius < 0:
            errors.append("lod_near_radius must be >= 0 (0 = all near)")
        if self.lod_far_every_k < 1:
            errors.append("lod_far_every_k must be >= 1")
        if self.peer_bandwidth_bytes < 0:
            errors.append("peer_bandwidth_bytes must be >= 0 (0 = off)")
        if self.delta_ticks == "on" and self.spatial_backend == "cpu":
            errors.append(
                "delta_ticks='on' requires a device spatial backend "
                "('tpu' or 'sharded') — the cpu backend resolves per "
                "query; use 'auto' to enable delta ticks only where "
                "supported"
            )
        if not 0 < self.delta_rebuild_threshold <= 1:
            errors.append(
                "delta_rebuild_threshold must be in (0, 1]"
            )
        if self.cluster_shards < 0:
            errors.append("cluster_shards must be >= 0 (0 = no cluster)")
        if self.cluster_role not in ("", "router", "shard"):
            errors.append("cluster_role must be '', 'router' or 'shard'")
        if self.cluster_shards > 0:
            if self.cluster_role == "shard":
                errors.append(
                    "cluster_role='shard' cannot itself spawn a cluster "
                    "— cluster_shards belongs to the router tier"
                )
            if not self.zmq_enabled:
                errors.append(
                    "cluster serving requires the ZMQ listener — the "
                    "router tier owns no other client transport"
                )
            if self.ws_enabled:
                errors.append(
                    "cluster serving is ZMQ-only for now — pass --no-ws "
                    "(the router tier has no WebSocket listener; shards "
                    "boot with WS off)"
                )
        if self.cluster_role == "router" and self.cluster_shards < 1:
            errors.append("cluster_role='router' requires cluster_shards >= 1")
        if self.cluster_autoshard not in ("off", "on"):
            errors.append("cluster_autoshard must be 'off' or 'on'")
        if self.reshard_buffer_bytes < 1:
            errors.append("reshard_buffer_bytes must be >= 1")
        if self.cluster_role == "shard" and not os.environ.get(
            "WQL_CLUSTER_SPEC"
        ):
            errors.append(
                "cluster_role='shard' requires the WQL_CLUSTER_SPEC "
                "topology (set by the router-tier supervisor)"
            )
        if self.entity_k < 1:
            errors.append("entity_k must be >= 1")
        if self.entity_bounds <= 0:
            errors.append("entity_bounds must be > 0")
        if self.entity_max < 1:
            errors.append("entity_max must be >= 1")

        if self.slo not in ("off", "on"):
            errors.append("slo must be 'off' or 'on'")
        if self.slo_file is not None:
            try:
                from ..observability.slo import load_objectives

                load_objectives(self.slo_file)
            except (OSError, ValueError, json.JSONDecodeError) as exc:
                errors.append(f"slo_file: {exc}")
        if self.incident_cooldown < 0:
            errors.append("incident_cooldown must be >= 0")
        if self.incident_keep < 1:
            errors.append("incident_keep must be >= 1")
        if self.incident_dir is not None and not self.slo_enabled:
            errors.append(
                "incident_dir requires the SLO engine (--slo on or "
                "--slo-file) — capsules trigger off burn transitions"
            )

        if errors:
            raise ValueError("; ".join(errors))

    @property
    def trace_enabled(self) -> bool:
        """Tracing is on when asked for explicitly OR implied by a
        slow-tick threshold — an auto-dump without spans would be an
        empty tree."""
        return self.trace or self.slow_tick_ms is not None

    @property
    def slo_enabled(self) -> bool:
        """The SLO engine runs when asked for explicitly OR implied by
        an objective file — a registry override with the engine off
        would be dead config."""
        return self.slo == "on" or self.slo_file is not None


#: device nodes whose presence means a non-CPU jax backend will attach
#: (TPU chips appear as /dev/accel*, PCIe VFIO passthrough as
#: /dev/vfio, NVIDIA GPUs as /dev/nvidia*). A filesystem probe instead
#: of importing jax: on a device-less host the CPU boot path must not
#: pay (or hang in) accelerator-plugin discovery just to learn there is
#: nothing to discover.
_DEVICE_NODES = ("/dev/accel0", "/dev/vfio/0", "/dev/nvidia0")


def accelerator_present(probe_paths=_DEVICE_NODES) -> bool:
    """True when a non-CPU accelerator is visibly attached. Honors the
    opt-outs: WQL_DEVICE_DEFAULTS=0 disables the probe outright, and a
    JAX_PLATFORMS env pinned to cpu means the operator already decided
    (jaxconf forces the cpu platform for that case)."""
    if os.environ.get("WQL_DEVICE_DEFAULTS", "1") == "0":
        return False
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        return False
    return any(os.path.exists(p) for p in probe_paths)


def apply_device_boot_defaults(
    config: Config,
    *,
    backend_explicit: bool,
    interval_explicit: bool,
    present: bool | None = None,
) -> bool:
    """Default-on device boot (ROADMAP item 5): when an accelerator is
    attached and the operator expressed NO preference (no flag, no env
    var), a bare ``python -m worldql_server_tpu`` serves the batched
    device engine — ``spatial_backend='tpu'``, ``tick_interval=0.05``.
    Explicit settings always win, field by field; on a CPU-only host
    the config is returned untouched, byte for byte. Returns whether
    the defaults were applied."""
    if backend_explicit or os.environ.get("WQL_SPATIAL_BACKEND"):
        return False
    if present is None:
        present = accelerator_present()
    if not present:
        return False
    config.spatial_backend = "tpu"
    if not interval_explicit and not os.environ.get("WQL_TICK_INTERVAL"):
        config.tick_interval = 0.05
    logger.info(
        "accelerator detected — defaulting to the batched device "
        "engine (--spatial-backend tpu --tick-interval %g)",
        config.tick_interval,
    )
    return True
