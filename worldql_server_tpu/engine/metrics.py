"""Metrics registry: counters, latency histograms, and gauges.

The reference's observability is log lines only — no counters, no
health endpoint (SURVEY §5 "Metrics/logging/observability: logging
only"). The rebuild's contract is structured per-tick timing and
engine state, exposed by ``GET /metrics`` (transports/http.py) and
importable for tests.

Thread-safe: counters were the first writers off the loop (the
resilience layer increments from the ticker's collect worker thread),
and since PR 3 histograms are too — ``tick.collect_ms`` is observed
from the collect worker, and PR 5's span/flight-recorder plumbing adds
the WAL writer thread. Lazy ``Histogram`` creation plus the bucket
list's read-modify-writes can lose updates across threads, so
``inc`` and ``observe_ms`` both take the registry lock. Histograms are
fixed log-spaced latency buckets — cheap, allocation-free, good enough
for p50/p99 estimates.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Callable

# Bucket upper bounds in milliseconds (log-spaced), +inf implicit.
# The ladder runs into the multi-MINUTE range on purpose: BENCH_r05
# recorded a 207,000 ms tick, and with a 2.5 s top bucket everything
# above it collapsed into +inf — exactly the outlier regime the
# flight recorder exists for. Anything past 250 s reports via the
# overflow bucket's max-observed estimate (see ``quantile``).
LATENCY_BUCKETS_MS = (
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 25000.0, 50000.0, 100000.0,
    250000.0,
)


class Histogram:
    __slots__ = ("buckets", "counts", "total", "sum_ms", "max_ms")

    def __init__(self, buckets=LATENCY_BUCKETS_MS):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0

    def observe_ms(self, value_ms: float) -> None:
        self.observe_ms_n(value_ms, 1)

    def observe_ms_n(self, value_ms: float, n: int) -> None:
        """``n`` identical observations in one bucket write — the
        batched-delivery paths close one wall clock for a whole tick's
        frames and must not pay a per-frame loop."""
        i = 0
        for i, bound in enumerate(self.buckets):  # noqa: B007
            if value_ms <= bound:
                break
        else:
            i = len(self.buckets)
        self.counts[i] += n
        self.total += n
        self.sum_ms += value_ms * n
        if value_ms > self.max_ms:
            self.max_ms = value_ms

    def merge_counts(self, counts, total: int, sum_ms: float,
                     max_ms: float) -> None:
        """Fold externally-accumulated bucket counts in (delivery
        workers push cumulative histograms over the control channel;
        the plane diffs consecutive packets and merges the deltas so
        the series stay monotone across worker restarts). Bucket
        bounds must match (delivery/worker.py BUCKETS_MS — pinned by
        test); a shorter/longer list folds positionally."""
        for i, c in enumerate(counts[: len(self.counts)]):
            self.counts[i] += c
        self.total += total
        self.sum_ms += sum_ms
        if max_ms > self.max_ms:
            self.max_ms = max_ms

    def quantile(self, q: float) -> float:
        """Upper-bound estimate of the q-quantile from bucket counts.
        Always finite: a rank landing in the overflow bucket reports
        the maximum observed value (a true upper bound) instead of the
        useless ``+inf`` the outlier regime used to collapse to."""
        if self.total == 0:
            return 0.0
        rank = q * self.total
        seen = 0
        for i, count in enumerate(self.counts):
            seen += count
            if seen >= rank:
                return (
                    self.buckets[i]
                    if i < len(self.buckets)
                    else self.max_ms
                )
        return self.max_ms

    def snapshot(self) -> dict:
        return {
            "count": self.total,
            "mean_ms": (self.sum_ms / self.total) if self.total else 0.0,
            "p50_ms": self.quantile(0.50),
            "p99_ms": self.quantile(0.99),
            "max_ms": self.max_ms,
        }


class Metrics:
    """Process-wide registry; one instance per server."""

    def __init__(self):
        self.started_at = time.time()
        self.counters: defaultdict[str, int] = defaultdict(int)
        self.histograms: dict[str, Histogram] = {}
        self._gauges: dict[str, Callable[[], object]] = {}
        self._lock = threading.Lock()

    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self.counters[name] += by

    def observe_ms(self, name: str, value_ms: float) -> None:
        """Thread-safe: observed from the event loop AND worker threads
        (tick.collect_ms from the collect worker, gc/wal series from
        their own threads). The lock covers BOTH the lazy Histogram
        creation (two racing creators would each keep half the
        observations) and the bucket increments (list writes are
        read-modify-write and can lose updates across threads)."""
        with self._lock:
            hist = self.histograms.get(name)
            if hist is None:
                hist = self.histograms[name] = Histogram()
            hist.observe_ms(value_ms)

    def observe_ms_n(self, name: str, value_ms: float, n: int) -> None:
        """``n`` identical observations under ONE lock acquisition —
        the frame clock closes a whole delivery batch at once (up to
        ``max_batch`` frames); per-frame ``observe_ms`` calls would
        put a 16K-iteration lock loop on the tick path."""
        if n <= 0:
            return
        with self._lock:
            hist = self.histograms.get(name)
            if hist is None:
                hist = self.histograms[name] = Histogram()
            hist.observe_ms_n(value_ms, n)

    def merge_histogram(self, name: str, counts, total: int,
                        sum_ms: float, max_ms: float) -> None:
        """Merge histogram DELTAS accumulated in another process (see
        ``Histogram.merge_counts``). Creating-on-first-merge means a
        worker's series appears in /metrics from its first stats
        packet even before it carried traffic."""
        with self._lock:
            hist = self.histograms.get(name)
            if hist is None:
                hist = self.histograms[name] = Histogram()
            hist.merge_counts(counts, total, sum_ms, max_ms)

    def export_histograms(self, prefixes: tuple[str, ...]) -> dict:
        """Raw cumulative bucket state of every histogram whose name
        starts with one of ``prefixes`` — the shard-side half of the
        cluster metrics federation: snapshots ride the ~1s control
        state packets and the router diffs consecutive packets into
        ``merge_histogram`` deltas (the delivery-worker idiom, now
        process-to-process). Copied under the lock so a concurrent
        observer can't tear a packet."""
        with self._lock:
            return {
                name: {
                    "counts": list(hist.counts),
                    "total": hist.total,
                    "sum_ms": hist.sum_ms,
                    "max_ms": hist.max_ms,
                }
                for name, hist in self.histograms.items()
                if name.startswith(prefixes)
            }

    @contextmanager
    def time_ms(self, name: str):
        """Histogram-timed block: ``with metrics.time_ms("x_ms"): ...``
        observes the block's wall time (including the error path — a
        failing store call still cost that latency)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe_ms(name, (time.perf_counter() - t0) * 1e3)

    def gauge(self, name: str, fn: Callable[[], object]) -> None:
        """Register a pull-style gauge; evaluated at snapshot time."""
        self._gauges[name] = fn

    def set_gauge(self, name: str, value) -> None:
        """Push-style gauge: record the latest value directly. For
        writers with no stable object to pull from — the tick
        batcher's per-flush pipeline depth and compaction bucket are
        snapshots of a moment, not a live view."""
        self._gauges[name] = lambda v=value: v

    def gauge_value(self, name: str):
        """Evaluate ONE registered gauge by name (``None`` when absent
        or broken).  The SLO engine samples floor objectives through
        this instead of rendering the whole registry every tick."""
        fn = self._gauges.get(name)
        if fn is None:
            return None
        try:
            return fn()
        except Exception:  # a broken gauge must not kill slo-eval
            return None

    def _eval_gauges(self) -> dict:
        gauges = {}
        for name, fn in self._gauges.items():
            try:
                gauges[name] = fn()
            except Exception as exc:  # a broken gauge must not kill /metrics
                gauges[name] = f"error: {exc}"
        return gauges

    def snapshot(self) -> dict:
        gauges = self._eval_gauges()
        with self._lock:
            # copy under the lock: a worker thread lazily creating a
            # histogram mid-iteration would otherwise blow up the scrape
            counters = dict(self.counters)
            hists = list(self.histograms.items())
        return {
            "uptime_s": round(time.time() - self.started_at, 3),
            "counters": counters,
            "latency": {name: hist.snapshot() for name, hist in hists},
            "gauges": gauges,
        }

    def render_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4) of the registry —
        what a scraper expects at GET /metrics. Counter/gauge names map
        dots to underscores under a ``wql_`` prefix; histograms emit
        the standard ``_bucket``/``_sum``/``_count`` series (bucket
        bounds in seconds, per convention); dict-valued gauges flatten
        one level, non-numeric leaves are skipped."""
        out: list[str] = []

        def name_of(raw: str) -> str:
            return "wql_" + raw.replace(".", "_").replace("-", "_")

        out.append("# TYPE wql_uptime_seconds gauge")
        out.append(
            f"wql_uptime_seconds {time.time() - self.started_at:.3f}"
        )
        with self._lock:
            counters = sorted(self.counters.items())
            hists = sorted(self.histograms.items())
        for raw, value in counters:
            n = name_of(raw) + "_total"  # Prometheus counter convention
            out.append(f"# TYPE {n} counter")
            out.append(f"{n} {value}")
        for raw, hist in hists:
            # registry names carry '_ms'; the export is in seconds, so
            # swap the unit suffix instead of stacking both
            n = name_of(raw.removesuffix("_ms")) + "_seconds"
            with self._lock:
                # consistent point-in-time copy: a worker observing
                # mid-render must not make +Inf's cumulative count
                # disagree with _count (scrapers reject that)
                counts = list(hist.counts)
                total, sum_ms = hist.total, hist.sum_ms
            out.append(f"# TYPE {n} histogram")
            acc = 0
            for bound, count in zip(hist.buckets, counts):
                acc += count
                out.append(f'{n}_bucket{{le="{bound / 1e3:g}"}} {acc}')
            out.append(f'{n}_bucket{{le="+Inf"}} {total}')
            out.append(f"{n}_sum {sum_ms / 1e3:.6f}")
            out.append(f"{n}_count {total}")
        for raw, value in sorted(self._eval_gauges().items()):
            leaves = (
                {f"{raw}.{k}": v for k, v in value.items()}
                if isinstance(value, dict) else {raw: value}
            )
            for leaf, v in sorted(leaves.items()):
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                n = name_of(leaf)
                out.append(f"# TYPE {n} gauge")
                out.append(f"{n} {v}")
        return "\n".join(out) + "\n"
