"""Metrics registry: counters, latency histograms, and gauges.

The reference's observability is log lines only — no counters, no
health endpoint (SURVEY §5 "Metrics/logging/observability: logging
only"). The rebuild's contract is structured per-tick timing and
engine state, exposed by ``GET /metrics`` (transports/http.py) and
importable for tests.

Single-threaded by design: all writers run on the asyncio loop, so
plain ints suffice (the tick batcher's worker thread reports through
loop-side code). Histograms are fixed log-spaced latency buckets —
cheap, allocation-free, good enough for p50/p99 estimates.
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Callable

# Bucket upper bounds in milliseconds (log-spaced), +inf implicit.
LATENCY_BUCKETS_MS = (
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0,
)


class Histogram:
    __slots__ = ("buckets", "counts", "total", "sum_ms")

    def __init__(self, buckets=LATENCY_BUCKETS_MS):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0
        self.sum_ms = 0.0

    def observe_ms(self, value_ms: float) -> None:
        i = 0
        for i, bound in enumerate(self.buckets):  # noqa: B007
            if value_ms <= bound:
                break
        else:
            i = len(self.buckets)
        self.counts[i] += 1
        self.total += 1
        self.sum_ms += value_ms

    def quantile(self, q: float) -> float:
        """Upper-bound estimate of the q-quantile from bucket counts."""
        if self.total == 0:
            return 0.0
        rank = q * self.total
        seen = 0
        for i, count in enumerate(self.counts):
            seen += count
            if seen >= rank:
                return (
                    self.buckets[i]
                    if i < len(self.buckets)
                    else float("inf")
                )
        return float("inf")

    def snapshot(self) -> dict:
        return {
            "count": self.total,
            "mean_ms": (self.sum_ms / self.total) if self.total else 0.0,
            "p50_ms": self.quantile(0.50),
            "p99_ms": self.quantile(0.99),
        }


class Metrics:
    """Process-wide registry; one instance per server."""

    def __init__(self):
        self.started_at = time.time()
        self.counters: defaultdict[str, int] = defaultdict(int)
        self.histograms: dict[str, Histogram] = {}
        self._gauges: dict[str, Callable[[], object]] = {}

    def inc(self, name: str, by: int = 1) -> None:
        self.counters[name] += by

    def observe_ms(self, name: str, value_ms: float) -> None:
        hist = self.histograms.get(name)
        if hist is None:
            hist = self.histograms[name] = Histogram()
        hist.observe_ms(value_ms)

    def gauge(self, name: str, fn: Callable[[], object]) -> None:
        """Register a pull-style gauge; evaluated at snapshot time."""
        self._gauges[name] = fn

    def snapshot(self) -> dict:
        gauges = {}
        for name, fn in self._gauges.items():
            try:
                gauges[name] = fn()
            except Exception as exc:  # a broken gauge must not kill /metrics
                gauges[name] = f"error: {exc}"
        return {
            "uptime_s": round(time.time() - self.started_at, 3),
            "counters": dict(self.counters),
            "latency": {
                name: hist.snapshot() for name, hist in self.histograms.items()
            },
            "gauges": gauges,
        }
