"""Metrics registry: counters, latency histograms, and gauges.

The reference's observability is log lines only — no counters, no
health endpoint (SURVEY §5 "Metrics/logging/observability: logging
only"). The rebuild's contract is structured per-tick timing and
engine state, exposed by ``GET /metrics`` (transports/http.py) and
importable for tests.

Mostly loop-confined: histogram and gauge writers all run on the
asyncio loop (the WAL writer thread reports via
``call_soon_threadsafe``). Counters are the one exception — the
resilience layer increments failure counters from the ticker's collect
worker thread — so ``inc`` takes a small lock: a read-modify-write on
a plain int can lose updates across threads, and a chaos run's
fault accounting must never under-count. Histograms are fixed
log-spaced latency buckets — cheap, allocation-free, good enough for
p50/p99 estimates.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Callable

# Bucket upper bounds in milliseconds (log-spaced), +inf implicit.
LATENCY_BUCKETS_MS = (
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0,
)


class Histogram:
    __slots__ = ("buckets", "counts", "total", "sum_ms")

    def __init__(self, buckets=LATENCY_BUCKETS_MS):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0
        self.sum_ms = 0.0

    def observe_ms(self, value_ms: float) -> None:
        i = 0
        for i, bound in enumerate(self.buckets):  # noqa: B007
            if value_ms <= bound:
                break
        else:
            i = len(self.buckets)
        self.counts[i] += 1
        self.total += 1
        self.sum_ms += value_ms

    def quantile(self, q: float) -> float:
        """Upper-bound estimate of the q-quantile from bucket counts."""
        if self.total == 0:
            return 0.0
        rank = q * self.total
        seen = 0
        for i, count in enumerate(self.counts):
            seen += count
            if seen >= rank:
                return (
                    self.buckets[i]
                    if i < len(self.buckets)
                    else float("inf")
                )
        return float("inf")

    def snapshot(self) -> dict:
        return {
            "count": self.total,
            "mean_ms": (self.sum_ms / self.total) if self.total else 0.0,
            "p50_ms": self.quantile(0.50),
            "p99_ms": self.quantile(0.99),
        }


class Metrics:
    """Process-wide registry; one instance per server."""

    def __init__(self):
        self.started_at = time.time()
        self.counters: defaultdict[str, int] = defaultdict(int)
        self.histograms: dict[str, Histogram] = {}
        self._gauges: dict[str, Callable[[], object]] = {}
        self._counter_lock = threading.Lock()

    def inc(self, name: str, by: int = 1) -> None:
        with self._counter_lock:
            self.counters[name] += by

    def observe_ms(self, name: str, value_ms: float) -> None:
        hist = self.histograms.get(name)
        if hist is None:
            hist = self.histograms[name] = Histogram()
        hist.observe_ms(value_ms)

    @contextmanager
    def time_ms(self, name: str):
        """Histogram-timed block: ``with metrics.time_ms("x_ms"): ...``
        observes the block's wall time (including the error path — a
        failing store call still cost that latency)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe_ms(name, (time.perf_counter() - t0) * 1e3)

    def gauge(self, name: str, fn: Callable[[], object]) -> None:
        """Register a pull-style gauge; evaluated at snapshot time."""
        self._gauges[name] = fn

    def set_gauge(self, name: str, value) -> None:
        """Push-style gauge: record the latest value directly. For
        writers with no stable object to pull from — the tick
        batcher's per-flush pipeline depth and compaction bucket are
        snapshots of a moment, not a live view."""
        self._gauges[name] = lambda v=value: v

    def _eval_gauges(self) -> dict:
        gauges = {}
        for name, fn in self._gauges.items():
            try:
                gauges[name] = fn()
            except Exception as exc:  # a broken gauge must not kill /metrics
                gauges[name] = f"error: {exc}"
        return gauges

    def snapshot(self) -> dict:
        gauges = self._eval_gauges()
        return {
            "uptime_s": round(time.time() - self.started_at, 3),
            "counters": dict(self.counters),
            "latency": {
                name: hist.snapshot() for name, hist in self.histograms.items()
            },
            "gauges": gauges,
        }

    def render_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4) of the registry —
        what a scraper expects at GET /metrics. Counter/gauge names map
        dots to underscores under a ``wql_`` prefix; histograms emit
        the standard ``_bucket``/``_sum``/``_count`` series (bucket
        bounds in seconds, per convention); dict-valued gauges flatten
        one level, non-numeric leaves are skipped."""
        out: list[str] = []

        def name_of(raw: str) -> str:
            return "wql_" + raw.replace(".", "_").replace("-", "_")

        out.append("# TYPE wql_uptime_seconds gauge")
        out.append(
            f"wql_uptime_seconds {time.time() - self.started_at:.3f}"
        )
        for raw, value in sorted(self.counters.items()):
            n = name_of(raw) + "_total"  # Prometheus counter convention
            out.append(f"# TYPE {n} counter")
            out.append(f"{n} {value}")
        for raw, hist in sorted(self.histograms.items()):
            # registry names carry '_ms'; the export is in seconds, so
            # swap the unit suffix instead of stacking both
            n = name_of(raw.removesuffix("_ms")) + "_seconds"
            out.append(f"# TYPE {n} histogram")
            acc = 0
            for bound, count in zip(hist.buckets, hist.counts):
                acc += count
                out.append(f'{n}_bucket{{le="{bound / 1e3:g}"}} {acc}')
            out.append(f'{n}_bucket{{le="+Inf"}} {hist.total}')
            out.append(f"{n}_sum {hist.sum_ms / 1e3:.6f}")
            out.append(f"{n}_count {hist.total}")
        for raw, value in sorted(self._eval_gauges().items()):
            leaves = (
                {f"{raw}.{k}": v for k, v in value.items()}
                if isinstance(value, dict) else {raw: value}
            )
            for leaf, v in sorted(leaves.items()):
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                n = name_of(leaf)
                out.append(f"# TYPE {n} gauge")
                out.append(f"{n} {v}")
        return "\n".join(out) + "\n"
