"""Columnar query staging: the enqueue-time encode for the tick batch.

The dispatch wall at BENCH_r05 was the host encode: ``dispatch_local_
batch`` re-walked every LocalQuery object in Python (interning dict
probes, row-by-row position fills) before the kernel ever launched —
~10 ms of the 14.5 ms engine p99 against a 5 ms budget. This module
moves that per-query work to MESSAGE-ARRIVAL time, amortized across
the tick window: the router's enqueue writes one row of preallocated
columnar staging arrays (``world_id i32 | pos f64[·,3] | sender_id i32
| repl i8 | kind i8 | par f64[·,PARAM_LANES]``, already interned — and
kind-parsed — through the backend's dicts and the query-kind registry),
and
``flush()`` just flips the double buffer and hands the filled column
views to :meth:`SpatialBackend.dispatch_staged_batch` — zero per-query
Python at flush time. The back buffer fills for tick N+1 while tick N
runs on device, so encode/compute overlap is structural rather than
incidental (TPU-KNN's host-side discipline, arXiv:2206.14286).

Interning contract: the backend's ``(world → id, peer → id)`` dicts
are owned by the event-loop thread (enqueue, subscription mutations and
dispatch all run there) and are append-only for a backend's lifetime,
so an id interned at arrival is still valid at flush. A world or peer
first interned AFTER a message arrived (but inside the same tick
window) resolves to ``-1`` for that message — the same
message-before-subscription race the object-list path has across
ticks, narrowed to one window. Wrappers that can invalidate ids
(robustness/resilient.py rebuilds swap the inner backend, and its
dicts, wholesale) bump :meth:`SpatialBackend.staging_epoch`; the
ticker compares epochs at flush and falls back to the retained
object-list path for that one window.

Buffers grow by power-of-two on demand and shrink with hysteresis: a
capacity is halved only after ``SHRINK_AFTER`` consecutive flushes
used under a quarter of it, so one quiet tick never thrashes a crowd-
sized allocation.

Delta ticks (spatial/delta_ticks.py) ride these columns: a query's
reuse identity is the 128-bit content signature of its staged row
(:func:`row_signatures`, re-exported here as the staging-side half of
the contract), and the staging-epoch check above doubles as the
wholesale invalidation — a window that straddles a backend swap never
reaches the staged (and therefore never the reuse) path at all.
"""

from __future__ import annotations

import numpy as np

from ..queries.kinds import PARAM_LANES
from ..spatial.delta_ticks import row_signatures  # noqa: F401  (re-export)

#: initial (and minimum) rows per buffer
MIN_CAP = 1024
#: consecutive under-quarter-full flushes before a buffer halves
SHRINK_AFTER = 32


class _Buffer:
    __slots__ = ("wid", "pos", "sid", "repl", "kind", "par", "n", "cap",
                 "epoch")

    def __init__(self, cap: int):
        self.alloc(cap)
        self.n = 0
        self.epoch = 0

    def alloc(self, cap: int) -> None:
        self.cap = cap
        self.wid = np.empty(cap, np.int32)
        self.pos = np.empty((cap, 3), np.float64)
        self.sid = np.empty(cap, np.int32)
        self.repl = np.empty(cap, np.int8)
        # query-library lanes (queries/): kind 0 = plain radius row; a
        # non-zero kind reads its parsed f64 parameter lanes from par
        self.kind = np.empty(cap, np.int8)
        self.par = np.empty((cap, PARAM_LANES), np.float64)

    def grow(self) -> None:
        n, cap = self.n, self.cap * 2
        wid, pos, sid, repl = self.wid, self.pos, self.sid, self.repl
        kind, par = self.kind, self.par
        self.alloc(cap)
        self.wid[:n] = wid[:n]
        self.pos[:n] = pos[:n]
        self.sid[:n] = sid[:n]
        self.repl[:n] = repl[:n]
        self.kind[:n] = kind[:n]
        self.par[:n] = par[:n]

    def views(self):
        n = self.n
        return (self.wid[:n], self.pos[:n], self.sid[:n], self.repl[:n],
                self.kind[:n], self.par[:n])


class QueryStaging:
    """Double-buffered columnar staging for one TickBatcher.

    Not thread-safe by design: append (router enqueue), swap (ticker
    flush) and the backend's interning all run on the event loop.
    """

    def __init__(self, backend, initial_cap: int = MIN_CAP):
        self._backend = backend
        self._world_ids, self._peer_ids = backend.interning_maps()
        cap = max(MIN_CAP, int(initial_cap))
        self._bufs = [_Buffer(cap), _Buffer(cap)]
        self._active = 0
        self._under = 0  # consecutive under-quarter-full swaps
        self.swaps = 0
        self.resyncs = 0

    @property
    def count(self) -> int:
        """Rows staged in the active buffer (must equal the ticker's
        queued-message count; a mismatch means a requeue desynced the
        window and the ticker takes the object-list path)."""
        return self._bufs[self._active].n

    @property
    def capacity(self) -> int:
        return self._bufs[self._active].cap

    def append(self, query) -> None:
        """Stage one LocalQuery: intern + write one row of each column.
        This is the per-query work the flush no longer does — paid at
        message-arrival time, on the event loop."""
        buf = self._bufs[self._active]
        if buf.n == 0:
            # ids written into this window are valid for this epoch
            # only; the ticker re-checks at flush
            buf.epoch = self._backend.staging_epoch()
        if buf.n == buf.cap:
            buf.grow()
        i = buf.n
        buf.wid[i] = self._world_ids.get(query.world, -1)
        p = query.position
        buf.pos[i, 0] = p.x
        buf.pos[i, 1] = p.y
        buf.pos[i, 2] = p.z
        buf.sid[i] = self._peer_ids.get(query.sender, -1)
        buf.repl[i] = int(query.replication)
        kind = query.kind
        buf.kind[i] = kind
        if kind:
            params = query.params
            buf.par[i, : len(params)] = params
            buf.par[i, len(params):] = 0.0
        buf.n = i + 1

    def epoch_ok(self) -> bool:
        """Every id in the active window was interned under the
        backend's CURRENT epoch (no resilience rebuild swapped the
        dicts mid-window)."""
        return (
            self._bufs[self._active].epoch
            == self._backend.staging_epoch()
        )

    def swap(self):
        """Flip buffers: returns the filled front buffer's trimmed
        column views for dispatch; the (cleared) back buffer starts
        filling for the next tick. The front views stay untouched until
        the next swap — the dispatch consumes them synchronously, the
        double buffer covers any retained references."""
        front = self._bufs[self._active]
        self._active ^= 1
        back = self._bufs[self._active]
        back.n = 0
        if back.cap < front.cap:
            # keep both buffers on the same capacity tier: tick N+1's
            # crowd is tick N's crowd — pre-sizing the back buffer
            # avoids re-growing through copy-doublings mid-window
            back.alloc(front.cap)
        self.swaps += 1
        self._note_fill(front)
        return front.views()

    def resync(self) -> None:
        """Drop the active window (the ticker is taking the object-list
        path for it) and refresh the interning-map references — after a
        resilience rebuild the maps are NEW dicts on a NEW inner
        backend."""
        self._bufs[self._active].n = 0
        self._world_ids, self._peer_ids = self._backend.interning_maps()
        self.resyncs += 1

    def _note_fill(self, buf: _Buffer) -> None:
        """Shrink hysteresis: both buffers track the shared streak (the
        workload is one stream; the buffers alternate serving it)."""
        if buf.cap > MIN_CAP and buf.n <= buf.cap // 4:
            self._under += 1
            if self._under >= SHRINK_AFTER:
                self._under = 0
                for b in self._bufs:
                    if b.cap > MIN_CAP:
                        # active buffer may already hold rows; never
                        # shrink below them (pow2 tier preserved)
                        floor = max(MIN_CAP, _next_pow2(b.n))
                        if b.cap // 2 >= floor:
                            n, wid, pos, sid, repl = (
                                b.n, b.wid, b.pos, b.sid, b.repl
                            )
                            kind, par = b.kind, b.par
                            b.alloc(b.cap // 2)
                            b.wid[:n] = wid[:n]
                            b.pos[:n] = pos[:n]
                            b.sid[:n] = sid[:n]
                            b.repl[:n] = repl[:n]
                            b.kind[:n] = kind[:n]
                            b.par[:n] = par[:n]
        else:
            self._under = 0

    def stats(self) -> dict:
        return {
            "capacity": self.capacity,
            "staged": self.count,
            "swaps": self.swaps,
            "resyncs": self.resyncs,
        }


def _next_pow2(n: int) -> int:
    return 1 << (max(n, 1) - 1).bit_length()
