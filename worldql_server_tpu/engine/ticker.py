"""Tick batcher: the per-tick device batch at the heart of the rebuild.

The reference resolves every LocalMessage the moment it arrives — one
HashMap probe and one broadcast per message under a global lock
(SURVEY §3.2). With ``tick_interval > 0`` this module instead collects
a tick's worth of LocalMessages and resolves them as ONE device batch
(SpatialBackend.dispatch/collect), then delivers each message's fan-out
in arrival order. Trade: up to one tick of added latency buys
per-batch instead of per-message device cost — the design the
1M-entity target requires (BASELINE.json north star).

Overlap: the dispatch (which reads loop-owned state) runs on the event
loop; the device wait + UUID decode run on a worker thread, so the loop
keeps serving transports while the device crunches. A full queue
(``max_batch``) flushes early. ``tick_interval == 0`` keeps the
reference-equivalent immediate path and never constructs this class.
"""

from __future__ import annotations

import asyncio
import logging
import time

from ..spatial.backend import LocalQuery, SpatialBackend
from ..protocol.types import Message
from .peers import PeerMap

logger = logging.getLogger(__name__)


class TickBatcher:
    def __init__(
        self,
        backend: SpatialBackend,
        peer_map: PeerMap,
        interval: float,
        max_batch: int = 16_384,
        metrics=None,
    ):
        self.backend = backend
        self.peer_map = peer_map
        self.interval = interval
        self.max_batch = max_batch
        self.metrics = metrics
        self._queue: list[tuple[Message, LocalQuery]] = []
        self._task: asyncio.Task | None = None
        self._flushing = asyncio.Lock()
        # stats (exposed via metrics)
        self.ticks = 0
        self.messages = 0
        self.last_batch = 0
        self.last_tick_ms = 0.0
        self.last_resolve_ms = 0.0   # dispatch + device/backend collect
        self.last_deliver_ms = 0.0   # PeerMap.deliver_batch

    def start(self) -> None:
        self._task = asyncio.create_task(self._run(), name="tick-batcher")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        await self.flush()  # drain whatever is left

    async def enqueue(self, message: Message, query: LocalQuery) -> None:
        self._queue.append((message, query))
        if len(self._queue) >= self.max_batch:
            await self.flush()

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                await self.flush()
            except Exception:
                logger.exception("tick flush failed — batch dropped")

    async def flush(self) -> None:
        """Resolve and deliver everything queued so far. Serialized so a
        size-triggered flush can't interleave with the timer's."""
        async with self._flushing:
            batch, self._queue = self._queue, []
            if not batch:
                return
            t0 = time.perf_counter()

            dispatched = False
            deliver_task = None
            try:
                handle = self.backend.dispatch_local_batch(
                    [query for _, query in batch]
                )
                targets = await asyncio.to_thread(
                    self.backend.collect_local_batch, handle
                )
                dispatched = True
                self.last_resolve_ms = (time.perf_counter() - t0) * 1e3
                # One batched delivery: every message's frame goes to
                # its targets' transport buffers synchronously; only
                # saturated/fast-path-less peers cost an await at the
                # end (engine/peers.py deliver_batch). Shielded: a
                # cancel must not abort the awaited (slow-path) tail
                # half-sent — fast-path frames are already in
                # transport buffers and re-sending would duplicate.
                deliver_task = asyncio.ensure_future(
                    self.peer_map.deliver_batch([
                        (message, tgts)
                        for (message, _), tgts in zip(batch, targets)
                        if tgts
                    ])
                )
                await asyncio.shield(deliver_task)
            except asyncio.CancelledError:
                if not dispatched:
                    # stop() landed before the device collect: the
                    # whole batch is still owed — re-queue it for the
                    # drain flush.
                    self._queue = batch + self._queue
                elif deliver_task is not None:
                    # delivery already in flight: let it finish (peers
                    # without a sync fast path — e.g. ZMQ — are only
                    # served by this awaited tail; abandoning it would
                    # silently drop their frames). Shield and re-await
                    # in a loop: a bare `await deliver_task` here would
                    # let a SECOND cancellation cancel the delivery
                    # itself, and suppress(Exception) would abandon the
                    # wait this branch exists to guarantee (ADVICE r5).
                    while not deliver_task.done():
                        try:
                            await asyncio.shield(deliver_task)
                        except asyncio.CancelledError:
                            continue  # repeated cancel — keep waiting
                        except Exception:
                            break  # delivery errors handled by _run
                raise

            self.ticks += 1
            self.messages += len(batch)
            self.last_batch = len(batch)
            self.last_tick_ms = (time.perf_counter() - t0) * 1e3
            self.last_deliver_ms = self.last_tick_ms - self.last_resolve_ms
            if self.metrics is not None:
                self.metrics.observe_ms("tick.flush_ms", self.last_tick_ms)
                self.metrics.observe_ms(
                    "tick.deliver_ms", self.last_deliver_ms
                )
                self.metrics.inc("tick.flushes")
                self.metrics.inc("tick.messages", len(batch))
