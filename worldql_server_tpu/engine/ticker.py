"""Tick batcher: the per-tick device batch at the heart of the rebuild.

The reference resolves every LocalMessage the moment it arrives — one
HashMap probe and one broadcast per message under a global lock
(SURVEY §3.2). With ``tick_interval > 0`` this module instead collects
a tick's worth of LocalMessages and resolves them as ONE device batch
(SpatialBackend.dispatch/collect), then delivers each message's fan-out
in arrival order. Trade: up to one tick of added latency buys
per-batch instead of per-message device cost — the design the
1M-entity target requires (BASELINE.json north star).

Overlap: the dispatch (which reads loop-owned state) runs on the event
loop; the device wait + UUID decode run on a worker thread, so the loop
keeps serving transports while the device crunches. A full queue
(``max_batch``) flushes early. ``tick_interval == 0`` keeps the
reference-equivalent immediate path and never constructs this class.

Pipelining (``pipeline`` > 1, ISSUE 3): ``flush`` splits into a
dispatch stage (on the loop, launches the device batch) and a
collect+deliver stage (a background task: device wait on a worker
thread, then the batched delivery). With the default depth 2 at most
ONE tick is in flight while the next accumulates and dispatches — tick
N+1's device work overlaps tick N's D2H fetch and delivery drain. The
stage tasks CHAIN (each awaits its predecessor before delivering), so
per-peer arrival order is exactly the sequential path's, and ``stop``
awaits the chain instead of cancelling it — the shield/re-queue
guarantees of the sequential flush carry over unchanged.
``pipeline == 1`` (the default) keeps the sequential flush byte for
byte.

Overload governance (``--overload on``, ISSUE 10): with a governor
attached, ``enqueue`` never awaits — a full queue signals the pump
(``_flush_request``) instead of flushing inline, so a slow device
collect cannot head-of-line-block the transport recv loop; admission
(drop-oldest past ``local_queue_cap``) is the only shedding on that
path. Flushes take at most the governor's admitted batch tier, tick
walls feed its deadline-degradation counters, and the entity
neighbor-frame leg skips every other tick while degraded. Without a
governor (the default) every one of those paths is byte-for-byte
today's behavior.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque

from ..observability.spans import NULL_TRACE, Tracer
from ..queries.kinds import KIND_DENSITY, kind_by_id
from ..queries.results import KindResult
from ..queries.wire import build_reply
from ..robustness import failpoints
from ..spatial.backend import LocalQuery, SpatialBackend
from ..protocol.types import Message
from .peers import PeerMap

logger = logging.getLogger(__name__)


class TickBatcher:
    def __init__(
        self,
        backend: SpatialBackend,
        peer_map: PeerMap,
        interval: float,
        max_batch: int = 16_384,
        metrics=None,
        pipeline: int = 1,
        supervisor=None,
        tracer: Tracer | None = None,
        device_telemetry=None,
        staging=None,
        entity_plane=None,
        governor=None,
        cluster=None,
        heatmap=None,
    ):
        self.backend = backend
        # Optional queries.heatmap.RegionHeatmap: density-query results
        # feed it as they fold out of each tick (the wql_region_density
        # gauge and GET /debug/heatmap read it)
        self._heatmap = heatmap
        self.peer_map = peer_map
        self.interval = interval
        self.max_batch = max_batch
        self.metrics = metrics
        # Optional entities.EntityPlane (--entity-sim): every flush
        # ALSO advances the simulation one tick — dispatch on the loop
        # (tick.sim.integrate), device wait + fetch on the worker
        # thread (tick.sim.knn), index churn + frame assembly back on
        # the loop (tick.sim.apply) — and the neighbor frames join the
        # tick's batched delivery. A flush with an empty query batch
        # still ticks the simulation. Sim failures drop only that sim
        # tick, never the flush.
        self._entity_plane = entity_plane
        # Optional engine.staging.QueryStaging: enqueue writes each
        # query into preallocated columnar arrays (interned at arrival
        # time), and flush dispatches the flipped buffer through
        # backend.dispatch_staged_batch with ZERO per-query Python —
        # the encode leg moves off the tick's critical path. None (the
        # default, and always for backends without staged dispatch)
        # keeps the object-list path byte for byte.
        self._staging = staging
        self.staged_flushes = 0
        self.staging_fallbacks = 0
        # Optional robustness.overload.OverloadGovernor (--overload on):
        # enqueue becomes NONBLOCKING (signal the pump instead of
        # awaiting a flush — the admission decision, drop-oldest past
        # local_queue_cap, is the only thing that can shed work on the
        # recv path), flushes take at most the admitted batch tier,
        # each tick wall feeds the deadline-degradation counters, and
        # entity neighbor-frame fan-out skips every other tick while
        # the tier is degraded. None (the default) keeps today's
        # behavior byte for byte, including the size-triggered inline
        # flush and its backpressure.
        self._governor = governor
        # staged columns go stale the moment admission drops or splits
        # the queue (rows no longer line up with queued messages);
        # the flag stops further appends until the next resync/swap
        self._staging_desynced = False
        # Optional cluster.shard.ClusterShardExtension (--cluster-role
        # shard): every flush drains the inter-shard rings BETWEEN the
        # local batch's device dispatch and its collect — the
        # cross-shard collective hides behind the in-flight device
        # window (``cluster.drain`` span) instead of serializing in
        # front of it. None (the default) costs one attribute test per
        # flush.
        self._cluster = cluster
        # Optional observability.device.DeviceTelemetry: after each
        # collect it tags the tick trace with the device timing split
        # (encode/h2d/compute/d2h) and polls the retrace GUARD so a
        # capacity-tier first hit surfaces as a counter + loose span
        # the same tick it happened.
        self._device_telemetry = device_telemetry
        # Span tracing (observability/): every flush opens a "tick"
        # trace whose stage spans the flight recorder ring-buffers.
        # A disabled (or absent) tracer hands back shared null objects
        # — the overhead is one branch per FLUSH, never per message.
        self._tracer = tracer if tracer is not None else Tracer()
        self._tick_seq = 0
        # Optional robustness.Supervisor: the pump runs as a CRITICAL
        # supervised task (restart with backoff; escalate to clean
        # shutdown on budget exhaustion — a server that stopped ticking
        # is deaf to its whole LocalMessage workload), and pipeline
        # stages spawn crash-contained.
        self._sup = supervisor
        self._handle = None
        self.pipeline = max(1, int(pipeline))
        self._queue: deque[tuple[Message, LocalQuery]] = deque()
        self._task: asyncio.Task | None = None
        self._flushing = asyncio.Lock()
        # size-triggered flush request: enqueue SETS it at max_batch
        # and the pump wakes immediately — hitting the cap mid-message
        # must never await a full device flush from inside the recv
        # path (head-of-line blocking, ISSUE 10)
        self._flush_request = asyncio.Event()
        # pipelined collect+deliver stages: _inflight caps the depth,
        # _tail is the chain head the NEXT stage must wait out before
        # delivering (arrival-order guarantee across ticks)
        self._inflight: deque[asyncio.Task] = deque()
        self._tail: asyncio.Task | None = None
        # stats (exposed via metrics)
        self.ticks = 0
        self.messages = 0
        self.last_batch = 0
        self.last_tick_ms = 0.0
        self.last_resolve_ms = 0.0   # dispatch + device/backend collect
        self.last_deliver_ms = 0.0   # PeerMap.deliver_batch
        self.last_dispatch_ms = 0.0  # host encode + device launch
        self.last_collect_ms = 0.0   # device wait + UUID decode
        self.last_compaction_bucket = 0
        # PeerMap.bytes_delivered high-water at the last _account —
        # diffed into the delivery.bytes_per_tick gauge
        self._bytes_mark = 0

    def start(self) -> None:
        if self._sup is not None:
            self._handle = self._sup.spawn(
                "tick-batcher", self._run, critical=True
            )
            return
        self._task = asyncio.create_task(self._run(), name="tick-batcher")  # wql: allow(unsupervised-task)

    async def stop(self) -> None:
        if self._handle is not None:
            await self._handle.stop()
            self._handle = None
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        await self.flush()  # drain in-flight stages + whatever is left
        while self._queue:
            # governed flushes take at most the admitted tier — keep
            # draining until the queue is empty (progress guaranteed:
            # every flush takes >= min_batch >= 1)
            await self.flush()

    def inflight(self) -> int:
        """Dispatched-but-undelivered ticks right now (gauge)."""
        return len(self._inflight)

    async def enqueue(self, message: Message, query: LocalQuery) -> None:
        gov = self._governor
        if gov is not None:
            # Governed ingest (--overload on): NEVER await a flush
            # here — signal the pump and return, so a slow device
            # collect cannot head-of-line-block the transport recv
            # loop. The admission decision is the only shedding:
            # past local_queue_cap the OLDEST queued query drops
            # (the newest position is the freshest work).
            if len(self._queue) >= gov.local_queue_cap():
                self._queue.popleft()
                gov.note_drop_oldest()
                self._staging_desynced = True
            self._queue.append((message, query))  # wql: allow(unbounded-ingest) — capped by local_queue_cap above
            if self._staging is not None and not self._staging_desynced:
                self._staging.append(query)
            gov.note_queue_depth(len(self._queue))
            if len(self._queue) >= self.max_batch:
                self._flush_request.set()
            return
        self._queue.append((message, query))  # wql: allow(unbounded-ingest) — legacy ungoverned path: size cap flushes inline below
        if self._staging is not None:
            # enqueue-time encode: intern + write one staging row NOW,
            # amortized across the tick window; the query object rides
            # the queue purely as the fallback/requeue safety net
            self._staging.append(query)
        if len(self._queue) >= self.max_batch:
            if self.pipeline > 1:
                await self.flush_pipelined()
            else:
                await self.flush()

    async def _run(self) -> None:
        while True:
            # the timer OR a size-triggered flush request, whichever
            # lands first — a full queue flushes immediately without
            # the recv path ever blocking on it
            try:
                await asyncio.wait_for(
                    self._flush_request.wait(), timeout=self.interval
                )
            except asyncio.TimeoutError:
                pass
            self._flush_request.clear()
            # deliberately OUTSIDE the containment below: an armed
            # `ticker.pump` failpoint kills the pump itself, which is
            # how the chaos suite drives supervisor restart/escalation
            failpoints.fire("ticker.pump")
            try:
                if self.pipeline > 1:
                    await self.flush_pipelined()
                else:
                    await self.flush()
            except Exception:
                logger.exception("tick flush failed — batch dropped")

    # region: entity-sim stages (--entity-sim)

    def _sim_dispatch(self, trace):
        """Launch the simulation tick (event-loop thread). Returns the
        collect handle, or None when the plane is idle, a previous sim
        tick is still in flight (pipelined flushes never stack sim
        ticks), or the dispatch failed (logged; the flush proceeds)."""
        plane = self._entity_plane
        if plane is None or not plane.active():
            return None
        try:
            with trace.span("tick.sim.integrate"):
                return plane.dispatch_tick()
        except Exception:
            logger.exception("entity sim dispatch failed — sim tick skipped")
            return None

    def _frame_skip(self, sim_handle) -> bool:
        """The governed frame-leg degradation decision for this tick.
        An interest-managed plane NEVER blind-skips: the governor's
        shed level widens the far-tier cadence (lossless deferral) via
        ``note_governor`` instead — PR 10's alternate-tick drop
        generalized into a cadence policy. Ungoverned or
        interest-off paths keep ``take_frame_skip`` byte for byte."""
        gov = self._governor
        if gov is None or sim_handle is None:
            return False
        plane = self._entity_plane
        interest = getattr(plane, "interest", None)
        if interest is not None:
            interest.note_governor(gov.level, gov.degraded())
            return False
        return gov.take_frame_skip()

    async def _sim_collect_apply(self, sim_handle, trace,
                                 skip_frames: bool = False) -> list:
        """Wait out the sim tick on a worker thread, then integrate it
        back into the host authority on the loop. Returns the tick's
        neighbor-frame delivery pairs; a failed sim tick aborts cleanly
        (host columns stay authoritative) and returns [].
        ``skip_frames`` (deadline degradation) applies the tick —
        positions and index churn always advance — but sheds the
        neighbor-frame fan-out leg."""
        plane = self._entity_plane
        try:
            with trace.span("tick.sim.knn"):
                result = await asyncio.to_thread(
                    plane.collect_tick, sim_handle
                )
            with trace.span("tick.sim.apply"):
                pairs = plane.apply(result, trace, skip_frames=skip_frames)
            interest = plane.interest
            if interest is not None and self.metrics is not None:
                st = interest.stats()
                self.metrics.set_gauge(
                    "frame.delta_ratio", st["delta_ratio"]
                )
                self.metrics.set_gauge("lod", {
                    "near": st["near"], "far": st["far"],
                    "demoted": st["demoted"],
                    "far_every_k": st["far_every_k"],
                })
            return pairs
        except asyncio.CancelledError:
            plane.abort_tick()
            raise
        except Exception:
            plane.abort_tick()
            logger.exception("entity sim tick failed — sim frames dropped")
            return []

    def _take_batch(self) -> list:
        """Drain the pending queue for one flush. Ungoverned: the
        whole queue, exactly as before. Governed: at most the admitted
        batch tier — the remainder stays queued and the pump is
        re-signalled, so a degraded tier serves smaller, deadline-
        fitting ticks instead of one giant bust."""
        queue = self._queue
        gov = self._governor
        if gov is not None:
            admitted = gov.admitted_batch
            if admitted < len(queue):
                batch = [queue.popleft() for _ in range(admitted)]
                self._flush_request.set()  # backlog remains
                return batch
        batch = list(queue)
        queue.clear()
        return batch

    # endregion

    # region: pipelined flush (pipeline > 1)

    async def flush_pipelined(self) -> None:
        """Dispatch everything queued and hand collect+delivery to a
        chained background stage, keeping at most ``pipeline`` ticks
        dispatched-but-undelivered: tick N+1 accumulates and launches
        while tick N's collect runs on the worker thread and its
        delivery drains. A dispatch failure drops the batch (same
        contract as the sequential path's _run handler)."""
        self._reap()
        async with self._flushing:
            batch = self._take_batch()
            plane = self._entity_plane
            sim_on = plane is not None and plane.active()
            if not batch and not sim_on:
                if self._cluster is not None:
                    await self._cluster.drain()
                if self._governor is not None:
                    # idle windows are healthy samples — the governor's
                    # road back to OK once load drops
                    self._governor.note_idle(len(self._queue))
            if batch or sim_on:
                trace = self._begin_trace(len(batch))
                t0 = time.perf_counter()
                # frame clock: opened at flush start (the accumulation
                # window is a config choice, not pipeline latency),
                # closed at delivery completion on whichever path
                t_ingress_ns = time.monotonic_ns()
                sim_handle = self._sim_dispatch(trace)
                skip_frames = self._frame_skip(sim_handle)
                handle = None
                if batch:
                    try:
                        with trace.span("tick.dispatch"):
                            handle = self._dispatch_batch(batch)
                            self.last_dispatch_ms = (
                                time.perf_counter() - t0
                            ) * 1e3
                            if self.metrics is not None:
                                self.metrics.observe_ms(
                                    "tick.dispatch_ms",
                                    self.last_dispatch_ms,
                                )
                    except BaseException:
                        if sim_handle is not None:
                            # the stage task never spawns — release
                            # the un-applied sim tick
                            plane.abort_tick()
                        raise
                if self._cluster is not None:
                    # between dispatch and the stage's collect — the
                    # device window — serialized under the flushing
                    # lock so pipelined stages never interleave drains
                    with trace.span("cluster.drain") as dspan:
                        dspan.tag(frames=await self._cluster.drain())
                stage = self._collect_deliver(
                    batch, handle, self._tail, t0, trace, t_ingress_ns,
                    sim_handle, skip_frames,
                )
                if self._sup is not None:
                    task = self._sup.spawn_transient("tick-collect", stage)
                else:
                    task = asyncio.create_task(stage, name="tick-collect")  # wql: allow(unsupervised-task)
                self._tail = task
                self._inflight.append(task)
        if self.metrics is not None:
            self.metrics.set_gauge(
                "tick.pipeline_inflight", len(self._inflight)
            )
        # backpressure: wait out the oldest stage once the pipeline is
        # full — after this, at most pipeline-1 ticks remain in flight
        # (pipeline=2: one tick overlaps the next accumulation window)
        while len(self._inflight) >= 1 + self.pipeline:
            await self._await_quiet(self._inflight[0])
            self._reap()

    async def _collect_deliver(self, batch, handle, prev, t0, trace,
                               t_ingress_ns: int = 0,
                               sim_handle=None,
                               skip_frames: bool = False) -> None:
        """Stage 2 of a pipelined tick: device collect (worker thread),
        then — strictly after tick N-1's stage finished — the batched
        delivery. Handles its own errors (a failed collect drops only
        ITS batch; the next tick's stage runs untouched) and is never
        cancelled by stop(), which awaits the chain instead."""
        try:
            await self._collect_deliver_inner(
                batch, handle, prev, t0, trace, t_ingress_ns, sim_handle,
                skip_frames,
            )
        finally:
            trace.finish()  # idempotent; seals drop/error paths too

    async def _collect_deliver_inner(
        self, batch, handle, prev, t0, trace, t_ingress_ns: int = 0,
        sim_handle=None, skip_frames: bool = False,
    ) -> None:
        targets = None
        if handle is not None:
            try:
                tc = time.perf_counter()
                with trace.span("tick.collect"):
                    targets = await asyncio.to_thread(
                        self.backend.collect_local_batch, handle
                    )
                    self.last_collect_ms = (time.perf_counter() - tc) * 1e3
                    if self.metrics is not None:
                        self.metrics.observe_ms(
                            "tick.collect_ms", self.last_collect_ms
                        )
                self._note_collect_stats(trace)
            except Exception:
                logger.exception("tick collect failed — batch dropped")
        # entity-sim stage: wait out the sim tick and fold it back into
        # the host authority; its neighbor frames join this tick's
        # batched delivery below. Runs before wait_prev so sim work
        # overlaps the predecessor's delivery drain.
        sim_pairs = []
        if sim_handle is not None:
            sim_pairs = await self._sim_collect_apply(
                sim_handle, trace, skip_frames
            )
        # Arrival order across ticks: tick N-1's deliveries must all
        # complete before ours start — even when our collect finished
        # first (worker threads overlap). Ride out cancellation: the
        # predecessor's delivery is owed regardless.
        if prev is not None:
            with trace.span("tick.wait_prev"):
                while not prev.done():
                    try:
                        await asyncio.shield(prev)
                    except (asyncio.CancelledError, Exception):
                        continue
        if targets is None and not sim_pairs:
            return
        try:
            pairs = self._build_pairs(batch, targets or [])
            pairs.extend(sim_pairs)
            # awaited in place below (shield loop) — not a dangling
            # loop, so it rides outside the supervisor
            deliver_task = asyncio.ensure_future(  # wql: allow(unsupervised-task)
                self.peer_map.deliver_batch(pairs, t_ingress_ns)
            )
            td = time.perf_counter()
            # same shield-and-re-await discipline as the sequential
            # flush: a cancellation must not abort the delivery tail
            # half-sent (fast-path frames are already in transport
            # buffers; re-sending would duplicate)
            with trace.span("tick.deliver"):
                while not deliver_task.done():
                    try:
                        await asyncio.shield(deliver_task)
                    except asyncio.CancelledError:
                        continue
                    except Exception:
                        logger.exception("tick delivery failed")
                        break
            if self._cluster is not None and pairs:
                # close the router-ingress clock (cluster.e2e_ms) for
                # every delivered frame carrying a trace context —
                # socket-write-complete, the conservative PR 7 close
                self._cluster.close_frames(m for m, _ in pairs)
            self._account(
                batch, t0, deliver_ms=(time.perf_counter() - td) * 1e3,
                trace=trace,
            )
        except Exception:
            logger.exception("tick delivery failed — batch dropped")

    def _build_pairs(self, batch, targets) -> list:
        """One tick's delivery pairs. Radius rows pair the original
        message with its fan-out list, exactly as before. Kind rows
        (query library) come back as :class:`KindResult` — each pairs a
        freshly built reply frame (queries/wire.py) with the REQUESTING
        peer, an empty result included (the sender is owed an answer
        either way) — and density rows additionally feed the region
        heatmap. Collect-side per-query list assembly is the existing
        contract; the dispatch path stays loop-free."""
        heatmap = self._heatmap
        pairs = []
        for (message, query), tgts in zip(batch, targets):
            if isinstance(tgts, KindResult):
                kind = kind_by_id(tgts.kind)
                if kind is None:  # unregistered kind staged: reply owed
                    continue  # to nobody — drop, the lint rule guards this
                pairs.append(
                    (build_reply(message, kind, tgts), [query.sender])
                )
                if self.metrics is not None:
                    self.metrics.inc("queries.kind_replies")
                if heatmap is not None and tgts.kind == KIND_DENSITY:
                    heatmap.record(
                        query.world, tgts.extra.get("cubes", ())
                    )
            elif tgts:
                pairs.append((message, tgts))
        return pairs

    def _dispatch_batch(self, batch):
        """Launch one tick's batch: the staged columnar path when the
        staging window is intact (zero per-query Python at flush —
        interning already happened at enqueue), the object-list path
        otherwise. A desynced window (a cancelled flush re-queued its
        batch, so queue and columns disagree) or a stale interning
        epoch (a resilience rebuild swapped the backend's dicts
        mid-window) takes ONE list-path dispatch from the retained
        query objects and resyncs — staging is an optimization, never
        a correctness dependency."""
        st = self._staging
        if st is not None:
            if (
                not self._staging_desynced
                and st.count == len(batch)
                and st.epoch_ok()
            ):
                cols = st.swap()
                self.staged_flushes += 1
                if self.metrics is not None:
                    self.metrics.inc("tick.staged_flushes")
                return self.backend.dispatch_staged_batch(
                    *cols, fallback=batch
                )
            st.resync()
            self._staging_desynced = False
            self.staging_fallbacks += 1
            if self.metrics is not None:
                self.metrics.inc("tick.staging_fallbacks")
        return self.backend.dispatch_local_batch(
            [query for _, query in batch]
        )

    def _reap(self) -> None:
        while self._inflight and self._inflight[0].done():
            self._inflight.popleft()

    @staticmethod
    async def _await_quiet(task: asyncio.Task) -> None:
        """Wait for a stage task without cancelling it and without
        letting its (already-logged) errors escape. Our own
        cancellation propagates once the task is done — the in-flight
        batch is owed its delivery first."""
        cancelled = False
        while not task.done():
            try:
                await asyncio.shield(task)
            except asyncio.CancelledError:
                cancelled = True
            except Exception:
                break
        if cancelled:
            raise asyncio.CancelledError

    async def _drain_inflight(self) -> None:
        while self._inflight:
            await self._await_quiet(self._inflight[0])
            self._reap()

    # endregion

    async def flush(self) -> None:
        """Resolve and deliver everything queued so far. Serialized so a
        size-triggered flush can't interleave with the timer's. In
        pipelined mode any in-flight stage is waited out FIRST, so the
        drained queue delivers after it (stop()'s exactly-once drain
        keeps cross-tick arrival order)."""
        await self._drain_inflight()
        async with self._flushing:
            batch = self._take_batch()
            plane = self._entity_plane
            sim_on = plane is not None and plane.active()
            if not batch and not sim_on:
                if self._cluster is not None:
                    # no local work this window — the inter-shard
                    # rings still owe their drain on the tick clock
                    await self._cluster.drain()
                if self._governor is not None:
                    self._governor.note_idle(len(self._queue))
                return
            trace = self._begin_trace(len(batch))
            t0 = time.perf_counter()
            t_ingress_ns = time.monotonic_ns()  # frame clock (see above)

            dispatched = not batch
            deliver_task = None
            sim_handle = self._sim_dispatch(trace)
            skip_frames = self._frame_skip(sim_handle)
            try:
                targets = []
                if batch:
                    td = time.perf_counter()
                    with trace.span("tick.dispatch"):
                        handle = self._dispatch_batch(batch)
                        self.last_dispatch_ms = (
                            time.perf_counter() - td
                        ) * 1e3
                        if self.metrics is not None:
                            self.metrics.observe_ms(
                                "tick.dispatch_ms", self.last_dispatch_ms
                            )
                if self._cluster is not None:
                    # cross-shard leg INSIDE the device window: the
                    # local batch (and sim tick) are already in flight
                    # on device while the inter-shard rings drain —
                    # the collective hides behind per-shard compute
                    with trace.span("cluster.drain") as dspan:
                        dspan.tag(frames=await self._cluster.drain())
                if batch:
                    tc = time.perf_counter()
                    with trace.span("tick.collect"):
                        targets = await asyncio.to_thread(
                            self.backend.collect_local_batch, handle
                        )
                        dispatched = True
                        self.last_collect_ms = (
                            time.perf_counter() - tc
                        ) * 1e3
                        self.last_resolve_ms = (
                            time.perf_counter() - t0
                        ) * 1e3
                        if self.metrics is not None:
                            self.metrics.observe_ms(
                                "tick.collect_ms", self.last_collect_ms
                            )
                    self._note_collect_stats(trace)
                pairs = self._build_pairs(batch, targets)
                if sim_handle is not None:
                    pairs.extend(
                        await self._sim_collect_apply(
                            sim_handle, trace, skip_frames
                        )
                    )
                # One batched delivery: every message's frame goes to
                # its targets' transport buffers synchronously; only
                # saturated/fast-path-less peers cost an await at the
                # end (engine/peers.py deliver_batch). Shielded: a
                # cancel must not abort the awaited (slow-path) tail
                # half-sent — fast-path frames are already in
                # transport buffers and re-sending would duplicate.
                deliver_task = asyncio.ensure_future(  # wql: allow(unsupervised-task)
                    self.peer_map.deliver_batch(pairs, t_ingress_ns)
                )
                with trace.span("tick.deliver"):
                    await asyncio.shield(deliver_task)
                if self._cluster is not None and pairs:
                    # cluster.e2e_ms close at socket-write-complete
                    # (see _collect_deliver_inner)
                    self._cluster.close_frames(m for m, _ in pairs)
            except asyncio.CancelledError:
                if sim_handle is not None:
                    # un-applied sim tick (cancel landed before or
                    # inside the sim stage): drop it cleanly — the
                    # host columns stay authoritative. Idempotent if
                    # the sim stage already applied or aborted.
                    plane.abort_tick()
                if not dispatched:
                    # stop() landed before the device collect: the
                    # whole batch is still owed — re-queue it for the
                    # drain flush.
                    self._queue.extendleft(reversed(batch))
                elif deliver_task is not None:
                    # delivery already in flight: let it finish (peers
                    # without a sync fast path — e.g. ZMQ — are only
                    # served by this awaited tail; abandoning it would
                    # silently drop their frames). Shield and re-await
                    # in a loop: a bare `await deliver_task` here would
                    # let a SECOND cancellation cancel the delivery
                    # itself, and suppress(Exception) would abandon the
                    # wait this branch exists to guarantee (ADVICE r5).
                    while not deliver_task.done():
                        try:
                            await asyncio.shield(deliver_task)
                        except asyncio.CancelledError:
                            continue  # repeated cancel — keep waiting
                        except Exception:
                            break  # delivery errors handled by _run
                raise
            except Exception:
                if sim_handle is not None:
                    # a dispatch/collect error escapes to _run's
                    # containment; the un-applied sim tick must not
                    # stay "in flight" forever (idempotent)
                    plane.abort_tick()
                raise

            self._account(batch, t0, trace=trace)

    def _begin_trace(self, batch_size: int):
        """Open this flush's "tick" trace (the shared null trace when
        tracing is off — one branch inside Tracer.begin, per flush)."""
        self._tick_seq += 1
        trace = self._tracer.begin(
            "tick", tick=self._tick_seq, batch=batch_size,
            inflight=len(self._inflight), pipeline=self.pipeline,
        )
        if self._governor is not None:
            # overload state rides every tick trace: a slow-tick dump
            # answers "was the governor shedding?" without a scrape
            trace.tag(overload=self._governor.state)
        if trace is not NULL_TRACE:
            stats_fn = getattr(self.backend, "device_stats", None)
            if stats_fn is not None:
                try:
                    trace.tags["device_stats_at_dispatch"] = {
                        k: v for k, v in stats_fn().items()
                        if isinstance(v, (int, float))
                    }
                except Exception:
                    pass  # diagnostics must never cost the tick
        return trace

    def _account(
        self, batch, t0, deliver_ms: float | None = None, trace=NULL_TRACE,
    ) -> None:
        self.ticks += 1
        self.messages += len(batch)
        self.last_batch = len(batch)
        self.last_tick_ms = (time.perf_counter() - t0) * 1e3
        self.last_deliver_ms = (
            deliver_ms if deliver_ms is not None
            else self.last_tick_ms - self.last_resolve_ms
        )
        if self.metrics is not None:
            # whole-tick accounting: the enclosing "tick" root trace IS
            # the span for these two series
            self.metrics.observe_ms("tick.flush_ms", self.last_tick_ms)  # wql: allow(unspanned-stage)
            self.metrics.observe_ms("tick.deliver_ms", self.last_deliver_ms)  # wql: allow(unspanned-stage)
            self.metrics.inc("tick.flushes")
            self.metrics.inc("tick.messages", len(batch))
            # delivered wire bytes attributable to THIS flush: the
            # PeerMap counter diffed across consecutive accounts (both
            # flush variants route here after their delivery settles)
            bd = getattr(self.peer_map, "bytes_delivered", 0)
            self.metrics.set_gauge(
                "delivery.bytes_per_tick", bd - self._bytes_mark
            )
            self._bytes_mark = bd
        if self._governor is not None:
            self._governor.note_tick(self.last_tick_ms, len(self._queue))
        trace.tag(tick_ms=round(self.last_tick_ms, 3))
        trace.finish()

    def _note_collect_stats(self, trace=NULL_TRACE) -> None:
        """Pull the backend's per-collect transfer stats (what the D2H
        fetch actually shipped, and whether the on-device compaction
        packed it) into the metrics registry and the tick trace.
        Backends without the stats (CPU reference) are silently
        skipped."""
        stats = getattr(self.backend, "last_collect_stats", None)
        if stats:
            self.last_compaction_bucket = int(
                stats.get("compaction_bucket", 0)
            )
            if self.metrics is not None:
                self.metrics.inc(
                    "tick.fetch_bytes", int(stats.get("fetch_bytes", 0))
                )
                # NOT also pushed as a set_gauge here: the server's
                # registered ``tick`` gauge dict already exports
                # ``last_compaction_bucket`` under the SAME flattened
                # name, and two exporters made /metrics emit a
                # duplicate # TYPE the strict parser rejects
            trace.tag(
                fetch_bytes=int(stats.get("fetch_bytes", 0)),
                compaction_bucket=self.last_compaction_bucket,
            )
        # delta ticks (spatial/delta_ticks.py): the dispatch's reuse
        # partition rides the tick trace as `tick.delta` tags and the
        # delta.* counter series — reused/recomputed query counts,
        # churn rows consumed, and the fallback reason when the batch
        # bypassed reuse entirely
        delta = getattr(self.backend, "last_delta_stats", None)
        if delta:
            trace.tag(delta=dict(delta))
            if self.metrics is not None:
                self.metrics.inc(
                    "delta.query_reused", int(delta.get("reused", 0))
                )
                self.metrics.inc(
                    "delta.query_recomputed",
                    int(delta.get("recomputed", 0)),
                )
                if delta.get("fallback"):
                    self.metrics.inc("delta.query_fallbacks")
        if self._device_telemetry is not None:
            # device timing split onto the tick root + retrace poll;
            # diagnostics must never cost the tick
            try:
                self._device_telemetry.on_tick(trace)
            except Exception:
                logger.exception("device telemetry tick hook failed")
