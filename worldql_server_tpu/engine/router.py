"""Instruction router: the processing layer.

Python rebuild of the reference's processing thread + handlers
(worldql_server/src/processing/). Dispatch table follows
thread.rs:72-108: heartbeats are handled inline; subscription ops and
pub/sub messages hit the spatial backend; record ops go through the
durability frontend (worldql_server_tpu/durability) — inline store
awaits in off mode, WAL + write-behind in wal/sync modes.
Client-bound instructions (Handshake, PeerConnect/Disconnect,
RecordReply) arriving inbound are dropped with a warning — the
reference panics (thread.rs:74-79), but a client must never be able to
kill the server, so we log instead.

Every handler is wrapped in per-message error isolation: a hostile
payload (e.g. NaN positions overflowing quantization) drops that
message, never the server.
"""

from __future__ import annotations

import logging
import time
import uuid as uuid_mod

from ..durability.pipeline import DurabilityPipeline
from ..queries.kinds import KIND_DENSITY, kind_by_id
from ..queries.results import KindResult
from ..queries.wire import build_reply, parse_query_message
from ..robustness import failpoints
from ..protocol import Instruction, Message, Replication
from ..spatial.backend import LocalQuery, SpatialBackend
from ..storage.store import RecordStore
from ..utils.names import GLOBAL_WORLD, SanitizeError, sanitize_world_name
from ..utils.timeutil import parse_epoch_millis
from ..utils.trace import trace_packet
from .peers import PeerMap

logger = logging.getLogger(__name__)

NIL = uuid_mod.UUID(int=0)

# Counter names precomputed: no per-message string building on the hot path.
_MSG_COUNTERS = {i: f"messages.{i.name.lower()}" for i in Instruction}


class Router:
    def __init__(
        self,
        peer_map: PeerMap,
        backend: SpatialBackend,
        store: RecordStore,
        ticker=None,
        metrics=None,
        durability: DurabilityPipeline | None = None,
        tracer=None,
        entity_plane=None,
        governor=None,
        query_limits=None,
        heatmap=None,
    ):
        self.peer_map = peer_map
        self.backend = backend
        self.store = store
        # Optional queries.kinds.QueryLimits: with limits set, a
        # LocalMessage whose parameter names a registered query kind
        # (query.cone / query.raycast / query.knn / query.density)
        # parses into kind + parameter lanes here, at ingest. None =
        # query library off — those parameters route as plain radius
        # messages, byte for byte the pre-library pipeline.
        self.query_limits = query_limits
        # Optional queries.heatmap.RegionHeatmap for the immediate
        # (tickerless) path's density results; the ticker feeds it on
        # the batched path.
        self.heatmap = heatmap
        # Optional TickBatcher: LocalMessages queue for a per-tick device
        # batch instead of resolving immediately (engine/ticker.py).
        self.ticker = ticker
        self.metrics = metrics
        # Optional entities.EntityPlane (--entity-sim): a Local/Global-
        # Message whose `entities` list is non-empty is an entity
        # registration/update batch for the simulation plane, consumed
        # here instead of fanning out as pub/sub.
        self.entity_plane = entity_plane
        # Optional observability.Tracer: per-message handle spans with
        # the instruction as tag. One `enabled` branch per message when
        # off — same budget as the trace_packet call below.
        self.tracer = tracer
        # Optional robustness.overload.OverloadGovernor (--overload
        # on): priority-classed admission at THE ingest choke point —
        # record ops are never shed, GlobalMessages shed last (REJECT
        # only), LocalMessages shed drop-oldest at the ticker queue,
        # entity updates coalesce in the plane, and per-peer token
        # buckets keep one chatty client from starving the rest. None
        # (the default) is zero-cost: one attribute test per message.
        self.governor = governor
        # Every record op goes through the durability frontend — never
        # `await self.store.…` directly (tools/check: store-on-loop).
        # Without an injected pipeline, an off-mode pass-through keeps
        # the reference-equivalent inline-store behavior.
        self.durability = (
            durability if durability is not None
            else DurabilityPipeline(store, mode="off")
        )

    async def handle_message(self, message: Message) -> None:
        """Route one inbound message (thread.rs:72-108). Never raises."""
        # Single choke point == the reference's trace_packet! call at
        # the top of every handler (e.g. heartbeat.rs:10).
        trace_packet(message)
        if self.metrics is not None:
            self.metrics.inc(_MSG_COUNTERS[message.instruction])
        tracer = self.tracer
        try:
            if tracer is not None and tracer.enabled:
                with tracer.span(
                    "router.handle", type=message.instruction.name
                ):
                    await self._dispatch(message)
            else:
                await self._dispatch(message)
        except Exception:
            if self.metrics is not None:
                self.metrics.inc("messages.errors")
            logger.exception(
                "error handling %s from %s — message dropped",
                message.instruction.name,
                message.sender_uuid,
            )

    async def _dispatch(self, message: Message) -> None:
        # handler-boundary fault injection: fires INSIDE
        # handle_message's containment, so an armed `router.dispatch`
        # drops this message (counted in messages.errors), never more
        failpoints.fire("router.dispatch")
        instruction = message.instruction

        governor = self.governor
        if governor is not None:
            is_entity = (
                self.entity_plane is not None
                and bool(message.entities)
                and instruction in (
                    Instruction.LOCAL_MESSAGE, Instruction.GLOBAL_MESSAGE
                )
            )
            if not governor.admit(
                instruction, message.sender_uuid, is_entity
            ):
                return  # shed — already classified and counted

        if instruction == Instruction.HEARTBEAT:
            await self._heartbeat(message)
        elif instruction == Instruction.AREA_SUBSCRIBE:
            self._area_subscribe(message)
        elif instruction == Instruction.AREA_UNSUBSCRIBE:
            self._area_unsubscribe(message)
        elif instruction == Instruction.LOCAL_MESSAGE:
            await self._local_message(message)
        elif instruction == Instruction.GLOBAL_MESSAGE:
            await self._global_message(message)
        elif instruction == Instruction.RECORD_CREATE:
            await self._record_create(message)
        elif instruction == Instruction.RECORD_READ:
            await self._record_read(message)
        elif instruction == Instruction.RECORD_UPDATE:
            # The reference leaves this unimplemented (thread.rs:168,
            # `todo!()`). Store inserts are append-with-dedupe-on-read,
            # so update == create; implemented rather than crashing.
            await self._record_create(message)
        elif instruction == Instruction.RECORD_DELETE:
            await self._record_delete(message)
        elif instruction in (
            Instruction.HANDSHAKE,
            Instruction.PEER_CONNECT,
            Instruction.PEER_DISCONNECT,
            Instruction.RECORD_REPLY,
        ):
            logger.warning(
                "client-bound instruction %s received from %s — dropped",
                instruction.name,
                message.sender_uuid,
            )
        else:
            logger.warning(
                "Unknown instruction received from %s", message.sender_uuid
            )

    # region: heartbeat (processing/heartbeat.rs:9-44)

    async def _heartbeat(self, message: Message) -> None:
        peer = self.peer_map.get(message.sender_uuid)
        if peer is None:
            logger.warning("missing peer for heartbeat: %s", message.sender_uuid)
            return
        peer.update_last_heartbeat()
        await peer.send(message.with_(sender_uuid=NIL))

    # endregion

    # region: subscriptions (processing/area_subscribe.rs, area_unsubscribe.rs)

    def _sanitize_or_log(self, message: Message) -> str | None:
        try:
            return sanitize_world_name(message.world_name)
        except SanitizeError as exc:
            logger.warning(
                "peer %s sent invalid world name: %s (%s)",
                message.sender_uuid,
                message.world_name,
                exc,
            )
            return None

    def _area_subscribe(self, message: Message) -> None:
        if message.world_name == GLOBAL_WORLD:
            return
        world = self._sanitize_or_log(message)
        if world is None:
            return
        if message.position is None:
            logger.debug(
                "invalid AreaSubscribe from %s, missing position",
                message.sender_uuid,
            )
            return
        self.backend.add_subscription(world, message.sender_uuid, message.position)

    def _area_unsubscribe(self, message: Message) -> None:
        if message.world_name == GLOBAL_WORLD:
            return
        world = self._sanitize_or_log(message)
        if world is None:
            return
        if message.position is None:
            logger.debug(
                "invalid AreaUnsubscribe from %s, missing position",
                message.sender_uuid,
            )
            return
        self.backend.remove_subscription(
            world, message.sender_uuid, message.position
        )

    # endregion

    # region: pub/sub fan-out (processing/local_message.rs, global_message.rs)

    def _entity_ingest(self, message: Message) -> bool:
        """Entity-sim control plane: in --entity-sim mode a Local/
        GlobalMessage carrying entities registers/updates them (or
        removes, parameter 'entity.remove') and is consumed — the
        reference carries the field but never uses it (SURVEY
        "What's missing" #3). Returns True when consumed."""
        if self.entity_plane is None or not message.entities:
            return False
        applied = self.entity_plane.ingest(message)
        if self.metrics is not None:
            self.metrics.inc("messages.entity_batches")
            if applied:
                self.metrics.inc("messages.entity_ops", applied)
        return True

    async def _local_message(self, message: Message) -> None:
        if self._entity_ingest(message):
            return
        if message.world_name == GLOBAL_WORLD:
            logger.debug(
                "invalid LocalMessage from %s, uses @global", message.sender_uuid
            )
            return
        if message.position is None:
            logger.debug(
                "invalid LocalMessage from %s, missing position",
                message.sender_uuid,
            )
            return
        world = self._sanitize_or_log(message)
        if world is None:
            return

        kind_id, params = 0, ()
        if self.query_limits is not None and message.parameter:
            try:
                parsed = parse_query_message(message, self.query_limits)
            except ValueError as exc:
                # hostile/malformed payload: drop THIS message with a
                # log line — the sender keeps its session, the tick
                # keeps its budget
                logger.warning(
                    "malformed %s from %s dropped: %s",
                    message.parameter, message.sender_uuid, exc,
                )
                if self.metrics is not None:
                    self.metrics.inc("queries.malformed")
                return
            if parsed is not None:
                kind_id = parsed[0].kind
                params = parsed[1]
                if self.metrics is not None:
                    self.metrics.inc("queries.kind_requests")

        query = LocalQuery(
            world=world,
            position=message.position,
            sender=message.sender_uuid,
            replication=message.replication,
            kind=kind_id,
            params=params,
        )
        if self.ticker is not None:
            # frame clock for batched mode opens at ticker flush start
            # (engine/ticker.py) — the accumulation window is a config
            # choice, not pipeline latency
            await self.ticker.enqueue(message, query)
            return
        # Immediate mode: the frame clock spans this handler's own
        # resolve + broadcast — the same dispatch→write-complete window
        # the ticker path reports, so frame.e2e_ms is comparable across
        # tick_interval settings.
        t_ingress_ns = time.monotonic_ns()
        [targets] = self.backend.match_local_batch([query])
        if isinstance(targets, KindResult):
            await self._deliver_kind_result(
                message, query, targets, t_ingress_ns
            )
            return
        if targets:
            await self.peer_map.broadcast_to(message, targets)
            if self.metrics is not None:
                self.metrics.observe_ms(
                    "frame.e2e_ms",
                    (time.monotonic_ns() - t_ingress_ns) / 1e6,
                )

    async def _deliver_kind_result(
        self, message: Message, query: LocalQuery, result: KindResult,
        t_ingress_ns: int,
    ) -> None:
        """Immediate-mode tail of a kind query: reply frame back to the
        requesting peer (an empty result included — the sender is owed
        an answer either way), density results into the heatmap."""
        kind = kind_by_id(result.kind)
        if kind is None:
            return
        if self.heatmap is not None and result.kind == KIND_DENSITY:
            self.heatmap.record(query.world, result.extra.get("cubes", ()))
        if self.metrics is not None:
            self.metrics.inc("queries.kind_replies")
        await self.peer_map.broadcast_to(
            build_reply(message, kind, result), [query.sender]
        )
        if self.metrics is not None:
            self.metrics.observe_ms(
                "frame.e2e_ms",
                (time.monotonic_ns() - t_ingress_ns) / 1e6,
            )

    async def _global_message(self, message: Message) -> None:
        if self._entity_ingest(message):
            return
        sender = message.sender_uuid
        if message.world_name == GLOBAL_WORLD:
            # World-wide broadcast to every connected peer
            # (global_message.rs:18-35).
            if message.replication == Replication.EXCEPT_SELF:
                await self.peer_map.broadcast_except(message, sender)
            elif message.replication == Replication.INCLUDING_SELF:
                await self.peer_map.broadcast_all(message)
            else:  # ONLY_SELF
                peer = self.peer_map.get(sender)
                if peer is None:
                    logger.warning("missing peer %s for GlobalMessage send", sender)
                    return
                await peer.send(message)
            return

        world = self._sanitize_or_log(message)
        if world is None:
            return
        peers = self.backend.query_world(world)
        if message.replication == Replication.EXCEPT_SELF:
            targets = [p for p in peers if p != sender]
        elif message.replication == Replication.ONLY_SELF:
            targets = [p for p in peers if p == sender]
        else:
            targets = list(peers)
        if targets:
            await self.peer_map.broadcast_to(message, targets)

    # endregion

    # region: records (processing/record_create.rs, record_read.rs, record_delete.rs)

    async def _record_create(self, message: Message) -> None:
        if message.world_name == GLOBAL_WORLD:
            return
        try:
            await self.durability.insert_records(message.records)
        except Exception as exc:
            logger.warning(
                "error inserting records for %s: %s", message.sender_uuid, exc
            )

    async def _record_delete(self, message: Message) -> None:
        if message.world_name == GLOBAL_WORLD:
            return
        try:
            await self.durability.delete_records(message.records)
        except Exception as exc:
            logger.warning(
                "error deleting records for %s: %s", message.sender_uuid, exc
            )

    async def _record_read(self, message: Message) -> None:
        """Region read + newest-per-uuid dedupe + read-repair
        (record_read.rs:11-135)."""
        if message.world_name == GLOBAL_WORLD:
            return
        sender = message.sender_uuid

        if message.position is None:
            # Reference: todo!() (record_read.rs:135). We log and drop.
            logger.warning(
                "RecordRead without position from %s not supported", sender
            )
            return

        after = None
        if message.parameter is not None:
            try:
                after = parse_epoch_millis(message.parameter)
            except ValueError as exc:
                logger.warning("error parsing timestamp for %s: %s", sender, exc)
                return

        try:
            # The durability frontend gives read-your-writes: in wal
            # mode it flushes pending ops for this region first.
            rows = await self.durability.get_records_in_region(
                message.world_name, message.position, after
            )
        except Exception as exc:
            logger.warning("error getting records for %s: %s", sender, exc)
            return
        if not rows:
            return

        # Deduplicate: newest row per record uuid (record_read.rs:61-81).
        newest: dict[uuid_mod.UUID, tuple] = {}
        for sr in rows:
            existing = newest.get(sr.record.uuid)
            if existing is None or sr.timestamp >= existing[0]:
                newest[sr.record.uuid] = (sr.timestamp, sr.record)

        dedupe_ops = [
            (rec.uuid, ts, rec.world_name, rec.position)
            for ts, rec in newest.values()
            if rec.position is not None
        ]
        records = [rec for _, rec in newest.values()]

        reply = Message(
            instruction=Instruction.RECORD_REPLY,
            world_name=message.world_name,
            records=records,
        )
        peer = self.peer_map.get(sender)
        if peer is None:
            logger.warning("missing peer %s for RecordReply send", sender)
            return
        try:
            await peer.send(reply)
        except Exception as exc:
            logger.debug("RecordReply send failed: %s", exc)

        # Read-repair in the background path (record_read.rs:126-130).
        try:
            await self.durability.dedupe_records(dedupe_ops)
        except Exception as exc:
            logger.warning("error deduping records for %s: %s", sender, exc)

    # endregion
