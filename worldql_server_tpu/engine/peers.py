"""Peer registry and broadcast hub.

Python rebuild of the reference's Peer/PeerMap
(worldql_server/src/transport/peer.rs, peer_map.rs). One asyncio event
loop replaces the Rust ``Arc<RwLock<PeerMap>>``: map mutations are
atomic between awaits, and broadcasts serialize the message once then
fan out concurrently (peer_map.rs:22-40).

Transports supply an async ``send_raw(bytes)`` and may mark themselves
heartbeat-tracked (ZeroMQ-style, staleness-swept) or not
(WebSocket-style, liveness == stream health; peer.rs:59-69).
"""

from __future__ import annotations

import asyncio
import logging
import time
import uuid as uuid_mod
from typing import Awaitable, Callable, Iterable

from ..protocol import Instruction, Message, serialize_message

logger = logging.getLogger(__name__)

SendRaw = Callable[[bytes], Awaitable[None]]
OnRemove = Callable[[uuid_mod.UUID], None]


class PeerSendError(Exception):
    pass


class FramedPayload:
    """One serialized Message shared across every recipient of a
    broadcast. ``payload`` is the wire bytes; ``cache`` holds
    transport-framed variants (e.g. the complete WebSocket frame) so a
    message delivered to N same-transport peers frames ONCE, not N
    times — server→client WS frames are unmasked and therefore
    byte-identical for every recipient."""

    __slots__ = ("payload", "cache", "ctx")

    def __init__(self, payload: bytes):
        self.payload = payload
        self.cache: dict[str, bytes] = {}
        # Cluster trace context (trace_id, t_router_ingress_ns) copied
        # from Message.trace_ctx at framing time, so a shard's ring
        # proxy can thread it onto the inter-shard bus and the REMOTE
        # shard closes the same router-ingress clock at its own socket
        # write. None everywhere outside a cluster shard.
        self.ctx: tuple | None = None


#: synchronous fast-path writer a transport may attach to its peers:
#: returns True when the frame was handed to the transport's buffer
#: without awaiting (the hot path for per-tick fan-out), False to fall
#: back to the awaited ``send_raw`` (saturated buffer, closing, or the
#: transport has no sync path)
TryWrite = Callable[[FramedPayload], bool]

#: batch variant: hand a peer's whole per-tick frame list to the
#: transport in one write (writev-style) — all or nothing
TryWriteMany = Callable[[list[FramedPayload]], bool]


class Peer:
    """Uniform outbound handle over any transport (peer.rs:33-88)."""

    __slots__ = ("uuid", "addr", "kind", "_send_raw", "_try_write",
                 "_try_write_many", "tracks_heartbeat", "last_heartbeat",
                 "closed", "shard", "slot")

    def __init__(
        self,
        uuid: uuid_mod.UUID,
        addr: str,
        send_raw: SendRaw,
        kind: str = "unknown",
        tracks_heartbeat: bool = False,
        try_write: TryWrite | None = None,
        try_write_many: TryWriteMany | None = None,
    ):
        self.uuid = uuid
        self.addr = addr
        self.kind = kind
        self._send_raw = send_raw
        self._try_write = try_write
        self._try_write_many = try_write_many
        self.tracks_heartbeat = tracks_heartbeat
        self.last_heartbeat = time.monotonic()
        self.closed = False
        # Delivery-plane ownership (delivery/plane.py adopt): the
        # sender-worker shard and per-shard socket slot this peer's
        # frames route to. None = parent-owned (single-process mode,
        # or degraded fallback) — the write paths above are then the
        # transport's own.
        self.shard: int | None = None
        self.slot: int | None = None

    def update_last_heartbeat(self) -> None:
        self.last_heartbeat = time.monotonic()

    def is_stale(self, now: float, max_age_secs: float) -> bool:
        """Heartbeat-tracked peers go stale; stream peers never do
        (peer.rs:59-69)."""
        if not self.tracks_heartbeat:
            return False
        return (now - self.last_heartbeat) > max_age_secs

    async def send(self, message: Message) -> None:
        await self.send_raw(serialize_message(message))

    async def send_raw(self, data: bytes) -> None:
        if self.closed:
            raise PeerSendError(f"peer {self.uuid} is closed")
        try:
            await self._send_raw(data)
        except Exception as exc:
            raise PeerSendError(str(exc)) from exc

    def try_write(self, framed: FramedPayload) -> bool:
        """Synchronous fast-path delivery; False = use ``send_raw``."""
        if self.closed or self._try_write is None:
            return False
        return self._try_write(framed)

    def try_write_many(self, framed_list: list[FramedPayload]) -> bool:
        """One coalesced write of a whole per-tick frame list; False =
        deliver each frame via ``send_raw`` instead."""
        if self.closed:
            return False
        if self._try_write_many is not None:
            return self._try_write_many(framed_list)
        if self._try_write is not None and len(framed_list) == 1:
            return self._try_write(framed_list[0])
        return False

    def __repr__(self) -> str:
        return f"Peer({self.kind}, {self.uuid}, {self.addr})"


class PeerMap:
    """UUID → Peer registry + broadcast primitives (peer_map.rs:16-176).

    ``on_remove`` mirrors the reference's remove channel
    (peer_map.rs:139): the engine hooks it to purge the spatial index
    when a peer disconnects.
    """

    def __init__(self, on_remove: OnRemove | None = None, metrics=None,
                 plane=None, sessions=None):
        self._map: dict[uuid_mod.UUID, Peer] = {}
        self._on_remove = on_remove
        self.metrics = metrics
        # Optional delivery plane (delivery/plane.py): when present,
        # deliver_batch groups worker-owned targets per shard and
        # writes each frame ONCE per shard ring; parent-owned peers
        # (and the whole map when plane is None — the default) take
        # the byte-for-byte in-process path below.
        self._plane = plane
        # Optional robustness.sessions.SessionStore (--session-ttl):
        # frames addressed to a PARKED peer (dropped transport, state
        # held for resume) are counted there — accounting, never
        # buffering. None (the default) costs one attribute test on
        # the map-miss path only.
        self._sessions = sessions
        # Optional loss hook (--interest on): called with a peer UUID
        # whenever a frame addressed to it could not be delivered on
        # THIS path — map miss (parked/unknown) or slow-path send
        # error. The server wires it to InterestManager.mark_resync so
        # no local loss can leak a delta past a gap; the worker plane
        # reports its own losses through on_peer_lost/on_frame_drop.
        self.on_frame_loss: Callable[[uuid_mod.UUID], None] | None = None
        #: cumulative wire bytes handed to transports by deliver_batch
        #: (both paths; failed slow-path sends subtracted) — the
        #: ticker diffs this into the delivery.bytes_per_tick gauge
        #: and the bench into bytes/recipient/s
        self.bytes_delivered = 0

    # region: lookups

    def __contains__(self, uuid: uuid_mod.UUID) -> bool:
        return uuid in self._map

    def get(self, uuid: uuid_mod.UUID) -> Peer | None:
        return self._map.get(uuid)

    def size(self) -> int:
        return len(self._map)

    def peer_ids(self) -> list[uuid_mod.UUID]:
        return list(self._map.keys())

    def stale_peers(self, max_age_secs: float) -> list[uuid_mod.UUID]:
        now = time.monotonic()
        return [
            p.uuid for p in self._map.values() if p.is_stale(now, max_age_secs)
        ]

    # endregion

    # region: modifiers

    async def insert(self, peer: Peer) -> Peer | None:
        """Register a peer and announce PeerConnect to everyone else
        (peer_map.rs:100-116)."""
        logger.info("[%s] %s peer connected", peer.addr, peer.kind)
        existing = self._map.get(peer.uuid)
        self._map[peer.uuid] = peer

        await self.broadcast_except(
            Message(
                instruction=Instruction.PEER_CONNECT,
                parameter=str(peer.uuid),
            ),
            peer.uuid,
        )
        return existing

    async def remove(self, uuid: uuid_mod.UUID) -> Peer | None:
        """Drop a peer, announce PeerDisconnect to all remaining peers,
        and fire the removal hook (peer_map.rs:121-141)."""
        peer = self._map.pop(uuid, None)
        if peer is not None:
            peer.closed = True
            logger.info("[%s] %s peer disconnected", peer.addr, peer.kind)
            await self.broadcast_all(
                Message(
                    instruction=Instruction.PEER_DISCONNECT,
                    parameter=str(uuid),
                )
            )
        if self._on_remove is not None:
            self._on_remove(uuid)
        return peer

    def detach(self, uuid: uuid_mod.UUID) -> Peer | None:
        """Silently pop a peer's TRANSPORT binding: no PeerDisconnect
        broadcast, no removal hook — the logical state (index rows,
        entity slots, session) stays untouched. The session-resume
        rebind uses this to swap a stale binding for a fresh one with
        zero survivor-visible churn."""
        peer = self._map.pop(uuid, None)
        if peer is not None:
            peer.closed = True
        return peer

    def rebind(self, peer: Peer) -> None:
        """Install a fresh transport binding for a peer the survivors
        still consider connected (resume-over-stale-binding): silent
        counterpart of :meth:`insert`."""
        peer.closed = False
        self._map[peer.uuid] = peer

    async def remove_if(self, uuid: uuid_mod.UUID, peer: Peer) -> bool:
        """Remove only when ``peer`` is still the CURRENT binding: a
        connection's teardown path must never evict the fresh binding
        a resume installed after it."""
        if self._map.get(uuid) is not peer:
            return False
        await self.remove(uuid)
        return True

    # endregion

    # region: broadcasts — serialize once, frame once per transport,
    # write synchronously where the transport allows, await the rest

    async def _broadcast(self, message: Message, peers: Iterable[Peer]) -> None:
        framed = FramedPayload(serialize_message(message))
        ctx = getattr(message, "trace_ctx", None)
        if ctx is not None:
            framed.ctx = ctx
        n, errors = 0, 0
        slow: list[Peer] = []
        for p in peers:
            n += 1
            if not p.try_write(framed):
                slow.append(p)
        if slow:
            results = await asyncio.gather(
                *(p.send_raw(framed.payload) for p in slow),
                return_exceptions=True,
            )
            for result in results:
                if isinstance(result, Exception):
                    errors += 1
                    logger.debug("broadcast error: %s", result)
        if self.metrics is not None:
            self.metrics.inc("broadcast.messages")
            self.metrics.inc("broadcast.sends", n - errors)
            if errors:
                self.metrics.inc("broadcast.send_errors", errors)

    async def deliver_batch(
        self,
        pairs: Iterable[tuple[Message, Iterable[uuid_mod.UUID]]],
        t_ingress_ns: int = 0,
    ) -> int:
        """Deliver a tick's worth of resolved fan-outs.

        Three levels of batching against the reference's per-message
        lock + join_all (peer_map.rs:22-40):
        * serialize once per message — and when the message still
          carries its inbound wire bytes (``Message.wire``: LocalMessage
          fan-out re-broadcasts the sender's bytes verbatim), skip
          re-serialization entirely;
        * frame once per transport kind (FramedPayload cache);
        * ONE ``try_write_many`` per peer per tick — each peer's frames
          coalesce into a single transport write (writev-style) instead
          of one write per delivery.
        Peers whose transport can't take the sync write (saturated, or
        no fast path) fall back to awaited sends in one gather at the
        end. ``t_ingress_ns`` is the batch's frame-clock stamp
        (``time.monotonic_ns`` at ticker flush start, 0 = unclocked):
        both paths close it at delivery completion into the
        ``frame.e2e_ms`` histogram — the honest dispatch→socket-write
        fan-out latency. Returns the number of sends attempted."""
        if self._plane is not None:
            return await self._deliver_batch_planed(pairs, t_ingress_ns)
        return await self._deliver_batch_local(pairs, t_ingress_ns)

    async def _deliver_batch_planed(
        self,
        pairs: Iterable[tuple[Message, Iterable[uuid_mod.UUID]]],
        t_ingress_ns: int = 0,
    ) -> int:
        """Sharded delivery (delivery plane enabled): each message's
        wire bytes are written ONCE into every owning shard's ring with
        the full slot list — no per-peer framing, no per-frame pickling
        — and the worker processes fan out from there. Targets not
        adopted by a worker (degraded shards, exotic transports) drain
        through the unchanged in-process path afterwards, preserving
        per-peer arrival order within this batch."""
        from array import array

        plane = self._plane
        worker_sends = n_msgs = 0
        local_pairs: list[tuple[Message, list[uuid_mod.UUID]]] = []
        with plane.tracer.span("delivery.fanout") as span:
            for message, uuids in pairs:
                n_msgs += 1
                data = message.wire
                if data is None:
                    data = serialize_message(message)
                groups: dict[int, tuple[bytes, array]] = {}
                local_targets: list[uuid_mod.UUID] = []
                for u in uuids:
                    p = self._map.get(u)
                    if p is None:
                        if self._sessions is not None:
                            self._sessions.note_undelivered(u)
                        if self.on_frame_loss is not None:
                            self.on_frame_loss(u)
                        continue
                    if p.shard is not None:
                        group = groups.get(p.shard)
                        if group is None:
                            groups[p.shard] = (data, array("I", (p.slot,)))
                        else:
                            group[1].append(p.slot)
                    else:
                        local_targets.append(u)
                if groups:
                    worker_sends += await plane.deliver(
                        groups, t_ingress_ns
                    )
                    self.bytes_delivered += len(data) * sum(
                        len(g[1]) for g in groups.values()
                    )
                if local_targets:
                    local_pairs.append((message, local_targets))
            span.tag(messages=n_msgs, worker_sends=worker_sends)
        n = worker_sends
        if local_pairs:
            # counts its own broadcast.messages/sends for these pairs
            n += await self._deliver_batch_local(local_pairs, t_ingress_ns)
        if self.metrics is not None:
            if n_msgs > len(local_pairs):
                self.metrics.inc(
                    "broadcast.messages", n_msgs - len(local_pairs)
                )
            if worker_sends:
                self.metrics.inc("broadcast.sends", worker_sends)
        return n

    async def _deliver_batch_local(
        self,
        pairs: Iterable[tuple[Message, Iterable[uuid_mod.UUID]]],
        t_ingress_ns: int = 0,
    ) -> int:
        t_start_ns = time.monotonic_ns()
        outbox: dict[Peer, list[FramedPayload]] = {}
        n = n_msgs = 0
        for message, uuids in pairs:
            n_msgs += 1
            data = message.wire
            framed = FramedPayload(
                serialize_message(message) if data is None else data
            )
            ctx = getattr(message, "trace_ctx", None)
            if ctx is not None:
                framed.ctx = ctx
            for u in uuids:
                p = self._map.get(u)
                if p is None:
                    if self._sessions is not None:
                        self._sessions.note_undelivered(u)
                    if self.on_frame_loss is not None:
                        self.on_frame_loss(u)
                    continue
                n += 1
                self.bytes_delivered += len(framed.payload)
                outbox.setdefault(p, []).append(framed)
        slow: list[tuple[Peer, list[FramedPayload]]] = []
        for p, framed_list in outbox.items():
            if not p.try_write_many(framed_list):
                slow.append((p, framed_list))
        errors = 0
        if slow:
            # SEQUENTIAL per peer: concurrent send() calls on one
            # websockets connection raise ConcurrencyError (and would
            # reorder frames anyway); distinct peers still overlap
            async def drain_peer(p: Peer, fl: list[FramedPayload]) -> int:
                failed = 0
                for f in fl:
                    try:
                        await p.send_raw(f.payload)
                    except Exception as exc:
                        failed += 1
                        self.bytes_delivered -= len(f.payload)
                        logger.debug("batch delivery error: %s", exc)
                if failed and self.on_frame_loss is not None:
                    # the peer missed >= 1 frame of this batch: the
                    # next interest frame must be a full resync
                    self.on_frame_loss(p.uuid)
                return failed
            for failed in await asyncio.gather(
                *(drain_peer(p, fl) for p, fl in slow)
            ):
                errors += failed
        if self.metrics is not None:
            self.metrics.inc("broadcast.messages", n_msgs)
            self.metrics.inc("broadcast.sends", n - errors)
            if errors:
                self.metrics.inc("broadcast.send_errors", errors)
            # e2e stamps, closed at batch completion (the slow-path
            # drain included — fast-path frames already sat in their
            # transport buffers by then, so this is the conservative
            # close). One batched histogram write per series, not one
            # per frame — the lock must not ride the 16K-frame loop.
            # delivery.e2e_ms mirrors the worker-side ring-write→
            # write-complete stamp so the two pump variants compare.
            now_ns = time.monotonic_ns()
            if n_msgs:
                self.metrics.observe_ms_n(
                    "delivery.e2e_ms", (now_ns - t_start_ns) / 1e6, n_msgs
                )
                if t_ingress_ns:
                    self.metrics.observe_ms_n(
                        "frame.e2e_ms", (now_ns - t_ingress_ns) / 1e6,
                        n_msgs,
                    )
        return n

    async def broadcast_all(self, message: Message) -> None:
        await self._broadcast(message, self._map.values())

    async def broadcast_to(
        self, message: Message, uuids: Iterable[uuid_mod.UUID]
    ) -> None:
        peers = [self._map[u] for u in set(uuids) if u in self._map]
        await self._broadcast(message, peers)

    async def broadcast_except(
        self, message: Message, except_uuid: uuid_mod.UUID
    ) -> None:
        peers = [p for p in self._map.values() if p.uuid != except_uuid]
        await self._broadcast(message, peers)

    # endregion
