"""Peer registry and broadcast hub.

Python rebuild of the reference's Peer/PeerMap
(worldql_server/src/transport/peer.rs, peer_map.rs). One asyncio event
loop replaces the Rust ``Arc<RwLock<PeerMap>>``: map mutations are
atomic between awaits, and broadcasts serialize the message once then
fan out concurrently (peer_map.rs:22-40).

Transports supply an async ``send_raw(bytes)`` and may mark themselves
heartbeat-tracked (ZeroMQ-style, staleness-swept) or not
(WebSocket-style, liveness == stream health; peer.rs:59-69).
"""

from __future__ import annotations

import asyncio
import logging
import time
import uuid as uuid_mod
from typing import Awaitable, Callable, Iterable

from ..protocol import Instruction, Message, serialize_message

logger = logging.getLogger(__name__)

SendRaw = Callable[[bytes], Awaitable[None]]
OnRemove = Callable[[uuid_mod.UUID], None]


class PeerSendError(Exception):
    pass


class Peer:
    """Uniform outbound handle over any transport (peer.rs:33-88)."""

    __slots__ = ("uuid", "addr", "kind", "_send_raw", "tracks_heartbeat",
                 "last_heartbeat", "closed")

    def __init__(
        self,
        uuid: uuid_mod.UUID,
        addr: str,
        send_raw: SendRaw,
        kind: str = "unknown",
        tracks_heartbeat: bool = False,
    ):
        self.uuid = uuid
        self.addr = addr
        self.kind = kind
        self._send_raw = send_raw
        self.tracks_heartbeat = tracks_heartbeat
        self.last_heartbeat = time.monotonic()
        self.closed = False

    def update_last_heartbeat(self) -> None:
        self.last_heartbeat = time.monotonic()

    def is_stale(self, now: float, max_age_secs: float) -> bool:
        """Heartbeat-tracked peers go stale; stream peers never do
        (peer.rs:59-69)."""
        if not self.tracks_heartbeat:
            return False
        return (now - self.last_heartbeat) > max_age_secs

    async def send(self, message: Message) -> None:
        await self.send_raw(serialize_message(message))

    async def send_raw(self, data: bytes) -> None:
        if self.closed:
            raise PeerSendError(f"peer {self.uuid} is closed")
        try:
            await self._send_raw(data)
        except Exception as exc:
            raise PeerSendError(str(exc)) from exc

    def __repr__(self) -> str:
        return f"Peer({self.kind}, {self.uuid}, {self.addr})"


class PeerMap:
    """UUID → Peer registry + broadcast primitives (peer_map.rs:16-176).

    ``on_remove`` mirrors the reference's remove channel
    (peer_map.rs:139): the engine hooks it to purge the spatial index
    when a peer disconnects.
    """

    def __init__(self, on_remove: OnRemove | None = None, metrics=None):
        self._map: dict[uuid_mod.UUID, Peer] = {}
        self._on_remove = on_remove
        self.metrics = metrics

    # region: lookups

    def __contains__(self, uuid: uuid_mod.UUID) -> bool:
        return uuid in self._map

    def get(self, uuid: uuid_mod.UUID) -> Peer | None:
        return self._map.get(uuid)

    def size(self) -> int:
        return len(self._map)

    def peer_ids(self) -> list[uuid_mod.UUID]:
        return list(self._map.keys())

    def stale_peers(self, max_age_secs: float) -> list[uuid_mod.UUID]:
        now = time.monotonic()
        return [
            p.uuid for p in self._map.values() if p.is_stale(now, max_age_secs)
        ]

    # endregion

    # region: modifiers

    async def insert(self, peer: Peer) -> Peer | None:
        """Register a peer and announce PeerConnect to everyone else
        (peer_map.rs:100-116)."""
        logger.info("[%s] %s peer connected", peer.addr, peer.kind)
        existing = self._map.get(peer.uuid)
        self._map[peer.uuid] = peer

        await self.broadcast_except(
            Message(
                instruction=Instruction.PEER_CONNECT,
                parameter=str(peer.uuid),
            ),
            peer.uuid,
        )
        return existing

    async def remove(self, uuid: uuid_mod.UUID) -> Peer | None:
        """Drop a peer, announce PeerDisconnect to all remaining peers,
        and fire the removal hook (peer_map.rs:121-141)."""
        peer = self._map.pop(uuid, None)
        if peer is not None:
            peer.closed = True
            logger.info("[%s] %s peer disconnected", peer.addr, peer.kind)
            await self.broadcast_all(
                Message(
                    instruction=Instruction.PEER_DISCONNECT,
                    parameter=str(uuid),
                )
            )
        if self._on_remove is not None:
            self._on_remove(uuid)
        return peer

    # endregion

    # region: broadcasts — serialize once, send concurrently

    async def _broadcast(self, message: Message, peers: Iterable[Peer]) -> None:
        data = serialize_message(message)
        peers = list(peers)
        results = await asyncio.gather(
            *(p.send_raw(data) for p in peers), return_exceptions=True
        )
        errors = 0
        for result in results:
            if isinstance(result, Exception):
                errors += 1
                logger.debug("broadcast error: %s", result)
        if self.metrics is not None:
            self.metrics.inc("broadcast.messages")
            self.metrics.inc("broadcast.sends", len(peers) - errors)
            if errors:
                self.metrics.inc("broadcast.send_errors", errors)

    async def broadcast_all(self, message: Message) -> None:
        await self._broadcast(message, self._map.values())

    async def broadcast_to(
        self, message: Message, uuids: Iterable[uuid_mod.UUID]
    ) -> None:
        peers = [self._map[u] for u in set(uuids) if u in self._map]
        await self._broadcast(message, peers)

    async def broadcast_except(
        self, message: Message, except_uuid: uuid_mod.UUID
    ) -> None:
        peers = [p for p in self._map.values() if p.uuid != except_uuid]
        await self._broadcast(message, peers)

    # endregion
