"""worldql-server-tpu — a TPU-native real-time spatial message broker.

A from-scratch rebuild of the capabilities of WorldQL server
(reference: Liborsaf/worldql_server, Rust/tokio): clients connect over
ZeroMQ / WebSocket / HTTP, speak a FlatBuffers ``Message`` protocol,
subscribe to cubic regions of named 3-D worlds, broadcast
position-scoped (``LocalMessage``) and world-scoped (``GlobalMessage``)
events, and persist positioned ``Record``s in a region-sharded store.

Unlike the reference's per-message HashMap hot path
(worldql_server/src/subscriptions/area_map.rs, processing/local_message.rs),
the subscription/query engine here is a batched spatial-hash engine that
executes on TPU via JAX/XLA behind a swappable ``SpatialBackend``
interface, with entity positions held in device-resident SoA buffers and
worlds/cells shardable across a ``jax.sharding.Mesh``.
"""

__version__ = "0.1.0"
