"""Autosharding: the router-side hot-shard watcher.

Zipfian traffic pins one hot world to one shard no matter how large
``--cluster-shards N`` is — bench's own zipf block lands ~60% of load
in a few capped cubes. This controller closes the loop the manual
``POST /reshard`` surface leaves open: it watches the per-shard
overload state the control channel already mirrors (the shard
governors fold tick-wall/queue/shed pressure into their exported
LEVEL — the same federated signal /metrics serves), and when one
shard stays hot while the fleet is not, it migrates that shard's
hottest world to the coldest shard.

Deliberately conservative:

* ``--autoshard on`` only (default off) — a migration freezes a
  world's traffic for its duration; nobody should get that surprise
  unarmed.
* A shard must hold SHED_HIGH+ for ``sustain_s`` continuously — a one
  tick spike or a restart blip never triggers.
* One migration at a time, ``cooldown_s`` between triggers — the
  controller must never thrash a world back and forth faster than the
  load signal settles.
* The hottest-world signal is the router's OWN forward accounting
  (per-world counters it increments on every world-routed forward,
  decayed each poll) — no extra control traffic, and it measures
  exactly what the router can act on: what it forwards.
"""

from __future__ import annotations

import asyncio
import logging
import time

logger = logging.getLogger(__name__)

#: governor level considered hot (shard.py exports it; router.py's
#: shed mirror holds it) — SHED_HIGH in the governor's ladder
HOT_LEVEL = 2


class AutoshardController:
    def __init__(self, router, *, interval_s: float = 2.0,
                 sustain_s: float = 6.0, cooldown_s: float = 30.0,
                 clock=time.monotonic):
        self.router = router
        self.interval_s = interval_s
        self.sustain_s = sustain_s
        self.cooldown_s = cooldown_s
        self._clock = clock
        #: shard → monotonic stamp when it FIRST went hot (cleared on
        #: any non-hot observation)
        self._hot_since: dict[int, float] = {}
        self._last_trigger = 0.0
        self.triggered = 0

    async def run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                self.poll()
            except Exception:
                logger.exception("autoshard poll failed — continuing")

    def poll(self) -> int | None:
        """One observation: age the hot set, decay the world-load
        window, trigger at most one migration. Returns the migration's
        xfer id when one fired (test hook)."""
        router = self.router
        now = self._clock()
        hot = None
        for shard in range(router.n_shards):
            if (
                router.supervisor.shard_alive(shard)
                and router.mirror.level(shard) >= HOT_LEVEL
            ):
                since = self._hot_since.setdefault(shard, now)
                if hot is None and now - since >= self.sustain_s:
                    hot = shard
            else:
                self._hot_since.pop(shard, None)
        router.decay_world_load()
        if hot is None:
            return None
        if now - self._last_trigger < self.cooldown_s:
            return None
        if router.migration is not None and router.migration.active:
            return None
        world = router.hottest_world(hot)
        if world is None:
            return None  # hot shard with no world-routed traffic window
        target = self._coldest_other(hot)
        if target is None:
            return None  # fleet-wide heat: migration would just move pain
        self._last_trigger = now
        self.triggered += 1
        router.metrics.inc("cluster.autoshard_triggered")
        logger.warning(
            "autoshard: shard %d hot ≥%.0fs — migrating its hottest "
            "world %r to shard %d", hot, self.sustain_s, world, target,
        )
        return router.start_reshard(world, target, reason="autoshard")

    def _coldest_other(self, hot: int) -> int | None:
        """The migration target: the alive shard with the lowest
        governor level (ties: least world-routed forward load). None
        when every other shard is hot too."""
        router = self.router
        best = None
        best_key = None
        for shard in range(router.n_shards):
            if shard == hot or not router.supervisor.shard_alive(shard):
                continue
            level = router.mirror.level(shard)
            if level >= HOT_LEVEL:
                continue
            key = (level, router.shard_forward_load(shard), shard)
            if best_key is None or key < best_key:
                best, best_key = shard, key
        return best

    def stats(self) -> dict:
        return {
            "hot_shards": sorted(self._hot_since),
            "triggered": self.triggered,
        }
