"""Epoch-versioned placement: the resharding layer's one contract.

:class:`~..world_map.WorldMap` is a pure hash — identical in every
process, but immutable: one hot world pins one shard forever. This
module makes placement a VERSIONED document instead:

* ``PlacementMap`` extends the stable hash with per-world and per-peer
  OVERRIDES (world W now lives on shard B; a peer whose parked session
  migrated with W now homes on B — the cross-shard resume fix of
  ISSUE 19 satellite 1).
* Every change bumps a MONOTONE ``epoch``. The router stamps the epoch
  on every forward (``tracectx.wrap_epoch``); a shard holding a newer
  map rejects a stale-epoch frame for a world it no longer owns with a
  re-route hint instead of misapplying it.
* ``to_spec``/``apply_spec`` serialize the whole map as one JSON
  document. The router broadcasts it over the control channel at every
  flip and piggybacks the epoch on the ~1s state exchange, so every
  process converges with NO external coordinator: ``apply_spec`` is
  last-writer-wins on the epoch and a no-op for stale or same-epoch
  specs — applying specs in any order converges on the newest one.

The base hash stays authoritative for everything without an override,
so an empty ``PlacementMap`` at epoch 0 is behavior-identical to the
``WorldMap`` it replaces — ``--cluster-shards N`` without a migration
is byte for byte what it was.
"""

from __future__ import annotations

import uuid as uuid_mod

from ..world_map import WorldMap


class PlacementMap(WorldMap):
    """``WorldMap`` + monotone epoch + world/peer overrides."""

    def __init__(self, n_shards: int, epoch: int = 0):
        super().__init__(n_shards)
        if epoch < 0:
            raise ValueError("placement epoch must be >= 0")
        self.epoch = int(epoch)
        #: world name → owner shard (set by a completed migration)
        self.world_overrides: dict[str, int] = {}
        #: peer uuid hex → home shard (parked sessions that migrated
        #: with their world resume on the NEW owner with their token)
        self.peer_overrides: dict[str, int] = {}

    # region: placement

    def shard_of_world(self, world: str) -> int:
        override = self.world_overrides.get(world)
        if override is not None:
            return override
        return super().shard_of_world(world)

    def shard_of_peer(self, peer: uuid_mod.UUID) -> int:
        override = self.peer_overrides.get(peer.hex)
        if override is not None:
            return override
        return super().shard_of_peer(peer)

    def base_shard_of_world(self, world: str) -> int:
        """The hash placement, ignoring overrides (migration targets
        report "returned home" by clearing the override instead of
        carrying a redundant one forever)."""
        return super().shard_of_world(world)

    # endregion

    # region: mutation (router-side only; shards apply specs)

    def bump(self) -> int:
        """Advance the epoch (every placement change is versioned)."""
        self.epoch += 1
        return self.epoch

    def move_world(
        self, world: str, shard: int,
        peers: list[uuid_mod.UUID] | None = None,
    ) -> int:
        """Install a world override (plus the peer overrides for its
        migrated parked sessions) and bump the epoch — the migration
        coordinator's FLIP step. Returns the new epoch."""
        if not 0 <= shard < self.n_shards:
            raise ValueError(f"shard {shard} out of range")
        if self.base_shard_of_world(world) == shard:
            self.world_overrides.pop(world, None)
        else:
            self.world_overrides[world] = shard
        for peer in peers or ():
            if super().shard_of_peer(peer) == shard:
                self.peer_overrides.pop(peer.hex, None)
            else:
                self.peer_overrides[peer.hex] = shard
        return self.bump()

    def clear_peer(self, peer: uuid_mod.UUID) -> None:
        """A migrated peer fully tore down — its override has nothing
        left to route. No epoch bump: routing by the base hash for a
        DEAD peer is indistinguishable from the override."""
        self.peer_overrides.pop(peer.hex, None)

    # endregion

    # region: serialization (control-channel convergence)

    def to_spec(self) -> dict:
        """One JSON-safe document carrying the whole placement state —
        broadcast over control at every flip; ``apply_spec`` on any
        process converges it."""
        return {
            "epoch": self.epoch,
            "n_shards": self.n_shards,
            "worlds": dict(self.world_overrides),
            "peers": dict(self.peer_overrides),
        }

    def apply_spec(self, spec: dict) -> bool:
        """Adopt a newer placement document; stale/same-epoch specs are
        REJECTED (monotone convergence: specs applied in any arrival
        order end on the newest). True = adopted."""
        try:
            epoch = int(spec["epoch"])
            worlds = {
                str(w): int(s) for w, s in (spec.get("worlds") or {}).items()
            }
            peers = {
                str(p): int(s) for p, s in (spec.get("peers") or {}).items()
            }
        except (KeyError, TypeError, ValueError):
            return False
        if epoch <= self.epoch:
            return False
        self.epoch = epoch
        self.world_overrides = worlds
        self.peer_overrides = peers
        return True

    @classmethod
    def from_spec(cls, n_shards: int, spec: dict) -> "PlacementMap":
        pm = cls(n_shards)
        pm.epoch = -1  # any well-formed spec (epoch >= 0) applies
        if not pm.apply_spec(spec):
            pm.epoch = 0
        return pm

    # endregion

    def describe(self) -> dict:
        return {
            **super().describe(),
            "epoch": self.epoch,
            "world_overrides": len(self.world_overrides),
            "peer_overrides": len(self.peer_overrides),
        }
