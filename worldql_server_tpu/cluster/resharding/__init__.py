"""Live resharding (ISSUE 19): epoch-versioned placement + zero-loss
online world migration + the autoshard loop.

* :mod:`.placement` — :class:`PlacementMap`: the stable hash plus
  monotone-epoch world/peer overrides, converged over the control
  channel with no external coordinator.
* :mod:`.transfer` — the bounded transfer buffer (counted shed, never
  silent loss) and the CRC-framed chunk codec the capsule streams
  over.
* :mod:`.worldstate` — the shard-side capsule: export / import /
  tombstone of one world's records, index rows, entity rows and
  parked sessions, always THROUGH the durability pipeline.
* :mod:`.migration` — :class:`MigrationCoordinator`: the router-side
  protocol state machine (freeze → stream → import → flip → replay →
  tombstone) with exactly-one-WAL-owner crash safety at every state.
* :mod:`.controller` — :class:`AutoshardController`: sustained-hot
  shard detection → hottest-world migration (``--autoshard on``,
  default off).
"""

from .controller import AutoshardController
from .migration import (
    FENCE_MAGIC,
    MigrationCoordinator,
    MigrationError,
    fence_payload,
    parse_fence,
)
from .placement import PlacementMap
from .transfer import ChunkAssembler, TransferBuffer, encode_chunks
from .worldstate import export_world, import_world, tombstone_world

__all__ = [
    "AutoshardController",
    "ChunkAssembler",
    "FENCE_MAGIC",
    "MigrationCoordinator",
    "MigrationError",
    "PlacementMap",
    "TransferBuffer",
    "encode_chunks",
    "export_world",
    "fence_payload",
    "import_world",
    "parse_fence",
    "tombstone_world",
]
