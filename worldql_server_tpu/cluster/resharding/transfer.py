"""Migration transport: bounded traffic parking + CRC-framed chunks.

Two small pieces the coordinator composes:

* :class:`TransferBuffer` — while world W is mid-migration the router
  PARKS W's inbound wire bytes here instead of forwarding them into a
  frozen (or half-transferred) owner. The buffer is bounded in BYTES:
  past the budget frames are SHED AND COUNTED (the PR 10 discipline —
  bounded degradation, never silent loss, never unbounded memory).
  After the flip the buffer replays in exact arrival order, stamped
  with the new epoch, so "offered == admitted + counted shed" keeps
  closing through a migration.

* Chunk framing — world state streams over the AF_UNIX control
  channel, whose datagrams are read 64 KiB at a time. ``encode_chunks``
  splits one JSON document into ≤``CHUNK_CHARS`` slices (the shard
  dump-chunk bound: JSON-escaped slice + envelope stays under one
  datagram), each carrying its CRC32 and the CRC32 of the WHOLE
  document; :class:`ChunkAssembler` reassembles and verifies both, so
  a torn/corrupt/cross-wired transfer fails loudly instead of
  replaying garbage into the destination's WAL. ``reset()`` restarts
  assembly from chunk 0 — the resume path when the destination shard
  is killed mid-transfer and the router re-streams from its retained
  copy.
"""

from __future__ import annotations

import json
import zlib

#: JSON-escaped chunk + envelope must stay under the control channel's
#: 64 KiB datagram read (the shard.py DUMP_CHUNK_CHARS precedent)
CHUNK_CHARS = 24_000


class TransferBuffer:
    """Arrival-ordered byte-bounded parking for one migrating world's
    inbound traffic."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._frames: list[bytes] = []
        self.parked_bytes = 0
        self.parked_frames = 0
        #: frames refused past the byte budget — a COUNTED shed class
        #: (cluster.reshard_buffer_shed), never silent loss
        self.shed = 0

    def park(self, data: bytes) -> bool:
        """True = parked for post-flip replay; False = over budget,
        the caller must count the shed."""
        if self.parked_bytes + len(data) > self.max_bytes:
            self.shed += 1
            return False
        self._frames.append(data)
        self.parked_bytes += len(data)
        self.parked_frames += 1
        return True

    def replay(self) -> list[bytes]:
        """Drain every parked frame in arrival order (the post-flip
        replay); the buffer is empty afterwards."""
        frames, self._frames = self._frames, []
        self.parked_bytes = 0
        return frames

    def stats(self) -> dict:
        return {
            "parked_frames": self.parked_frames,
            "parked_bytes": self.parked_bytes,
            "shed": self.shed,
        }


def encode_chunks(obj: dict) -> list[dict]:
    """One JSON document → ordered CRC-framed control-channel chunks.
    Every chunk is self-describing (``seq``/``n``/``crc``/``total_crc``)
    so the assembler can verify each slice on arrival and the whole
    document at completion."""
    blob = json.dumps(obj)
    total_crc = zlib.crc32(blob.encode())
    slices = [
        blob[i:i + CHUNK_CHARS] for i in range(0, len(blob), CHUNK_CHARS)
    ] or [""]
    return [
        {
            "seq": seq,
            "n": len(slices),
            "crc": zlib.crc32(chunk.encode()),
            "total_crc": total_crc,
            "data": chunk,
        }
        for seq, chunk in enumerate(slices)
    ]


class ChunkAssembler:
    """Reassemble + verify a chunk stream. Chunks may repeat (resume
    re-streams from 0) but never conflict: a CRC or shape mismatch
    poisons the assembly until ``reset()``."""

    def __init__(self):
        self._parts: dict[int, str] = {}
        self._n: int | None = None
        self._total_crc: int | None = None
        self.corrupt = False

    def reset(self) -> None:
        self._parts.clear()
        self._n = None
        self._total_crc = None
        self.corrupt = False

    def feed(self, chunk: dict) -> dict | None:
        """Absorb one chunk; returns the decoded document when the
        stream completes and verifies, else None. Sets ``corrupt`` on
        any CRC/shape violation (the caller aborts the transfer)."""
        if self.corrupt:
            return None
        try:
            seq = int(chunk["seq"])
            n = int(chunk["n"])
            crc = int(chunk["crc"])
            total_crc = int(chunk["total_crc"])
            data = str(chunk["data"])
        except (KeyError, TypeError, ValueError):
            self.corrupt = True
            return None
        if zlib.crc32(data.encode()) != crc:
            self.corrupt = True
            return None
        if self._n is None:
            self._n, self._total_crc = n, total_crc
        elif n != self._n or total_crc != self._total_crc:
            self.corrupt = True
            return None
        if not 0 <= seq < n:
            self.corrupt = True
            return None
        self._parts[seq] = data
        if len(self._parts) < self._n:
            return None
        blob = "".join(self._parts[i] for i in range(self._n))
        if zlib.crc32(blob.encode()) != self._total_crc:
            self.corrupt = True
            return None
        try:
            return json.loads(blob)
        except ValueError:
            self.corrupt = True
            return None
