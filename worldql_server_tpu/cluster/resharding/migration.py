"""Online world migration: the router-side protocol coordinator.

One :class:`MigrationCoordinator` drives one world W from shard A
(source) to shard B (destination) through a fixed state machine:

====================  ====================================================
state                 what is true when it completes
====================  ====================================================
``freeze``            W's inbound traffic parks in the transfer buffer
                      (bounded; overflow = counted shed) and A has
                      PROCESSED every pre-freeze W frame — proven by a
                      fence frame pushed through the same FIFO data path
                      and acked over control.
``streaming``         A exported W's full capsule (records, subscription
                      rows, entity rows, parked sessions) and the router
                      holds it, CRC-verified, chunk list RETAINED.
``importing``         B replayed the capsule THROUGH its durability
                      pipeline and acked — W is now recoverable from
                      B's WAL. B dying here is survivable: the router
                      re-streams the retained chunks from zero when B's
                      restart reports ready.
``flipping``          the placement map moved W (and its migrated parked
                      peers) to B under a NEW epoch, broadcast to every
                      shard.
``replaying``         every parked frame re-entered the normal routing
                      path in arrival order — stamped with the new
                      epoch, landing on B.
``tombstoning``       A deleted W through its OWN durability pipeline
                      (the deletes hit A's WAL — replay cannot resurrect
                      a moved world). A dying first is survivable: the
                      tombstone is queued and re-issued when A returns.
====================  ====================================================

Crash safety is the design invariant: at every state exactly one shard
can recover W from WAL. Before B's durable ack that shard is A (abort:
tell B to tombstone any partial state, replay the buffer back to A).
From the ack on it is B (continue: flip, replay, queue the tombstone).
The kill-at-every-protocol-state property test drives exactly this
case split.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
import uuid as uuid_mod

from .transfer import ChunkAssembler, TransferBuffer, encode_chunks  # noqa: F401  (encode_chunks: shard-side counterpart, re-exported)

logger = logging.getLogger(__name__)

#: payload magic for the freeze fence frame — rides the router→shard
#: DATA path (TCP FIFO + in-order processing make its ack a proof that
#: every earlier frame for the frozen world was already processed)
FENCE_MAGIC = b"WQFN"

#: per-state deadlines; generous enough to ride one shard restart
#: (supervisor backoff caps at 5s + boot)
FENCE_TIMEOUT_S = 15.0
EXPORT_TIMEOUT_S = 60.0
IMPORT_TIMEOUT_S = 120.0
TOMBSTONE_TIMEOUT_S = 30.0


class MigrationError(Exception):
    """A protocol step failed or timed out — the migration aborts and
    ownership stays with the source."""


class MigrationCoordinator:
    """One in-flight migration. The router holds at most one (a second
    ``POST /reshard`` gets 409) and routes control packets + shard
    up/down events into it."""

    def __init__(self, router, world: str, source: int, target: int,
                 xfer_id: int, buffer_bytes: int):
        self.router = router
        self.world = world
        self.source = source
        self.target = target
        self.xfer = xfer_id
        self.state = "idle"
        self.buffer = TransferBuffer(buffer_bytes)
        self.capsule: dict | None = None
        self.chunks: list[dict] = []
        self.import_counts: dict | None = None
        self.replayed = 0
        self.error: str | None = None
        self.started = time.monotonic()
        self.finished: float | None = None
        #: uuid hexes of the parked sessions riding the capsule —
        #: their resume handshakes park too once known, so a resume
        #: racing the flip lands on the NEW owner, not a shard about
        #: to tombstone the state
        self.migrating_peers: set[str] = set()
        self._assembler = ChunkAssembler()
        self._fence_ack = asyncio.Event()
        self._export_done = asyncio.Event()
        self._import_ack = asyncio.Event()
        self._tombstone_ack = asyncio.Event()
        self._failed = asyncio.Event()
        self._restreams: set[asyncio.Task] = set()

    # region: router-facing surface

    @property
    def active(self) -> bool:
        return self.state not in ("idle", "done", "aborted")

    def should_park(self, instruction, world_name, sender) -> bool:
        """The router's interception predicate, checked per inbound
        message after decode: park W's world-routed traffic for the
        whole migration, plus — once the capsule names them — the
        resume handshakes of its migrating parked peers. Parking STOPS
        at the flip: from ``replaying`` on, the new placement routes
        W's frames to their owner — including the replayed frames
        themselves, which would otherwise re-park into the drained
        buffer and be lost."""
        if not self.active or self.state in ("replaying", "tombstoning"):
            return False
        from ...protocol import Instruction

        if instruction == Instruction.HANDSHAKE:
            return (
                sender is not None
                and sender.hex in self.migrating_peers
            )
        return world_name == self.world

    def describe(self) -> dict:
        return {
            "xfer": self.xfer,
            "world": self.world,
            "source": self.source,
            "target": self.target,
            "state": self.state,
            "buffer": self.buffer.stats(),
            "replayed": self.replayed,
            "chunks": len(self.chunks),
            "error": self.error,
            "elapsed_s": round(
                (self.finished or time.monotonic()) - self.started, 3
            ),
        }

    # endregion

    # region: control-packet hooks (router.on_shard_message)

    def on_fence_ack(self, shard: int, msg: dict) -> None:
        if shard == self.source and int(msg.get("xfer", -1)) == self.xfer:
            self._fence_ack.set()

    def on_chunk(self, shard: int, msg: dict) -> None:
        """One capsule chunk from the source: retained verbatim (the
        resume-from-zero re-stream source) and fed to the assembler."""
        if shard != self.source or int(msg.get("xfer", -1)) != self.xfer:
            return
        chunk = msg.get("chunk")
        if not isinstance(chunk, dict):
            return
        self.chunks.append(chunk)
        doc = self._assembler.feed(chunk)
        if self._assembler.corrupt:
            self._fail("capsule chunk stream failed CRC verification")
        elif doc is not None:
            self.capsule = doc
            self.migrating_peers = {
                str(row.get("uuid")) for row in doc.get("sessions", ())
            }
            self._export_done.set()

    def on_import_ack(self, shard: int, msg: dict) -> None:
        if shard == self.target and int(msg.get("xfer", -1)) == self.xfer:
            self.import_counts = msg.get("counts")
            self._import_ack.set()

    def on_tombstone_ack(self, shard: int, msg: dict) -> None:
        if shard == self.source and int(msg.get("xfer", -1)) == self.xfer:
            self._tombstone_ack.set()

    def on_shard_down(self, shard: int) -> None:
        """SIGKILL at any protocol state lands here. Source death
        before B's durable ack aborts (A's restart recovers W from its
        WAL). Source death after the ack continues — the tombstone
        queue catches A's restart. Destination death never aborts:
        the retained chunks re-stream from zero on its ready."""
        if shard == self.source and not self._import_ack.is_set():
            if self.state in ("freeze", "streaming", "importing"):
                self._fail(
                    f"source shard {shard} died before the durable "
                    "import ack"
                )

    def on_shard_ready(self, shard: int) -> None:
        """A restarted destination mid-import gets the whole retained
        chunk stream again from zero (its fresh assembler re-verifies
        every CRC)."""
        if (
            shard == self.target
            and self.state == "importing"
            and not self._import_ack.is_set()
            and self.chunks
        ):
            task = asyncio.get_running_loop().create_task(  # wql: allow(unsupervised-task) — one-shot re-stream, retained below
                self._stream_to_target()
            )
            self._restreams.add(task)
            task.add_done_callback(self._restreams.discard)

    # endregion

    # region: the protocol

    def _set_state(self, state: str) -> None:
        self.state = state
        self.router.metrics.inc(f"cluster.reshard_state_{state}")
        logger.info(
            "reshard %d: world %r %d→%d entered %s",
            self.xfer, self.world, self.source, self.target, state,
        )

    def _fail(self, reason: str) -> None:
        if self.error is None:
            self.error = reason
        self._failed.set()

    async def _wait(self, event: asyncio.Event, timeout: float,
                    what: str) -> None:
        waiters = [
            asyncio.ensure_future(event.wait()),  # wql: allow(unsupervised-task) — awaited + cancelled below
            asyncio.ensure_future(self._failed.wait()),  # wql: allow(unsupervised-task) — awaited + cancelled below
        ]
        try:
            done, _ = await asyncio.wait(
                waiters, timeout=timeout,
                return_when=asyncio.FIRST_COMPLETED,
            )
        finally:
            for w in waiters:
                w.cancel()
        if self._failed.is_set():
            raise MigrationError(self.error or f"{what} failed")
        if not event.is_set():
            raise MigrationError(f"timed out waiting for {what}")

    async def _ctl_send_retry(self, shard: int, msg: dict,
                              deadline_s: float = 5.0) -> None:
        """Control sends are best-effort non-blocking; a full socket
        retries until the deadline (the shard-side dump-chunk idiom)."""
        deadline = time.monotonic() + deadline_s
        while not self.router.supervisor.ctl_send(shard, msg):
            if self._failed.is_set():
                raise MigrationError(self.error or "migration failed")
            if time.monotonic() >= deadline:
                raise MigrationError(
                    f"control send to shard {shard} timed out"
                )
            await asyncio.sleep(0.01)

    async def _stream_to_target(self) -> None:
        try:
            for chunk in list(self.chunks):
                await self._ctl_send_retry(self.target, {
                    "op": "reshard_import_chunk",
                    "xfer": self.xfer,
                    "world": self.world,
                    "chunk": chunk,
                })
        except MigrationError as exc:
            logger.warning(
                "reshard %d: chunk stream to shard %d stalled: %s",
                self.xfer, self.target, exc,
            )

    async def run(self) -> bool:
        """Drive the protocol end to end. True = migrated; False =
        aborted with ownership intact on the source."""
        router = self.router
        try:
            # FREEZE — interception is already live (the router
            # installed this coordinator before spawning run()); the
            # fence rides the data path so its ack proves A drained
            # every W frame forwarded before the freeze.
            self._set_state("freeze")
            if not router.send_fence(self.source, self.xfer):
                raise MigrationError("fence send failed (push queue full)")
            await self._wait(self._fence_ack, FENCE_TIMEOUT_S, "fence ack")

            # STREAMING — A exports; chunks arrive over control.
            self._set_state("streaming")
            await self._ctl_send_retry(self.source, {
                "op": "reshard_export",
                "xfer": self.xfer,
                "world": self.world,
            })
            await self._wait(
                self._export_done, EXPORT_TIMEOUT_S, "world export"
            )

            # IMPORTING — stream the retained chunks to B; its ack is
            # sent only after a durability drain (WAL-durable on B).
            self._set_state("importing")
            await self._stream_to_target()
            await self._wait(
                self._import_ack, IMPORT_TIMEOUT_S, "durable import ack"
            )

            # FLIP — one epoch bump moves the world and its migrated
            # parked peers; every shard converges via the broadcast
            # now and the ~1s state-packet epoch check later.
            self._set_state("flipping")
            peers = []
            for hexed in self.migrating_peers:
                try:
                    peers.append(uuid_mod.UUID(hex=hexed))
                except ValueError:
                    continue
            epoch = router.world_map.move_world(
                self.world, self.target, peers
            )
            router.broadcast_placement()
            logger.info(
                "reshard %d: world %r now owned by shard %d (epoch %d)",
                self.xfer, self.world, self.target, epoch,
            )

            # REPLAY — parked frames re-enter the normal route path in
            # arrival order; the new epoch stamps them onto B.
            self._set_state("replaying")
            for frame in self.buffer.replay():
                router.route_replay(frame)
                self.replayed += 1
            router.metrics.inc("cluster.reshard_replayed", self.replayed)

            # TOMBSTONE — queued first: if A is dead or dies mid-ack
            # the router re-issues on its ready and W stays gone.
            self._set_state("tombstoning")
            router.queue_tombstone(self.source, self.world, self.xfer)
            try:
                await self._wait(
                    self._tombstone_ack, TOMBSTONE_TIMEOUT_S,
                    "tombstone ack",
                )
            except MigrationError:
                # the flip is durable either way; the queued tombstone
                # fires when the source returns
                logger.warning(
                    "reshard %d: tombstone ack pending — queued for "
                    "shard %d's next ready", self.xfer, self.source,
                )
            self._set_state("done")
            router.metrics.inc("cluster.reshard_completed")
            return True
        except (MigrationError, Exception) as exc:
            await self._abort(str(exc))
            return False
        finally:
            self.finished = time.monotonic()

    async def _abort(self, reason: str) -> None:
        """Ownership stays with the source: tell the destination to
        tombstone any partial state, then replay the parked frames
        back through the unchanged placement."""
        self.error = self.error or reason
        logger.warning(
            "reshard %d: world %r %d→%d ABORTED in %s: %s",
            self.xfer, self.world, self.source, self.target,
            self.state, self.error,
        )
        self._set_state("aborted")
        self.router.metrics.inc("cluster.reshard_aborted")
        try:
            await self._ctl_send_retry(self.target, {
                "op": "reshard_abort",
                "xfer": self.xfer,
                "world": self.world,
            }, deadline_s=2.0)
        except MigrationError:
            pass  # a dead destination lost its partial state with it
        for frame in self.buffer.replay():
            self.router.route_replay(frame)
            self.replayed += 1

    # endregion


def fence_payload(xfer_id: int) -> bytes:
    """The freeze fence's wire payload: magic + JSON meta. Never a
    valid FlatBuffers message (same bounds-rejection argument as the
    trace-context magics)."""
    return FENCE_MAGIC + json.dumps({"xfer": xfer_id}).encode()


def parse_fence(payload: bytes) -> int | None:
    """Shard side: the fence's transfer id, or None for a frame that
    merely starts with the magic but carries no valid meta."""
    if not payload.startswith(FENCE_MAGIC):
        return None
    try:
        return int(json.loads(payload[len(FENCE_MAGIC):])["xfer"])
    except (KeyError, TypeError, ValueError):
        return None
