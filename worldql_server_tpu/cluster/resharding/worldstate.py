"""Shard-side world-state capsule: export, import, tombstone.

A migration moves ONE world between two full-engine shard processes.
This module is the shard half of the protocol — three idempotent
operations the control handlers call:

* :func:`export_world` (source, STREAMING) — drain the durability
  pipeline (the capsule must contain every ACKED record), then capture
  everything shard-local about world W into one JSON-safe document:
  record rows (the WAL-backed state), subscription index rows, entity
  SoA rows, and the PARKED sessions of W's peers with their tokens
  intact. Pure read: the source keeps serving its other worlds and
  still owns W until the router flips.
* :func:`import_world` (destination, REPLAY) — apply the capsule.
  Records go THROUGH the destination's durability pipeline and a
  drain barrier, so by the time the ack leaves, W is recoverable from
  the DESTINATION's WAL — the property that makes "exactly one owner
  can recover" true at every crash point. Imported parked sessions
  funnel through ``mark_resync`` (ISSUE 18's one loss hook): the first
  frame a migrated peer sees after resume is a forced full keyframe,
  never a delta against state the new owner never held.
* :func:`tombstone_world` (source, AFTER the ack is durable) — delete
  W's records through the source's OWN durability pipeline (the
  deletes append to its WAL, so a post-tombstone crash + replay does
  not resurrect a world the placement map routed away), drop the
  index/entity rows, and discard the migrated sessions WITHOUT the
  ``peer_gone`` teardown broadcast — those peers moved, they did not
  die.
"""

from __future__ import annotations

import base64
import logging
import uuid as uuid_mod

from ...protocol.types import Record, Vector3

logger = logging.getLogger(__name__)


def _encode_record(stored) -> dict:
    record = stored.record
    return {
        "uuid": record.uuid.hex,
        "pos": [record.position.x, record.position.y, record.position.z],
        "data": record.data,
        "flex": (
            base64.b64encode(record.flex).decode()
            if record.flex is not None else None
        ),
    }


def _decode_record(row: dict, world: str) -> Record:
    return Record(
        uuid=uuid_mod.UUID(hex=row["uuid"]),
        position=Vector3(*(float(v) for v in row["pos"])),
        world_name=world,
        data=row.get("data"),
        flex=(
            base64.b64decode(row["flex"])
            if row.get("flex") is not None else None
        ),
    )


def _subscription_rows(backend, world: str) -> list[list]:
    """World W's index rows as ``[peer_hex, cx, cy, cz]`` — built from
    the backend's generic snapshot export so every SpatialBackend
    (cpu, tpu, sharded) exports the same way."""
    worlds, peers, wid_col, cube_rows, pid_col = backend.export_rows()
    try:
        wid = worlds.index(world)
    except ValueError:
        return []
    out = []
    for row in range(len(wid_col)):
        if int(wid_col[row]) != wid:
            continue
        cube = cube_rows[row]
        out.append([
            peers[int(pid_col[row])].hex,
            int(cube[0]), int(cube[1]), int(cube[2]),
        ])
    return out


async def export_world(server, world: str) -> dict:
    """Capture world ``world``'s full shard-local state (records,
    subscription rows, entity rows, parked sessions of its peers)."""
    if server.durability is not None:
        # every acked-but-unapplied record reaches the store first —
        # the capsule must be a superset of everything acknowledged
        await server.durability.drain()
    stored = await server.store.export_world_records(world)
    records = [_encode_record(s) for s in (stored or [])]
    subs = _subscription_rows(server.backend, world)
    entities = []
    if server.entity_plane is not None:
        entities = server.entity_plane.export_world(world)
    peer_hexes = {row[0] for row in subs}
    peer_hexes.update(e["owner"] for e in entities)
    sessions = []
    if server.sessions is not None:
        sessions = server.sessions.export_parked(
            uuid_mod.UUID(hex=h) for h in peer_hexes
        )
    return {
        "world": world,
        "records": records,
        "subs": subs,
        "entities": entities,
        "sessions": sessions,
    }


async def import_world(server, payload: dict) -> dict:
    """Replay a capsule into THIS shard; returns the applied counts
    (the ack body). Records land through the durability pipeline + a
    drain barrier so the ack implies WAL-durable ownership."""
    world = payload["world"]
    records = [_decode_record(r, world) for r in payload.get("records", ())]
    if records:
        sink = server.durability if server.durability is not None \
            else server.store
        await sink.insert_records(records)
    if server.durability is not None:
        await server.durability.drain()  # the DURABLE in "durable ack"
    subs_added = 0
    for peer_hex, cx, cy, cz in payload.get("subs", ()):
        if server.backend.add_subscription(
            world, uuid_mod.UUID(hex=peer_hex),
            (int(cx), int(cy), int(cz)),
        ):
            subs_added += 1
    entities_added = 0
    if payload.get("entities") and server.entity_plane is not None:
        entities_added = server.entity_plane.import_world(
            world, payload["entities"]
        )
    sessions_added = 0
    if payload.get("sessions") and server.sessions is not None:
        imported = server.sessions.import_parked(payload["sessions"])
        sessions_added = len(imported)
        for peer in imported:
            # the one loss hook (ISSUE 18): a migrated peer's first
            # post-resume frame must be a full keyframe — the ledger
            # state it accumulated lived on the OLD owner
            if server.interest is not None:
                server.interest.mark_resync(peer)
    counts = {
        "records": len(records),
        "subs": subs_added,
        "entities": entities_added,
        "sessions": sessions_added,
    }
    logger.info("imported world %r: %s", world, counts)
    return counts


async def tombstone_world(server, world: str) -> dict:
    """Delete world ``world`` from THIS shard after the destination's
    ack is durable. Deletions ride the durability pipeline so they
    append to the WAL: a crash after the tombstone replays the deletes
    too, and the world stays gone."""
    stored = await server.store.export_world_records(world)
    records = [s.record for s in (stored or [])]
    if records:
        sink = server.durability if server.durability is not None \
            else server.store
        await sink.delete_records(records)
    if server.durability is not None:
        await server.durability.drain()
    subs = _subscription_rows(server.backend, world)
    for peer_hex, cx, cy, cz in subs:
        server.backend.remove_subscription(
            world, uuid_mod.UUID(hex=peer_hex), (int(cx), int(cy), int(cz))
        )
    entities_removed = 0
    if server.entity_plane is not None:
        entities_removed = server.entity_plane.remove_world(world)
    sessions_dropped = 0
    if server.sessions is not None:
        peer_hexes = {row[0] for row in subs}
        for peer_hex in peer_hexes:
            peer = uuid_mod.UUID(hex=peer_hex)
            session = server.sessions.get(peer)
            if session is not None and session.parked:
                # migrated, not dead: discard WITHOUT the peer_gone
                # broadcast — the new owner holds the live session
                server.sessions.discard(peer)
                sessions_dropped += 1
    counts = {
        "records": len(records),
        "subs": len(subs),
        "entities": entities_removed,
        "sessions": sessions_dropped,
    }
    logger.info("tombstoned world %r: %s", world, counts)
    return counts
