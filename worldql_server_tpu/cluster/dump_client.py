"""Chunked control-channel dump client (the PR 15 pull protocol).

A shard's flight-recorder/subsystem dump does not fit one control
packet, so the shard answers a ``{"op": "dump", "req_id": N}`` request
with a series of ``{"op": "dump_chunk", "req_id", "seq", "n", "data"}``
pieces that this client reassembles.  Extracted from ``ClusterRouter``
so the TWO consumers — ``GET /debug/cluster`` and the SLO incident
capture — share ONE code path: the same req-id slots, the same chunk
reassembly, and the same timeout-degrading semantics (a dead or slow
shard yields ``None``, never an error), so a capsule can never drift
from what the debug endpoint would have shown.
"""

from __future__ import annotations

import asyncio
import json
import logging

logger = logging.getLogger(__name__)

#: Per-shard pull deadline — matches the shard's chunk retry window.
DUMP_TIMEOUT_S = 8.0


class ChunkedDumpClient:
    """Reassembles chunked control-channel dumps, one slot per
    in-flight request."""

    def __init__(self, supervisor) -> None:
        self.supervisor = supervisor
        #: in-flight collections: req_id → {"parts", "n", "event"}
        self._reqs: dict[int, dict] = {}
        self._seq = 0

    def note_chunk(self, msg: dict) -> None:
        """Control-channel reader hook: file one ``dump_chunk`` into
        its request slot (late chunks for timed-out requests drop)."""
        slot = self._reqs.get(msg.get("req_id"))
        if slot is None:
            return
        try:
            slot["parts"][int(msg["seq"])] = str(msg.get("data", ""))
            slot["n"] = int(msg["n"])
        except (KeyError, TypeError, ValueError):
            return
        if len(slot["parts"]) >= slot["n"]:
            slot["event"].set()

    async def collect(
        self, shard: int, timeout: float = DUMP_TIMEOUT_S
    ) -> dict | None:
        """Pull one shard's dump over the control channel (request →
        chunked response).  ``None`` on a dead shard or a timeout — the
        caller degrades to the processes that answered, never errors."""
        if not self.supervisor.shard_alive(shard):
            return None
        self._seq += 1
        req_id = self._seq
        slot = {"parts": {}, "n": 1 << 30, "event": asyncio.Event()}
        self._reqs[req_id] = slot
        try:
            if not self.supervisor.ctl_send(
                shard, {"op": "dump", "req_id": req_id}
            ):
                return None
            try:
                await asyncio.wait_for(slot["event"].wait(), timeout)
            except asyncio.TimeoutError:
                logger.warning("shard %d dump pull timed out", shard)
                return None
            blob = "".join(slot["parts"][i] for i in range(slot["n"]))
            return json.loads(blob)
        except Exception:
            logger.exception("shard %d dump collection failed", shard)
            return None
        finally:
            self._reqs.pop(req_id, None)
