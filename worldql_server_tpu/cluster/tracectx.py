"""Cluster trace context: the cross-process frame clock + trace id.

PR 7 gave a single process one honest frame clock (CLOCK_MONOTONIC ns
stamped at dispatch, closed at socket-write-complete). A cluster frame
crosses up to three processes — router → home shard → remote shard —
and every hop is on ONE host (the router supervises its shard
subprocesses), so the same clock domain spans the whole path. This
module defines the compact context that rides every router→shard
forward as a small framed prefix:

    [4B magic "WQTX"][u64 trace_id][u64 t_ingress_ns]      (20 bytes)

* ``trace_id`` — a random nonzero 64-bit id minted by the router per
  inbound message. Every span/segment any process records for this
  message carries it (hex-tagged), so ``GET /debug/cluster`` can
  stitch one frame's router→home→remote chain across pid lanes.
* ``t_ingress_ns`` — ``time.monotonic_ns()`` at router ingress. Shards
  close it at socket-write-complete into the live ``cluster.e2e_ms``
  histogram (the PR 7 ring-stamp precedent, stretched across the
  process boundary; comparable across processes on one host).

``unwrap`` is safe on unprefixed bytes: anything not starting with the
magic passes through untouched ``(0, 0, data)``, so a shard reached
directly (tests, a misconfigured client) still decodes. The magic can
never be a valid FlatBuffers message start: read as the root offset it
is ~1.1 GB, which the codec's bounds validation rejects.

The prefix rides ONLY the router→shard leg. Fan-out re-broadcasts the
UNWRAPPED wire bytes (``Message.wire`` is set after stripping), and
inter-shard ring frames carry the context in their own fixed header
(``cluster/bus.py``) — the delivery ring record layout itself is
untouched, so ``--cluster-shards 0`` stays byte-for-byte.

Live resharding (ISSUE 19) extends the prefix with the PLACEMENT
EPOCH the router stamped the forward under:

    [4B magic "WQT2"][u64 trace_id][u64 t_ingress_ns][u64 epoch]  (28B)

A shard compares the frame's epoch against its control-synced
:class:`~.resharding.placement.PlacementMap`: a frame stamped under an
OLDER epoch whose world the shard no longer owns is rejected with a
re-route hint instead of misapplied (router push backlogs drain across
a migration flip). ``unwrap_epoch`` decodes BOTH magics — v1 frames
carry epoch 0, which never fails the staleness check — so mixed
fleets and pre-cluster tests keep decoding.
"""

from __future__ import annotations

import random
import struct

MAGIC = b"WQTX"
_PREFIX = struct.Struct("<4sQQ")
PREFIX_LEN = _PREFIX.size  # 20

#: epoch-stamped v2 prefix (live resharding)
MAGIC2 = b"WQT2"
_PREFIX2 = struct.Struct("<4sQQQ")
PREFIX2_LEN = _PREFIX2.size  # 28

#: module-owned RNG for trace-id minting (seedable in tests)
_rng = random.Random()


def new_trace_id(rng: random.Random | None = None) -> int:
    """A random NONZERO 64-bit trace id (0 means "no context" on the
    wire, so it is never minted)."""
    r = rng if rng is not None else _rng
    while True:
        tid = r.getrandbits(64)
        if tid:
            return tid


def wrap(data: bytes, trace_id: int, t_ingress_ns: int) -> bytes:
    """Prefix one wire message with its trace context (router side)."""
    return _PREFIX.pack(MAGIC, trace_id, t_ingress_ns) + data


def unwrap(data: bytes) -> tuple[int, int, bytes]:
    """Strip a trace-context prefix → ``(trace_id, t_ingress_ns,
    payload)``; unprefixed bytes pass through as ``(0, 0, data)``."""
    if len(data) >= PREFIX_LEN and data[:4] == MAGIC:
        _, trace_id, t_ingress = _PREFIX.unpack_from(data)
        return trace_id, t_ingress, data[PREFIX_LEN:]
    return 0, 0, data


def wrap_epoch(
    data: bytes, trace_id: int, t_ingress_ns: int, epoch: int
) -> bytes:
    """Prefix one wire message with trace context + the placement
    epoch it was routed under (the resharding router's forward path —
    the ``epochless-forward`` lint rule keeps every forwarding site on
    this wrapper)."""
    return _PREFIX2.pack(MAGIC2, trace_id, t_ingress_ns, epoch) + data


def unwrap_epoch(data: bytes) -> tuple[int, int, int, bytes]:
    """Strip either prefix generation → ``(trace_id, t_ingress_ns,
    epoch, payload)``. v1 ("WQTX") frames and unprefixed bytes carry
    epoch 0 — "no placement claim", never stale."""
    if len(data) >= PREFIX2_LEN and data[:4] == MAGIC2:
        _, trace_id, t_ingress, epoch = _PREFIX2.unpack_from(data)
        return trace_id, t_ingress, epoch, data[PREFIX2_LEN:]
    if len(data) >= PREFIX_LEN and data[:4] == MAGIC:
        _, trace_id, t_ingress = _PREFIX.unpack_from(data)
        return trace_id, t_ingress, 0, data[PREFIX_LEN:]
    return 0, 0, 0, data


def trace_id_hex(trace_id: int) -> str:
    """The canonical span-tag form of a trace id."""
    return format(trace_id, "016x")
