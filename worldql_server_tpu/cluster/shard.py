"""Shard-side cluster extension: remote peers, ring drain, control.

A cluster shard IS the existing single-process server — same router,
ticker, WAL, governor, entity plane — plus this extension, attached
when ``--cluster-role shard`` boots with a ``WQL_CLUSTER_SPEC``
topology. It adds exactly three things:

* **Remote peer proxies.** Every peer is HOMED on one shard (stable
  uuid hash — world_map.py); the home shard owns the real connect-back
  socket. When the router announces a peer homed elsewhere (control
  ``adopt``), this shard registers a :class:`~..engine.peers.Peer`
  whose write paths enqueue the frame onto the inter-shard ring toward
  the home shard — so the UNCHANGED fan-out code (``PeerMap.
  deliver_batch``, broadcasts, record replies) transparently reaches
  peers connected anywhere in the cluster. Proxies register/deregister
  through the SILENT map paths (``rebind``/``detach``): peer lifecycle
  broadcasts (PeerConnect/Disconnect) are emitted once, by the home
  shard, and reach every client exactly once — local peers directly,
  remote ones through the proxies of THAT broadcast.
* **The cross-shard drain.** Frames arriving on the inbound rings are
  delivered to local sockets inside the tick, between the local
  batch's device dispatch and its collect (``cluster.drain`` span) —
  the TileLoom overlap discipline: the inter-shard leg hides behind
  the in-flight device window instead of serializing in front of it.
  Tickerless (immediate-mode) shards run a supervised drain pump
  instead. The cross-shard leg is enqueue-and-drain ONLY (lint:
  ``blocking-cross-shard``) — nothing on the tick path ever awaits a
  remote shard.
* **The control channel.** AF_UNIX SEQPACKET to the router-tier
  supervisor: inbound ``adopt``/``drop`` maintain the proxy plane;
  outbound ``state`` exports the shard's overload-governor level (the
  router's shed mirror REJECTs at the router before this shard ever
  sees the bytes) and ``peer_gone`` reports a homed peer's teardown so
  the router reaps its proxies cluster-wide. Control-channel EOF means
  the router died: the shard requests its own clean shutdown rather
  than serving unreachable.

Cluster observability (ISSUE 15) rides the same three surfaces:

* Every router-forwarded message carries a trace context
  (``tracectx.py``: 64-bit trace id + router-ingress monotonic-ns
  stamp). The shard closes that clock at socket-write-complete —
  locally delivered frames through the ticker's post-delivery
  :meth:`close_frames`, ring-drained frames inside :meth:`drain` —
  into the live ``cluster.e2e_ms`` histogram, and closes
  ``cluster.xshard_ms`` (home-shard-enqueue → remote-shard-write)
  for every drained frame. A frame slower than ``--slow-frame-ms``
  auto-dumps its stitched router→home→remote stage chain as one JSON
  line (the PR 5 slow-tick discipline, per cross-shard frame).
* ``state`` packets piggyback cumulative histogram/counter snapshots
  (``Metrics.export_histograms``); the router diffs consecutive
  packets and merges them restart-monotone into ONE federated
  /metrics (cluster/federation.py).
* A control ``dump`` request chunks the shard's FlightRecorder
  snapshot back to the router, which splices every process's spans
  into one Chrome trace at ``GET /debug/cluster``. Drained-frame
  segments are stitched as ``router.forward`` / ``cluster.ring_dwell``
  spans under the receiving shard's tick trace at export time (the
  PR 7 delivery-plane stitcher idiom).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import socket
import time
import uuid as uuid_mod
from collections import deque

from ..engine.peers import Peer
from ..protocol import Instruction
from ..robustness import failpoints
from . import tracectx
from .bus import InterShardBus
from .resharding import (
    FENCE_MAGIC,
    ChunkAssembler,
    PlacementMap,
    encode_chunks,
    export_world,
    import_world,
    parse_fence,
    tombstone_world,
)

logger = logging.getLogger(__name__)

from .supervisor import CLUSTER_SPEC_ENV  # noqa: E402  (shared env name)

#: inbound ring records consumed per drain call — bounds one tick's
#: drain leg; the remainder stays queued for the next tick (or the
#: immediate re-drain when the pump sees pending bytes)
DRAIN_MAX = 4096

#: governor state export cadence: immediate on a level change, plus a
#: heartbeat so the router can age out a wedged shard's state
STATE_INTERVAL_S = 1.0
STATE_POLL_S = 0.1

#: histogram series piggybacked on state packets for the router-side
#: metrics federation — bounded by prefix so a packet stays well under
#: the control channel's 64 KiB datagram read
FED_HIST_PREFIXES = (
    "cluster.", "frame.", "tick.", "delivery.", "broadcast.",
)

#: drained-frame segments retained for flight-recorder stitching and
#: counter packets kept per state push (the PR 7 ≤128-segment bound)
SEGMENT_DEPTH = 512

#: slow-frame dumps are the pathological path, but a drain can carry
#: thousands of frames — bound the per-drain dump burst (the rest are
#: counted, never silent)
SLOW_FRAME_DUMPS_PER_DRAIN = 8

SLOW_FRAME_FILENAME = "slow-frames.jsonl"

#: control-channel dump chunking: JSON-escaped chunk + envelope must
#: stay under the supervisor's 64 KiB sock_recv
DUMP_CHUNK_CHARS = 24_000


class _BusFrame:
    """Ready wire bytes off the inter-shard ring — deliver_batch
    consumes ``.wire`` and never re-serializes."""

    __slots__ = ("wire",)

    def __init__(self, wire: bytes):
        self.wire = wire


def load_spec(env: dict | None = None) -> dict:
    raw = (env or os.environ).get(CLUSTER_SPEC_ENV)
    if not raw:
        raise RuntimeError(
            "--cluster-role shard requires the WQL_CLUSTER_SPEC "
            "topology (set by the router-tier supervisor)"
        )
    return json.loads(raw)


class ClusterShardExtension:
    #: re-exported for the transports (which check the fence payload
    #: prefix via this attribute, never importing the cluster package)
    FENCE_MAGIC = FENCE_MAGIC

    def __init__(self, server, spec: dict | None = None):
        self.server = server
        spec = spec if spec is not None else load_spec()
        self.shard_id = int(spec["shard_id"])
        self.n_shards = int(spec["n_shards"])
        # epoch-versioned placement (live resharding): converged from
        # router broadcasts + the epoch check on the ~1s state exchange
        self.placement = PlacementMap(self.n_shards)
        self.world_map = self.placement  # compatibility alias
        self.bus = InterShardBus(self.shard_id)
        rings = spec.get("rings") or {"out": {}, "in": {}}
        self.bus.attach(rings.get("out", {}), rings.get("in", {}))
        self._ctl_path = spec["ctl_path"]
        self._ctl: socket.socket | None = None
        #: uuid → home shard for every remote proxy this shard holds
        self._remote: dict[uuid_mod.UUID, int] = {}
        self._last_level_sent: int | None = None
        self._last_state_push = 0.0
        self.xshard_frames = 0
        #: drained-frame telemetry segments for trace stitching:
        #: (trace_id, t_router_ingress, t_enqueue, t_ring_write,
        #: t_read, t_done) — monotonic ns, zeros where unknown
        self._segments: deque = deque(maxlen=SEGMENT_DEPTH)
        self.slow_frame_ms = getattr(server.config, "slow_frame_ms", None)
        self.slow_frames_dumped = 0
        self.slow_frames_skipped = 0
        # live resharding (destination side): one capsule stream at a
        # time, resumable from chunk 0 after a restart re-stream
        self._import_xfer: int | None = None
        self._import_assembler = ChunkAssembler()
        #: completed imports: xfer → counts — a re-streamed capsule
        #: after a lost ack is RE-ACKED, never re-applied
        self._import_counts: dict[int, dict] = {}
        self._reshard_tasks: set = set()
        self.rerouted = 0

    # region: lifecycle

    async def start(self) -> None:
        """Connect the control channel and announce readiness — called
        at the END of server.start(), once the ZMQ listener is bound,
        so the router never forwards into an unbound socket."""
        ctl = socket.socket(socket.AF_UNIX, socket.SOCK_SEQPACKET)
        ctl.settimeout(10.0)
        ctl.connect(self._ctl_path)
        ctl.setblocking(False)
        self._ctl = ctl
        self._ctl_send({"op": "ready", "shard": self.shard_id})
        self.server.supervisor.spawn(
            "cluster-control", self._control_loop, critical=True
        )
        if self.server.ticker is None:
            # immediate-mode shard: no tick clock to ride — a
            # supervised pump drains the inbound rings instead
            self.server.supervisor.spawn("cluster-drain", self._drain_pump)
        logger.info(
            "cluster shard %d/%d attached (%d peer rings)",
            self.shard_id, self.n_shards, len(self.bus.peers()),
        )

    async def stop(self) -> None:
        for task in list(self._reshard_tasks):
            task.cancel()
        if self._ctl is not None:
            self._ctl.close()
            self._ctl = None
        self.bus.close()

    # endregion

    # region: remote peer proxies

    def _make_proxy(self, peer_uuid: uuid_mod.UUID, home: int) -> Peer:
        bus = self.bus
        metrics = self.server.metrics

        def try_write(framed, _u=peer_uuid, _h=home) -> bool:
            # fire-and-forget onto the home shard's ring; a full ring
            # drops (counted) — bounded degradation, never a stalled
            # tick. Returning True keeps deliver_batch off the awaited
            # slow path: there is nothing more awaiting could do. The
            # framed payload's trace context rides the frame header so
            # the REMOTE shard closes the router-ingress clock.
            if not bus.send_frame(_h, _u, framed.payload,
                                  time.monotonic_ns(), ctx=framed.ctx):
                metrics.inc("cluster.ring_full_drops")
            return True

        def try_write_many(framed_list, _u=peer_uuid, _h=home) -> bool:
            now = time.monotonic_ns()
            for framed in framed_list:
                if not bus.send_frame(_h, _u, framed.payload, now,
                                      ctx=framed.ctx):
                    metrics.inc("cluster.ring_full_drops")
            return True

        async def send_raw(data: bytes, _u=peer_uuid, _h=home) -> None:
            if not bus.send_frame(_h, _u, data, time.monotonic_ns()):
                metrics.inc("cluster.ring_full_drops")

        return Peer(
            uuid=peer_uuid,
            addr=f"shard-{home}",
            send_raw=send_raw,
            kind="cluster-remote",
            tracks_heartbeat=False,
            try_write=try_write,
            try_write_many=try_write_many,
        )

    def adopt_remote(self, peer_uuid: uuid_mod.UUID, home: int) -> None:
        """Router announced a peer homed on another shard: register the
        ring-backed proxy (silently — the home shard owns the lifecycle
        broadcasts). Re-adoption after a shard restart just replaces
        the proxy; a peer homed HERE is never proxied."""
        if home == self.shard_id:
            return
        existing = self.server.peer_map.get(peer_uuid)
        if existing is not None and existing.kind != "cluster-remote":
            # a real local binding outranks a proxy announcement
            return
        self.server.peer_map.rebind(self._make_proxy(peer_uuid, home))
        self._remote[peer_uuid] = home

    def drop_remote(self, peer_uuid: uuid_mod.UUID) -> None:
        if self._remote.pop(peer_uuid, None) is None:
            return
        existing = self.server.peer_map.get(peer_uuid)
        if existing is not None and existing.kind == "cluster-remote":
            self.server.peer_map.detach(peer_uuid)

    def on_peer_torn_down(self, peer_uuid: uuid_mod.UUID) -> None:
        """Server hook: a peer HOMED here fully tore down (session
        expiry, eviction, clean disconnect past the TTL) — tell the
        router so every other shard reaps its proxy."""
        if peer_uuid in self._remote or self._ctl is None:
            return
        self._ctl_send({"op": "peer_gone", "uuid": peer_uuid.hex})

    # endregion

    # region: trace context (the router-stamped frame clock)

    @staticmethod
    def unwrap(data: bytes) -> tuple[int, int, int, bytes]:
        """Strip the router's trace+epoch prefix (transport hook — the
        transports never import the cluster package directly). Returns
        ``(trace_id, t_ingress_ns, epoch, payload)``; v1/unprefixed
        frames decode as epoch 0, which is never stale."""
        return tracectx.unwrap_epoch(data)

    def close_frames(self, messages) -> None:
        """Close the router-ingress clock for locally-delivered frames
        — called by the ticker AFTER a tick's batched delivery
        completes (socket-write-complete, the conservative PR 7
        close). Messages without a context (entity frames, locally
        injected traffic) cost one attribute read each."""
        now_ns = time.monotonic_ns()
        metrics = self.server.metrics
        for message in messages:
            ctx = getattr(message, "trace_ctx", None)
            if ctx is not None and ctx[1]:
                metrics.observe_ms(
                    "cluster.e2e_ms", (now_ns - ctx[1]) / 1e6
                )

    # endregion

    # region: drain (the tick's cross-shard leg)

    async def drain(self) -> int:
        """Deliver everything queued on the inbound rings to LOCAL
        sockets. Called by the ticker between the local batch's device
        dispatch and collect (the ``cluster.drain`` span), or by the
        standalone pump on tickerless shards. Returns frames drained.

        Both cross-process clocks close HERE, after the delivery
        completes (socket-write-complete): ``cluster.xshard_ms`` from
        the home shard's enqueue stamp and ``cluster.e2e_ms`` from the
        router-ingress stamp in the frame's trace context. Per-frame
        segments feed the flight-recorder stitcher, and a frame whose
        e2e wall blows ``--slow-frame-ms`` dumps its stitched
        router→home→remote stage chain as one JSON line."""
        t0_ns = time.monotonic_ns()
        # chaos site: a delay stretches the remote leg (ring dwell) —
        # the slow-frame acceptance drives its dump deterministically
        await failpoints.afire("cluster.ring_deliver")
        records = self.bus.drain(DRAIN_MAX)
        if not records:
            return 0
        t_read_ns = time.monotonic_ns()
        metrics = self.server.metrics
        pairs = [
            (_BusFrame(data), (peer_uuid,))
            for peer_uuid, data, _te, _tw, _tid, _tc in records
        ]
        self.xshard_frames += len(records)
        metrics.inc("cluster.frames_drained", len(records))
        await self.server.peer_map.deliver_batch(pairs)
        t_done_ns = time.monotonic_ns()
        tracing = self.server.tracer.enabled
        slow_ms = self.slow_frame_ms
        dumps_left = SLOW_FRAME_DUMPS_PER_DRAIN
        for _peer, _data, t_enqueue, t_write, trace_id, t_ctx in records:
            if t_enqueue:
                metrics.observe_ms(
                    "cluster.xshard_ms", (t_done_ns - t_enqueue) / 1e6
                )
            if t_ctx:
                total_ms = (t_done_ns - t_ctx) / 1e6
                metrics.observe_ms("cluster.e2e_ms", total_ms)
                if slow_ms is not None and total_ms >= slow_ms:
                    if dumps_left > 0:
                        dumps_left -= 1
                        self._dump_slow_frame(
                            trace_id, t_ctx, t_enqueue, t_write,
                            t_read_ns, t_done_ns, total_ms,
                        )
                    else:
                        self.slow_frames_skipped += 1
            if tracing:
                self._segments.append((
                    trace_id, t_ctx, t_enqueue, t_write, t_read_ns,
                    t_done_ns,
                ))
        return len(records)

    async def _drain_pump(self) -> None:
        interval = max(self.server.config.tick_interval, 0.005)
        while True:
            await asyncio.sleep(interval)
            await self.drain()

    def _frame_stages(
        self, t_ctx: int, t_enqueue: int, t_write: int, t_read: int,
        t_done: int,
    ) -> dict[str, float]:
        """One cross-shard frame's wall, attributed to named stages:
        ``router.forward`` (router ingress → home-shard ring enqueue —
        the forward hop plus the home shard's decode/queue/resolve),
        ``cluster.ring_dwell`` (ring write → remote drain read) and
        ``cluster.deliver`` (drain read → socket-write-complete). The
        only unattributed sliver is the enqueue→ring-write gap, a few
        µs of struct packing — ≥90% attribution by construction."""
        stages = {}
        if t_ctx and t_enqueue:
            stages["router.forward"] = (t_enqueue - t_ctx) / 1e6
        if t_write:
            stages["cluster.ring_dwell"] = (t_read - t_write) / 1e6
        stages["cluster.deliver"] = (t_done - t_read) / 1e6
        return stages

    def _dump_slow_frame(
        self, trace_id: int, t_ctx: int, t_enqueue: int, t_write: int,
        t_read: int, t_done: int, total_ms: float,
    ) -> None:
        """The PR 5 slow-tick auto-dump, per cross-shard frame: one
        JSON line with the stitched stage chain + a CRITICAL log."""
        self.slow_frames_dumped += 1
        metrics = self.server.metrics
        metrics.inc("cluster.slow_frame_dumps")
        stages = self._frame_stages(
            t_ctx, t_enqueue, t_write, t_read, t_done
        )
        record = {
            "dumped_at_unix_s": round(time.time(), 6),
            "slow_frame_ms_threshold": self.slow_frame_ms,
            "shard": self.shard_id,
            "trace_id": tracectx.trace_id_hex(trace_id),
            "total_ms": round(total_ms, 3),
            "stages": {k: round(v, 3) for k, v in stages.items()},
        }
        dump_dir = self.server.config.slow_tick_dir
        path = os.path.join(dump_dir, SLOW_FRAME_FILENAME)
        try:
            os.makedirs(dump_dir, exist_ok=True)
            with open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps(record) + "\n")
            where = path
        except Exception:
            logger.exception("slow-frame dump write failed")
            where = "<dump write failed>"
        attributed = sum(stages.values())
        logger.critical(
            "SLOW CLUSTER FRAME: %.1f ms (threshold %.1f ms) trace %s — "
            "stages %s attribute %.1f ms (%.0f%%); dumped to %s",
            total_ms, self.slow_frame_ms, record["trace_id"],
            {k: round(v, 1) for k, v in sorted(stages.items())},
            attributed,
            100.0 * attributed / total_ms if total_ms else 0.0,
            where,
        )

    # endregion

    # region: trace stitching (flight-recorder export hook)

    def chain_stitcher(self, prev):
        """Compose this extension's stitcher with whatever the
        recorder already has (the delivery plane claims the slot when
        ``--delivery-workers`` > 0)."""
        if prev is None:
            return self.stitch

        def chained(trace):
            out = list(prev(trace) or [])
            out.extend(self.stitch(trace) or [])
            return out

        return chained

    def stitch(self, trace) -> list[dict]:
        """Graft ``router.forward`` + ``cluster.ring_dwell`` spans for
        every drained frame whose read stamp falls inside this tick's
        ``cluster.drain`` window — the cross-shard legs of the frame,
        reconstructed from the trace-context and ring stamps (all
        CLOCK_MONOTONIC on one host, the PR 7 stitching precedent).
        Bounded per trace; a miss degrades to local spans only."""
        with trace._lock:
            drains = [s for s in trace.spans if s.name == "cluster.drain"]
        if not drains or not self._segments:
            return []
        out: list[dict] = []
        base = trace.perf_start
        for ds in drains:
            w0 = ds.t0 - 1e-4
            w1 = ds.t0 + ds.dur_ms / 1e3 + 1e-4
            for (trace_id, t_ctx, t_enqueue, t_write, t_read,
                 t_done) in self._segments:
                t_read_s = t_read / 1e9
                if not (w0 <= t_read_s <= w1):
                    continue
                tid_hex = tracectx.trace_id_hex(trace_id)
                if t_ctx and t_enqueue:
                    out.append({
                        # negative ids offset past the delivery plane's
                        # synthetic range: stitched spans never collide
                        # with the trace's own positive ids
                        "id": -(1000 + len(out) + 1),
                        "parent": ds.id,
                        "name": "router.forward",
                        "t0_ms": round((t_ctx / 1e9 - base) * 1e3, 3),
                        "dur_ms": round((t_enqueue - t_ctx) / 1e6, 3),
                        "tags": {"trace_id": tid_hex},
                        "thread": "cluster",
                    })
                if t_write:
                    out.append({
                        "id": -(1000 + len(out) + 1),
                        "parent": ds.id,
                        "name": "cluster.ring_dwell",
                        "t0_ms": round((t_write / 1e9 - base) * 1e3, 3),
                        "dur_ms": round((t_read - t_write) / 1e6, 3),
                        "tags": {
                            "trace_id": tid_hex,
                            "deliver_ms": round((t_done - t_read) / 1e6, 3),
                        },
                        "thread": "cluster",
                    })
                if len(out) >= 64:
                    return out
        return out

    # endregion

    # region: live resharding (the shard half of the protocol)

    def frame_stale(self, epoch: int) -> bool:
        """True when the frame was stamped under an OLDER placement
        than this shard holds: the transport diverts it off the fast
        path into the full decode + ownership check — a stale entity
        frame must never touch the SoA columns directly. Epoch 0
        (pre-resharding router, replayed WAL, direct client) is never
        stale."""
        return epoch != 0 and epoch < self.placement.epoch

    def frame_misrouted(self, message, epoch: int) -> bool:
        """Post-decode ownership check for a stale-epoch frame: a frame
        for a world (or peer) this shard no longer owns under the
        CURRENT placement bounces back to the router over control as a
        re-route hint — applied here it would mutate state the
        placement already moved away. True = bounced, caller drops."""
        if message.instruction in (
            Instruction.HANDSHAKE, Instruction.HEARTBEAT
        ):
            if message.sender_uuid is None:
                return False
            owner = self.placement.shard_of_peer(message.sender_uuid)
        else:
            owner = self.placement.shard_of_world(message.world_name)
        if owner == self.shard_id:
            return False  # stale stamp, still the right owner: process
        wire = message.wire
        if wire is None:
            return False
        import base64

        self.rerouted += 1
        self.server.metrics.inc("cluster.shard_rerouted")
        self._spawn_reshard(self._ctl_send_retry({
            "op": "reroute",
            "data": base64.b64encode(wire).decode(),
        }, deadline_s=2.0))
        return True

    def on_fence(self, payload: bytes) -> None:
        """A freeze fence arrived on the DATA path: the PULL socket is
        FIFO and processing is in-order, so every frame the router
        forwarded before the fence has already been handled — the
        control ack is the drain proof the migration coordinator waits
        on before exporting."""
        xfer = parse_fence(payload)
        if xfer is None:
            return
        self.server.metrics.inc("cluster.fence_seen")
        self._spawn_reshard(self._ctl_send_retry({
            "op": "fence_ack", "xfer": xfer,
        }))

    def _spawn_reshard(self, coro) -> None:
        task = asyncio.get_running_loop().create_task(coro)  # wql: allow(unsupervised-task) — one-shot, retained below, cancelled in stop()
        self._reshard_tasks.add(task)
        task.add_done_callback(self._reshard_tasks.discard)

    async def _ctl_send_retry(self, packet: dict,
                              deadline_s: float = 5.0) -> bool:
        """The dump-chunk deadline-retry idiom for migration control
        packets: a momentarily full control socket retries briefly
        instead of silently dropping a protocol step (the coordinator's
        timeouts catch a genuinely dead channel)."""
        deadline = time.monotonic() + deadline_s
        while not self._ctl_send(packet):
            if time.monotonic() >= deadline:
                return False
            await asyncio.sleep(0.01)
        return True

    async def _do_export(self, xfer: int, world: str) -> None:
        """Source side, STREAMING: capture the capsule (behind a
        durability drain) and chunk it to the router CRC-framed."""
        try:
            payload = await export_world(self.server, world)
        except Exception:
            logger.exception(
                "reshard %d: export of %r failed", xfer, world
            )
            return
        for chunk in encode_chunks(payload):
            if not await self._ctl_send_retry({
                "op": "reshard_chunk", "xfer": xfer, "chunk": chunk,
            }):
                logger.warning(
                    "reshard %d: capsule chunk send timed out", xfer
                )
                return

    def _on_import_chunk(self, msg: dict) -> None:
        """Destination side: feed one retained chunk. A transfer id
        change (new migration, or the router re-streaming from zero
        after this shard restarted) resets assembly; corruption resets
        and waits — the coordinator's CRC check aborts its side."""
        try:
            xfer = int(msg["xfer"])
            chunk = msg["chunk"]
        except (KeyError, TypeError, ValueError):
            return
        if xfer != self._import_xfer:
            self._import_xfer = xfer
            self._import_assembler.reset()
        doc = self._import_assembler.feed(chunk)
        if self._import_assembler.corrupt:
            logger.warning(
                "reshard %d: corrupt capsule chunk — assembly reset, "
                "awaiting re-stream", xfer,
            )
            self._import_assembler.reset()
            return
        if doc is not None:
            self._spawn_reshard(self._do_import(xfer, doc))

    async def _do_import(self, xfer: int, doc: dict) -> None:
        """Apply the capsule THROUGH the durability pipeline (+ drain
        barrier), then ack with the counts: from the ack on, this shard
        can recover the world from its OWN WAL. Idempotent: a re-stream
        after a lost ack re-acks the cached counts."""
        if xfer not in self._import_counts:
            try:
                counts = await import_world(self.server, doc)
            except Exception:
                logger.exception("reshard %d: capsule import failed", xfer)
                return
            self._import_counts[xfer] = counts
            while len(self._import_counts) > 8:
                self._import_counts.pop(next(iter(self._import_counts)))
        await self._ctl_send_retry({
            "op": "reshard_imported", "xfer": xfer,
            "counts": self._import_counts[xfer],
        })

    async def _do_tombstone(self, xfer: int, world: str) -> None:
        """Source side, AFTER the destination's ack is durable: delete
        the moved world through this shard's own WAL. Idempotent — the
        router re-issues on every ready until the ack lands."""
        try:
            counts = await tombstone_world(self.server, world)
        except Exception:
            logger.exception(
                "reshard %d: tombstone of %r failed", xfer, world
            )
            return
        await self._ctl_send_retry({
            "op": "reshard_tombstoned", "xfer": xfer, "counts": counts,
        })

    def _on_reshard_abort(self, msg: dict) -> None:
        """The coordinator aborted: ownership stays with the source.
        Drop any partial assembly and scrub whatever this shard already
        applied (tombstone_world is idempotent; a no-op for nothing)."""
        try:
            xfer = int(msg["xfer"])
        except (KeyError, TypeError, ValueError):
            return
        if xfer == self._import_xfer:
            self._import_xfer = None
            self._import_assembler.reset()
        world = msg.get("world")
        if isinstance(world, str) and world:
            self._import_counts.pop(xfer, None)
            self._spawn_reshard(self._scrub_aborted(xfer, world))

    async def _scrub_aborted(self, xfer: int, world: str) -> None:
        try:
            counts = await tombstone_world(self.server, world)
            logger.warning(
                "reshard %d aborted: scrubbed partial import of %r: %s",
                xfer, world, counts,
            )
        except Exception:
            logger.exception("reshard %d: abort scrub failed", xfer)

    # endregion

    # region: control channel

    def _ctl_send(self, msg: dict) -> bool:
        if self._ctl is None:
            return False
        try:
            self._ctl.send(json.dumps(msg).encode())
            return True
        except (BlockingIOError, InterruptedError):
            return False  # control is best-effort; state re-pushes
        except OSError:
            return False

    def _state_packet(self) -> dict:
        gov = self.server.governor
        metrics = self.server.metrics
        counters = metrics.snapshot()["counters"]
        packet = {
            "op": "state",
            "shard": self.shard_id,
            "level": 0,
            "state": "ok",
            "peers": self.server.peer_map.size(),
            # the router re-pushes the placement spec when this lags
            # its epoch — restart convergence with no coordinator
            "placement_epoch": self.placement.epoch,
            "bus": self.bus.stats(),
            "counters": {
                k: v for k, v in counters.items()
                if k.startswith(("messages.", "overload.", "tick.",
                                 "cluster.", "broadcast."))
            },
            # cumulative histogram snapshots for the router's metrics
            # federation — diffed packet-to-packet into merge_histogram
            # deltas there, so the federated series stay monotone
            # across shard restarts (a fresh shard re-baselines)
            "hist": metrics.export_histograms(FED_HIST_PREFIXES),
        }
        if gov is not None:
            packet.update(gov.export_state())
            packet["op"] = "state"  # export_state must not shadow it
        if self.server.slo is not None:
            # local compliance piggybacks the ~1s state clock — the
            # router's fleet SLO report names the burning process
            packet["slo"] = self.server.slo.compliance()
        return packet

    def _maybe_push_state(self) -> None:
        gov = self.server.governor
        level = gov.level if gov is not None else 0
        now = time.monotonic()
        if (
            level == self._last_level_sent
            and now - self._last_state_push < STATE_INTERVAL_S
        ):
            return
        try:
            # chaos site: an armed error silences this shard's
            # telemetry exports while the process stays alive — the
            # router's telemetry_stale freshness probe must see it
            failpoints.fire("cluster.state_push")
        except failpoints.FailpointError:
            return
        if self._ctl_send(self._state_packet()):
            self._last_level_sent = level
            self._last_state_push = now

    async def _control_loop(self) -> None:
        """Supervised: inbound adopt/drop + the state export clock.
        Control EOF == the router (and its supervisor) is gone — a
        shard nobody can reach must hand control back cleanly."""
        loop = asyncio.get_running_loop()
        while True:
            try:
                data = await asyncio.wait_for(
                    loop.sock_recv(self._ctl, 65536), STATE_POLL_S
                )
                if not data:
                    raise ConnectionResetError("router control EOF")
                await self._handle_control(data)
            except asyncio.TimeoutError:
                pass
            except (ConnectionResetError, BrokenPipeError, OSError):
                logger.critical(
                    "cluster control channel lost — router is gone; "
                    "requesting clean shard shutdown"
                )
                self.server.shutdown_requested.set()
                return
            self._maybe_push_state()

    async def _handle_control(self, data: bytes) -> None:
        try:
            msg = json.loads(data)
        except ValueError:
            return
        op = msg.get("op")
        if op == "adopt":
            self.adopt_remote(
                uuid_mod.UUID(hex=msg["uuid"]), int(msg["home"])
            )
        elif op == "drop":
            self.drop_remote(uuid_mod.UUID(hex=msg["uuid"]))
        elif op == "dump":
            # router-side GET /debug/cluster: chunk this shard's
            # flight-recorder snapshot back over the control channel
            await self._send_dump(int(msg.get("req_id", 0)))
        elif op == "placement":
            spec = msg.get("spec")
            if isinstance(spec, dict) and self.placement.apply_spec(spec):
                self.server.metrics.inc("cluster.placement_applied")
        elif op == "reshard_export":
            self._spawn_reshard(self._do_export(
                int(msg.get("xfer", 0)), str(msg.get("world", ""))
            ))
        elif op == "reshard_import_chunk":
            self._on_import_chunk(msg)
        elif op == "reshard_tombstone":
            self._spawn_reshard(self._do_tombstone(
                int(msg.get("xfer", 0)), str(msg.get("world", ""))
            ))
        elif op == "reshard_abort":
            self._on_reshard_abort(msg)
        elif op == "inject":
            # router-side HTTP /global_message: a trusted in-process
            # injection stretched across the process boundary — the
            # public PULL would (rightly) drop its nil sender
            import base64

            from ..protocol import deserialize_message

            try:
                message = deserialize_message(
                    base64.b64decode(msg["data"])
                )
            except Exception:
                logger.warning("undecodable control injection dropped")
                return
            await self.server.router.handle_message(message)

    async def _send_dump(self, req_id: int) -> None:
        """Chunk the flight-recorder snapshot + this process's
        subsystem sections to the router (the control channel's 64 KiB
        datagrams can't carry a whole Chrome-trace worth of spans in
        one packet). The SAME dump serves ``GET /debug/cluster`` (which
        reads ticks/loose) and the router's incident capture (which
        additionally embeds the sections), so the capsule can never see
        a different shard state than the debug endpoint. Tracing off
        sends an empty-but-well-formed dump so the router never times
        out on a healthy shard."""
        from ..observability.incidents import capsule_sections

        recorder = getattr(self.server, "recorder", None)
        payload = {
            "shard": self.shard_id,
            "pid": os.getpid(),
            "ticks": recorder.snapshot() if recorder is not None else [],
            "loose": (
                recorder.loose_snapshot() if recorder is not None else []
            ),
            "sections": capsule_sections(self.server),
        }
        try:
            blob = json.dumps(payload)
        except (TypeError, ValueError):
            logger.exception("flight-recorder dump not serializable")
            blob = json.dumps({
                "shard": self.shard_id, "pid": os.getpid(),
                "ticks": [], "loose": [],
            })
        chunks = [
            blob[i:i + DUMP_CHUNK_CHARS]
            for i in range(0, len(blob), DUMP_CHUNK_CHARS)
        ] or [""]
        for seq, chunk in enumerate(chunks):
            packet = {
                "op": "dump_chunk", "req_id": req_id, "seq": seq,
                "n": len(chunks), "data": chunk,
            }
            deadline = time.monotonic() + 2.0
            while not self._ctl_send(packet):
                if time.monotonic() >= deadline:
                    logger.warning(
                        "dump chunk %d/%d to router timed out",
                        seq + 1, len(chunks),
                    )
                    return
                await asyncio.sleep(0.01)

    # endregion

    def stats(self) -> dict:
        return {
            "shard_id": self.shard_id,
            "n_shards": self.n_shards,
            "remote_peers": len(self._remote),
            "placement_epoch": self.placement.epoch,
            "rerouted": self.rerouted,
            "xshard_frames": self.xshard_frames,
            "slow_frames_dumped": self.slow_frames_dumped,
            "slow_frames_skipped": self.slow_frames_skipped,
            **self.bus.stats(),
        }
