"""Shard-side cluster extension: remote peers, ring drain, control.

A cluster shard IS the existing single-process server — same router,
ticker, WAL, governor, entity plane — plus this extension, attached
when ``--cluster-role shard`` boots with a ``WQL_CLUSTER_SPEC``
topology. It adds exactly three things:

* **Remote peer proxies.** Every peer is HOMED on one shard (stable
  uuid hash — world_map.py); the home shard owns the real connect-back
  socket. When the router announces a peer homed elsewhere (control
  ``adopt``), this shard registers a :class:`~..engine.peers.Peer`
  whose write paths enqueue the frame onto the inter-shard ring toward
  the home shard — so the UNCHANGED fan-out code (``PeerMap.
  deliver_batch``, broadcasts, record replies) transparently reaches
  peers connected anywhere in the cluster. Proxies register/deregister
  through the SILENT map paths (``rebind``/``detach``): peer lifecycle
  broadcasts (PeerConnect/Disconnect) are emitted once, by the home
  shard, and reach every client exactly once — local peers directly,
  remote ones through the proxies of THAT broadcast.
* **The cross-shard drain.** Frames arriving on the inbound rings are
  delivered to local sockets inside the tick, between the local
  batch's device dispatch and its collect (``cluster.drain`` span) —
  the TileLoom overlap discipline: the inter-shard leg hides behind
  the in-flight device window instead of serializing in front of it.
  Tickerless (immediate-mode) shards run a supervised drain pump
  instead. The cross-shard leg is enqueue-and-drain ONLY (lint:
  ``blocking-cross-shard``) — nothing on the tick path ever awaits a
  remote shard.
* **The control channel.** AF_UNIX SEQPACKET to the router-tier
  supervisor: inbound ``adopt``/``drop`` maintain the proxy plane;
  outbound ``state`` exports the shard's overload-governor level (the
  router's shed mirror REJECTs at the router before this shard ever
  sees the bytes) and ``peer_gone`` reports a homed peer's teardown so
  the router reaps its proxies cluster-wide. Control-channel EOF means
  the router died: the shard requests its own clean shutdown rather
  than serving unreachable.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import socket
import time
import uuid as uuid_mod

from ..engine.peers import Peer
from .bus import InterShardBus
from .world_map import WorldMap

logger = logging.getLogger(__name__)

from .supervisor import CLUSTER_SPEC_ENV  # noqa: E402  (shared env name)

#: inbound ring records consumed per drain call — bounds one tick's
#: drain leg; the remainder stays queued for the next tick (or the
#: immediate re-drain when the pump sees pending bytes)
DRAIN_MAX = 4096

#: governor state export cadence: immediate on a level change, plus a
#: heartbeat so the router can age out a wedged shard's state
STATE_INTERVAL_S = 1.0
STATE_POLL_S = 0.1


class _BusFrame:
    """Ready wire bytes off the inter-shard ring — deliver_batch
    consumes ``.wire`` and never re-serializes."""

    __slots__ = ("wire",)

    def __init__(self, wire: bytes):
        self.wire = wire


def load_spec(env: dict | None = None) -> dict:
    raw = (env or os.environ).get(CLUSTER_SPEC_ENV)
    if not raw:
        raise RuntimeError(
            "--cluster-role shard requires the WQL_CLUSTER_SPEC "
            "topology (set by the router-tier supervisor)"
        )
    return json.loads(raw)


class ClusterShardExtension:
    def __init__(self, server, spec: dict | None = None):
        self.server = server
        spec = spec if spec is not None else load_spec()
        self.shard_id = int(spec["shard_id"])
        self.n_shards = int(spec["n_shards"])
        self.world_map = WorldMap(self.n_shards)
        self.bus = InterShardBus(self.shard_id)
        rings = spec.get("rings") or {"out": {}, "in": {}}
        self.bus.attach(rings.get("out", {}), rings.get("in", {}))
        self._ctl_path = spec["ctl_path"]
        self._ctl: socket.socket | None = None
        #: uuid → home shard for every remote proxy this shard holds
        self._remote: dict[uuid_mod.UUID, int] = {}
        self._last_level_sent: int | None = None
        self._last_state_push = 0.0
        self.xshard_frames = 0

    # region: lifecycle

    async def start(self) -> None:
        """Connect the control channel and announce readiness — called
        at the END of server.start(), once the ZMQ listener is bound,
        so the router never forwards into an unbound socket."""
        ctl = socket.socket(socket.AF_UNIX, socket.SOCK_SEQPACKET)
        ctl.settimeout(10.0)
        ctl.connect(self._ctl_path)
        ctl.setblocking(False)
        self._ctl = ctl
        self._ctl_send({"op": "ready", "shard": self.shard_id})
        self.server.supervisor.spawn(
            "cluster-control", self._control_loop, critical=True
        )
        if self.server.ticker is None:
            # immediate-mode shard: no tick clock to ride — a
            # supervised pump drains the inbound rings instead
            self.server.supervisor.spawn("cluster-drain", self._drain_pump)
        logger.info(
            "cluster shard %d/%d attached (%d peer rings)",
            self.shard_id, self.n_shards, len(self.bus.peers()),
        )

    async def stop(self) -> None:
        if self._ctl is not None:
            self._ctl.close()
            self._ctl = None
        self.bus.close()

    # endregion

    # region: remote peer proxies

    def _make_proxy(self, peer_uuid: uuid_mod.UUID, home: int) -> Peer:
        bus = self.bus
        metrics = self.server.metrics

        def try_write(framed, _u=peer_uuid, _h=home) -> bool:
            # fire-and-forget onto the home shard's ring; a full ring
            # drops (counted) — bounded degradation, never a stalled
            # tick. Returning True keeps deliver_batch off the awaited
            # slow path: there is nothing more awaiting could do.
            if not bus.send_frame(_h, _u, framed.payload,
                                  time.monotonic_ns()):
                metrics.inc("cluster.ring_full_drops")
            return True

        def try_write_many(framed_list, _u=peer_uuid, _h=home) -> bool:
            now = time.monotonic_ns()
            for framed in framed_list:
                if not bus.send_frame(_h, _u, framed.payload, now):
                    metrics.inc("cluster.ring_full_drops")
            return True

        async def send_raw(data: bytes, _u=peer_uuid, _h=home) -> None:
            if not bus.send_frame(_h, _u, data, time.monotonic_ns()):
                metrics.inc("cluster.ring_full_drops")

        return Peer(
            uuid=peer_uuid,
            addr=f"shard-{home}",
            send_raw=send_raw,
            kind="cluster-remote",
            tracks_heartbeat=False,
            try_write=try_write,
            try_write_many=try_write_many,
        )

    def adopt_remote(self, peer_uuid: uuid_mod.UUID, home: int) -> None:
        """Router announced a peer homed on another shard: register the
        ring-backed proxy (silently — the home shard owns the lifecycle
        broadcasts). Re-adoption after a shard restart just replaces
        the proxy; a peer homed HERE is never proxied."""
        if home == self.shard_id:
            return
        existing = self.server.peer_map.get(peer_uuid)
        if existing is not None and existing.kind != "cluster-remote":
            # a real local binding outranks a proxy announcement
            return
        self.server.peer_map.rebind(self._make_proxy(peer_uuid, home))
        self._remote[peer_uuid] = home

    def drop_remote(self, peer_uuid: uuid_mod.UUID) -> None:
        if self._remote.pop(peer_uuid, None) is None:
            return
        existing = self.server.peer_map.get(peer_uuid)
        if existing is not None and existing.kind == "cluster-remote":
            self.server.peer_map.detach(peer_uuid)

    def on_peer_torn_down(self, peer_uuid: uuid_mod.UUID) -> None:
        """Server hook: a peer HOMED here fully tore down (session
        expiry, eviction, clean disconnect past the TTL) — tell the
        router so every other shard reaps its proxy."""
        if peer_uuid in self._remote or self._ctl is None:
            return
        self._ctl_send({"op": "peer_gone", "uuid": peer_uuid.hex})

    # endregion

    # region: drain (the tick's cross-shard leg)

    async def drain(self) -> int:
        """Deliver everything queued on the inbound rings to LOCAL
        sockets. Called by the ticker between the local batch's device
        dispatch and collect (the ``cluster.drain`` span), or by the
        standalone pump on tickerless shards. Returns frames drained."""
        records = self.bus.drain(DRAIN_MAX)
        if not records:
            return 0
        now_ns = time.monotonic_ns()
        metrics = self.server.metrics
        pairs = []
        for peer_uuid, data, t_ingress in records:
            pairs.append((_BusFrame(data), (peer_uuid,)))
            if t_ingress:
                metrics.observe_ms(
                    "cluster.xshard_ms", (now_ns - t_ingress) / 1e6
                )
        self.xshard_frames += len(records)
        metrics.inc("cluster.frames_drained", len(records))
        await self.server.peer_map.deliver_batch(pairs)
        return len(records)

    async def _drain_pump(self) -> None:
        interval = max(self.server.config.tick_interval, 0.005)
        while True:
            await asyncio.sleep(interval)
            await self.drain()

    # endregion

    # region: control channel

    def _ctl_send(self, msg: dict) -> bool:
        if self._ctl is None:
            return False
        try:
            self._ctl.send(json.dumps(msg).encode())
            return True
        except (BlockingIOError, InterruptedError):
            return False  # control is best-effort; state re-pushes
        except OSError:
            return False

    def _state_packet(self) -> dict:
        gov = self.server.governor
        counters = self.server.metrics.snapshot()["counters"]
        packet = {
            "op": "state",
            "shard": self.shard_id,
            "level": 0,
            "state": "ok",
            "peers": self.server.peer_map.size(),
            "bus": self.bus.stats(),
            "counters": {
                k: v for k, v in counters.items()
                if k.startswith(("messages.", "overload.", "tick.",
                                 "cluster."))
            },
        }
        if gov is not None:
            packet.update(gov.export_state())
            packet["op"] = "state"  # export_state must not shadow it
        return packet

    def _maybe_push_state(self) -> None:
        gov = self.server.governor
        level = gov.level if gov is not None else 0
        now = time.monotonic()
        if (
            level == self._last_level_sent
            and now - self._last_state_push < STATE_INTERVAL_S
        ):
            return
        if self._ctl_send(self._state_packet()):
            self._last_level_sent = level
            self._last_state_push = now

    async def _control_loop(self) -> None:
        """Supervised: inbound adopt/drop + the state export clock.
        Control EOF == the router (and its supervisor) is gone — a
        shard nobody can reach must hand control back cleanly."""
        loop = asyncio.get_running_loop()
        while True:
            try:
                data = await asyncio.wait_for(
                    loop.sock_recv(self._ctl, 65536), STATE_POLL_S
                )
                if not data:
                    raise ConnectionResetError("router control EOF")
                await self._handle_control(data)
            except asyncio.TimeoutError:
                pass
            except (ConnectionResetError, BrokenPipeError, OSError):
                logger.critical(
                    "cluster control channel lost — router is gone; "
                    "requesting clean shard shutdown"
                )
                self.server.shutdown_requested.set()
                return
            self._maybe_push_state()

    async def _handle_control(self, data: bytes) -> None:
        try:
            msg = json.loads(data)
        except ValueError:
            return
        op = msg.get("op")
        if op == "adopt":
            self.adopt_remote(
                uuid_mod.UUID(hex=msg["uuid"]), int(msg["home"])
            )
        elif op == "drop":
            self.drop_remote(uuid_mod.UUID(hex=msg["uuid"]))
        elif op == "inject":
            # router-side HTTP /global_message: a trusted in-process
            # injection stretched across the process boundary — the
            # public PULL would (rightly) drop its nil sender
            import base64

            from ..protocol import deserialize_message

            try:
                message = deserialize_message(
                    base64.b64decode(msg["data"])
                )
            except Exception:
                logger.warning("undecodable control injection dropped")
                return
            await self.server.router.handle_message(message)

    # endregion

    def stats(self) -> dict:
        return {
            "shard_id": self.shard_id,
            "n_shards": self.n_shards,
            "remote_peers": len(self._remote),
            "xshard_frames": self.xshard_frames,
            **self.bus.stats(),
        }
