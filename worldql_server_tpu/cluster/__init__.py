"""Horizontal serving: world-sharded multi-process scale-out (ROADMAP 3).

``--cluster-shards N`` turns the single-process server into a serving
CLUSTER: a thin router tier owning the public ZMQ listener
(:mod:`.router`), N shard server processes each running the existing
engine end to end — own device backend, own WAL + recovery, own
entity plane, own overload governor (:mod:`.shard`, spawned and
supervised by :mod:`.supervisor`) — a stable world/peer placement
contract every process derives independently (:mod:`.world_map`), and
a full mesh of shared-memory rings carrying cross-shard delivery
frames (:mod:`.bus`, the PR 6 ring reused process-to-process).

``--cluster-shards 0`` (the default) never imports this package: the
single-process server stays byte for byte what it was.
"""

from .bus import InterShardBus, create_ring_mesh
from .router import ClusterRouter, ClusterRuntime, ShedMirror
from .shard import ClusterShardExtension
from .supervisor import (
    ClusterSupervisor,
    shard_argv,
    shard_http_port,
    shard_store_url,
    shard_wal_dir,
    shard_zmq_port,
)
from .world_map import WorldMap

__all__ = [
    "ClusterRouter",
    "ClusterRuntime",
    "ClusterShardExtension",
    "ClusterSupervisor",
    "InterShardBus",
    "ShedMirror",
    "WorldMap",
    "create_ring_mesh",
    "shard_argv",
    "shard_http_port",
    "shard_store_url",
    "shard_wal_dir",
    "shard_zmq_port",
]
