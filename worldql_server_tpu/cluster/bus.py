"""Inter-shard frame bus: the cluster's data plane.

The PR 6 delivery ring (:class:`~..delivery.ring.Ring`) was built as a
parent→sender-worker conduit; here the SAME shared-memory SPSC ring is
reused between two UNRELATED server processes — one ring per ordered
shard pair (i→j), created by the router-tier supervisor and attached
by name from the ``WQL_CLUSTER_SPEC`` topology, so an N-shard cluster
carries a full N×(N−1) mesh of lock-free byte conduits with no broker
in the middle.

Bus records are delivery frames for peers homed on the consuming
shard: ``[u64 trace_id][u64 t_router_ingress_ns][16-byte target uuid]
[wire bytes]`` in the ring's frame slot (the slot list stays empty —
slot ids are a delivery-plane concept; here the target is a wire-level
uuid). The leading 16 bytes are the CLUSTER TRACE CONTEXT
(cluster/tracectx.py — zeros when the frame was never router-stamped),
carried INSIDE the frame so the delivery ring's own record layout is
untouched. The ring's two monotonic-ns stamps ride along unchanged:
``t_ingress`` is the SENDING shard's enqueue clock and
``t_ring_write`` the ring's own write stamp, so the consuming shard
closes two honest clocks at socket-write-complete:
``cluster.xshard_ms`` (home-shard-enqueue→remote-shard-write) and —
when the context is present — ``cluster.e2e_ms``
(router-ingress→remote-shard-write).

The cardinal rule (enforced by the ``blocking-cross-shard`` lint
rule): tick-path code never awaits an inter-shard ROUND TRIP. Sends
are fire-and-forget ``try_write`` (a full ring drops + counts — the
PR 6 bounded-degradation discipline; a wedged peer shard can never
stall this shard's tick), and receives happen in the tick's own
``cluster.drain`` leg, overlapped with the in-flight local dispatch.

Rings are created (and unlinked) ONLY by the supervisor: a shard
SIGKILL leaves its rings intact, the restarted process re-attaches by
name and drains whatever queued while it was down — cross-shard
frames for its reconnecting peers degrade to undelivered counts, not
to a torn conduit.
"""

from __future__ import annotations

import logging
import struct
import uuid as uuid_mod

from ..delivery.ring import Ring

logger = logging.getLogger(__name__)

UUID_LEN = 16

#: per-frame cluster trace context: [u64 trace_id][u64 t_router_ingress]
_CTX = struct.Struct("<QQ")
CTX_LEN = _CTX.size
HEADER_LEN = CTX_LEN + UUID_LEN


class InterShardBus:
    """One shard's view of the ring mesh: producer on every outbound
    ring (this shard → peer), consumer on every inbound ring (peer →
    this shard). Attach-by-name from the supervisor's topology spec."""

    def __init__(self, shard_id: int):
        self.shard_id = shard_id
        self._tx: dict[int, Ring] = {}
        self._rx: dict[int, Ring] = {}
        # accounting — nothing the bus drops is ever silent
        self.sent = 0
        self.dropped = 0
        self.drained = 0

    # region: topology

    def attach(self, rings_out: dict, rings_in: dict) -> None:
        """Attach the shard to its ring mesh. Keys are PEER shard ids
        (as int or str — JSON round-trips them as str), values are
        shared-memory names minted by the supervisor."""
        for peer, name in rings_out.items():
            self._tx[int(peer)] = Ring.attach(name)
        for peer, name in rings_in.items():
            self._rx[int(peer)] = Ring.attach(name)

    def close(self) -> None:
        """Detach (attachers never unlink — the supervisor owns the
        shared memory's lifetime)."""
        for ring in (*self._tx.values(), *self._rx.values()):
            ring.close()
        self._tx.clear()
        self._rx.clear()

    def peers(self) -> list[int]:
        return sorted(self._rx)

    # endregion

    # region: data plane

    def send_frame(
        self, target_shard: int, peer: uuid_mod.UUID, data: bytes,
        t_ingress_ns: int = 0, ctx: tuple | None = None,
    ) -> bool:
        """Enqueue one delivery frame toward ``peer``'s home shard.
        Fire-and-forget: a full ring (peer shard down or drowning)
        DROPS the frame — counted, never blocking the caller's tick.
        Record ops never ride this path (they route to the owner shard
        at the router), so a bus drop can only cost pub/sub frames,
        exactly like the delivery plane's ring_full_drops. ``ctx`` is
        the frame's cluster trace context ``(trace_id,
        t_router_ingress_ns)`` — it rides the frame header so the
        remote shard closes the router-ingress clock and stitches the
        frame into its tick trace; None writes a zeroed header."""
        ring = self._tx.get(target_shard)
        if ring is None:
            self.dropped += 1
            return False
        trace_id, t_ctx = ctx if ctx is not None else (0, 0)
        ctx_header = _CTX.pack(trace_id, t_ctx) + peer.bytes
        if ring.try_write(ctx_header + data, b"", t_ingress_ns):
            self.sent += 1
            return True
        self.dropped += 1
        return False

    def drain(self, max_records: int = 4096) -> list:
        """Consume up to ``max_records`` inbound frames across all
        peer rings (round-robin by ring, bounded so one chatty peer
        shard cannot monopolize a tick) →
        ``[(peer_uuid, wire_bytes, t_enqueue_ns, t_ring_write_ns,
        trace_id, t_router_ingress_ns), ...]`` — the two ring stamps
        plus the frame-header trace context, everything the consuming
        shard needs to close both cross-process clocks at
        socket-write-complete."""
        out: list = []
        budget = max_records
        for ring in self._rx.values():
            while budget > 0:
                rec = ring.read_record()
                if rec is None:
                    break
                frame, _slots, t_ingress, t_write = rec
                if len(frame) <= HEADER_LEN:
                    logger.warning("runt inter-shard record dropped")
                    continue
                trace_id, t_ctx = _CTX.unpack_from(frame)
                out.append((
                    uuid_mod.UUID(bytes=frame[CTX_LEN:HEADER_LEN]),
                    frame[HEADER_LEN:],
                    t_ingress,
                    t_write,
                    trace_id,
                    t_ctx,
                ))
                budget -= 1
        self.drained += len(out)
        return out

    def pending(self) -> bool:
        """Whether any inbound ring holds unread records (cheap cursor
        peek — the drain pump's idle test)."""
        return any(r.pending_bytes() > 0 for r in self._rx.values())

    # endregion

    def stats(self) -> dict:
        return {
            "sent": self.sent,
            "dropped": self.dropped,
            "drained": self.drained,
        }


def create_ring_mesh(n_shards: int, ring_bytes: int) -> dict:
    """Supervisor-side: create the full N×(N−1) ring mesh. Returns
    ``{"rings": {(i, j): Ring}, "names": {i: {"out": {j: name},
    "in": {j: name}}}}`` — ``names[i]`` is shard i's attach spec."""
    rings: dict[tuple, Ring] = {}
    for i in range(n_shards):
        for j in range(n_shards):
            if i != j:
                rings[(i, j)] = Ring.create(ring_bytes)
    names = {
        i: {
            "out": {
                j: rings[(i, j)].name for j in range(n_shards) if j != i
            },
            "in": {
                j: rings[(j, i)].name for j in range(n_shards) if j != i
            },
        }
        for i in range(n_shards)
    }
    return {"rings": rings, "names": names}
