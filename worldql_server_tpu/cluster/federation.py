"""Metrics federation: one /metrics for the whole fleet.

Before this module, diagnosing a slow cluster meant hand-correlating
N+1 separate /metrics scrapes (router + every shard). Now each shard
piggybacks cumulative histogram/counter snapshots on the ~1s control
state packets it already sends (``shard.py _state_packet``), and the
router folds them into its OWN registry two ways:

* **Aggregates** under the shard's original series name — scraping the
  router answers cluster-wide questions (``cluster.e2e_ms`` p99 across
  every process, total ``messages.local_message``) with one request.
* **Per-shard series** under ``cluster.shard.<i>.*`` (a shard-reported
  ``cluster.``-prefixed name drops the redundant prefix:
  ``cluster.e2e_ms`` → ``cluster.shard.0.e2e_ms``) — so a drowning
  shard stands out without a second scrape.

Restart-monotone by the PR 7 delivery-worker idiom: the router diffs
each packet against the shard's PREVIOUS packet and merges only the
DELTA (``Metrics.merge_histogram`` / counter increments). A restarted
shard re-zeroes its cumulatives AND its baseline here
(:meth:`reset`, fired from ``on_shard_ready``), so the federated
series only ever grow — no counter-reset sawtooth, pinned by test
across a shard SIGKILL→restart.

``deliveries_per_s_per_core`` is the ROADMAP item 4 number, live:
delivery throughput (the shards' ``broadcast.sends`` counters) over
actual CPU-seconds burned by the fleet (``/proc/<pid>/stat`` utime +
stime of the router and every live shard process). On a box where N
processes time-share one core the gauge stays honest — CPU-seconds,
not wall-seconds, is the denominator.

Freshness (the PR 7 ``stats_stale`` idiom, process-to-process): the
router tracks each shard's last packet age; a wedged-but-alive shard
whose telemetry went silent surfaces as ``telemetry_stale`` in
/healthz instead of silently freezing its federated series.
"""

from __future__ import annotations

import logging
import os
import time

logger = logging.getLogger(__name__)

#: a shard pushes state at least every STATE_INTERVAL_S (shard.py);
#: > 3 missed intervals == stale (the delivery-plane horizon)
TELEMETRY_STALE_S = 3.5

#: minimum sampling window for the per-core rate gauge — scrapes more
#: frequent than this reuse the last computed rate
RATE_WINDOW_S = 1.0


def _proc_cpu_s(pid: int, clk_tck: float) -> float:
    """utime+stime of one process in seconds (0.0 when unreadable —
    a just-died shard must not break the gauge)."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            raw = f.read()
        # comm may contain spaces/parens: fields start after the last ')'
        fields = raw[raw.rindex(b")") + 2:].split()
        # fields[11]/[12] are utime/stime (stat fields 14/15, 1-based)
        return (int(fields[11]) + int(fields[12])) / clk_tck
    except Exception:
        return 0.0


class MetricsFederation:
    """Router-side fold of every shard's telemetry into one registry."""

    def __init__(self, metrics, n_shards: int):
        self.metrics = metrics
        self.n_shards = n_shards
        self._prev_counters: list[dict] = [{} for _ in range(n_shards)]
        self._prev_hists: list[dict] = [{} for _ in range(n_shards)]
        self._last_at = [0.0] * n_shards
        self._pids: dict[int, int] = {}
        self._router_pid = os.getpid()
        try:
            self._clk_tck = float(os.sysconf("SC_CLK_TCK")) or 100.0
        except (ValueError, OSError, AttributeError):
            self._clk_tck = 100.0
        self.packets = 0
        #: monotone cluster-wide delivery total (the rate numerator)
        self._sends_total = 0
        self._rate_prev: tuple[float, int, float] | None = None
        self._rate = 0.0

    # region: shard lifecycle

    def reset(self, shard: int) -> None:
        """A shard (re)booted: drop its diff baselines so its fresh
        cumulative state merges as a new delta, never a subtraction —
        the restart-monotone contract."""
        self._prev_counters[shard] = {}
        self._prev_hists[shard] = {}

    def note_pid(self, shard: int, pid: int | None) -> None:
        if pid:
            self._pids[shard] = pid

    # endregion

    # region: packet ingestion (router loop, one writer)

    @staticmethod
    def shard_series(shard: int, name: str) -> str:
        stem = name
        if stem.startswith("cluster.") and not stem.startswith(
            "cluster.shard."
        ):
            stem = stem[len("cluster."):]
        return f"cluster.shard.{shard}.{stem}"

    def ingest(self, shard: int, packet: dict) -> None:
        """Fold one state packet's counter/histogram snapshots into
        the router registry (aggregate + per-shard series). Never
        raises — a malformed packet degrades to freshness-only."""
        self._last_at[shard] = time.monotonic()
        self.packets += 1
        try:
            self._ingest_counters(shard, packet.get("counters") or {})
            self._ingest_hists(shard, packet.get("hist") or {})
        except Exception:
            logger.exception(
                "federation: bad telemetry packet from shard %d", shard
            )

    def _ingest_counters(self, shard: int, counters: dict) -> None:
        prev = self._prev_counters[shard]
        for name, cur in counters.items():
            if not isinstance(cur, (int, float)):
                continue
            cur = int(cur)
            last = prev.get(name, 0)
            # a cumulative that shrank means the shard re-zeroed
            # mid-baseline (torn restart): re-baseline from the full
            # value rather than subtracting into negatives
            delta = cur - last if cur >= last else cur
            prev[name] = cur
            if delta <= 0:
                continue
            self.metrics.inc(name, delta)
            self.metrics.inc(self.shard_series(shard, name), delta)
            if name == "broadcast.sends":
                self._sends_total += delta

    def _ingest_hists(self, shard: int, hists: dict) -> None:
        prev_all = self._prev_hists[shard]
        for name, cur in hists.items():
            if not isinstance(cur, dict) or "counts" not in cur:
                continue
            prev = prev_all.get(name)
            prev_counts = (prev or {}).get("counts") or []
            deltas = [
                int(c) - int(prev_counts[i])
                if i < len(prev_counts) else int(c)
                for i, c in enumerate(cur["counts"])
            ]
            if any(d < 0 for d in deltas):
                deltas = [int(c) for c in cur["counts"]]
                prev = None
            d_total = sum(deltas)
            d_sum = float(cur.get("sum_ms", 0.0)) - float(
                (prev or {}).get("sum_ms", 0.0)
            )
            max_ms = float(cur.get("max_ms", 0.0))
            prev_all[name] = cur
            # merge even a zero delta: the series appears in /metrics
            # from the shard's FIRST packet (the worker-plane contract)
            for series in (name, self.shard_series(shard, name)):
                self.metrics.merge_histogram(
                    series, deltas, d_total, max(d_sum, 0.0), max_ms
                )

    # endregion

    # region: freshness + the per-core efficiency gauge

    def telemetry_age_s(self, shard: int) -> float | None:
        """Seconds since the shard's last telemetry packet (None =
        never heard from this incarnation)."""
        at = self._last_at[shard]
        if not at:
            return None
        return max(0.0, time.monotonic() - at)

    def telemetry_stale(self, shard: int, alive_for_s: float | None = None
                        ) -> bool:
        """Silent-metrics-gap detection: stale once the last packet
        (or, before any packet, the shard's boot) is older than the
        3-interval horizon — a wedged-but-alive shard must not look
        healthy."""
        age = self.telemetry_age_s(shard)
        if age is None:
            return (
                alive_for_s is not None and alive_for_s > TELEMETRY_STALE_S
            )
        return age > TELEMETRY_STALE_S

    def fleet_cpu_s(self) -> float:
        """Cumulative CPU-seconds burned by the router + every shard
        process whose pid we know (dead pids read as 0)."""
        total = _proc_cpu_s(self._router_pid, self._clk_tck)
        for pid in self._pids.values():
            total += _proc_cpu_s(pid, self._clk_tck)
        return total

    def deliveries_per_s_per_core(self) -> float:
        """ROADMAP item 4's per-core efficiency number, live: delivery
        throughput per CPU-second across the whole fleet (Δ
        broadcast.sends ÷ Δ cpu-seconds over the sampling window).
        0.0 until two samples ≥ RATE_WINDOW_S apart exist."""
        now = time.monotonic()
        if self._rate_prev is None:
            self._rate_prev = (now, self._sends_total, self.fleet_cpu_s())
            return 0.0
        t0, sends0, cpu0 = self._rate_prev
        if now - t0 >= RATE_WINDOW_S:
            cpu = self.fleet_cpu_s()
            d_cpu = cpu - cpu0
            d_sends = self._sends_total - sends0
            if d_cpu > 0:
                self._rate = d_sends / d_cpu
            self._rate_prev = (now, self._sends_total, cpu)
        return round(self._rate, 1)

    # endregion

    def stats(self) -> dict:
        """The ``cluster_federation`` gauge body."""
        ages = [self.telemetry_age_s(i) for i in range(self.n_shards)]
        return {
            "packets": self.packets,
            "sends_total": self._sends_total,
            "stale_shards": sum(
                1 for i in range(self.n_shards) if self.telemetry_stale(i)
            ),
            "oldest_telemetry_s": round(
                max((a for a in ages if a is not None), default=-1.0), 3
            ),
        }
