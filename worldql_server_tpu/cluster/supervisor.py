"""Shard process supervision: boot, monitor, restart the shard tier.

The router process owns N shard SERVER processes (each a full
``python -m worldql_server_tpu --cluster-role shard`` boot: its own
event loop, spatial backend, WAL + recovery, entity plane, governor).
This module is the part of the router that keeps them alive:

* creates the inter-shard ring mesh (``bus.create_ring_mesh``) ONCE —
  ring shared-memory outlives any single shard process, so a SIGKILLed
  shard re-attaches the same conduits on restart and drains what
  queued while it was down;
* spawns each shard with its topology in ``WQL_CLUSTER_SPEC`` (shard
  id, ring names, control-socket path, router port) and a derived
  argv (:func:`shard_argv`) that gives every shard its OWN zmq port,
  OWN wal dir, OWN store and OWN /healthz port while inheriting every
  engine knob from the router's config;
* runs one control-channel reader per shard (the PR 6 delivery-plane
  idiom: AF_UNIX SOCK_SEQPACKET, JSON datagrams, EOF == death):
  shard→router packets carry governor state for the router's shed
  mirror and peer-teardown notices for proxy reaping; router→shard
  packets carry peer adoption/drop for the remote-proxy plane;
* restarts a dead shard with exponential backoff (counted in
  ``cluster.shard_restarts``) and replays the adoption state through
  ``on_shard_ready`` — the shard comes back owning exactly the same
  worlds (stable WorldMap hash) and replays its own WAL, so records
  survive the kill with no cross-shard coordination.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import shlex
import signal
import socket
import subprocess
import sys
import tempfile
import time

from .bus import create_ring_mesh

logger = logging.getLogger(__name__)

#: env var carrying the shard's topology (JSON; see shard spec below)
CLUSTER_SPEC_ENV = "WQL_CLUSTER_SPEC"

#: flags forwarded verbatim from the router's Config to every shard —
#: the shard tier IS the existing engine, so every engine knob applies
_PASSTHROUGH_FLAGS = (
    ("sub_region_size", "--sub-region-size"),
    ("spatial_backend", "--spatial-backend"),
    ("tick_interval", "--tick-interval"),
    ("tick_pipeline", "--tick-pipeline"),
    ("query_staging", "--query-staging"),
    ("mesh_batch", "--mesh-batch"),
    ("mesh_space", "--mesh-space"),
    ("durability", "--durability"),
    ("wal_fsync_ms", "--wal-fsync-ms"),
    ("wal_segment_bytes", "--wal-segment-bytes"),
    ("checkpoint_interval", "--checkpoint-interval"),
    ("max_message_size", "--max-message-size"),
    ("delivery_workers", "--delivery-workers"),
    ("delivery_ring_bytes", "--delivery-ring-bytes"),
    ("resilience", "--resilience"),
    ("failover_after", "--failover-after"),
    ("supervisor_budget", "--supervisor-budget"),
    ("supervisor_backoff", "--supervisor-backoff"),
    ("max_batch", "--max-batch"),
    ("overload", "--overload"),
    ("overload_tick_budget_ms", "--overload-tick-budget-ms"),
    ("overload_deadline_k", "--overload-deadline-k"),
    ("overload_recover_ticks", "--overload-recover-ticks"),
    ("overload_min_batch", "--overload-min-batch"),
    ("overload_peer_rate", "--overload-peer-rate"),
    ("overload_peer_burst", "--overload-peer-burst"),
    ("overload_evict_after", "--overload-evict-after"),
    ("overload_rss_limit_mb", "--overload-rss-limit-mb"),
    ("session_ttl", "--session-ttl"),
    ("session_resume_rate", "--session-resume-rate"),
    ("delta_ticks", "--delta-ticks"),
    ("delta_rebuild_threshold", "--delta-rebuild-threshold"),
    ("entity_k", "--entity-k"),
    ("entity_bounds", "--entity-bounds"),
    ("entity_max", "--entity-max"),
    ("zmq_timeout_secs", "--zmq-timeout-secs"),
)


def shard_zmq_port(config, shard_id: int) -> int:
    """Shard i's inbound ZMQ port: public port + 1 + i (the router owns
    the public port; shards sit behind it on the next N)."""
    return config.zmq_server_port + 1 + shard_id


def shard_http_port(config, shard_id: int) -> int:
    """Shard i's /healthz + /metrics port (router http port + 1 + i);
    only bound when the router's HTTP surface is enabled."""
    return config.http_port + 1 + shard_id


def shard_store_url(config, shard_id: int) -> str:
    """Per-shard record store. SQLite paths get a ``.shard<i>`` suffix
    (one file per shard — the per-shard durability unit); ``memory://``
    is inherently per-process; anything else (postgres) is shared —
    worlds are disjoint across shards, so shards never contend on the
    same rows."""
    url = config.store_url
    if url.startswith("sqlite://"):
        return f"{url}.shard{shard_id}"
    return url


def shard_wal_dir(config, shard_id: int) -> str:
    return os.path.join(config.wal_dir, f"shard-{shard_id}")


def shard_argv(config, shard_id: int) -> list[str]:
    """The shard process's full command line, derived from the router's
    config: same engine knobs, per-shard ports/store/WAL, WS off (the
    cluster's client surface is the router's ZMQ listener)."""
    argv = [
        sys.executable, "-m", "worldql_server_tpu",
        "--cluster-role", "shard",
        "--no-ws",
        "--zmq-server-host", config.zmq_server_host,
        "--zmq-server-port", str(shard_zmq_port(config, shard_id)),
        "--store-url", shard_store_url(config, shard_id),
        "--wal-dir", shard_wal_dir(config, shard_id),
    ]
    if config.http_enabled:
        argv += [
            "--http-host", config.http_host,
            "--http-port", str(shard_http_port(config, shard_id)),
        ]
    else:
        argv.append("--no-http")
    for field, flag in _PASSTHROUGH_FLAGS:
        argv += [flag, str(getattr(config, field))]
    if not config.precompile_tiers:
        argv.append("--no-precompile-tiers")
    if config.entity_sim:
        argv.append("--entity-sim")
    if config.trace:
        argv.append("--trace")
    if config.slow_tick_ms is not None:
        argv += ["--slow-tick-ms", str(config.slow_tick_ms)]
    if config.slow_frame_ms is not None:
        argv += ["--slow-frame-ms", str(config.slow_frame_ms)]
    if config.slow_tick_ms is not None or config.slow_frame_ms is not None:
        argv += ["--slow-tick-dir",
                 os.path.join(config.slow_tick_dir, f"shard-{shard_id}")]
    if config.index_snapshot:
        argv += ["--index-snapshot",
                 f"{config.index_snapshot}.shard{shard_id}"]
    if config.slo_enabled:
        # shards judge their LOCAL objectives and piggyback compliance
        # on the state packets; incidents stay router-side (the fleet
        # capsule pulls every shard's sections over the dump channel),
        # so --incident-dir deliberately does NOT propagate
        argv += ["--slo", "on"]
        if config.slo_file:
            argv += ["--slo-file", config.slo_file]
    if config.failpoints:
        argv += ["--failpoints", config.failpoints]
    if config.failpoints_seed is not None:
        argv += ["--failpoints-seed", str(config.failpoints_seed)]
    if config.verbose:
        argv.append("-" + "v" * min(config.verbose, 3))
    return argv


class _ShardProc:
    """One shard slot: the current process generation plus its control
    channel and last-reported state."""

    def __init__(self, idx: int):
        self.idx = idx
        self.gen = 0
        self.proc: subprocess.Popen | None = None
        self.ctl: socket.socket | None = None
        self.reader: asyncio.Task | None = None
        self.alive = False
        self.ready = asyncio.Event()
        self.state: dict = {}        # last {"op": "state"} payload
        self.state_at = 0.0
        self.restarts = 0
        self.born = 0.0


class ClusterSupervisor:
    """Owns the shard processes + ring mesh + control channels for one
    router. ``on_shard_ready(idx)`` fires after every (re)boot once the
    shard's control channel is up — the router replays peer adoptions
    there; ``on_shard_down(idx)`` fires when a shard dies;
    ``on_shard_message(idx, msg)`` receives every shard→router control
    packet (state reports, peer teardown notices)."""

    def __init__(
        self, config, n_shards: int, *, metrics=None,
        on_shard_ready=None, on_shard_down=None, on_shard_message=None,
        spawn_timeout: float = 60.0,
    ):
        self.config = config
        self.n_shards = n_shards
        self.metrics = metrics
        self.on_shard_ready = on_shard_ready
        self.on_shard_down = on_shard_down
        self.on_shard_message = on_shard_message
        self.spawn_timeout = spawn_timeout
        self._mesh: dict | None = None
        self._dir: str | None = None
        self._shards = [_ShardProc(i) for i in range(n_shards)]
        self._stopping = False
        self._restarters: set[asyncio.Task] = set()

    # region: lifecycle

    async def start(self) -> None:
        self._dir = tempfile.mkdtemp(prefix="wql-cluster-")
        self._mesh = create_ring_mesh(
            self.n_shards, self.config.delivery_ring_bytes
        )
        await asyncio.gather(
            *(self._bring_up(s) for s in self._shards)
        )
        logger.info(
            "cluster shard tier up: %d shard processes behind the "
            "router", self.n_shards,
        )

    async def _bring_up(self, shard: _ShardProc) -> None:
        path = os.path.join(self._dir, f"s{shard.idx}-{shard.gen}.sock")
        lsock = socket.socket(socket.AF_UNIX, socket.SOCK_SEQPACKET)
        lsock.bind(path)
        lsock.listen(1)
        lsock.setblocking(False)
        spec = {
            "shard_id": shard.idx,
            "n_shards": self.n_shards,
            "ctl_path": path,
            "rings": self._mesh["names"][shard.idx],
            "router_zmq_port": self.config.zmq_server_port,
        }
        env = dict(os.environ)
        env[CLUSTER_SPEC_ENV] = json.dumps(spec)
        # the shard must import THIS package even when the router was
        # launched from an unrelated cwd with no installed dist — the
        # parent provably imported it, so export its root
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ))
        env["PYTHONPATH"] = (
            pkg_root + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else pkg_root
        )
        argv = shard_argv(self.config, shard.idx)
        logger.info(
            "spawning shard %d (gen %d): %s",
            shard.idx, shard.gen, shlex.join(argv[2:]),
        )
        proc = await asyncio.to_thread(subprocess.Popen, argv, env=env)
        loop = asyncio.get_running_loop()
        try:
            ctl, _ = await asyncio.wait_for(
                loop.sock_accept(lsock), self.spawn_timeout
            )
            ctl.setblocking(False)
            ready = json.loads(await asyncio.wait_for(
                loop.sock_recv(ctl, 65536), self.spawn_timeout
            ))
            if ready.get("op") != "ready":
                raise RuntimeError(
                    f"unexpected first shard packet: {ready}"
                )
        except Exception:
            if proc.poll() is None:
                proc.kill()
            raise
        finally:
            lsock.close()
            try:
                os.unlink(path)
            except OSError:
                pass
        shard.proc, shard.ctl = proc, ctl
        shard.alive = True
        shard.born = time.monotonic()
        shard.ready.set()
        shard.reader = asyncio.create_task(  # wql: allow(unsupervised-task) — the reader IS the shard monitor; its EOF path drives restart
            self._reader(shard), name=f"cluster-shard-{shard.idx}"
        )
        if self.on_shard_ready is not None:
            self.on_shard_ready(shard.idx)

    async def stop(self) -> None:
        self._stopping = True
        for task in list(self._restarters):
            task.cancel()
        for shard in self._shards:
            if shard.proc is not None and shard.proc.poll() is None:
                shard.proc.send_signal(signal.SIGTERM)
        for shard in self._shards:
            if shard.proc is not None:
                try:
                    await asyncio.to_thread(shard.proc.wait, 10)
                except subprocess.TimeoutExpired:
                    logger.warning(
                        "shard %d did not stop — killing", shard.idx
                    )
                    shard.proc.kill()
                    await asyncio.to_thread(shard.proc.wait, 10)
            if shard.reader is not None:
                shard.reader.cancel()
                try:
                    await shard.reader
                except (asyncio.CancelledError, Exception):
                    pass
                shard.reader = None
            if shard.ctl is not None:
                shard.ctl.close()
                shard.ctl = None
            shard.alive = False
        if self._mesh is not None:
            for ring in self._mesh["rings"].values():
                ring.close()
                ring.unlink()
            self._mesh = None
        if self._dir is not None:
            try:
                os.rmdir(self._dir)
            except OSError:
                pass

    # endregion

    # region: control channel

    def ctl_send(self, idx: int, msg: dict) -> bool:
        """Bounded-retry control send to shard ``idx`` (non-blocking
        socket; control volume is handshake-rate)."""
        shard = self._shards[idx]
        if not shard.alive or shard.ctl is None:
            return False
        data = json.dumps(msg).encode()
        deadline = time.monotonic() + 1.0
        while True:
            try:
                shard.ctl.send(data)
                return True
            except (BlockingIOError, InterruptedError):
                if time.monotonic() >= deadline:
                    return False
                time.sleep(0.005)
            except OSError:
                return False

    async def _reader(self, shard: _ShardProc) -> None:
        """Drain shard→router packets; EOF means the shard died and
        triggers the restart path."""
        loop = asyncio.get_running_loop()
        try:
            while True:
                data = await loop.sock_recv(shard.ctl, 65536)
                if not data:
                    break
                try:
                    msg = json.loads(data)
                except ValueError:
                    continue
                if msg.get("op") == "state":
                    shard.state = msg
                    shard.state_at = time.monotonic()
                if self.on_shard_message is not None:
                    try:
                        self.on_shard_message(shard.idx, msg)
                    except Exception:
                        logger.exception(
                            "shard %d control handler failed", shard.idx
                        )
        except (ConnectionResetError, BrokenPipeError, OSError):
            pass
        except asyncio.CancelledError:
            raise
        if not self._stopping and shard.alive:
            await self._shard_down(shard)

    async def _shard_down(self, shard: _ShardProc) -> None:
        shard.alive = False
        shard.ready.clear()
        if shard.ctl is not None:
            shard.ctl.close()
            shard.ctl = None
        rc = None
        if shard.proc is not None:
            try:
                rc = await asyncio.to_thread(shard.proc.wait, 10)
            except subprocess.TimeoutExpired:
                shard.proc.kill()
        logger.warning(
            "cluster shard %d died (exit %s) — restarting", shard.idx, rc,
        )
        if self.metrics is not None:
            self.metrics.inc("cluster.shard_deaths")
        if self.on_shard_down is not None:
            try:
                self.on_shard_down(shard.idx)
            except Exception:
                logger.exception("shard-down handler failed")
        task = asyncio.create_task(  # wql: allow(unsupervised-task) — restart driver; retained below
            self._restart(shard), name=f"cluster-restart-{shard.idx}"
        )
        self._restarters.add(task)
        task.add_done_callback(self._restarters.discard)

    async def _restart(self, shard: _ShardProc) -> None:
        """Respawn with exponential backoff. Unlimited attempts by
        design: the shard owns worlds no other process can serve, so
        the router keeps trying until its orchestrator intervenes —
        every attempt is counted and visible in /healthz."""
        backoff = 0.2
        while not self._stopping:
            shard.gen += 1
            shard.restarts += 1
            if self.metrics is not None:
                self.metrics.inc("cluster.shard_restarts")
            try:
                await self._bring_up(shard)
                return
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception(
                    "shard %d restart failed — retrying in %.1fs",
                    shard.idx, backoff,
                )
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 5.0)

    # endregion

    # region: state for the router

    def shard_state(self, idx: int) -> dict:
        return self._shards[idx].state

    def shard_alive(self, idx: int) -> bool:
        return self._shards[idx].alive

    def shard_pid(self, idx: int) -> int | None:
        """The current incarnation's pid (None before first boot) —
        the federation's /proc CPU accounting reads it."""
        proc = self._shards[idx].proc
        return proc.pid if proc is not None else None

    def kill_shard(self, idx: int, sig: int = 9) -> bool:
        """Chaos helper: signal shard ``idx``'s current incarnation
        (default SIGKILL — no cleanup handlers run). The normal
        death→restart machinery takes it from there; the resharding
        kill-at-every-protocol-state suite drives this at each step.
        True when a signal was delivered."""
        proc = self._shards[idx].proc
        if proc is None or proc.returncode is not None:
            return False
        try:
            proc.send_signal(sig)
            return True
        except ProcessLookupError:
            return False

    def alive_count(self) -> int:
        return sum(1 for s in self._shards if s.alive)

    def stats(self) -> dict:
        return {
            "shards": self.n_shards,
            "alive": self.alive_count(),
            "restarts": sum(s.restarts for s in self._shards),
        }

    # endregion
