"""Router tier: the cluster's client-facing front door.

One thin process owns the PUBLIC ZMQ listener and forwards every
inbound message to the shard that owns it — world-scoped instructions
(subscriptions, Local/GlobalMessages, record ops) to
``WorldMap.shard_of_world``, peer-scoped instructions (handshakes,
heartbeats) to ``WorldMap.shard_of_peer`` — as the ORIGINAL wire
bytes (``Message.wire``): the router decodes for routing, never
re-encodes. Return traffic never touches the router at all: each
shard's connect-back PUSH goes straight to the client (the reference's
asymmetric ZMQ pattern scales to N servers for free), and cross-shard
fan-out rides the inter-shard rings.

The router is also where overload becomes a CLUSTER property. Every
shard exports its governor level over the control channel (shard.py
``state`` packets) into the :class:`ShedMirror`; a message bound for a
shard in REJECT is shed AT THE ROUTER — same admission classes as the
shard's own governor (records/entity/control never shed; locals and
globals shed in REJECT; new handshakes shed at SHED_HIGH+ with a
budgeted jittered retry-after hint, resumes ride through below
REJECT) — so a drowning shard's refusals cost one decode here instead
of a socket write, a queue slot and a decode there. Every router-side
shed is counted per class (``cluster.router_shed_*``): offered ==
forwarded + shed-at-router, and forwarded == admitted + shed-at-shard,
the exact-accounting invariant bench config 11 gates.

The router is also the cluster's observability front door (ISSUE 15):
every forward is stamped with a trace context (``tracectx.py`` —
64-bit trace id + router-ingress monotonic-ns clock the shards close
at socket-write-complete), shard telemetry folds restart-monotone into
ONE federated ``/metrics`` (``federation.py``: per-shard
``cluster.shard.<i>.*`` series + cluster aggregates + the live
``deliveries_per_s_per_core`` gauge), ``/healthz`` carries per-shard
telemetry freshness, and ``GET /debug/cluster`` splices every
process's flight-recorder snapshot into one Chrome trace with named
pid lanes.

``ClusterRuntime`` composes the router with the shard-process
supervisor — ``python -m worldql_server_tpu --cluster-shards N`` boots
it; scenarios, bench config 11 and the e2e suite embed it.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import time
import uuid as uuid_mod

import zmq
import zmq.asyncio

from ..engine.metrics import Metrics
from ..observability import FlightRecorder, Tracer
from ..protocol import (
    DeserializeError,
    Instruction,
    Message,
    deserialize_message,
    serialize_message,
)
from ..utils.names import GLOBAL_WORLD  # noqa: F401  (routing contract doc)
from . import tracectx
from .dump_client import ChunkedDumpClient
from .federation import MetricsFederation
from .resharding import (
    AutoshardController,
    MigrationCoordinator,
    PlacementMap,
    fence_payload,
)
from .supervisor import ClusterSupervisor, shard_zmq_port

logger = logging.getLogger(__name__)

#: governor levels mirrored from shard state packets
_SHED_HIGH = 2
_REJECT = 3

#: instructions routed by WORLD (owner shard) vs by SENDER (home shard)
_WORLD_ROUTED = frozenset((
    Instruction.AREA_SUBSCRIBE, Instruction.AREA_UNSUBSCRIBE,
    Instruction.LOCAL_MESSAGE, Instruction.GLOBAL_MESSAGE,
    Instruction.RECORD_CREATE, Instruction.RECORD_READ,
    Instruction.RECORD_UPDATE, Instruction.RECORD_DELETE,
))


def _connect_host(bind_host: str) -> str:
    return "127.0.0.1" if bind_host in ("0.0.0.0", "::", "*", "") else bind_host


class ShedMirror:
    """Router-side view of every shard's governor level, fed by the
    control channel. Stale state degrades to level 0 on a shard
    restart (the fresh shard re-reports within its first state tick)."""

    def __init__(self, n_shards: int):
        self.levels = [0] * n_shards

    def note_state(self, shard: int, msg: dict) -> None:
        self.levels[shard] = int(msg.get("level", 0))

    def reset(self, shard: int) -> None:
        self.levels[shard] = 0

    def level(self, shard: int) -> int:
        return self.levels[shard]


class ClusterRouter:
    """The forwarding loop + shed mirror + admin surface. Owns no
    world state — restartable at any time without data loss."""

    def __init__(self, config, supervisor: ClusterSupervisor,
                 metrics: Metrics | None = None):
        self.config = config
        self.supervisor = supervisor
        self.n_shards = supervisor.n_shards
        # the epoch-versioned placement document (live resharding):
        # at epoch 0 with no overrides it IS the stable WorldMap hash
        self.world_map = PlacementMap(self.n_shards)
        self.metrics = metrics if metrics is not None else Metrics()
        self.mirror = ShedMirror(self.n_shards)
        self.ctx = zmq.asyncio.Context()
        self._pull: zmq.asyncio.Socket | None = None
        self._push: list[zmq.asyncio.Socket] = []
        self._recv_task: asyncio.Task | None = None
        self._http_runner = None
        #: uuid → home shard for every handshaked peer (adoption replay
        #: state for shard restarts; reaped on peer_gone notices)
        self._peers: dict[uuid_mod.UUID, int] = {}
        self._hint_bucket = [50.0, time.monotonic()]
        self._jitter = random.Random()
        self.forwarded = 0
        self._refusals: set[asyncio.Task] = set()
        # Cluster observability (ISSUE 15): trace ids minted per
        # inbound message ride every forward as a framed prefix; with
        # tracing on the forwards also record router.forward spans
        # into this process's own flight recorder (loose ring — the
        # router has no tick clock), served at /debug/cluster.
        self._trace_rng = random.Random()
        self.tracer = Tracer(enabled=config.trace_enabled)
        self.recorder = None
        if config.trace_enabled:
            self.recorder = FlightRecorder(
                depth=config.flight_recorder_depth,
                metrics=self.metrics,
            )
            self.tracer.on_trace = self.recorder.record
        # metrics federation: shard state packets fold into THIS
        # registry (aggregates + cluster.shard.<i>.* series), so the
        # router's /metrics is the one scrape for the whole fleet
        self.federation = MetricsFederation(self.metrics, self.n_shards)
        # ONE chunked-dump pull path for /debug/cluster AND incident
        # capture — shared slots, reassembly and timeout-degrade
        # semantics, so a capsule can't drift from the debug endpoint
        self.dumps = ChunkedDumpClient(supervisor)
        # Live resharding (ISSUE 19): at most one migration in flight;
        # its coordinator intercepts the moving world's traffic into a
        # bounded transfer buffer until the epoch flips.
        self.migration: MigrationCoordinator | None = None
        self._migration_task: asyncio.Task | None = None
        self._xfer_seq = 0
        self.resharded = 0
        #: tombstones owed to a shard that was down when its migration
        #: completed: shard → {xfer: world}, re-issued on every ready
        self._pending_tombstones: dict[int, dict[int, str]] = {}
        #: decayed per-world forward counts — the autoshard
        #: controller's hottest-world signal
        self._world_load: dict[str, float] = {}
        self.autoshard = AutoshardController(self)
        self._autoshard_task: asyncio.Task | None = None
        self.metrics.gauge("cluster", self.status)
        self.metrics.gauge("cluster_federation", self.federation.stats)
        self.metrics.gauge(
            "deliveries_per_s_per_core",
            self.federation.deliveries_per_s_per_core,
        )
        # Fleet SLO state: the router's engine judges THIS registry —
        # federation already folds every shard's series in — and the
        # shards additionally piggyback their local compliance on the
        # ~1s state packets (note_remote below). Incidents captured
        # here pull every process's sections over the shared dump
        # client, so one capsule holds the whole fleet's causal state.
        self.slo = None
        self.incidents = None
        self._slo_task: asyncio.Task | None = None
        if config.slo_enabled:
            from ..observability.slo import SloEngine, load_objectives

            interval, objectives = load_objectives(config.slo_file)
            self.slo = SloEngine(
                self.metrics, objectives, eval_interval_s=interval
            )
            self.metrics.gauge("slo", self.slo.gauge)
            if config.incident_dir is not None:
                from ..observability.incidents import IncidentRecorder

                self.incidents = IncidentRecorder(
                    config.incident_dir,
                    cooldown_s=config.incident_cooldown,
                    keep=config.incident_keep,
                    metrics=self.metrics,
                )
                self.incidents.collect = self._collect_incident_body
                self.slo.on_burning = self._on_slo_burning
                self.metrics.gauge("incidents", self.incidents.stats)

    # region: lifecycle

    async def start(self) -> None:
        config = self.config
        self._pull = self.ctx.socket(zmq.PULL)
        self._pull.setsockopt(zmq.MAXMSGSIZE, config.max_message_size)
        self._pull.bind(
            f"tcp://{config.zmq_server_host}:{config.zmq_server_port}"
        )
        host = _connect_host(config.zmq_server_host)
        for i in range(self.n_shards):
            push = self.ctx.socket(zmq.PUSH)
            push.setsockopt(zmq.LINGER, 0)
            # deep enough to ride out a shard restart window at storm
            # rates; past it the router degrades to counted drops
            # rather than a wedged recv loop
            push.setsockopt(zmq.SNDHWM, 100_000)
            push.connect(f"tcp://{host}:{shard_zmq_port(config, i)}")
            self._push.append(push)
        self._recv_task = asyncio.create_task(  # wql: allow(unsupervised-task) — the runtime's run loop awaits/aborts on this task
            self._recv_loop(), name="cluster-router-recv"
        )
        if config.http_enabled:
            await self._start_http()
        if getattr(config, "cluster_autoshard", "off") == "on":
            self._autoshard_task = asyncio.create_task(  # wql: allow(unsupervised-task) — poll loop contains its own errors; cancelled in stop()
                self.autoshard.run(), name="cluster-autoshard"
            )
        if self.slo is not None:
            self._slo_task = asyncio.create_task(  # wql: allow(unsupervised-task) — eval loop contains its own errors; cancelled in stop()
                self.slo.run(), name="cluster-slo-eval"
            )
        logger.info(
            "cluster router listening on %s:%s, %d shards behind it",
            config.zmq_server_host, config.zmq_server_port, self.n_shards,
        )

    async def stop(self) -> None:
        for task in (
            self._slo_task, self._autoshard_task, self._migration_task
        ):
            if task is not None:
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
        self._slo_task = self._autoshard_task = self._migration_task = None
        if self.incidents is not None:
            # after slo-eval stops (no new triggers) — let any
            # in-flight fleet capsule finish before the sockets close
            await self.incidents.drain()
        if self._recv_task is not None:
            self._recv_task.cancel()
            try:
                await self._recv_task
            except (asyncio.CancelledError, Exception):
                pass
            self._recv_task = None
        for task in list(self._refusals):
            task.cancel()
        if self._http_runner is not None:
            await self._http_runner.cleanup()
            self._http_runner = None
        for push in self._push:
            push.close(linger=0)
        self._push.clear()
        if self._pull is not None:
            self._pull.close(linger=0)
            self._pull = None
        self.ctx.term()

    # endregion

    # region: control-plane hooks (wired by ClusterRuntime)

    def on_shard_message(self, shard: int, msg: dict) -> None:
        op = msg.get("op")
        if op == "state":
            self.mirror.note_state(shard, msg)
            self.federation.ingest(shard, msg)
            if self.slo is not None:
                # shard-local compliance piggybacks the state packet —
                # the fleet report shows WHICH process burns, not just
                # that the aggregate does
                self.slo.note_remote(shard, msg.get("slo"))
            # placement convergence via the ~1s state packets: a shard
            # reporting an older epoch (missed a flip broadcast, or
            # restarted) gets the current document re-pushed — every
            # process converges with no external coordinator
            try:
                reported = int(msg.get("placement_epoch", 0))
            except (TypeError, ValueError):
                reported = 0
            if reported < self.world_map.epoch:
                self.supervisor.ctl_send(shard, {
                    "op": "placement", "spec": self.world_map.to_spec(),
                })
        elif op == "dump_chunk":
            self.dumps.note_chunk(msg)
        elif op == "reroute":
            self._note_reroute(shard, msg)
        elif op == "fence_ack":
            if self.migration is not None:
                self.migration.on_fence_ack(shard, msg)
        elif op == "reshard_chunk":
            if self.migration is not None:
                self.migration.on_chunk(shard, msg)
        elif op == "reshard_imported":
            if self.migration is not None:
                self.migration.on_import_ack(shard, msg)
        elif op == "reshard_tombstoned":
            try:
                xfer = int(msg.get("xfer", -1))
            except (TypeError, ValueError):
                xfer = -1
            self._pending_tombstones.get(shard, {}).pop(xfer, None)
            if self.migration is not None:
                self.migration.on_tombstone_ack(shard, msg)
        elif op == "peer_gone":
            try:
                peer = uuid_mod.UUID(hex=msg["uuid"])
            except (KeyError, ValueError):
                return
            self.world_map.clear_peer(peer)
            if self._peers.pop(peer, None) is not None:
                for i in range(self.n_shards):
                    if i != shard:
                        self.supervisor.ctl_send(
                            i, {"op": "drop", "uuid": peer.hex}
                        )

    def on_shard_ready(self, shard: int) -> None:
        """(Re)boot adoption replay: the fresh shard learns every
        living peer homed elsewhere, so its fan-out reaches the whole
        cluster from its first tick."""
        self.mirror.reset(shard)
        # restart-monotone federation: the fresh shard's cumulatives
        # re-baseline from zero, so merged series only ever grow
        self.federation.reset(shard)
        if self.slo is not None:
            # stale pre-restart compliance must not hold the fleet
            # report degraded — the fresh shard re-reports within ~1s
            self.slo.drop_remote(shard)
        self.federation.note_pid(shard, self.supervisor.shard_pid(shard))
        # placement replay: a restarted shard boots at epoch 0 — it
        # must learn every override BEFORE serving, or it would apply
        # frames for worlds it no longer owns
        if self.world_map.epoch > 0:
            self.supervisor.ctl_send(shard, {
                "op": "placement", "spec": self.world_map.to_spec(),
            })
        # a source shard that died before acking its tombstone comes
        # back holding a WAL copy of a world it no longer owns: the
        # re-issued tombstone deletes it through that same WAL
        for xfer, world in list(
            self._pending_tombstones.get(shard, {}).items()
        ):
            self.supervisor.ctl_send(shard, {
                "op": "reshard_tombstone", "xfer": xfer, "world": world,
            })
        if self.migration is not None:
            self.migration.on_shard_ready(shard)
        for peer, home in self._peers.items():
            if home != shard:
                self.supervisor.ctl_send(
                    shard, {"op": "adopt", "uuid": peer.hex, "home": home}
                )

    def on_shard_down(self, shard: int) -> None:
        """A dead shard's homed peers lost their sockets with it:
        drop their proxies cluster-wide and forget them — the clients
        reconnect through the router and re-adopt."""
        self.mirror.reset(shard)
        if self.migration is not None:
            self.migration.on_shard_down(shard)
        gone = [u for u, h in self._peers.items() if h == shard]
        for peer in gone:
            del self._peers[peer]
            for i in range(self.n_shards):
                if i != shard:
                    self.supervisor.ctl_send(
                        i, {"op": "drop", "uuid": peer.hex}
                    )
        if gone:
            logger.warning(
                "shard %d down: forgot %d homed peers (clients must "
                "re-handshake)", shard, len(gone),
            )

    # endregion

    # region: forwarding

    async def _recv_loop(self) -> None:
        assert self._pull is not None
        limit = self.config.max_message_size
        while True:
            parts = await self._pull.recv_multipart()
            try:
                if sum(len(p) for p in parts) > limit:
                    self.metrics.inc("cluster.router_oversized")
                    continue
                data = parts[0] if len(parts) == 1 else b"".join(parts)
                self._route(data)
            except Exception:
                self.metrics.inc("cluster.router_recv_errors")
                logger.exception(
                    "error routing inbound message — dropped"
                )

    def _route(self, data: bytes) -> None:
        # the frame clock opens at ROUTER ingress — every shard-side
        # close (home delivery, remote ring drain) measures the same
        # router-ingress→socket-write window, cluster.e2e_ms
        t_ingress_ns = time.monotonic_ns()
        try:
            message = deserialize_message(data)
        except DeserializeError:
            self.metrics.inc("cluster.router_decode_errors")
            return
        instruction = message.instruction
        if instruction in _WORLD_ROUTED:
            shard = self.world_map.shard_of_world(message.world_name)
            self._world_load[message.world_name] = (
                self._world_load.get(message.world_name, 0.0) + 1.0
            )
        elif instruction in (Instruction.HANDSHAKE, Instruction.HEARTBEAT):
            shard = self.world_map.shard_of_peer(message.sender_uuid)
        else:
            # client-bound / unknown instructions die here — the shard
            # would only log-and-drop them anyway
            self.metrics.inc("cluster.router_dropped_unroutable")
            return
        # Live resharding interception: a migrating world's traffic
        # (and its migrated parked peers' resume handshakes) parks in
        # the bounded transfer buffer for post-flip replay in arrival
        # order — overflow is shed AND counted, never silently lost.
        mig = self.migration
        if mig is not None and mig.should_park(
            instruction, message.world_name, message.sender_uuid
        ):
            if mig.buffer.park(data):
                self.metrics.inc("cluster.reshard_parked")
            else:
                self.metrics.inc("cluster.reshard_buffer_shed")
            return
        if not self._admit(message, instruction, shard):
            return
        if instruction == Instruction.HANDSHAKE:
            self._note_handshake(message.sender_uuid, shard)
        ctx = (
            tracectx.new_trace_id(self._trace_rng), t_ingress_ns,
            self.world_map.epoch,
        )
        payload = message.wire if message.wire is not None else data
        if self.tracer.enabled:
            with self.tracer.span(
                "router.forward",
                trace_id=tracectx.trace_id_hex(ctx[0]),
                shard=shard,
                instruction=instruction.name,
            ):
                self._forward(shard, payload, ctx)
        else:
            self._forward(shard, payload, ctx)

    def _admit(self, message: Message, instruction, shard: int) -> bool:
        """The shed mirror: REJECT a drowning shard's sheddable load at
        the router, before the shard pays a socket read for it. Same
        class semantics as OverloadGovernor.admit — records, entity
        updates, subscriptions and heartbeats always pass."""
        level = self.mirror.level(shard)
        if level < _SHED_HIGH:
            return True
        if instruction == Instruction.HANDSHAKE:
            resume = message.flex is not None
            if resume and level < _REJECT:
                return True
            if resume:
                return True  # REJECT resumes: the shard's token bucket decides
            self.metrics.inc("cluster.router_shed_handshake_new")
            self._send_refusal(message)
            return False
        if level < _REJECT:
            return True
        if instruction == Instruction.LOCAL_MESSAGE:
            if message.entities:
                return True  # entity updates coalesce at the shard, never shed
            self.metrics.inc("cluster.router_shed_local")
            return False
        if instruction == Instruction.GLOBAL_MESSAGE:
            if message.entities:
                return True
            self.metrics.inc("cluster.router_shed_global")
            return False
        return True

    def _forward(self, shard: int, data: bytes, ctx: tuple) -> None:
        """Non-blocking forward, trace context + placement epoch
        framed on (``ctx`` is ``(trace_id, t_ingress_ns, epoch)`` —
        the ``untraced-forward`` and ``epochless-forward`` lint rules
        keep every forwarding site threading both). A full push queue
        (shard mid-restart past the 100K backlog) drops + counts —
        the router's recv loop must never wedge on one dead shard
        while the others serve."""
        try:
            self._push[shard].send(
                tracectx.wrap_epoch(data, ctx[0], ctx[1], ctx[2]),
                flags=zmq.NOBLOCK,
            )
            self.forwarded += 1
            self.metrics.inc("cluster.router_forwarded")
        except zmq.Again:
            self.metrics.inc("cluster.router_queue_drops")

    def _note_handshake(self, peer: uuid_mod.UUID, home: int) -> None:
        known = self._peers.get(peer)
        self._peers[peer] = home
        if known == home:
            return
        for i in range(self.n_shards):
            if i != home:
                self.supervisor.ctl_send(
                    i, {"op": "adopt", "uuid": peer.hex, "home": home}
                )

    # region: live resharding (cluster/resharding)

    def route_replay(self, data: bytes) -> None:
        """Post-flip transfer-buffer replay: each parked frame
        re-enters ``_route`` — re-decoded, re-admitted, stamped with
        the NEW epoch, landing on the new owner in arrival order."""
        try:
            self._route(data)
        except Exception:
            self.metrics.inc("cluster.router_recv_errors")
            logger.exception("error replaying parked frame — dropped")

    def send_fence(self, shard: int, xfer_id: int) -> bool:
        """Push the freeze fence through the DATA path: the shard's
        PULL is FIFO and processing is in-order, so the fence's
        control ack proves every earlier frame for the frozen world
        was already processed (and is therefore in the capsule)."""
        ctx = (
            tracectx.new_trace_id(self._trace_rng), time.monotonic_ns(),
            self.world_map.epoch,
        )
        try:
            self._push[shard].send(
                tracectx.wrap_epoch(
                    fence_payload(xfer_id), ctx[0], ctx[1], ctx[2]
                ),
                flags=zmq.NOBLOCK,
            )
            return True
        except zmq.Again:
            return False

    def _note_reroute(self, shard: int, msg: dict) -> None:
        """A shard rejected a stale-epoch frame for a world it no
        longer owns and bounced the wire bytes back: re-route under
        the CURRENT placement (one hop, re-stamped epoch) instead of
        misapplying or dropping."""
        import base64

        try:
            data = base64.b64decode(msg["data"])
        except (KeyError, TypeError, ValueError):
            return
        self.metrics.inc("cluster.router_reroutes")
        self.route_replay(data)

    def broadcast_placement(self) -> None:
        """Push the placement document to every live shard (the flip
        path); stragglers converge via the epoch check on their ~1s
        state packets."""
        spec = self.world_map.to_spec()
        for i in range(self.n_shards):
            self.supervisor.ctl_send(i, {"op": "placement", "spec": spec})

    def queue_tombstone(self, shard: int, world: str, xfer: int) -> None:
        """Issue (and remember) a tombstone: re-sent on every ready of
        ``shard`` until its ack arrives, so a source SIGKILLed at any
        point after the flip still deletes its stale WAL copy."""
        self._pending_tombstones.setdefault(shard, {})[xfer] = world
        self.supervisor.ctl_send(shard, {
            "op": "reshard_tombstone", "xfer": xfer, "world": world,
        })

    def start_reshard(self, world: str, target: int,
                      reason: str = "manual") -> int | None:
        """Begin migrating ``world`` to ``target``. Returns the xfer
        id, or None when refused (already where it belongs, shard out
        of range, or a migration is already in flight)."""
        if not 0 <= target < self.n_shards:
            return None
        if self.migration is not None and self.migration.active:
            return None
        source = self.world_map.shard_of_world(world)
        if source == target:
            return None
        self._xfer_seq += 1
        self._xfer_seq %= 1 << 31
        xfer = self._xfer_seq
        coordinator = MigrationCoordinator(
            self, world, source, target, xfer,
            getattr(self.config, "reshard_buffer_bytes", 8 << 20),
        )
        # interception must be live BEFORE the fence goes out: every
        # frame between now and the flip parks (or sheds, counted)
        self.migration = coordinator
        coordinator.state = "freeze"
        logger.warning(
            "reshard %d (%s): migrating world %r from shard %d to %d",
            xfer, reason, world, source, target,
        )
        self.metrics.inc("cluster.reshard_started")
        self._migration_task = asyncio.get_running_loop().create_task(  # wql: allow(unsupervised-task) — run() contains its own abort path; cancelled in stop()
            self._run_migration(coordinator),
            name=f"cluster-reshard-{xfer}",
        )
        return xfer

    async def _run_migration(self, coordinator: MigrationCoordinator
                             ) -> None:
        try:
            if await coordinator.run():
                self.resharded += 1
        finally:
            if self.migration is coordinator:
                # keep the coordinator for describe(); interception is
                # off (state done/aborted → should_park False)
                self._migration_task = None

    def hottest_world(self, shard: int) -> str | None:
        """The decayed-forward-count argmax among worlds the placement
        currently puts on ``shard`` — the autoshard pick."""
        best, best_load = None, 0.0
        for world, load in self._world_load.items():
            if load > best_load and \
                    self.world_map.shard_of_world(world) == shard:
                best, best_load = world, load
        return best

    def shard_forward_load(self, shard: int) -> float:
        return sum(
            load for world, load in self._world_load.items()
            if self.world_map.shard_of_world(world) == shard
        )

    def decay_world_load(self, factor: float = 0.5) -> None:
        """Exponential decay of the per-world forward window (called
        each autoshard poll) — the hottest-world signal tracks RECENT
        load, not lifetime totals."""
        drop = [w for w, v in self._world_load.items() if v * factor < 1.0]
        for world in drop:
            del self._world_load[world]
        for world in self._world_load:
            self._world_load[world] *= factor

    # endregion

    def _send_refusal(self, message: Message) -> None:
        """Budgeted jittered retry-after hint for a router-shed NEW
        handshake, pushed to the connect-back address the client just
        supplied — the ZmqTransport refusal contract, moved to the
        tier that shed it."""
        self.metrics.inc("cluster.router_handshakes_refused")
        now = time.monotonic()
        bucket = self._hint_bucket
        bucket[0] = min(bucket[0] + (now - bucket[1]) * 50.0, 50.0)
        bucket[1] = now
        if bucket[0] < 1.0 or not message.parameter:
            return
        bucket[0] -= 1.0
        retry_ms = max(1, int(500 * (0.5 + self._jitter.random())))
        task = asyncio.get_running_loop().create_task(  # wql: allow(unsupervised-task) — one-shot, retained below
            self._push_refusal(message.parameter, retry_ms)
        )
        self._refusals.add(task)
        task.add_done_callback(self._refusals.discard)

    async def _push_refusal(self, parameter: str, retry_ms: int) -> None:
        push = self.ctx.socket(zmq.PUSH)
        push.setsockopt(zmq.LINGER, 200)
        try:
            push.connect(f"tcp://{parameter}")
            await push.send(serialize_message(Message(  # wql: allow(untraced-forward) — client-bound refusal hint, not a shard forward
                instruction=Instruction.HANDSHAKE,
                parameter=f"retry-after:{retry_ms}",
            )))
            self.metrics.inc("cluster.router_refusal_hints")
        except Exception:
            logger.debug("router refusal hint to %s failed", parameter)
        finally:
            push.close(linger=200)

    # endregion

    # region: admin surface

    def status(self) -> dict:
        """The ``cluster`` gauge + the /healthz aggregation body."""
        now = time.monotonic()
        shard_states = {}
        stale = 0
        for i in range(self.n_shards):
            state = self.supervisor.shard_state(i)
            slot = self.supervisor._shards[i]
            alive = self.supervisor.shard_alive(i)
            age = self.federation.telemetry_age_s(i)
            # telemetry freshness (the PR 7 stats_stale idiom): a
            # wedged-but-alive shard whose metrics exports went silent
            # must not look healthy. A shard that never reported this
            # incarnation counts from its boot.
            is_stale = alive and self.federation.telemetry_stale(
                i,
                alive_for_s=(now - slot.born) if slot.born else None,
            )
            if is_stale:
                stale += 1
            shard_states[str(i)] = {
                "alive": alive,
                "level": self.mirror.level(i),
                "state": state.get("state", "unknown"),
                "peers": state.get("peers", 0),
                "state_age_s": (
                    round(now - slot.state_at, 2)
                    if slot.state_at else None
                ),
                "telemetry_age_s": (
                    round(age, 3) if age is not None else None
                ),
                "telemetry_stale": is_stale,
            }
        body = {
            "shards": self.n_shards,
            "alive": self.supervisor.alive_count(),
            "restarts": self.supervisor.stats()["restarts"],
            "known_peers": len(self._peers),
            "forwarded": self.forwarded,
            "telemetry_stale": stale,
            "shard_states": shard_states,
            "placement": {
                "epoch": self.world_map.epoch,
                "world_overrides": len(self.world_map.world_overrides),
            },
            "resharded": self.resharded,
            "autoshard": self.autoshard.stats(),
        }
        if self.migration is not None:
            body["migration"] = self.migration.describe()
        return body

    async def _start_http(self) -> None:
        from aiohttp import web

        app = web.Application()
        app.router.add_get("/healthz", self._get_healthz)
        app.router.add_get("/metrics", self._get_metrics)
        app.router.add_get("/debug/cluster", self._get_debug_cluster)
        if self.slo is not None:
            app.router.add_get("/debug/slo", self._get_debug_slo)
        if self.incidents is not None:
            app.router.add_get("/debug/incidents", self._get_debug_incidents)
        app.router.add_post("/global_message", self._post_global_message)
        app.router.add_post("/reshard", self._post_reshard)
        self._http_runner = web.AppRunner(app)
        await self._http_runner.setup()
        site = web.TCPSite(
            self._http_runner, self.config.http_host, self.config.http_port
        )
        await site.start()

    async def _get_healthz(self, request):
        from aiohttp import web

        cluster = self.status()
        body = {"status": "ok", "role": "router", "cluster": cluster}
        if (
            self.supervisor.alive_count() < self.n_shards
            or cluster["telemetry_stale"]
            or any(
                self.mirror.level(i) >= _SHED_HIGH
                for i in range(self.n_shards)
            )
        ):
            body["status"] = "degraded"
        if self.slo is not None:
            # fleet burn state: the router's own engine (judging the
            # federated registry) plus every shard's piggybacked worst
            slo = self.slo.healthz()
            body["slo"] = slo
            if slo["state"] == "burning":
                body["status"] = "degraded"
        return web.json_response(body)

    async def _get_metrics(self, request):
        from aiohttp import web

        if "application/json" in request.headers.get("Accept", ""):
            return web.json_response(self.metrics.snapshot())
        return web.Response(
            text=self.metrics.render_prometheus(),
            content_type="text/plain", charset="utf-8",
        )

    # region: cluster flight recorder (GET /debug/cluster)

    async def collect_shard_dump(
        self, shard: int, timeout: float = 8.0
    ) -> dict | None:
        """Pull one shard's flight-recorder + subsystem-section dump
        over the shared :class:`ChunkedDumpClient` (request → chunked
        response). None on a dead shard or a timeout — the caller
        degrades to the processes that answered, never errors."""
        return await self.dumps.collect(shard, timeout)

    async def _get_debug_cluster(self, request):
        """ONE flight recorder for the fleet: every shard's snapshot
        pulled over the control channel and spliced with the router's
        own spans. ``?format=chrome`` renders Trace Event Format with
        one NAMED pid lane per process (router / shard-N), so a
        cross-shard frame's router→home→remote chain reads off one
        timeline — the spans share its trace id."""
        from aiohttp import web

        dumps = await asyncio.gather(
            *(self.collect_shard_dump(i) for i in range(self.n_shards))
        )
        own: list[dict] = []
        if self.recorder is not None:
            own = self.recorder.snapshot() + self.recorder.loose_snapshot()
        if request.query.get("format") == "chrome":
            from ..observability.export import chrome_trace

            events = chrome_trace(
                own, pid=os.getpid(), process_name="router"
            )["traceEvents"]
            for i, dump in enumerate(dumps):
                if not dump:
                    continue
                events.extend(chrome_trace(
                    list(dump.get("ticks") or [])
                    + list(dump.get("loose") or []),
                    pid=int(dump.get("pid") or (1_000_000 + i)),
                    process_name=f"shard-{i}",
                )["traceEvents"])
            return web.json_response(
                {"traceEvents": events, "displayTimeUnit": "ms"}
            )
        return web.json_response({
            "router": {"pid": os.getpid(), "traces": own},
            "shards": {
                str(i): dump for i, dump in enumerate(dumps)
                if dump is not None
            },
        })

    # endregion

    # region: fleet SLO surface (GET /debug/slo, /debug/incidents)

    async def _get_debug_slo(self, request):
        from aiohttp import web

        return web.json_response(self.slo.status())

    async def _get_debug_incidents(self, request):
        from aiohttp import web

        incident_id = request.query.get("id")
        if incident_id is None:
            return web.json_response({
                "incidents": self.incidents.list(),
                "stats": self.incidents.stats(),
            })
        capsule = self.incidents.load(incident_id)
        if capsule is None:
            return web.Response(status=404)
        return web.json_response(capsule)

    def _on_slo_burning(self, objective) -> None:
        """SLO eval hook: a fleet objective transitioned into BURNING.
        The recorder debounces and pulls the capsule asynchronously."""
        if self.incidents is not None:
            self.incidents.trigger(objective, self.slo.status())

    def _router_sections(self) -> dict:
        """The router process's own capsule sections (its subsystems
        differ from an engine process: no governor/interest/device —
        instead placement, federation and the shed mirror)."""
        from ..observability.incidents import top_stage_attribution
        from ..robustness import failpoints

        sections: dict = {
            "placement": {
                "epoch": self.world_map.epoch,
                "world_overrides": len(self.world_map.world_overrides),
                "migration": (
                    self.migration.describe()
                    if self.migration is not None else None
                ),
            },
            "federation": self.federation.stats(),
            "shed_mirror": {
                str(i): self.mirror.level(i) for i in range(self.n_shards)
            },
            "cluster": self.status(),
            "failpoints": dict(failpoints.registry.fired_counts()),
        }
        if self.recorder is not None:
            sections["flight_recorder"] = {
                "stats": self.recorder.stats(),
                "ticks": self.recorder.snapshot(),
                "loose": self.recorder.loose_snapshot(),
                "top_stages": top_stage_attribution(self.recorder),
            }
        else:
            sections["flight_recorder"] = {"enabled": False}
        return sections

    async def _collect_incident_body(self) -> dict:
        """Fleet capsule body: the router's sections plus EVERY shard's
        dump (flight recorder + its subsystem sections) pulled over the
        same chunked control path /debug/cluster uses."""
        dumps = await asyncio.gather(
            *(self.collect_shard_dump(i) for i in range(self.n_shards))
        )
        return {
            "pid": os.getpid(),
            "sections": self._router_sections(),
            "shards": {
                str(i): dump for i, dump in enumerate(dumps)
                if dump is not None
            },
        }

    # endregion

    async def _post_reshard(self, request):
        """Manual migration trigger: ``{"world": ..., "target": N}``.
        202 with the xfer id when accepted; 409 while another migration
        is in flight; 400 on a bad body or a no-op placement."""
        from aiohttp import web

        try:
            body = await request.json()
            world = body["world"]
            target = int(body["target"])
            if not isinstance(world, str) or not world:
                raise ValueError("world must be a non-empty string")
        except Exception:
            return web.Response(status=400)
        if self.migration is not None and self.migration.active:
            return web.json_response(
                {"error": "migration in flight",
                 "migration": self.migration.describe()},
                status=409,
            )
        xfer = self.start_reshard(world, target, reason="manual")
        if xfer is None:
            return web.json_response(
                {"error": "refused (bad target or world already there)"},
                status=400,
            )
        return web.json_response(
            {"xfer": xfer, "world": world, "target": target}, status=202
        )

    async def _post_global_message(self, request):
        from aiohttp import web

        try:
            body = await request.json()
            world_name = body["world_name"]
            parameter = body.get("parameter")
            if not isinstance(world_name, str) or not (
                parameter is None or isinstance(parameter, str)
            ):
                raise ValueError("wrong field types")
        except Exception:
            return web.Response(status=400)
        message = Message(
            instruction=Instruction.GLOBAL_MESSAGE,
            parameter=parameter,
            world_name=world_name,
        )
        # rides the PRIVATE control channel, not the shard's public
        # PULL: the transport there drops nil-sender wire messages
        # (anti-spoofing — only the in-process HTTP surface may inject),
        # and the control channel is exactly that trusted in-process
        # surface stretched across the process boundary
        import base64

        self.supervisor.ctl_send(
            self.world_map.shard_of_world(world_name),
            {
                "op": "inject",
                "data": base64.b64encode(
                    serialize_message(message)
                ).decode(),
            },
        )
        return web.Response(status=204)

    # endregion


class ClusterRuntime:
    """Supervisor + router composition: the thing ``--cluster-shards
    N`` boots. Also embedded by the scenario engine, bench config 11
    and the e2e suite (the router runs in the embedding process; the
    shards are always real subprocesses)."""

    def __init__(self, config, metrics: Metrics | None = None):
        config.validate()
        self.config = config
        self.metrics = metrics if metrics is not None else Metrics()
        self.supervisor = ClusterSupervisor(
            config, config.cluster_shards, metrics=self.metrics,
        )
        self.router = ClusterRouter(
            config, self.supervisor, metrics=self.metrics
        )
        self.supervisor.on_shard_ready = self.router.on_shard_ready
        self.supervisor.on_shard_down = self.router.on_shard_down
        self.supervisor.on_shard_message = self.router.on_shard_message
        self.shutdown_requested = asyncio.Event()
        # scenario-engine compatibility surface
        self.governor = None
        self.ticker = None

    async def start(self) -> None:
        await self.supervisor.start()
        await self.router.start()

    async def stop(self) -> None:
        await self.router.stop()
        await self.supervisor.stop()

    async def run_forever(self) -> None:
        import signal as signal_mod

        await self.start()
        stop_requested = asyncio.Event()
        loop = asyncio.get_running_loop()
        hooked = []
        for sig in (signal_mod.SIGINT, signal_mod.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop_requested.set)
                hooked.append(sig)
            except (NotImplementedError, RuntimeError):
                pass
        waiters = [
            asyncio.ensure_future(stop_requested.wait()),  # wql: allow(unsupervised-task)
            asyncio.ensure_future(self.shutdown_requested.wait()),  # wql: allow(unsupervised-task)
        ]
        try:
            await asyncio.wait(
                waiters, return_when=asyncio.FIRST_COMPLETED
            )
            logger.info("cluster router shutting down")
        finally:
            for waiter in waiters:
                waiter.cancel()
            for sig in hooked:
                loop.remove_signal_handler(sig)
            await self.stop()
