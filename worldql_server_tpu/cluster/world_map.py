"""World→shard and peer→shard placement (the cluster's one contract).

Every process in a cluster — the router and all N shards — must agree
on two pure functions, with NO coordination traffic:

* ``shard_of_world(world)``: which shard owns a world's spatial index,
  record store and WAL. Every world-scoped instruction (Area
  Subscribe/Unsubscribe, Local/GlobalMessage, Record*) routes here, so
  a world's subscriptions, records and fan-out resolution are always
  colocated on one shard — the property that lets each shard run the
  existing single-process engine end to end, unchanged.
* ``shard_of_peer(uuid)``: which shard HOMES a peer — owns its
  connect-back socket, heartbeat liveness, session parking and
  delivery-plane slot. Handshakes and heartbeats route here; every
  other shard holds a remote proxy whose writes ride the inter-shard
  ring to this home.

Both are stable hashes of wire-visible identity (blake2b — NEVER
Python's ``hash``, which is salted per process), so the mapping is
identical across processes and across restarts: a shard that comes
back after a SIGKILL recovers exactly the worlds it owned, and its WAL
replay re-covers exactly the records routed to it.

``WorldMap`` is deliberately pluggable: subclass and override
``shard_of_world`` for locality-aware placement (e.g. splitting one
hot world's regions across shards — the region key is already part of
the spatial key, so a future RegionMap can route by
``(world, region)`` without touching the router's forwarding loop).
Live resharding (``resharding/placement.py``) takes exactly this seam:
:class:`~.resharding.placement.PlacementMap` layers epoch-versioned
per-world/per-peer overrides on top of the stable hash, so a migrated
world routes to its NEW owner while everything else stays on the pure
hash below.
"""

from __future__ import annotations

import hashlib
import uuid as uuid_mod

#: domain-separation prefixes: a world named like a uuid hex string
#: must not collide with peer placement
_WORLD_TAG = b"wql.world\x00"
_PEER_TAG = b"wql.peer\x00"


def _stable_hash(tag: bytes, payload: bytes) -> int:
    return int.from_bytes(
        hashlib.blake2b(tag + payload, digest_size=8).digest(), "big"
    )


class WorldMap:
    """Consistent world/peer → shard placement for an ``n_shards``
    cluster. Pure and process-independent — construct freely anywhere."""

    def __init__(self, n_shards: int):
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.n_shards = int(n_shards)

    def shard_of_world(self, world: str) -> int:
        """Owner shard of a world's index + records. The GLOBAL world
        ("@global") maps like any other name — exactly one shard owns
        the all-peers broadcast resolution."""
        return _stable_hash(_WORLD_TAG, world.encode()) % self.n_shards

    def shard_of_peer(self, peer: uuid_mod.UUID) -> int:
        """Home shard of a peer's transport + session state."""
        return _stable_hash(_PEER_TAG, peer.bytes) % self.n_shards

    def describe(self) -> dict:
        return {"kind": type(self).__name__, "n_shards": self.n_shards}
