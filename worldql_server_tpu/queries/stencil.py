"""Stencil lattice helpers shared by kernels and oracles (jax-free).

Split from :mod:`geometry` so the CPU-only paths (base
``match_local_batch``, the resilient mirror, the oracles) never import
jax: the *cube-sampled* candidate contract — one lattice point per
cube, never arithmetic in label space — is documented there.
"""

from __future__ import annotations

import numpy as np

_STENCILS: dict[int, np.ndarray] = {}


def stencil_offsets(radius: int) -> np.ndarray:
    """``[(2r+1)³, 3]`` int64 lattice offsets in lexicographic order
    (x-major, each axis ``-r..r`` ascending) — the canonical probe
    order for every kind except kNN (which re-orders by distance)."""
    radius = int(radius)
    cached = _STENCILS.get(radius)
    if cached is None:
        axis = np.arange(-radius, radius + 1, dtype=np.int64)
        ux, uy, uz = np.meshgrid(axis, axis, axis, indexing="ij")
        cached = np.ascontiguousarray(
            np.stack([ux.ravel(), uy.ravel(), uz.ravel()], axis=1)
        )
        cached.setflags(write=False)
        _STENCILS[radius] = cached
    return cached


def stencil_radius(reach: np.ndarray | float, cube_size: int,
                   stencil_max: int) -> int:
    """Stencil radius in cubes covering a world-units ``reach``:
    ``min(stencil_max, ceil(reach / cube_size))``, floor 1. Computed
    identically by the device expansion and the oracles — the clamp is
    part of the query semantics, not an implementation detail."""
    reach = float(np.max(reach)) if np.ndim(reach) else float(reach)
    cubes = int(np.ceil(reach / float(cube_size))) if reach > 0 else 1
    return max(1, min(int(stencil_max), cubes))
