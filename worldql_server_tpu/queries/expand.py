"""Kind expansion: mixed staged batches → probe rows on the radius path.

``expand_staged`` is the dispatch-side half of the library: it takes
the staged columns (now carrying ``kind``/``par`` lanes), partitions
the batch by kind, runs each kind's pre-jitted stencil kernel
(:mod:`geometry`, :mod:`knn`) and emits one flat *probe batch* —
(world, sample-position, sender, replication) rows in the exact layout
:func:`~worldql_server_tpu.spatial.native_keys.encode_queries` already
consumes. The probe batch then rides the UNCHANGED dispatch/CSR
machinery (including delta-tick reuse: probes are content-addressed
rows, so a repeated cone replays its cached cubes), and
``fold_collected`` — the collect-side half — folds the per-probe
fan-out lists back into one result per original query.

Everything here is vectorized numpy + device kernels over the whole
batch: no per-query Python on the dispatch path (the
``per-query-python-loop`` lint rule covers this module's dispatch
functions). The fold runs collect-side, where per-query list assembly
is already the contract.

Probe-batch layout (group-contiguous, order significant for the fold):
radius rows first (original relative order, one probe each), then
cone / raycast / kNN / density groups — within a group, probes are
owner-major in the order the kind's semantics walk them (stencil-lex
for cone and density, ascending ``t`` for raycast, kernel distance
order for kNN), deduplicated keep-first per (owner, cube).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..protocol.types import Replication
from ..spatial.quantize import cube_coords_batch
from .geometry import cone_mask, density_mask
from .kinds import (
    KIND_CONE,
    KIND_DENSITY,
    KIND_KNN,
    KIND_RADIUS,
    KIND_RAYCAST,
    RAY_ALL_HITS,
)
from .knn import knn_order
from .results import KindResult, _uuid_key  # noqa: F401  (re-export)
from .stencil import stencil_offsets, stencil_radius


@dataclass
class KindPlan:
    """Host-side fold plan built at expansion (owned copies — the
    staging views it was built from are recycled by the double
    buffer)."""

    m: int
    kinds: np.ndarray        # i8 [m]
    params: np.ndarray       # f64 [m, PARAM_LANES]
    probe_owner: np.ndarray  # i32 [P] original query index per probe
    probe_t: np.ndarray      # f64 [P] ray parameter (0 for other kinds)
    probe_cube: np.ndarray   # i64 [P, 3] cube label per probe


def _sample_probes(owner, positions, disp):
    """Owner rows + f64 displacements → probe sample positions."""
    return positions[owner] + disp


def _dedupe_keep_first(owner, pos, cube_size):
    """→ (keep_idx, cubes[keep]) deduplicated per (owner, cube),
    preserving first occurrence in the given order. Vectorized: one
    quantize + one lexicographic unique, no per-probe Python."""
    cubes = cube_coords_batch(pos, cube_size)
    key = np.concatenate(
        [owner[:, None].astype(np.int64), cubes], axis=1
    )
    _, first = np.unique(key, axis=0, return_index=True)
    keep = np.sort(first)
    return keep, cubes[keep]


def expand_staged(world_ids, positions, sender_ids, repls, kinds, params,
                  *, cube_size: int, stencil_max: int = 3,
                  ray_steps_max: int = 64):
    """Mixed staged columns → (plan, probe world_ids, probe positions,
    probe sender_ids, probe repls). The probe columns are dispatch-ready
    for the plain radius pipeline; ``plan`` drives the fold."""
    kinds = np.ascontiguousarray(kinds, np.int8)
    params = np.ascontiguousarray(params, np.float64)
    positions = np.ascontiguousarray(positions, np.float64)
    world_ids = np.ascontiguousarray(world_ids, np.int32)
    sender_ids = np.ascontiguousarray(sender_ids, np.int32)
    repls = np.ascontiguousarray(repls, np.int8)
    m = int(kinds.shape[0])
    size = float(cube_size)

    owners: list[np.ndarray] = []
    probe_pos: list[np.ndarray] = []
    probe_t: list[np.ndarray] = []
    probe_cube: list[np.ndarray] = []
    repl_rows: list[np.ndarray] = []

    def _push(owner, pos, t=None, repl_override=None):
        if owner.size == 0:
            return
        owner = owner.astype(np.int32)
        keep, cubes = _dedupe_keep_first(owner, pos, cube_size)
        owners.append(owner[keep])
        probe_pos.append(pos[keep])
        probe_t.append(
            t[keep] if t is not None
            else np.zeros(keep.shape[0], np.float64)
        )
        probe_cube.append(cubes)
        if repl_override is None:
            repl_rows.append(repls[owner[keep]])
        else:
            repl_rows.append(
                np.full(keep.shape[0], repl_override, np.int8)
            )

    # radius rows pass through 1:1 in original order (no dedupe — the
    # pure-radius contract is byte-for-byte the existing path)
    radius_idx = np.flatnonzero(kinds == KIND_RADIUS).astype(np.int32)
    if radius_idx.size:
        owners.append(radius_idx)
        probe_pos.append(positions[radius_idx])
        probe_t.append(np.zeros(radius_idx.shape[0], np.float64))
        probe_cube.append(
            cube_coords_batch(positions[radius_idx], cube_size)
        )
        repl_rows.append(repls[radius_idx])

    ci = np.flatnonzero(kinds == KIND_CONE)
    if ci.size:
        pc = params[ci]
        off = stencil_offsets(
            stencil_radius(pc[:, 4], cube_size, stencil_max)
        ).astype(np.float64)
        mask = cone_mask(pc, off, cube_size)
        sel_q, sel_s = np.nonzero(mask)
        _push(ci[sel_q], _sample_probes(ci[sel_q], positions,
                                        off[sel_s] * size))

    ri = np.flatnonzero(kinds == KIND_RAYCAST)
    if ri.size:
        pr = params[ri]
        half = size / 2.0
        max_t = pr[:, 3]
        top = int(min(ray_steps_max, np.floor(np.max(max_t) / half)))
        t_axis = np.arange(top + 1, dtype=np.float64) * half
        valid = t_axis[None, :] <= max_t[:, None]
        sel_q, sel_s = np.nonzero(valid)
        tvals = t_axis[sel_s]
        pos = positions[ri[sel_q]] + pr[sel_q, 0:3] * tvals[:, None]
        _push(ri[sel_q], pos, t=tvals)

    ki = np.flatnonzero(kinds == KIND_KNN)
    if ki.size:
        pk = params[ki]
        off = stencil_offsets(
            stencil_radius(pk[:, 1], cube_size, stencil_max)
        ).astype(np.float64)
        order, n_ok = knn_order(pk, off, cube_size)
        valid = np.arange(order.shape[1])[None, :] < n_ok[:, None]
        sel_q, sel_s = np.nonzero(valid)          # row-major: rank order
        disp = off[order[sel_q, sel_s]] * size
        _push(ki[sel_q], _sample_probes(ki[sel_q], positions, disp))

    di = np.flatnonzero(kinds == KIND_DENSITY)
    if di.size:
        pd = params[di]
        off = stencil_offsets(
            max(1, min(stencil_max, int(np.max(pd[:, 0]))))
        ).astype(np.float64)
        mask = density_mask(pd, off)
        sel_q, sel_s = np.nonzero(mask)
        # density counts EVERY subscriber of a cube, the sender's own
        # subscription included
        _push(di[sel_q], _sample_probes(di[sel_q], positions,
                                        off[sel_s] * size),
              repl_override=np.int8(int(Replication.INCLUDING_SELF)))

    owner_all = np.concatenate(owners) if owners else np.empty(0, np.int32)
    pos_all = (
        np.concatenate(probe_pos)
        if probe_pos else np.empty((0, 3), np.float64)
    )
    plan = KindPlan(
        m=m,
        kinds=kinds.copy(),
        params=params.copy(),
        probe_owner=owner_all,
        probe_t=(
            np.concatenate(probe_t) if probe_t
            else np.empty(0, np.float64)
        ),
        probe_cube=(
            np.concatenate(probe_cube) if probe_cube
            else np.empty((0, 3), np.int64)
        ),
    )
    repl_all = (
        np.concatenate(repl_rows) if repl_rows else np.empty(0, np.int8)
    )
    return (
        plan,
        world_ids[owner_all],
        pos_all,
        sender_ids[owner_all],
        repl_all,
    )


def fold_collected(plan: KindPlan, probe_targets) -> list:
    """Collect-side fold: per-probe fan-out lists → one entry per
    original query. Radius rows get their plain peer list (identical
    to the unexpanded path); kind rows get a :class:`KindResult`."""
    out: list = [None] * plan.m
    groups: dict[int, list[int]] = {}
    for p in range(plan.probe_owner.shape[0]):
        qi = int(plan.probe_owner[p])
        if plan.kinds[qi] == KIND_RADIUS:
            out[qi] = probe_targets[p]
        else:
            groups.setdefault(qi, []).append(p)

    for qi, probes in groups.items():
        kind = int(plan.kinds[qi])
        if kind == KIND_CONE:
            seen: set = set()
            for p in probes:
                seen.update(probe_targets[p])
            out[qi] = KindResult(kind, sorted(seen, key=_uuid_key))
        elif kind == KIND_RAYCAST:
            out[qi] = _fold_raycast(plan, qi, probes, probe_targets)
        elif kind == KIND_KNN:
            out[qi] = _fold_knn(plan, qi, probes, probe_targets)
        elif kind == KIND_DENSITY:
            out[qi] = _fold_density(plan, qi, probes, probe_targets)
        else:  # unregistered kind staged somehow: reply empty, loudly
            out[qi] = KindResult(kind, [])
    return out


def _fold_raycast(plan, qi, probes, probe_targets) -> KindResult:
    all_hits = plan.params[qi, 4] == RAY_ALL_HITS
    peers: list = []
    ts: list = []
    seen: set = set()
    for p in probes:
        hit = sorted(set(probe_targets[p]), key=_uuid_key)
        if not hit:
            continue
        t = float(plan.probe_t[p])
        if not all_hits:
            return KindResult(
                KIND_RAYCAST, hit, {"t": t, "mode": "first_hit"}
            )
        for u in hit:
            if u not in seen:
                seen.add(u)
                peers.append(u)
                ts.append(t)
    if not all_hits:
        return KindResult(KIND_RAYCAST, [], {"t": None, "mode": "first_hit"})
    return KindResult(KIND_RAYCAST, peers, {"ts": ts, "mode": "all_hits"})


def _fold_knn(plan, qi, probes, probe_targets) -> KindResult:
    k = int(plan.params[qi, 0])
    peers: list = []
    seen: set = set()
    for p in probes:
        if len(peers) >= k:
            break
        for u in sorted(set(probe_targets[p]), key=_uuid_key):
            if u not in seen:
                seen.add(u)
                peers.append(u)
                if len(peers) >= k:
                    break
    return KindResult(KIND_KNN, peers, {"k": k})


def _fold_density(plan, qi, probes, probe_targets) -> KindResult:
    entries = []
    for p in probes:
        count = len(set(probe_targets[p]))
        if count:
            cube = plan.probe_cube[p]
            entries.append(
                (int(cube[0]), int(cube[1]), int(cube[2]), count)
            )
    entries.sort(key=lambda e: (-e[3], e[0], e[1], e[2]))
    top_n = int(plan.params[qi, 1])
    return KindResult(
        KIND_DENSITY, [],
        {"cubes": [list(e) for e in entries[:top_n]]},
    )
