"""The kind-query result row (jax-free — shared by device fold, CPU
oracles, wire reply building and the ticker's pair builder)."""

from __future__ import annotations

import uuid as uuid_mod


def _uuid_key(u: uuid_mod.UUID) -> int:
    return u.int


class KindResult:
    """One kind query's folded result: the reply-frame payload plus the
    (possibly empty) peer list. Always truthy — an empty cone still
    owes its sender a reply frame, unlike a radius row with no
    listeners."""

    __slots__ = ("kind", "peers", "extra")

    def __init__(self, kind: int, peers: list, extra: dict | None = None):
        self.kind = kind
        self.peers = peers
        self.extra = extra or {}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"KindResult(kind={self.kind}, peers={len(self.peers)}, "
            f"extra={self.extra})"
        )
