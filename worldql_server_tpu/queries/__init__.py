"""Batched spatial query library (ISSUE 17, ROADMAP item 5).

ASH (arXiv:2110.00511) layers a generalized spatial-hash op set —
queries, raycasts, aggregates — over ONE hash structure; TPU-KNN
(arXiv:2206.14286) recasts neighbor selection as blocked distance
tiles. This package is that template applied to the staged
LocalMessage pipeline: the staging columns grow a ``kind i8`` plus
per-kind parameter lanes, and every kind expands at dispatch time into
*probe rows* — (world, sample-position, sender, replication) quadruples
that ride the EXISTING encode → hash-probe → CSR-collect machinery
against the SAME persistent device index. Candidate generation is the
cube walk the radius path already does; the per-kind geometric filter
(cone / segment / k-ball / region extent) runs as a pre-jitted,
GUARD-registered device kernel over the kind's stencil lattice,
replacing the sphere test. Compaction, delta-tick reuse (probes are
content-addressed rows), precompile tier-walking and ResilientBackend
CPU-mirror degradation all come along for free.

Four ops ship on the mechanism:

* ``query.cone`` — cone-of-sight / frustum visibility
  (:mod:`geometry`): apex, direction, half-angle, range.
* ``query.raycast`` — segment hit-scan: origin, direction, max-t,
  first-hit or all-hits (host-side f64 ray march; the device leg is
  the shared hash-probe dispatch).
* ``query.knn`` — k-nearest subscribed peers with the replication
  predicate (:mod:`knn`, reusing the packed-sort top-k idiom from
  ``ops/tick.py``).
* ``query.density`` — per-cube subscriber counts feeding the live
  region heatmap (:mod:`heatmap`).

Wire contract and parity semantics live in :mod:`wire` and
:mod:`oracle`; the README "Spatial query library" section documents
both.
"""

# The package surface stays jax-free: the device-kernel modules
# (geometry/knn/expand) are imported explicitly by the TPU backend,
# never as a side effect of touching the registry or the oracles.
from .kinds import (  # noqa: F401
    KIND_CONE,
    KIND_DENSITY,
    KIND_KNN,
    KIND_RADIUS,
    KIND_RAYCAST,
    PARAM_LANES,
    QueryKind,
    QueryLimits,
    kind_by_id,
    kind_by_wire,
    registered_kinds,
)
from .results import KindResult  # noqa: F401

__all__ = [
    "KIND_CONE",
    "KIND_DENSITY",
    "KIND_KNN",
    "KIND_RADIUS",
    "KIND_RAYCAST",
    "PARAM_LANES",
    "KindResult",
    "QueryKind",
    "QueryLimits",
    "kind_by_id",
    "kind_by_wire",
    "registered_kinds",
]
