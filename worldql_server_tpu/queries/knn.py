"""Filtered k-nearest: distance-ordered stencil probes on device.

The index holds CUBES, not exact peer positions, so "k nearest" is
defined on the cube lattice: walk the stencil cubes in ascending
squared displacement ``|u·size|²`` and collect subscribed peers (the
replication predicate rides the probe rows' ``repl`` lanes through the
existing device filter) until ``k`` distinct peers are found. Within
one cube, peers tie-break by uuid; across cubes at equal distance, by
stencil index — fully deterministic, pinned lane-for-lane by the
oracle.

The ordering kernel reuses the packed single-sort top-k idiom from
``ops/tick.py``'s stencil-kNN (TPU-KNN's blocked-selection insight,
arXiv:2206.14286): bitcast the f32 distance to its ordered uint32
image, pack ``(d2_bits << 32) | stencil_idx`` into one uint64, and a
single ``jnp.sort`` yields both the order and the tie-break — no
argsort, no gather storm. f32 is exact enough here on purpose: equal
f64 distances that f32 merges fall to the index tie-break, identically
in kernel and oracle (both cast through f32).
"""

from __future__ import annotations

import numpy as np

from ..spatial import jaxconf  # noqa: F401  (must precede jax import)
import jax
import jax.numpy as jnp

from ..utils import retrace


@jax.jit
def _knn_order_kernel(params, offsets, size):
    """``[M, L]`` knn params × ``[S, 3]`` f64 offsets → (``order``
    int32 ``[M, S]`` stencil indices ascending by (d2, idx), ``n_ok``
    int32 ``[M]`` count of in-range probes per query)."""
    dx = offsets[:, 0] * size
    dy = offsets[:, 1] * size
    dz = offsets[:, 2] * size
    d2 = dx * dx + dy * dy + dz * dz                       # [S]
    dist = jnp.sqrt(d2)
    ok = dist[None, :] <= params[:, 1:2]                   # [M, S]
    d2_bits = jax.lax.bitcast_convert_type(
        d2.astype(jnp.float32), jnp.uint32
    ).astype(jnp.uint64)                                   # [S]
    idx = jnp.arange(d2.shape[0], dtype=jnp.uint64)
    packed = jnp.where(
        ok,
        (d2_bits[None, :] << np.uint64(32)) | idx[None, :],
        jnp.uint64(0xFFFFFFFFFFFFFFFF),
    )
    packed = jnp.sort(packed, axis=1)
    order = (packed & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32)
    n_ok = jnp.sum(ok, axis=1, dtype=jnp.int32)
    return order, n_ok


retrace.GUARD.register("queries.knn_order", _knn_order_kernel)


def knn_order(params: np.ndarray, offsets: np.ndarray,
              cube_size: int) -> tuple[np.ndarray, np.ndarray]:
    """Host wrapper: → (order int32 ``[M, S]``, n_ok int32 ``[M]``).
    Rows pad to a pow2 tier like the mask kernels (geometry._pad_rows)
    so the jit shapes stay enumerable for the boot tier walk."""
    from .geometry import _pad_rows

    padded, m = _pad_rows(params)
    order, n_ok = _knn_order_kernel(
        jnp.asarray(padded, jnp.float64),
        jnp.asarray(offsets, jnp.float64),
        jnp.float64(cube_size),
    )
    return np.asarray(order)[:m], np.asarray(n_ok)[:m]
