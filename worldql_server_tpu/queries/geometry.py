"""Per-kind geometric filter kernels over the stencil lattice.

The library's candidate-generation semantics (the *cube-sampled*
contract every oracle in :mod:`oracle` replicates): a kind's candidate
cubes are the cubes containing the sample points ``pos + u * size``
for stencil offsets ``u ∈ [-r, r]³`` that pass the kind's geometric
test on the displacement ``d = u * size``. Exactly one lattice point
per cube (the lattice spacing equals the cube size), so the stencil
mask IS the cube selection — no arithmetic in label space, ever
(adjacent cube labels are not uniform integers; sample points are
quantized by the same host-f64 ``cube_coords_batch`` as everything
else).

Each kernel is a batched device op: ``[M, PARAM_LANES]`` parameter rows
against one ``[S, 3]`` stencil — jitted once, GUARD-registered, and
precompiled by the boot tier walk (spatial/precompile.py) over the
query-cap ladder × stencil radii, so a mixed-kind tick after boot
retraces nothing. Geometry runs in f64 (jax_enable_x64 is on —
spatial/jaxconf.py) with explicit component-sum arithmetic in a fixed
order, so the numpy oracles produce bit-identical masks.
"""

from __future__ import annotations

import numpy as np

from ..spatial import jaxconf  # noqa: F401  (must precede jax import)
import jax
import jax.numpy as jnp

from ..spatial.hashing import next_pow2
from ..utils import retrace
from .kinds import PARAM_LANES
from .stencil import stencil_offsets, stencil_radius  # noqa: F401  (re-export)

#: kind-parameter rows pad to power-of-two tiers (this floor) before
#: entering a kernel, so the row counts jit keys on form the same small
#: enumerable ladder the dispatch capacities do — the boot tier walk
#: (spatial/precompile.py) covers it, and a mid-serving change in the
#: per-kind row count lands on a warm tier instead of retracing
KIND_ROW_FLOOR = 64


def _pad_rows(params: np.ndarray) -> tuple[np.ndarray, int]:
    params = np.asarray(params, np.float64)
    m = params.shape[0]
    cap = next_pow2(m, floor=KIND_ROW_FLOOR)
    if cap == m:
        return params, m
    out = np.zeros((cap, params.shape[1]), np.float64)
    out[:m] = params
    return out, m


@jax.jit
def _cone_mask_kernel(params, offsets, size):
    """``[M, L]`` cone params × ``[S, 3]`` f64 offsets → bool ``[M, S]``:
    displacement within range AND inside the half-angle (the apex cube
    ``d == 0`` is always visible)."""
    dx = offsets[:, 0] * size
    dy = offsets[:, 1] * size
    dz = offsets[:, 2] * size
    d2 = dx * dx + dy * dy + dz * dz                      # [S]
    dist = jnp.sqrt(d2)
    ax, ay, az = params[:, 0:1], params[:, 1:2], params[:, 2:3]
    dot = dx[None, :] * ax + dy[None, :] * ay + dz[None, :] * az
    cos_half = params[:, 3:4]
    within = dist[None, :] <= params[:, 4:5]
    inside = dot >= dist[None, :] * cos_half
    return within & (inside | (d2[None, :] == 0.0))


@jax.jit
def _density_mask_kernel(params, offsets):
    """``[M, L]`` density params × ``[S, 3]`` f64 offsets → bool
    ``[M, S]``: Chebyshev box of ``extent`` cubes (lane 0). Integer
    geometry — exact in f64 by construction."""
    cheb = jnp.max(jnp.abs(offsets), axis=1)              # [S]
    return cheb[None, :] <= params[:, 0:1]


retrace.GUARD.register("queries.cone_mask", _cone_mask_kernel)
retrace.GUARD.register("queries.density_mask", _density_mask_kernel)


def cone_mask(params: np.ndarray, offsets: np.ndarray,
              cube_size: int) -> np.ndarray:
    """Host wrapper: f64 in, bool ``[M, S]`` out (one fetch at the
    dispatch boundary, like the staging encode). Rows pad to a pow2
    tier (see ``KIND_ROW_FLOOR``); the pad rows are sliced away."""
    padded, m = _pad_rows(params)
    out = _cone_mask_kernel(
        jnp.asarray(padded, jnp.float64),
        jnp.asarray(offsets, jnp.float64),
        jnp.float64(cube_size),
    )
    return np.asarray(out)[:m]


def density_mask(params: np.ndarray, offsets: np.ndarray) -> np.ndarray:
    padded, m = _pad_rows(params)
    out = _density_mask_kernel(
        jnp.asarray(padded, jnp.float64),
        jnp.asarray(offsets, jnp.float64),
    )
    return np.asarray(out)[:m]


def precompile_kind_kernels(qcap: int, radius: int, cube_size: int) -> int:
    """Warm each REGISTERED kind's kernel at one (row-tier, stencil-
    radius) shape — the per-kind leg of the boot tier walk. Iterating
    the registry (not a hardcoded list) keeps a newly registered kind
    from paying its first trace mid-serving. Returns the number of
    kernel calls made (precompile budget accounting)."""
    from .kinds import registered_kinds
    from .knn import knn_order  # local: avoid import cycle at module load

    offsets = stencil_offsets(radius)
    params = np.zeros((qcap, PARAM_LANES), np.float64)
    params[:, 0] = 1.0  # a unit direction keeps the cone kernel honest
    calls = 0
    for kind in registered_kinds():
        if kind.name == "cone":
            cone_mask(params, offsets, cube_size)
        elif kind.name == "density":
            density_mask(params, offsets)
        elif kind.name == "knn":
            knn_order(params, offsets, cube_size)
        else:
            continue  # raycast: host-side f64 march, no kernel to warm
        calls += 1
    return calls
