"""Query-kind registry: the dispatch table the whole library pivots on.

A *kind* is one spatial query shape. Kind 0 is the classic radius
(single-cube) LocalMessage and never appears here as a handler — it IS
the existing pipeline. Every other kind registers:

* a stable ``kind`` id (the staging column's ``i8`` value),
* its wire parameter (``query.<name>`` on a LocalMessage; the reply
  frame uses ``query.<name>.result``),
* a ``parse`` function mapping the request's JSON payload to the fixed
  ``f64[PARAM_LANES]`` parameter row staged alongside the query
  columns (clamped against :class:`QueryLimits` so a hostile payload
  can never demand an unbounded stencil or ray march).

The registry is consulted by the router (wire → kind), the backend's
staged expansion (kind → stencil/kernel), precompile (tier walk over
registered kinds) and the ``unregistered-query-kind`` lint rule
(tools/check/rules_jax.py) — a wire parameter routed without an entry
here is a build failure, not a runtime surprise.

Parameter lane layouts (all f64, unused lanes zero):

==========  =====================================================
kind        lanes
==========  =====================================================
cone (1)    [ux, uy, uz (unit dir), cos_half_angle, range, 0]
raycast (2) [ux, uy, uz (unit dir), max_t, mode (0=first, 1=all), 0]
knn (3)     [k, max_range, 0, 0, 0, 0]
density (4) [extent_cubes, top_n, 0, 0, 0, 0]
==========  =====================================================
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable

import numpy as np

#: f64 parameter lanes staged per query row (engine/staging.py ``par``)
PARAM_LANES = 6

KIND_RADIUS = 0
KIND_CONE = 1
KIND_RAYCAST = 2
KIND_KNN = 3
KIND_DENSITY = 4

#: raycast mode lane values
RAY_FIRST_HIT = 0.0
RAY_ALL_HITS = 1.0

#: hard cap on k regardless of limits (reply frames stay bounded)
KNN_K_CAP = 256


@dataclass(frozen=True)
class QueryLimits:
    """Server-side clamps applied at parse time (engine/config.py:
    ``query_stencil_max`` / ``query_ray_steps`` / ``query_density_top_n``
    flags). The backend applies the SAME stencil clamp at expansion, so
    a stale staged row can never out-run the configured stencil."""

    cube_size: int = 16
    stencil_max: int = 3
    ray_steps_max: int = 64
    density_top_n: int = 16


@dataclass(frozen=True)
class QueryKind:
    kind: int
    name: str
    wire: str
    parse: Callable[[dict, QueryLimits], np.ndarray]


def _unit_dir(payload: dict) -> tuple[float, float, float]:
    raw = payload.get("dir")
    if (
        not isinstance(raw, (list, tuple)) or len(raw) != 3
        or not all(isinstance(v, (int, float)) for v in raw)
    ):
        raise ValueError("dir must be a [x, y, z] number triple")
    dx, dy, dz = (float(v) for v in raw)
    if not all(math.isfinite(v) for v in (dx, dy, dz)):
        raise ValueError("dir components must be finite")
    norm = math.sqrt(dx * dx + dy * dy + dz * dz)
    if norm == 0.0:
        raise ValueError("dir must be non-zero")
    return dx / norm, dy / norm, dz / norm


def _finite_pos(payload: dict, key: str) -> float:
    raw = payload.get(key)
    if not isinstance(raw, (int, float)) or not math.isfinite(float(raw)):
        raise ValueError(f"{key} must be a finite number")
    value = float(raw)
    if value <= 0.0:
        raise ValueError(f"{key} must be > 0")
    return value


def _row(*lanes: float) -> np.ndarray:
    out = np.zeros(PARAM_LANES, np.float64)
    out[: len(lanes)] = lanes
    return out


def _parse_cone(payload: dict, limits: QueryLimits) -> np.ndarray:
    ux, uy, uz = _unit_dir(payload)
    half_deg = _finite_pos(payload, "half_angle_deg")
    if half_deg > 180.0:
        raise ValueError("half_angle_deg must be <= 180")
    rng = min(
        _finite_pos(payload, "range"),
        float(limits.stencil_max * limits.cube_size),
    )
    return _row(ux, uy, uz, math.cos(math.radians(half_deg)), rng)


def _parse_raycast(payload: dict, limits: QueryLimits) -> np.ndarray:
    ux, uy, uz = _unit_dir(payload)
    max_t = min(
        _finite_pos(payload, "max_t"),
        limits.ray_steps_max * (limits.cube_size / 2.0),
    )
    mode = payload.get("mode", "first_hit")
    if mode not in ("first_hit", "all_hits"):
        raise ValueError("mode must be 'first_hit' or 'all_hits'")
    lane = RAY_ALL_HITS if mode == "all_hits" else RAY_FIRST_HIT
    return _row(ux, uy, uz, max_t, lane)


def _parse_knn(payload: dict, limits: QueryLimits) -> np.ndarray:
    k = payload.get("k")
    if not isinstance(k, int) or isinstance(k, bool) or k < 1:
        raise ValueError("k must be a positive integer")
    max_range = min(
        _finite_pos(payload, "max_range"),
        float(limits.stencil_max * limits.cube_size),
    )
    return _row(float(min(k, KNN_K_CAP)), max_range)


def _parse_density(payload: dict, limits: QueryLimits) -> np.ndarray:
    extent = payload.get("extent", 1)
    if not isinstance(extent, int) or isinstance(extent, bool) or extent < 0:
        raise ValueError("extent must be a non-negative integer")
    top_n = payload.get("top_n", limits.density_top_n)
    if not isinstance(top_n, int) or isinstance(top_n, bool) or top_n < 1:
        raise ValueError("top_n must be a positive integer")
    return _row(
        float(min(extent, limits.stencil_max)),
        float(min(top_n, limits.density_top_n)),
    )


_REGISTRY: dict[int, QueryKind] = {}
_BY_WIRE: dict[str, QueryKind] = {}


def register(kind: QueryKind) -> QueryKind:
    if kind.kind in _REGISTRY or kind.wire in _BY_WIRE:
        raise ValueError(f"query kind {kind.kind}/{kind.wire} already registered")
    _REGISTRY[kind.kind] = kind
    _BY_WIRE[kind.wire] = kind
    return kind


CONE = register(QueryKind(KIND_CONE, "cone", "query.cone", _parse_cone))
RAYCAST = register(
    QueryKind(KIND_RAYCAST, "raycast", "query.raycast", _parse_raycast)
)
KNN = register(QueryKind(KIND_KNN, "knn", "query.knn", _parse_knn))
DENSITY = register(
    QueryKind(KIND_DENSITY, "density", "query.density", _parse_density)
)


def registered_kinds() -> list[QueryKind]:
    """Registered kinds ordered by id (stable for tier walks)."""
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def kind_by_id(kind: int) -> QueryKind | None:
    return _REGISTRY.get(kind)


def kind_by_wire(parameter: str) -> QueryKind | None:
    """The kind whose wire parameter matches, else None. Reply
    parameters (``query.<name>.result``) deliberately do NOT resolve —
    a reply re-ingested as a request must fall through to the plain
    radius path, not loop."""
    return _BY_WIRE.get(parameter)


def wire_names() -> set[str]:
    """Every wire parameter the library answers, plus its reply twin —
    the allow-list the ``unregistered-query-kind`` lint rule checks
    string literals against."""
    out: set[str] = set()
    for kind in _REGISTRY.values():
        out.add(kind.wire)
        out.add(kind.wire + ".result")
    return out
