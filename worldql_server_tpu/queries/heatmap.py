"""Live region heatmap fed by ``query.density`` results.

Every folded density reply the ticker (or the router's immediate path)
delivers also lands here: the heatmap keeps, per (world, cube), the
most recent subscriber count with a freshness horizon, so the hottest
regions of the fleet are one scrape away. Two export surfaces:

* the ``wql_region_density`` gauge on ``/metrics`` — top-N cube counts
  as rank-indexed leaves (``wql_region_density_top0`` …), plus the
  tracked-cube/world totals; strict-parser clean (rank keys, no label
  games);
* ``GET /debug/heatmap`` — the full JSON snapshot, per world.

Guarded by a lock: recording happens on the event loop, but /metrics
and /debug scrapes may render from transport threads.
"""

from __future__ import annotations

import threading
import time

#: cubes silent for longer than this drop out of gauge/top views
DEFAULT_HORIZON_S = 60.0


class RegionHeatmap:
    def __init__(self, top_n: int = 16,
                 horizon_s: float = DEFAULT_HORIZON_S):
        self.top_n = int(top_n)
        self.horizon_s = float(horizon_s)
        self._lock = threading.Lock()
        #: (world, (cx, cy, cz)) → [count, monotonic_ts]
        self._cells: dict[tuple, list] = {}
        self.updates = 0

    def record(self, world: str, cubes) -> None:
        """Fold one density result: ``cubes`` is the reply's
        ``[[cx, cy, cz, count], ...]`` rows."""
        now = time.monotonic()
        with self._lock:
            for cx, cy, cz, count in cubes:
                self._cells[(world, (int(cx), int(cy), int(cz)))] = [
                    int(count), now,
                ]
            self.updates += 1

    def _live(self):
        horizon = time.monotonic() - self.horizon_s
        dead = [k for k, v in self._cells.items() if v[1] < horizon]
        for k in dead:
            del self._cells[k]
        return self._cells

    def top(self, n: int | None = None) -> list:
        """→ ``[[world, cx, cy, cz, count], ...]`` hottest first
        (count desc, then world/cube for determinism)."""
        with self._lock:
            cells = [
                (world, cube, v[0]) for (world, cube), v in
                self._live().items()
            ]
        cells.sort(key=lambda c: (-c[2], c[0], c[1]))
        return [
            [world, cube[0], cube[1], cube[2], count]
            for world, cube, count in cells[: n or self.top_n]
        ]

    def snapshot(self, n: int | None = None) -> dict:
        """Full per-world JSON view for ``GET /debug/heatmap``;
        ``n`` caps the rows kept per world (hottest first)."""
        with self._lock:
            live = [
                (world, cube, v[0])
                for (world, cube), v in self._live().items()
            ]
        out: dict = {}
        for world, cube, count in live:
            out.setdefault(world, []).append([*cube, count])
        for world, rows in out.items():
            rows.sort(key=lambda r: (-r[3], r[0], r[1], r[2]))
            if n is not None:
                out[world] = rows[:n]
        return out

    def gauge(self) -> dict:
        """The ``wql_region_density`` dict gauge: numeric leaves only
        (render_prometheus flattens one level)."""
        top = self.top()
        out = {
            "tracked_cubes": float(len(self._cells)),
            "worlds": float(len({w for (w, _c) in self._cells})),
            "updates": float(self.updates),
        }
        for rank, row in enumerate(top):
            out[f"top{rank}"] = float(row[4])
        return out
