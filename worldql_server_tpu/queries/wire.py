"""Wire contract for the query library.

Requests are plain LocalMessages with a parameter-namespaced kind
(``query.cone`` / ``query.raycast`` / ``query.knn`` / ``query.density``)
and a JSON payload in ``flex`` (``data`` accepted as a fallback for
text-only clients). They flow through the normal LocalMessage pipeline
— admission, governor, staging — with the kind + parsed parameter
lanes riding the staged columns. Results come back as *reply frames*:
a LocalMessage with ``parameter="query.<kind>.result"`` and a JSON
``flex`` body, delivered to the requesting peer only.

Reply bodies (all peers as lowercase hex uuids):

* cone —    ``{"kind": "cone", "peers": [...]}``
* raycast — ``{"kind": "raycast", "mode": "first_hit", "peers": [...],
  "t": <float|null>}`` or ``{"mode": "all_hits", "peers": [...],
  "ts": [...]}``
* knn —     ``{"kind": "knn", "k": <int>, "peers": [...]}``
* density — ``{"kind": "density", "cubes": [[cx, cy, cz, count], ...]}``

A malformed payload is dropped at the router with a log line (the
sender keeps its session; a hostile payload must not cost a tick), and
reply parameters never resolve back to a kind — re-ingesting a reply
is just a radius message.
"""

from __future__ import annotations

import json

from ..protocol.types import Instruction, Message
from .kinds import QueryKind, QueryLimits, kind_by_wire
from .results import KindResult


def parse_query_message(message: Message, limits: QueryLimits):
    """→ ``(QueryKind, params tuple)`` for a query-parameter
    LocalMessage, or ``None`` when the parameter is not a registered
    kind. Raises ``ValueError`` on a malformed payload."""
    kind = kind_by_wire(message.parameter or "")
    if kind is None:
        return None
    if message.flex:
        try:
            payload = json.loads(message.flex.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as exc:
            raise ValueError(f"bad {kind.wire} payload: {exc}") from None
    elif message.data:
        try:
            payload = json.loads(message.data)
        except json.JSONDecodeError as exc:
            raise ValueError(f"bad {kind.wire} payload: {exc}") from None
    else:
        payload = {}
    if not isinstance(payload, dict):
        raise ValueError(f"{kind.wire} payload must be a JSON object")
    return kind, tuple(kind.parse(payload, limits))


def build_reply(message: Message, kind: QueryKind,
                result: KindResult) -> Message:
    """The reply frame for one resolved kind query — addressed to the
    requesting peer by the delivery pair, not by this frame."""
    body: dict = {"kind": kind.name}
    extra = result.extra
    if kind.name == "raycast":
        body["mode"] = extra.get("mode", "first_hit")
        body["peers"] = [u.hex for u in result.peers]
        if body["mode"] == "all_hits":
            body["ts"] = extra.get("ts", [])
        else:
            body["t"] = extra.get("t")
    elif kind.name == "density":
        body["cubes"] = extra.get("cubes", [])
    else:
        if kind.name == "knn":
            body["k"] = extra.get("k")
        body["peers"] = [u.hex for u in result.peers]
    return Message(
        instruction=Instruction.LOCAL_MESSAGE,
        parameter=f"{kind.wire}.result",
        sender_uuid=message.sender_uuid,
        world_name=message.world_name,
        position=message.position,
        flex=json.dumps(body, separators=(",", ":")).encode("utf-8"),
    )
