"""CPU-parity oracles: the reference semantics for every query kind.

Each oracle resolves ONE kind query through the host-authority
``query_cube`` path — plain numpy + Python over the same cube-sampled
contract :mod:`geometry` documents — and returns exactly the
:class:`~worldql_server_tpu.queries.expand.KindResult` the device
expansion + fold produces, lane for lane. The property suite
(tests/test_queries.py) pins the two paths against each other across
randomized worlds, replication modes, empty results and overflow;
ResilientBackend's degraded CPU mirror and the plain
:class:`CpuSpatialBackend` both answer kind queries through here
(``SpatialBackend.match_local_batch``), so degradation keeps parity by
construction.

Geometry parity notes: displacements, dot products and distances are
computed with the same f64 expressions, in the same order, as the
device kernels (jax_enable_x64 is on); the kNN ordering casts squared
distances through f32 exactly like the packed-sort kernel, so f32-tied
probes fall to the identical index tie-break.
"""

from __future__ import annotations

import numpy as np

from ..spatial.quantize import cube_coords_batch
from .results import KindResult, _uuid_key
from .stencil import stencil_offsets, stencil_radius
from .kinds import (
    KIND_CONE,
    KIND_DENSITY,
    KIND_KNN,
    KIND_RAYCAST,
    RAY_ALL_HITS,
)


def _filtered(backend, world, cube, sender, replication) -> list:
    from ..spatial.backend import _apply_replication

    peers = backend.query_cube(world, cube)
    return _apply_replication(peers, sender, replication)


def _unique_cubes_keep_first(samples: np.ndarray, cube_size: int):
    """Sample points → deduplicated cube labels, first occurrence
    order — the oracle twin of ``expand._dedupe_keep_first``."""
    cubes = cube_coords_batch(samples, cube_size)
    _, first = np.unique(cubes, axis=0, return_index=True)
    return cubes[np.sort(first)]


def _pos_row(position) -> np.ndarray:
    return np.array(
        [position.x, position.y, position.z], np.float64
    )


def match_kind(backend, query, params: np.ndarray,
               *, stencil_max: int = 3,
               ray_steps_max: int = 64) -> KindResult:
    """Resolve one kind query against ``backend``'s host index."""
    p = np.asarray(params, np.float64)
    kind = int(query.kind)
    if kind == KIND_CONE:
        return _match_cone(backend, query, p, stencil_max)
    if kind == KIND_RAYCAST:
        return _match_raycast(backend, query, p, ray_steps_max)
    if kind == KIND_KNN:
        return _match_knn(backend, query, p, stencil_max)
    if kind == KIND_DENSITY:
        return _match_density(backend, query, p, stencil_max)
    return KindResult(kind, [])


def _displacements(off: np.ndarray, cube_size: int):
    size = np.float64(cube_size)
    dx = off[:, 0] * size
    dy = off[:, 1] * size
    dz = off[:, 2] * size
    d2 = dx * dx + dy * dy + dz * dz
    return dx, dy, dz, d2


def _match_cone(backend, query, p, stencil_max) -> KindResult:
    size = backend.cube_size
    off = stencil_offsets(
        stencil_radius(p[4], size, stencil_max)
    ).astype(np.float64)
    dx, dy, dz, d2 = _displacements(off, size)
    dist = np.sqrt(d2)
    dot = dx * p[0] + dy * p[1] + dz * p[2]
    mask = (dist <= p[4]) & ((dot >= dist * p[3]) | (d2 == 0.0))
    samples = _pos_row(query.position) + np.stack(
        [dx[mask], dy[mask], dz[mask]], axis=1
    )
    seen: set = set()
    for cube in _unique_cubes_keep_first(samples, size):
        seen.update(_filtered(
            backend, query.world, tuple(int(c) for c in cube),
            query.sender, query.replication,
        ))
    return KindResult(KIND_CONE, sorted(seen, key=_uuid_key))


def _match_raycast(backend, query, p, ray_steps_max) -> KindResult:
    size = backend.cube_size
    half = float(size) / 2.0
    max_t = p[3]
    all_hits = p[4] == RAY_ALL_HITS
    origin = _pos_row(query.position)
    unit = p[0:3]
    peers: list = []
    ts: list = []
    hit_seen: set = set()
    cube_seen: set = set()
    for j in range(int(ray_steps_max) + 1):
        t = np.float64(j) * np.float64(half)
        if t > max_t:
            break
        sample = origin + unit * t
        cube = tuple(
            int(c) for c in cube_coords_batch(sample[None, :], size)[0]
        )
        if cube in cube_seen:
            continue
        cube_seen.add(cube)
        hit = sorted(set(_filtered(
            backend, query.world, cube, query.sender, query.replication,
        )), key=_uuid_key)
        if not hit:
            continue
        if not all_hits:
            return KindResult(
                KIND_RAYCAST, hit, {"t": float(t), "mode": "first_hit"}
            )
        for u in hit:
            if u not in hit_seen:
                hit_seen.add(u)
                peers.append(u)
                ts.append(float(t))
    if not all_hits:
        return KindResult(KIND_RAYCAST, [], {"t": None, "mode": "first_hit"})
    return KindResult(KIND_RAYCAST, peers, {"ts": ts, "mode": "all_hits"})


def _match_knn(backend, query, p, stencil_max) -> KindResult:
    size = backend.cube_size
    off = stencil_offsets(
        stencil_radius(p[1], size, stencil_max)
    ).astype(np.float64)
    dx, dy, dz, d2 = _displacements(off, size)
    dist = np.sqrt(d2)
    ok = dist <= p[1]
    # the kernel's packed-sort order: f32 distance image, index ties
    d2_32 = d2.astype(np.float32)
    order = np.lexsort((np.arange(off.shape[0]), d2_32))
    k = int(p[0])
    origin = _pos_row(query.position)
    peers: list = []
    seen: set = set()
    cube_seen: set = set()
    for s in order:
        if not ok[s] or len(peers) >= k:
            continue
        sample = origin + np.array([dx[s], dy[s], dz[s]], np.float64)
        cube = tuple(
            int(c) for c in cube_coords_batch(sample[None, :], size)[0]
        )
        if cube in cube_seen:
            continue
        cube_seen.add(cube)
        for u in sorted(set(_filtered(
            backend, query.world, cube, query.sender, query.replication,
        )), key=_uuid_key):
            if u not in seen:
                seen.add(u)
                peers.append(u)
                if len(peers) >= k:
                    break
    return KindResult(KIND_KNN, peers, {"k": k})


def _match_density(backend, query, p, stencil_max) -> KindResult:
    size = backend.cube_size
    off = stencil_offsets(
        max(1, min(int(stencil_max), int(p[0])))
    ).astype(np.float64)
    cheb = np.max(np.abs(off), axis=1)
    mask = cheb <= p[0]
    samples = _pos_row(query.position) + off[mask] * np.float64(size)
    entries = []
    for cube in _unique_cubes_keep_first(samples, size):
        cube_t = tuple(int(c) for c in cube)
        count = len(backend.query_cube(query.world, cube_t))
        if count:
            entries.append((*cube_t, count))
    entries.sort(key=lambda e: (-e[3], e[0], e[1], e[2]))
    top_n = int(p[1])
    return KindResult(
        KIND_DENSITY, [],
        {"cubes": [list(e) for e in entries[:top_n]]},
    )
