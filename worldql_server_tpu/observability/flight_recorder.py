"""Fixed-size ring of the last N completed tick traces + slow-tick dumps.

The recorder is the ``on_trace`` sink of the server's
:class:`~worldql_server_tpu.observability.spans.Tracer`: tick traces
(root name ``"tick"``) land in the tick ring, everything else
(per-message router handles, WAL fsyncs, transport recv spans) in a
loose ring four times as deep. Both are dumpable on demand
(``GET /debug/ticks``) and survive for exactly as long as an operator
debugging a latency incident needs recent history — a bounded deque,
no unbounded growth, no disk I/O on the happy path.

Auto-dump: a tick trace whose wall time exceeds ``slow_tick_ms`` is
appended — full span tree plus the loop-health context (event-loop lag
and GC stats from ``loop_monitor``) — as one JSON line to
``<dump_dir>/slow-ticks.jsonl``, with a CRITICAL log line carrying the
stage breakdown, so the next BENCH_r05-style 207 s outlier explains
itself instead of leaving a bare percentile. ``slow_tick_ms = 0``
dumps every tick (the CI smoke uses this to prove the path end to
end); ``None`` disables dumping while keeping the ring.

Thread-safety: ``record`` is called from the event loop (tick traces)
AND from worker threads (loose WAL-fsync traces), so the rings sit
behind one lock. The dump write is synchronous on purpose — it fires
only in the pathological case it documents, and a tick already 200 s
late is not hurt by one small buffered write.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque

logger = logging.getLogger(__name__)

DUMP_FILENAME = "slow-ticks.jsonl"


class FlightRecorder:
    def __init__(
        self,
        depth: int = 64,
        slow_tick_ms: float | None = None,
        dump_dir: str = "slow_ticks",
        metrics=None,
        context=None,
    ):
        self.depth = max(1, int(depth))
        self.slow_tick_ms = slow_tick_ms
        self.dump_dir = dump_dir
        self.metrics = metrics
        #: zero-arg callable returning loop-health context for dumps
        #: (the LoopMonitor's snapshot); None = no extra context
        self.context = context
        self._ticks: deque = deque(maxlen=self.depth)
        self._loose: deque = deque(maxlen=self.depth * 4)
        self._lock = threading.Lock()
        self.ticks_recorded = 0
        self.slow_ticks = 0
        #: optional cross-process stitcher: callable(trace) → list of
        #: extra span dicts appended to the trace's snapshot. The
        #: delivery plane hooks this (DeliveryPlane.stitch) to graft
        #: worker-side ``delivery.worker_flush`` spans under
        #: ``tick.deliver`` — worker segments arrive over the control
        #: channel AFTER the trace seals, so stitching happens at
        #: export time, not record time.
        self.stitcher = None

    @property
    def dump_path(self) -> str:
        return os.path.join(self.dump_dir, DUMP_FILENAME)

    def record(self, trace) -> None:
        """Tracer sink: ring-buffer the finished trace; auto-dump slow
        ticks. Never raises (the tracer guards, but a recorder bug
        must not cost a tick either way)."""
        is_tick = trace.name == "tick"
        with self._lock:
            if is_tick:
                self._ticks.append(trace)
                self.ticks_recorded += 1
            else:
                self._loose.append(trace)
        if (
            is_tick
            and self.slow_tick_ms is not None
            and trace.dur_ms >= self.slow_tick_ms
        ):
            self._dump_slow(trace)

    def _dump_slow(self, trace) -> None:
        self.slow_ticks += 1
        if self.metrics is not None:
            self.metrics.inc("tick.slow_dumps")
        record = {
            "dumped_at_unix_s": round(time.time(), 6),
            "slow_tick_ms_threshold": self.slow_tick_ms,
            "trace": trace.as_dict(),
        }
        if self.context is not None:
            try:
                record["loop_health"] = self.context()
            except Exception:
                logger.exception("slow-tick dump: loop-health probe failed")
        stages = trace.stage_ms()
        attributed = sum(stages.values())
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            with open(self.dump_path, "a", encoding="utf-8") as f:
                f.write(json.dumps(record) + "\n")
            where = self.dump_path
        except Exception:
            logger.exception("slow-tick dump write failed")
            where = "<dump write failed>"
        logger.critical(
            "SLOW TICK: %.1f ms (threshold %.1f ms) — stages %s attribute "
            "%.1f ms (%.0f%%); full span tree dumped to %s",
            trace.dur_ms, self.slow_tick_ms,
            {k: round(v, 1) for k, v in sorted(stages.items())},
            attributed,
            100.0 * attributed / trace.dur_ms if trace.dur_ms else 0.0,
            where,
        )

    # region: introspection (HTTP debug surface + tests)

    def snapshot(self) -> list[dict]:
        """Tick traces, oldest first — with any stitcher-provided
        cross-process spans grafted in (a broken stitcher degrades the
        snapshot to parent-side spans, never breaks the endpoint)."""
        with self._lock:
            ticks = list(self._ticks)
        out = []
        for t in ticks:
            d = t.as_dict()
            if self.stitcher is not None:
                try:
                    extra = self.stitcher(t)
                    if extra:
                        d["spans"] = d["spans"] + extra
                except Exception:
                    logger.exception("trace stitcher failed")
            out.append(d)
        return out

    def loose_snapshot(self) -> list[dict]:
        with self._lock:
            return [t.as_dict() for t in self._loose]

    def last_tick(self):
        with self._lock:
            return self._ticks[-1] if self._ticks else None

    def worst_tick(self):
        """The slowest recorded tick trace (None when empty)."""
        with self._lock:
            if not self._ticks:
                return None
            return max(self._ticks, key=lambda t: t.dur_ms)

    def stats(self) -> dict:
        with self._lock:
            recorded = len(self._ticks)
        return {
            "depth": self.depth,
            "recorded": recorded,
            "ticks_seen": self.ticks_recorded,
            "slow_ticks": self.slow_ticks,
            "slow_tick_ms": self.slow_tick_ms,
        }

    # endregion
