"""Event-loop lag probe + GC-pause accounting.

A blocked asyncio loop is indistinguishable from a slow device in
today's numbers: the ticker's wall timers run ON the loop, so a 300 ms
GC pause or a synchronous store commit shows up as a "slow tick" with
no further signature. This module gives both their own series:

* ``loop.lag_ms`` — a supervised probe sleeps ``interval`` and records
  how late it wakes. Lag is scheduling delay: anything hogging the
  loop (sync I/O, giant JSON dumps, GC) shows here even when no tick
  is in flight.
* ``gc.pause_ms`` — a ``gc.callbacks`` hook times every collection
  pass. CPython's collector runs inside whatever thread triggered it,
  which for this server is almost always the event loop.

``snapshot()`` feeds the slow-tick dump so every dump carries the
loop-health context alongside the span tree.
"""

from __future__ import annotations

import asyncio
import gc
import logging
import time

logger = logging.getLogger(__name__)


class LoopMonitor:
    def __init__(self, metrics=None, interval: float = 0.25):
        self.metrics = metrics
        self.interval = interval
        self.last_lag_ms = 0.0
        self.max_lag_ms = 0.0
        self.last_gc_pause_ms = 0.0
        self.max_gc_pause_ms = 0.0
        self.gc_passes = 0
        self._gc_t0: float | None = None
        self._installed = False

    # region: GC hook

    def install(self) -> None:
        """Register the GC callback (idempotent)."""
        if not self._installed:
            gc.callbacks.append(self._gc_callback)
            self._installed = True

    def uninstall(self) -> None:
        if self._installed:
            try:
                gc.callbacks.remove(self._gc_callback)
            except ValueError:
                pass
            self._installed = False

    def _gc_callback(self, phase: str, info: dict) -> None:
        if phase == "start":
            self._gc_t0 = time.perf_counter()
            return
        if self._gc_t0 is None:
            return
        pause_ms = (time.perf_counter() - self._gc_t0) * 1e3
        self._gc_t0 = None
        self.gc_passes += 1
        self.last_gc_pause_ms = pause_ms
        if pause_ms > self.max_gc_pause_ms:
            self.max_gc_pause_ms = pause_ms
        if self.metrics is not None:
            self.metrics.observe_ms("gc.pause_ms", pause_ms)

    # endregion

    async def run(self) -> None:
        """The lag probe loop — run under the server's Supervisor so a
        crashed probe restarts instead of silently going dark."""
        interval = self.interval
        while True:
            t0 = time.perf_counter()
            await asyncio.sleep(interval)
            lag_ms = max((time.perf_counter() - t0 - interval) * 1e3, 0.0)
            self.last_lag_ms = lag_ms
            if lag_ms > self.max_lag_ms:
                self.max_lag_ms = lag_ms
            if self.metrics is not None:
                self.metrics.observe_ms("loop.lag_ms", lag_ms)

    def snapshot(self) -> dict:
        """Loop-health context for slow-tick dumps and the gauge."""
        return {
            "loop_lag_ms": round(self.last_lag_ms, 3),
            "loop_lag_max_ms": round(self.max_lag_ms, 3),
            "gc_last_pause_ms": round(self.last_gc_pause_ms, 3),
            "gc_max_pause_ms": round(self.max_gc_pause_ms, 3),
            "gc_passes": self.gc_passes,
            "gc_counts": gc.get_count(),
        }
