"""Trace export: Chrome-trace/Perfetto JSON + the jax.profiler hook.

``chrome_trace`` converts flight-recorder trace dicts into the Trace
Event Format every Chrome/Perfetto build loads (``chrome://tracing``,
https://ui.perfetto.dev): complete events (``ph: "X"``) with
microsecond epoch timestamps, one ``pid`` per process and one ``tid``
per recorded thread name (named via ``thread_name`` metadata events).
Served at ``GET /debug/ticks?format=chrome`` by the HTTP transport.

``ProfilerHook`` is the device-level escalation: when host-side spans
show the wall time disappearing INSIDE a dispatch/collect, a
``POST /debug/profile`` round captures a ``jax.profiler`` trace
(viewable in xprof/tensorboard) without restarting the server. jax is
imported lazily so the debug surface itself never forces device
bring-up.
"""

from __future__ import annotations

import logging
import threading

logger = logging.getLogger(__name__)


def chrome_trace(
    traces: list[dict],
    pid: int | None = None,
    process_name: str | None = None,
) -> dict:
    """Trace Event Format JSON for a list of ``Trace.as_dict()`` dicts.

    ``process_name`` labels the pid lane with a human-readable name
    (``process_name`` metadata event — "router", "shard-0", …) so a
    multi-process splice (``GET /debug/cluster``) reads as named
    process tracks instead of bare pids; thread lanes are named the
    same way (``thread_name``, e.g. ``delivery-worker-N``)."""
    import os

    if pid is None:
        pid = os.getpid()
    events: list[dict] = []
    tids: dict[str, int] = {}
    if process_name is not None:
        events.append({
            "name": "process_name",
            "ph": "M",
            "ts": 0,
            "pid": pid,
            "tid": 0,
            "args": {"name": process_name},
        })
    for trace in traces:
        base_us = trace.get("start_unix_s", 0.0) * 1e6
        for span in trace.get("spans", ()):
            thread = span.get("thread") or "main"
            tid = tids.setdefault(thread, len(tids) + 1)
            args = dict(span.get("tags") or {})
            args["trace"] = trace.get("name")
            args.update(trace.get("tags") or {})
            events.append({
                "name": span["name"],
                "cat": trace.get("name", "trace"),
                "ph": "X",
                "ts": round(base_us + span["t0_ms"] * 1e3, 3),
                "dur": round(span["dur_ms"] * 1e3, 3),
                "pid": pid,
                "tid": tid,
                "args": args,
            })
    for thread, tid in tids.items():
        events.append({
            "name": "thread_name",
            "ph": "M",
            "ts": 0,
            "pid": pid,
            "tid": tid,
            "args": {"name": thread},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


class ProfilerHook:
    """Start/stop guard around ``jax.profiler`` for the HTTP hook.

    One capture at a time (jax itself enforces this); start/stop from
    the admin endpoint, state readable for ``GET``. Thread-safe — the
    aiohttp handlers run on the loop but tests poke it directly.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.active_dir: str | None = None
        self.captures = 0

    def start(self, log_dir: str) -> None:
        with self._lock:
            if self.active_dir is not None:
                raise RuntimeError(
                    f"profiler already capturing into {self.active_dir}"
                )
            import jax

            jax.profiler.start_trace(log_dir)
            self.active_dir = log_dir
            logger.info("jax profiler capture started → %s", log_dir)

    def stop(self) -> str:
        with self._lock:
            if self.active_dir is None:
                raise RuntimeError("no profiler capture in flight")
            import jax

            jax.profiler.stop_trace()
            log_dir, self.active_dir = self.active_dir, None
            self.captures += 1
            logger.info("jax profiler capture stopped → %s", log_dir)
            return log_dir

    def status(self) -> dict:
        return {"active_dir": self.active_dir, "captures": self.captures}
