"""Tick flight recorder: span tracing, slow-tick dumps, loop health.

The diagnostic substrate under every perf PR (ISSUE 5): ``spans``
records per-stage wall time for every tick and message,
``flight_recorder`` keeps the last N tick traces and auto-dumps slow
ones, ``export`` renders Chrome-trace JSON for ``GET /debug/ticks``
and hosts the ``jax.profiler`` hook, ``loop_monitor`` separates a
blocked event loop from a slow device, ``device`` attributes jit
compiles/retraces and the per-tick encode/transfer/compute/fetch
split (ISSUE 7).
"""

from .device import DeviceTelemetry
from .flight_recorder import FlightRecorder
from .loop_monitor import LoopMonitor
from .spans import NOOP_SPAN, NULL_TRACE, Trace, Tracer
from .export import ProfilerHook, chrome_trace

__all__ = [
    "DeviceTelemetry",
    "FlightRecorder",
    "LoopMonitor",
    "NOOP_SPAN",
    "NULL_TRACE",
    "ProfilerHook",
    "Trace",
    "Tracer",
    "chrome_trace",
]
