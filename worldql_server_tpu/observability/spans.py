"""Lightweight span tracing: the causal substrate of the flight recorder.

The aggregate histograms in ``engine/metrics.py`` can say a tick was
slow; they can never say *why* — BENCH_r05's 207 s ``p99_ms_depth2``
outlier could only be explained structurally because no record of that
one tick survived. This module records per-tick, per-stage wall time
the way TPU-KNN (arXiv:2206.14286) accounts a device query pipeline:
every stage of every tick is a :class:`Span` inside a causally-linked
:class:`Trace`, cheap enough to leave on in production and (following
``utils/trace.py``'s one-branch-when-off discipline) near-free when
off — ``Tracer.begin``/``Tracer.span`` cost one attribute check and
return shared null singletons that swallow everything.

Thread-safety: the ticker's collect stage runs on a worker thread and
the WAL writer thread emits fsync spans, so ``Trace.add`` takes a small
lock and parent links ride a :mod:`contextvars` var (copied into
``asyncio.to_thread`` and ``create_task``, so spans opened inside a
pipelined stage task still attach to their tick's trace).

Two entry points:

* ``tracer.begin(name, **tags)`` — an explicit trace object for flows
  that cross task boundaries (the pipelined tick: dispatch on the
  loop, collect+deliver in a chained stage task). The caller threads
  the ``Trace`` through and calls ``trace.span(...)`` / ``finish()``.
* ``tracer.span(name, **tags)`` — a context manager that attaches to
  the current trace if one is active, else records a single-span
  "loose" trace (per-message router handles, WAL fsyncs); finished
  root traces are handed to ``tracer.on_trace`` (the flight recorder).
"""

from __future__ import annotations

import contextvars
import threading
import time

#: (Trace, parent_span_id) of the innermost open span, per context
_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "wql_current_span", default=None
)


class Span:
    """One completed (or open) stage: name + wall window + tags."""

    __slots__ = ("id", "parent", "name", "t0", "dur_ms", "tags", "thread")

    def __init__(self, id, parent, name, t0, tags, thread):
        self.id = id
        self.parent = parent
        self.name = name
        self.t0 = t0           # perf_counter seconds
        self.dur_ms = 0.0
        self.tags = tags
        self.thread = thread

    def tag(self, **tags) -> None:
        """Late tags (values known only at stage end) — same surface
        as ``_NullSpan.tag`` so callers never branch on enablement."""
        self.tags.update(tags)

    def as_dict(self, perf_start: float) -> dict:
        return {
            "id": self.id,
            "parent": self.parent,
            "name": self.name,
            "t0_ms": round((self.t0 - perf_start) * 1e3, 3),
            "dur_ms": round(self.dur_ms, 3),
            "tags": self.tags,
            "thread": self.thread,
        }


class Trace:
    """A finished-or-in-flight span tree (one tick, or one loose op)."""

    __slots__ = (
        "name", "tags", "wall_start", "perf_start", "dur_ms", "spans",
        "_lock", "_next_id", "_on_finish", "_done",
    )

    def __init__(self, name: str, on_finish=None, **tags):
        self.name = name
        self.tags = tags
        self.wall_start = time.time()
        self.perf_start = time.perf_counter()
        self.dur_ms = 0.0
        self.spans: list[Span] = []
        self._lock = threading.Lock()
        self._next_id = 0
        self._on_finish = on_finish
        self._done = False

    def span(self, name: str, **tags) -> "_SpanCtx":
        """Open a child span in THIS trace (parented to the innermost
        open span of the calling context, or the trace root)."""
        return _SpanCtx(self, name, tags)

    def tag(self, **tags) -> None:
        self.tags.update(tags)

    def add(self, span: Span) -> None:
        with self._lock:
            self.spans.append(span)

    def _new_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def finish(self) -> None:
        """Seal the trace (idempotent) and hand it to the sink."""
        if self._done:
            return
        self._done = True
        self.dur_ms = (time.perf_counter() - self.perf_start) * 1e3
        if self._on_finish is not None:
            self._on_finish(self)

    def stage_ms(self) -> dict[str, float]:
        """Per-span-name wall-time totals — the breakdown a slow-tick
        dump leads with. Only TOP-LEVEL spans (parent is the trace
        root) are summed, so nested child spans don't double-count
        their parents' wall."""
        out: dict[str, float] = {}
        with self._lock:
            for s in self.spans:
                if s.parent is None:
                    out[s.name] = out.get(s.name, 0.0) + s.dur_ms
        return out

    def as_dict(self) -> dict:
        with self._lock:
            spans = [s.as_dict(self.perf_start) for s in self.spans]
        return {
            "name": self.name,
            "tags": self.tags,
            "start_unix_s": round(self.wall_start, 6),
            "dur_ms": round(self.dur_ms, 3),
            "spans": spans,
        }


class _SpanCtx:
    """Context manager recording one span into a known trace; sets the
    parent-link context var for the duration so nested ``tracer.span``
    calls attach underneath."""

    __slots__ = ("_trace", "_name", "_tags", "_span", "_token", "_root")

    def __init__(self, trace: Trace, name: str, tags: dict, root=False):
        self._trace = trace
        self._name = name
        self._tags = tags
        self._span = None
        self._token = None
        self._root = root

    def __enter__(self):
        trace = self._trace
        cur = _CURRENT.get()
        parent = cur[1] if cur is not None and cur[0] is trace else None
        self._span = Span(
            trace._new_id(), parent, self._name, time.perf_counter(),
            self._tags, threading.current_thread().name,
        )
        self._token = _CURRENT.set((trace, self._span.id))
        return self._span

    def __exit__(self, *exc) -> bool:
        span = self._span
        span.dur_ms = (time.perf_counter() - span.t0) * 1e3
        _CURRENT.reset(self._token)
        self._trace.add(span)
        if self._root:
            self._trace.finish()
        return False


class _NullSpan:
    """Shared do-nothing span/context-manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def tag(self, **tags) -> None:
        pass


class _NullTrace:
    """Shared do-nothing trace for the disabled path."""

    __slots__ = ()
    dur_ms = 0.0

    def span(self, name: str, **tags) -> _NullSpan:
        return NOOP_SPAN

    def tag(self, **tags) -> None:
        pass

    def finish(self) -> None:
        pass

    def stage_ms(self) -> dict:
        return {}

    def as_dict(self) -> dict:
        return {}


NOOP_SPAN = _NullSpan()
NULL_TRACE = _NullTrace()


class Tracer:
    """Per-server tracing switchboard. ``enabled`` is THE one branch
    the disabled hot path pays; ``on_trace`` receives every finished
    root trace (the flight recorder's ``record``)."""

    __slots__ = ("enabled", "on_trace")

    def __init__(self, enabled: bool = False, on_trace=None):
        self.enabled = enabled
        self.on_trace = on_trace

    def begin(self, name: str, **tags):
        """Start an explicit trace (the tick root). Returns the shared
        null trace when disabled — callers never branch."""
        if not self.enabled:
            return NULL_TRACE
        return Trace(name, on_finish=self._emit, **tags)

    def span(self, name: str, **tags):
        """A span in the current context's trace; with no trace active
        it becomes its own single-span loose trace (per-message router
        handles, WAL fsyncs from the writer thread)."""
        if not self.enabled:
            return NOOP_SPAN
        cur = _CURRENT.get()
        if cur is not None:
            return _SpanCtx(cur[0], name, tags)
        trace = Trace(name, on_finish=self._emit, **tags)
        return _SpanCtx(trace, name, tags, root=True)

    def _emit(self, trace: Trace) -> None:
        if self.on_trace is not None:
            try:
                self.on_trace(trace)
            except Exception:  # a broken sink must never break a tick
                import logging

                logging.getLogger(__name__).exception(
                    "trace sink failed for %r", trace.name
                )
