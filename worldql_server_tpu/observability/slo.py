"""SLO registry + multi-window burn-rate sentinel.

Turns the telemetry the fleet already records into a *judgment* layer:
declarative objectives over existing metric series, evaluated every
``eval_interval_s`` by a supervised ``slo-eval`` task from the same
cumulative histogram/counter state that ``/metrics`` renders.  Each
objective carries a fast and a slow window (Google-SRE multi-window
burn-rate alerting): the fast window trips quickly on an acute breach,
the slow window confirms it is sustained, and the pair drives a
three-state machine per objective::

    OK(0) -> WARN(1) -> BURNING(2)

* ``burn >= 1`` on BOTH windows  => BURNING
* ``burn >= 1`` on either window => WARN
* otherwise                      => OK

so recovery drains back BURNING -> WARN -> OK as the windows clear.
A transition INTO ``BURNING`` fires :attr:`SloEngine.on_burning`
(wired to the incident recorder by the server; debounced there).

Objective kinds
---------------

``latency_p99``
    ``series`` is a histogram; the objective is "at most ``budget``
    (fraction) of observations in the window may exceed ``target_ms``".
    burn = bad_fraction / budget.  Default targets are aligned with
    :data:`~worldql_server_tpu.engine.metrics.LATENCY_BUCKETS_MS` bucket
    edges so the over-target count is exact, not interpolated.
``rate``
    ``series`` is a counter; the objective is "at most ``max_per_s``
    events per second over the window".  burn = rate / max_per_s.
``gauge_floor``
    ``series`` is a pull gauge; the objective is "the gauge must stay
    at or above ``floor``".  burn per sample = floor / value when the
    value is positive and below the floor; a window's burn is the mean
    of its samples' burns.  A gauge that is absent or still warming up
    (``<= 0``) contributes no burn — floors only judge measured data.

``DEFAULT_OBJECTIVES`` below is a pure literal on purpose: the
``unexported-slo-series`` lint rule reads the ``series`` names straight
out of this tuple and fails the build if the repo has no call site that
can produce one of them (an SLO over a phantom series is dead config).

Overrides ride ``--slo-file`` (JSON): either a bare list of objective
dicts, or ``{"eval_interval_s": ..., "objectives": [...]}``.  A file
REPLACES the default registry so tests and operators can pin exactly
the objectives (and windows) they mean.
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import time
from typing import Any, Callable

from ..engine.metrics import LATENCY_BUCKETS_MS, Metrics

log = logging.getLogger("worldql.slo")

OK = 0
WARN = 1
BURNING = 2

STATE_NAMES = {OK: "ok", WARN: "warn", BURNING: "burning"}

#: Default evaluation cadence, aligned with the shards' ~1s control
#: packets so fleet state federates at the same rhythm.
EVAL_INTERVAL_S = 1.0

#: How many recent evaluations each objective keeps for its burn
#: trajectory (what the incident capsule embeds).
TRAJECTORY_DEPTH = 120

_KINDS = ("latency_p99", "rate", "gauge_floor")

# Pure literal — read by tools/check rule `unexported-slo-series`.
DEFAULT_OBJECTIVES = (
    {
        "name": "frame_e2e_p99",
        "series": "frame.e2e_ms",
        "kind": "latency_p99",
        "target_ms": 5.0,
        "budget": 0.01,
        "fast_s": 10.0,
        "slow_s": 60.0,
    },
    {
        "name": "cluster_e2e_p99",
        "series": "cluster.e2e_ms",
        "kind": "latency_p99",
        "target_ms": 25.0,
        "budget": 0.01,
        "fast_s": 10.0,
        "slow_s": 60.0,
    },
    {
        "name": "ring_full_drops",
        "series": "delivery.ring_full_drops",
        "kind": "rate",
        "max_per_s": 1.0,
        "fast_s": 10.0,
        "slow_s": 60.0,
    },
    {
        "name": "interest_resyncs",
        "series": "interest.resyncs",
        "kind": "rate",
        "max_per_s": 5.0,
        "fast_s": 10.0,
        "slow_s": 60.0,
    },
    {
        "name": "per_core_floor",
        "series": "deliveries_per_s_per_core",
        "kind": "gauge_floor",
        "floor": 10000.0,
        "fast_s": 10.0,
        "slow_s": 60.0,
    },
    {
        "name": "wal_fsync_p99",
        "series": "durability.fsync_ms",
        "kind": "latency_p99",
        "target_ms": 25.0,
        "budget": 0.01,
        "fast_s": 10.0,
        "slow_s": 60.0,
    },
)


def validate_objective(obj: dict) -> None:
    """Raise ``ValueError`` on a malformed objective dict."""
    if not isinstance(obj, dict):
        raise ValueError(f"slo objective must be an object, got {type(obj).__name__}")
    name = obj.get("name")
    if not name or not isinstance(name, str):
        raise ValueError("slo objective missing 'name'")
    if not all(c.isalnum() or c == "_" for c in name):
        raise ValueError(f"slo objective name {name!r} must be [A-Za-z0-9_]")
    if not obj.get("series") or not isinstance(obj.get("series"), str):
        raise ValueError(f"slo objective {name!r} missing 'series'")
    kind = obj.get("kind")
    if kind not in _KINDS:
        raise ValueError(f"slo objective {name!r} kind {kind!r} not in {_KINDS}")
    if kind == "latency_p99":
        if float(obj.get("target_ms", 0)) <= 0:
            raise ValueError(f"slo objective {name!r} needs target_ms > 0")
        budget = float(obj.get("budget", 0.01))
        if not 0 < budget <= 1:
            raise ValueError(f"slo objective {name!r} budget must be in (0, 1]")
    elif kind == "rate":
        if float(obj.get("max_per_s", 0)) <= 0:
            raise ValueError(f"slo objective {name!r} needs max_per_s > 0")
    elif kind == "gauge_floor":
        if float(obj.get("floor", 0)) <= 0:
            raise ValueError(f"slo objective {name!r} needs floor > 0")
    for win in ("fast_s", "slow_s"):
        if float(obj.get(win, 1.0)) <= 0:
            raise ValueError(f"slo objective {name!r} needs {win} > 0")
    if float(obj.get("fast_s", 10.0)) > float(obj.get("slow_s", 60.0)):
        raise ValueError(f"slo objective {name!r} fast_s must be <= slow_s")


def load_objectives(path: str | None) -> tuple[float, list[dict]]:
    """Load ``(eval_interval_s, objectives)`` from a ``--slo-file`` JSON
    document, or the built-in defaults when ``path`` is ``None``.  The
    file replaces the default registry wholesale."""
    if path is None:
        return EVAL_INTERVAL_S, [dict(o) for o in DEFAULT_OBJECTIVES]
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if isinstance(doc, list):
        interval, objectives = EVAL_INTERVAL_S, doc
    elif isinstance(doc, dict):
        interval = float(doc.get("eval_interval_s", EVAL_INTERVAL_S))
        objectives = doc.get("objectives")
        if not isinstance(objectives, list):
            raise ValueError("slo file object needs an 'objectives' list")
    else:
        raise ValueError("slo file must be a JSON list or object")
    if interval <= 0:
        raise ValueError("slo file eval_interval_s must be > 0")
    if not objectives:
        raise ValueError("slo file declares no objectives")
    seen: set[str] = set()
    out = []
    for obj in objectives:
        validate_objective(obj)
        if obj["name"] in seen:
            raise ValueError(f"duplicate slo objective name {obj['name']!r}")
        seen.add(obj["name"])
        out.append(dict(obj))
    return interval, out


def _over_target_index(target_ms: float) -> int:
    """First bucket index whose upper bound exceeds ``target_ms`` —
    deltas from that index up (incl. overflow) count as over-target."""
    for i, bound in enumerate(LATENCY_BUCKETS_MS):
        if bound > target_ms:
            return i
    return len(LATENCY_BUCKETS_MS)


class _Objective:
    """One declared objective plus its live burn/state bookkeeping."""

    def __init__(self, spec: dict) -> None:
        validate_objective(spec)
        self.spec = dict(spec)
        self.name: str = spec["name"]
        self.series: str = spec["series"]
        self.kind: str = spec["kind"]
        self.fast_s = float(spec.get("fast_s", 10.0))
        self.slow_s = float(spec.get("slow_s", 60.0))
        self.level = OK
        self.burn_fast = 0.0
        self.burn_slow = 0.0
        self.value: float | None = None  # window p99 / rate / gauge value
        self.transitions = 0
        self.last_transition_t: float | None = None
        self.trajectory: collections.deque = collections.deque(
            maxlen=TRAJECTORY_DEPTH
        )
        if self.kind == "latency_p99":
            self._over_idx = _over_target_index(float(spec["target_ms"]))

    # -- window burn computation ------------------------------------

    def _window_burn(self, newest: "_Sample", oldest: "_Sample") -> float:
        if self.kind == "latency_p99":
            cur = newest.hists.get(self.series)
            old = oldest.hists.get(self.series)
            if cur is None:
                return 0.0
            if old is None:
                old_counts, old_total = None, 0
            else:
                old_counts, old_total = old
            counts, total = cur
            d_total = total - old_total
            if d_total <= 0:
                return 0.0
            bad = 0
            for i in range(self._over_idx, len(counts)):
                prev = old_counts[i] if old_counts is not None else 0
                bad += counts[i] - prev
            if bad < 0:  # counter reset (restart) — re-baseline quietly
                return 0.0
            frac = bad / d_total
            self.value = round(frac, 6)
            return frac / float(self.spec.get("budget", 0.01))
        if self.kind == "rate":
            cur = newest.counters.get(self.series, 0)
            old = oldest.counters.get(self.series, 0)
            span = max(newest.t - oldest.t, 1e-9)
            delta = cur - old
            if delta < 0:  # reset
                return 0.0
            rate = delta / span
            self.value = round(rate, 3)
            return rate / float(self.spec["max_per_s"])
        # gauge_floor: mean of per-sample burns across the window.
        floor = float(self.spec["floor"])
        burns = []
        for sample in (oldest, newest):
            val = sample.gauges.get(self.series)
            if val is None or val <= 0:
                continue
            self.value = val
            burns.append(floor / val if val < floor else 0.0)
        return sum(burns) / len(burns) if burns else 0.0

    def evaluate(self, now: float, newest, fast_old, slow_old) -> tuple[int, int]:
        """Recompute burns + state; returns ``(old_level, new_level)``."""
        self.value = None
        self.burn_fast = round(self._window_burn(newest, fast_old), 4)
        self.burn_slow = round(self._window_burn(newest, slow_old), 4)
        old = self.level
        if self.burn_fast >= 1.0 and self.burn_slow >= 1.0:
            new = BURNING
        elif self.burn_fast >= 1.0 or self.burn_slow >= 1.0:
            new = WARN
        else:
            new = OK
        if new != old:
            self.transitions += 1
            self.last_transition_t = now
            log.log(
                logging.WARNING if new > old else logging.INFO,
                "slo objective %s: %s -> %s (burn fast=%.2f slow=%.2f)",
                self.name, STATE_NAMES[old], STATE_NAMES[new],
                self.burn_fast, self.burn_slow,
            )
        self.level = new
        self.trajectory.append(
            {
                "t": round(now, 3),
                "burn_fast": self.burn_fast,
                "burn_slow": self.burn_slow,
                "level": new,
            }
        )
        return old, new

    @property
    def budget_remaining(self) -> float:
        """Fraction of the slow window's error budget still unspent."""
        return round(max(0.0, 1.0 - self.burn_slow), 4)

    def status(self) -> dict:
        out = {
            "series": self.series,
            "kind": self.kind,
            "state": STATE_NAMES[self.level],
            "level": self.level,
            "burn_fast": self.burn_fast,
            "burn_slow": self.burn_slow,
            "budget_remaining": self.budget_remaining,
            "transitions": self.transitions,
            "windows": {"fast_s": self.fast_s, "slow_s": self.slow_s},
        }
        if self.kind == "latency_p99":
            out["target_ms"] = float(self.spec["target_ms"])
            out["budget"] = float(self.spec.get("budget", 0.01))
            if self.value is not None:
                out["bad_fraction"] = self.value
        elif self.kind == "rate":
            out["max_per_s"] = float(self.spec["max_per_s"])
            if self.value is not None:
                out["rate_per_s"] = self.value
        else:
            out["floor"] = float(self.spec["floor"])
            if self.value is not None:
                out["value"] = self.value
        return out


class _Sample:
    """One timestamped cumulative snapshot of every referenced series."""

    __slots__ = ("t", "hists", "counters", "gauges")

    def __init__(self, t: float, hists: dict, counters: dict, gauges: dict):
        self.t = t
        self.hists = hists
        self.counters = counters
        self.gauges = gauges


class SloEngine:
    """Evaluates the objective registry against a :class:`Metrics`
    registry on a fixed cadence, keeping just enough cumulative history
    to diff the slow window.  One instance per process: shards and the
    single-process server judge their local registry; the router's
    instance judges the federated registry (which already folds every
    shard's series in) and additionally mirrors the per-shard compliance
    summaries that piggyback the ~1s control packets."""

    def __init__(
        self,
        metrics: Metrics,
        objectives: list[dict] | None = None,
        *,
        eval_interval_s: float = EVAL_INTERVAL_S,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if objectives is None:
            _, objectives = load_objectives(None)
        self.metrics = metrics
        self.clock = clock
        self.eval_interval_s = float(eval_interval_s)
        self.objectives = [_Objective(o) for o in objectives]
        #: Called with the objective on any transition INTO ``BURNING``
        #: (wired to the incident recorder; debounce lives there).
        self.on_burning: Callable[[_Objective], None] | None = None
        self._series_h = sorted(
            {o.series for o in self.objectives if o.kind == "latency_p99"}
        )
        self._series_c = sorted(
            {o.series for o in self.objectives if o.kind == "rate"}
        )
        self._series_g = sorted(
            {o.series for o in self.objectives if o.kind == "gauge_floor"}
        )
        slow_max = max(o.slow_s for o in self.objectives)
        depth = int(slow_max / self.eval_interval_s) + 3
        self._ring: collections.deque = collections.deque(maxlen=depth)
        self.evals = 0
        # Per-shard compliance mirrored off control packets (router only).
        self._remote: dict[int, dict] = {}

    # -- sampling ---------------------------------------------------

    def _snap(self, now: float) -> _Sample:
        hists = {}
        if self._series_h:
            raw = self.metrics.export_histograms(tuple(self._series_h))
            for name, h in raw.items():
                if name in self._series_h:
                    hists[name] = (h["counts"], h["total"])
        counters = {
            name: self.metrics.counters.get(name, 0) for name in self._series_c
        }
        gauges = {}
        for name in self._series_g:
            val = self.metrics.gauge_value(name)
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                gauges[name] = float(val)
        return _Sample(now, hists, counters, gauges)

    def _window_anchor(self, now: float, window_s: float) -> _Sample:
        """Newest sample at least ``window_s`` old; falls back to the
        oldest retained sample while history is still shorter than the
        window (partial-window evaluation, same as a cold SRE alert)."""
        anchor = self._ring[0]
        for sample in self._ring:
            if now - sample.t >= window_s:
                anchor = sample
            else:
                break
        return anchor

    # -- evaluation -------------------------------------------------

    def evaluate(self) -> None:
        now = self.clock()
        sample = self._snap(now)
        self._ring.append(sample)
        self.evals += 1
        for obj in self.objectives:
            fast_old = self._window_anchor(now, obj.fast_s)
            slow_old = self._window_anchor(now, obj.slow_s)
            old, new = obj.evaluate(now, sample, fast_old, slow_old)
            if new == BURNING and old != BURNING and self.on_burning:
                try:
                    self.on_burning(obj)
                except Exception:  # noqa: BLE001 — alerting must not kill eval
                    log.exception("slo on_burning hook failed for %s", obj.name)

    async def run(self) -> None:
        """Supervised ``slo-eval`` loop body."""
        while True:
            await asyncio.sleep(self.eval_interval_s)
            self.evaluate()

    # -- exports ----------------------------------------------------

    @property
    def worst_level(self) -> int:
        worst = max((o.level for o in self.objectives), default=OK)
        for remote in self._remote.values():
            worst = max(worst, int(remote.get("worst", OK)))
        return worst

    def gauge(self) -> dict:
        """Pull-gauge payload: numeric per-objective levels flatten to
        ``wql_slo_<name>`` in the Prometheus exposition."""
        out: dict[str, Any] = {o.name: o.level for o in self.objectives}
        out["worst"] = self.worst_level
        return out

    def compliance(self) -> dict:
        """Compact summary shards piggyback on control packets."""
        return {
            "levels": {o.name: o.level for o in self.objectives},
            "burns": {o.name: o.burn_slow for o in self.objectives},
            "worst": max((o.level for o in self.objectives), default=OK),
        }

    def note_remote(self, shard: int, compliance: dict | None) -> None:
        """Router side: fold one shard's piggybacked compliance in."""
        if isinstance(compliance, dict):
            self._remote[int(shard)] = compliance

    def drop_remote(self, shard: int) -> None:
        self._remote.pop(int(shard), None)

    def status(self) -> dict:
        """Full report for ``GET /debug/slo`` and the healthz block."""
        out: dict[str, Any] = {
            "state": STATE_NAMES[self.worst_level],
            "worst": self.worst_level,
            "evals": self.evals,
            "eval_interval_s": self.eval_interval_s,
            "objectives": {o.name: o.status() for o in self.objectives},
        }
        if self._remote:
            out["shards"] = {str(k): v for k, v in sorted(self._remote.items())}
        return out

    def healthz(self) -> dict:
        """Compact block for ``/healthz``."""
        return {
            "state": STATE_NAMES[self.worst_level],
            "burning": [o.name for o in self.objectives if o.level == BURNING],
        }

    def trajectory(self, name: str) -> list[dict]:
        for obj in self.objectives:
            if obj.name == name:
                return list(obj.trajectory)
        return []
